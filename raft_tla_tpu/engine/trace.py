"""Predecessor-trace stores: counterexample reconstruction (SURVEY §2.4 R5).

The engine appends one (fingerprint, parent fingerprint, action id) record
per newly discovered state; walking the records backwards from a violating
fingerprint and replaying the recorded action ids through the expand kernel
reproduces TLC's counterexample traces bit-exactly.

Two interchangeable implementations:

- ``NativeTraceStore`` — the C++ open-addressing map (native/trace_store.cpp)
  bound via ctypes; batch inserts take numpy arrays directly.
- ``PyTraceStore`` — dict fallback when no compiler is available.

``make_trace_store()`` picks the native one when it loads.  Action id -1
marks roots (initial states), whose full ``PyState`` is kept host-side in
``roots`` for replay starts.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.pystate import PyState
from .. import native


class PyTraceStore:
    """fp64 -> (parent fp64, action id); pure-Python fallback."""

    def __init__(self):
        self._d: Dict[int, Tuple[int, int]] = {}
        self.roots: Dict[int, PyState] = {}

    def __len__(self):
        return len(self._d)

    def add_batch(self, fps, parent_fps, actions):
        d = self._d
        for f, p, g in zip(fps.tolist(), parent_fps.tolist(),
                           actions.tolist()):
            if f not in d:
                d[f] = (p, g)

    def get(self, fp: int) -> Optional[Tuple[int, int]]:
        return self._d.get(fp)

    def export(self):
        n = len(self._d)
        fps = np.fromiter(self._d.keys(), np.uint64, n)
        parents = np.fromiter((p for p, _g in self._d.values()), np.uint64, n)
        actions = np.fromiter((g for _p, g in self._d.values()), np.int32, n)
        return fps, parents, actions

    def edges(self):
        """The recorded discovery edges as ``(fps, parents, actions)``
        numpy columns — ``export()`` under its graph name.  Root records
        carry action -1 (no incoming edge); one record per first
        discovery, so the edge set is TLC's BFS tree, which is what the
        full-graph export (engine/explain.py ``export_graph``) draws.
        Shared by both store implementations (NativeTraceStore overrides
        ``export`` only)."""
        return self.export()

    def chain(self, fp: int) -> List[Tuple[int, int]]:
        """Walk back to a root; returns [(fp, action_into_fp)] root-first."""
        out = []
        seen = set()
        while fp not in seen:
            rec = self.get(fp)
            if rec is None:
                break
            seen.add(fp)
            p, g = rec
            out.append((fp, g))
            if g < 0:
                break
            fp = p
        return list(reversed(out))


class NativeTraceStore(PyTraceStore):
    """C++-backed store; inherits the chain() walk (uses ``get``)."""

    def __init__(self, lib, initial_capacity: int = 1 << 16):
        self._lib = lib
        self._h = lib.ts_create(initial_capacity)
        self.roots: Dict[int, PyState] = {}

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.ts_destroy(h)

    def __len__(self):
        return int(self._lib.ts_size(self._h))

    def add_batch(self, fps, parent_fps, actions):
        fps = np.ascontiguousarray(fps, np.uint64)
        parents = np.ascontiguousarray(parent_fps, np.uint64)
        acts = np.ascontiguousarray(actions, np.int32)
        n = fps.shape[0]
        if n == 0:
            return
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        self._lib.ts_add_batch(
            self._h, fps.ctypes.data_as(u64p), parents.ctypes.data_as(u64p),
            acts.ctypes.data_as(i32p), n)

    def get(self, fp: int) -> Optional[Tuple[int, int]]:
        parent = ctypes.c_uint64()
        action = ctypes.c_int32()
        found = self._lib.ts_get(self._h, np.uint64(fp),
                                 ctypes.byref(parent), ctypes.byref(action))
        return (parent.value, action.value) if found else None

    def export(self):
        n = len(self)
        fps = np.empty(n, np.uint64)
        parents = np.empty(n, np.uint64)
        actions = np.empty(n, np.int32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        got = self._lib.ts_export(
            self._h, fps.ctypes.data_as(u64p), parents.ctypes.data_as(u64p),
            actions.ctypes.data_as(i32p), n)
        assert got == n
        return fps, parents, actions


def make_trace_store(initial_capacity: int = 1 << 16):
    lib = native.load()
    if lib is not None:
        return NativeTraceStore(lib, initial_capacity)
    return PyTraceStore()
