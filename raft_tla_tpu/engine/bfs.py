"""Level-synchronous BFS — TLC's exhaustive mode as a data-parallel device loop.

The classical TLC loop (one state at a time: enumerate actions, fingerprint,
probe the FPSet, enqueue — SURVEY §1 L6) becomes a batched pipeline compiled
to one XLA program per step:

    slice B states off the current-level queue
      -> vmap(expand): all G action instances of all B states   [B,G]
      -> vmap(fingerprint) over the B*G candidates (cheap reduce per lane)
      -> COMPACT the enabled lanes to K << B*G slots (prefix-sum scatter;
         measured fan-out is ~6% of G, so K = 16*B loses nothing, and a
         fan-out burst just advances fewer parents that step)
      -> batched hash-table insert (ops/fpset.py) on the K compacted keys:
         in-batch dedup + HBM seen-set probe/update in one pass
      -> gather the K candidate states; materialize uint8 rows, evaluate
         invariants + the state constraint, scatter the new rows into the
         next-level queue — all O(K), never O(B*G)
      -> deadlock mask, violation/overflow reporting

Everything device-resident: the two level queues (flat uint8 state rows),
the FPSet, and all masks.  The host loop only advances offsets, swaps queues
between levels, reads back a handful of scalars per batch, and appends
(fingerprint -> parent fingerprint, action id) records to the trace store —
exactly the host/device split the SURVEY's north star prescribes.

TLC-semantics notes:
- constraint-violating states are counted distinct and invariant-checked but
  not enqueued (CONSTRAINT behavior; SURVEY §2.4 R9);
- a state with no successors at all is a deadlock (reported unless
  ``check_deadlock=False``, Smokeraft.cfg:48);
- the run stops at the first invariant violation, like TLC; counterexamples
  are reconstructed by fingerprint walk-back plus *kernel replay* (the trace
  stores (parent fp, action instance id); re-running the expand kernel on the
  replayed parent yields each next state bit-exactly);
- ``generated`` counts every enabled successor evaluation (TLC's "states
  generated"), ``distinct`` counts FPSet insertions.

Budgets (``max_seconds``/``max_diameter``) reproduce the Smokeraft StopAfter
control channel (TLCGet("duration")/TLCGet("diameter") — Smokeraft.tla:88-92)
at batch granularity.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.dims import RaftDims
from ..models.actions import build_expand
from ..models.invariants import build_inv_id
from ..models.pystate import PyState
from ..models.schema import (ROW_DTYPE, StateBatch, build_pack_guard,
                             check_packable, decode_state, encode_state,
                             flatten_state, state_width, unflatten_state)
from ..obs import (ActionCoverage, MetricsRegistry, RunEventLog,
                   SpanTracer, all_device_memory_stats,
                   device_memory_stats, events_path, peak_host_rss_bytes,
                   phase_delta)
from ..obs.flight import RECORDER as _FLIGHT
from ..resilience import faults as _faults
from ..resilience.faults import is_resource_exhausted
from ..ops import compact as compact_mod
from ..ops import fpset
from ..ops.fingerprint import build_fingerprint
from .chunk import build_chunk_body

_I32 = jnp.int32


@dataclasses.dataclass
class EngineConfig:
    batch: int = 256             # states expanded per device step
    # None => size from the device's reported HBM (see _auto_capacities).
    # Neither is a hard limit on the state space: the frontier spills to
    # host memory when the device queue fills (TLC's disk queue), and the
    # seen-set grows by rehashing when its load factor passes the
    # threshold; these set the *device-resident* working set.
    queue_capacity: Optional[int] = 1 << 16
    seen_capacity: Optional[int] = 1 << 18
    # Width (lanes) of the compacted-candidate buffer: the B*G enabled
    # masks are prefix-summed into this many lanes before the hash insert,
    # row materialization, invariant/constraint evaluation, and enqueue —
    # so those stages cost O(K), not O(B*G).  Enabled fraction is typically
    # well under 10% (measured fan-out ~8 of G=132 on MCraft_bounded), so
    # the default of 16 lanes per frontier state loses nothing; when a
    # batch's fan-out does exceed K the device loop simply takes fewer
    # parents that step (progress-limited, never dropped).  None => auto
    # (16*batch); any value is floored at max(G, batch) and rounded to a
    # power of two (ops/compact.py choose_k).
    compact_lanes: Optional[int] = None
    # Successor pipeline: "auto" = the v2 delta pipeline (models/
    # actions2.py — guards-only masks, delta fingerprints, K-lane sparse
    # construction; the TPU-profile-driven rework) wherever it applies
    # (base action alphabet), v1 expand for spec variants with
    # extra_families.  "v1"/"v2" force one path (v2 raises on variants).
    # "v3" = v2 semantics with the chunk stages progressively fused into
    # Pallas kernels (ops/pipeline_v3.py: Pallas compact scan + the
    # fused probe/insert->enqueue tail, VMEM-resident survivor window;
    # interpret mode off-TPU).  Bit-identical to v2 by contract; every
    # stage that cannot lower falls back to its XLA lowering
    # automatically, with the resolved per-stage plan recorded on
    # ``EngineResult.fused_stages``.  "v4" = v2 semantics with the WHOLE
    # chunk body fused into two Pallas megakernels (ops/pipeline_v4.py:
    # the masks->compact->fingerprint front over the VMEM-resident
    # parent window, plus v3's probe/insert->enqueue tail); same
    # bit-identity and per-stage-fallback contract.  Opt-in: "auto"
    # never selects v3/v4.
    pipeline: str = "auto"
    # Per-stage override for the v3 plan ({"compact": "pallas"|"xla",
    # "insert": "fused"|"xla", ...}) — tests force the full Pallas chain
    # on CPU through this; None = the platform policy.
    v3_force_stages: Optional[dict] = None
    # Same for the v4 plan (ops/pipeline_v4.py _VALID; any front member
    # forced off "fused" degrades the whole front group).  The
    # RAFT_V4_FORCE env var merges over this, env winning per stage —
    # the fallback-lattice tests' no-plumbing hook.
    v4_force_stages: Optional[dict] = None
    # Lane-compaction lowering (ops/compact.py): "scatter" (original) or
    # "searchsorted" (binary-search inversion; identical outputs).  Kept
    # switchable until a TPU profile picks the winner.
    compact_method: str = "scatter"
    # Enqueue/trace-record lowering (engine/chunk.py): "scatter" writes
    # each compacted row at its cumsum position (+ per-lane trash for
    # masked lanes); "window" rebuilds a K-row window at next_count with
    # a searchsorted gather + one dynamic_update_slice; "pallas" issues
    # run-coalesced HBM-to-HBM segment DMAs (ops/enqueue_pallas.py — the
    # contiguous-append formulation; interpret mode off-TPU).  Live rows
    # are bit-identical; switchable until a TPU profile picks the winner.
    enqueue_method: str = "scatter"
    # FPSet insert lowering: "xla" (ops/fpset.py sort + claim protocol) or
    # "pallas" (ops/fpset_pallas.py single sequential-grid kernel, no sort,
    # no claims; interpret mode off-TPU).  Engine results are bit-identical
    # (is_new contract matches); switchable until a TPU profile decides
    # the fused-chunk question (NORTHSTAR.md §d).  Single-host engine only.
    insert_method: str = "xla"
    # Statically-certified partial-order reduction (analysis/por.py).
    # ``por=True`` certifies in-process at engine construction (traces
    # the kernels once, proving the ample certificates against THIS
    # run's invariants + constraint); ``por_table`` supplies a
    # pre-certified table instead — a PorTable object or a path to the
    # versioned artifact `analyze --passes por --por-artifact` writes.
    # Every table is admission-checked (fingerprint, model signature,
    # predicate coverage) before the mask is applied; a hand-edited or
    # mismatched certificate raises instead of silently reducing.
    por: bool = False
    por_table: Optional[object] = None
    # None = defer to the cfg file (make_engine fills it in); a bool from
    # the caller always wins — the documented precedence chain.
    check_deadlock: Optional[bool] = None
    record_trace: bool = True
    sync_every: int = 32         # device batches per host round-trip
    max_seconds: Optional[float] = None   # StopAfter duration budget
    max_diameter: Optional[int] = None    # StopAfter diameter budget
    # Further TLCGet-consulting budgets as (counter, threshold) pairs over
    # "distinct" / "generated" / "queue" (utils/cfg.py EXIT_COUNTERS) —
    # the general metrics-control coupling (SURVEY §5.5): checked against
    # live counters after every chunk stats fetch, stop_reason
    # "<counter>_budget".  duration/diameter ride the two fields above.
    exit_conditions: tuple = ()
    # TLC prints a progress line roughly every minute; 0 disables.  The
    # CLI defaults this to 60 for `check` runs (SURVEY §5.1: duration,
    # diameter, states/sec, queue as live counters).
    progress_interval_seconds: float = 0.0
    checkpoint_dir: Optional[str] = None  # R8: level-boundary snapshots
    # Shared-filesystem directory for MULTI-HOST trace piece exchange
    # (parallel/mesh.py): controllers write their per-host trace stores
    # there and replay() merges the group.  None defers to
    # checkpoint_dir; setting it alone gives multi-host tracing WITHOUT
    # enabling periodic checkpoint snapshots.
    trace_dir: Optional[str] = None
    checkpoint_every: int = 1             # snapshot every k levels...
    checkpoint_interval_seconds: float = 0.0  # ...but at most this often.
    # Retention: after each successful snapshot, delete all but the
    # newest N intact snapshots/piece groups (checkpoint.gc).  None/0 =
    # keep all — the historical behavior; long supervised runs should
    # set a small N so the states/ dir stays bounded.
    keep_checkpoints: Optional[int] = None
    # Snapshot cost is O(seen states), so a per-level cadence is quadratic
    # over a long run; big runs should set a TLC-style time cadence (TLC
    # defaults to ~30 min between states/ checkpoints) and the CLI does.
    #
    # Directory for spilled level segments (TLC's disk-backed state
    # queue): None keeps them in host RAM; a path memory-maps them to
    # disk so frontiers larger than host memory survive (spillpool.py).
    spill_dir: Optional[str] = None
    # -- telemetry (obs/) ----------------------------------------------
    # JSONL run-event log (run_start / level_complete / fpset_resize /
    # spill / checkpoint / violation / deadlock / run_end).  None defers
    # to ``<checkpoint_dir>/events.jsonl`` when checkpointing is on,
    # else disabled.  Multi-host runs write one file per controller
    # (obs/events.py events_path).
    events_out: Optional[str] = None
    # Shared MetricsRegistry (obs/metrics.py); None gives the engine its
    # own.  Pass one to aggregate several runs (the checker service
    # does) or to read live gauges from another thread.
    metrics: Optional[object] = None
    # Chrome trace-event span log (obs/tracing.py): every phase_timer
    # block, a span per BFS level, and the whole run serialize to this
    # file at run end — opens directly in Perfetto/chrome://tracing.
    # None disables (zero overhead: the tracer no-ops).
    trace_out: Optional[str] = None
    # Per-stage chunk profiling (obs/profile.py): sample every Nth chunk
    # call through separately-fenced expand/fingerprint/dedup-insert/
    # enqueue stage programs, accumulating chunk_stage/* histograms and
    # a run-end chunk_profile event + stage-budget table.  Observational
    # (the real fused chunk still does all the work — results are
    # bit-identical profiling on or off); None = unset (a --perf run
    # then samples every 16th call), 0 = explicitly disabled (perf will
    # not re-enable it).  Single-chip engine only; the mesh ignores it
    # (its per-chip stages interleave collectives that a staged
    # decomposition cannot fence honestly).
    profile_chunks_every: Optional[int] = None
    # -- performance observatory (obs/perf.py, obs/roofline.py) --------
    # ``perf=True`` builds the launch-accounting + static-roofline
    # layer: the engine's REAL chunk program is traced once at build
    # for the static launch model (device ops per batch, a pre-fusion
    # upper bound — CI pins it per pipeline so a stage un-fusing can
    # never land silently), the shared stage programs are traced for
    # per-stage HBM-traffic floors, and the host loop feeds (batches,
    # seconds) per chunk call.  At run end the ``perf`` event /
    # ``EngineResult.perf`` / ``perf/*`` gauges carry launches-per-
    # chunk, the launch tax priced against measured chunk time,
    # achieved-bandwidth fractions per stage, and the fusion advisor's
    # top candidate.  Observational: engine counts are bit-identical
    # with perf on or off (tested).  Implies chunk profiling (the
    # roofline's measured half): when profile_chunks_every is unset, a
    # --perf run samples every 16th chunk call.
    perf: bool = False
    # Mesh skew telemetry (parallel/mesh.py): emit a ``skew`` warning
    # event when the per-shard frontier imbalance (max/mean of this
    # controller's shard next-level counts) reaches this ratio at a
    # level boundary.  The balance gauges + level_complete fields are
    # always on (a handful of host ints per level); only the warning
    # threshold is configurable.  The collective-latency probe rides
    # the ``perf`` flag instead (it costs a compile + a collective).
    skew_warn_ratio: float = 2.0
    # Deadline for collecting sibling controllers' trace piece files at
    # replay (parallel/mesh.py _merge_trace_pieces).  None = auto: a 30 s
    # base plus a size-proportional allowance — the sibling of a large
    # local piece is probably still compressing its own.
    trace_merge_timeout_seconds: Optional[float] = None
    # -- flight recorder / live introspection (obs/flight.py) ----------
    # Directory for the crash postmortem dump (postmortem.json, written
    # on an exception escaping the run, SIGTERM, or a fault-injected
    # hard kill — never on a completed run).  None defers to
    # checkpoint_dir; with neither set the dump is disabled (the
    # in-memory flight ring still feeds watch/metrics-port attach).
    postmortem_dir: Optional[str] = None
    # Extra key/values merged into the flight recorder's ``run_context``
    # record when the run arms (serving/: the job manager tags each
    # server-executed run with ``{"job_id": ..., "tenant": ...}`` so
    # ring snapshots, watch consoles, and postmortem dumps attribute
    # device time to the job that spent it).  Host-side only — safe to
    # set per-request on a warm cached engine, like the budgets.
    run_context_extra: Optional[dict] = None
    # Device-profiler capture (obs/profile.py XlaProfileCapture;
    # --xla-profile[=N] / XLA_PROFILE directive): bracket the first N
    # chunk calls of the run in a jax.profiler trace window, correlated
    # with the SpanTracer's "chunk" spans by shared span name +
    # step_num.  Artifacts land under xla_profile_dir (None =
    # "<checkpoint_dir>/xla_profile", or "./xla_profile" without a
    # checkpoint dir).  Observational: engine results are bit-identical
    # with the capture on or off; a profiler that cannot start records
    # its failure in the xla_profile event instead of raising.
    xla_profile_chunks: Optional[int] = None
    xla_profile_dir: Optional[str] = None
    # -- semantic observability (obs/report.py, engine/explain.py) -----
    # TLC-parity run report: assembled HOST-SIDE at run end from
    # counters the loop already fetched (fingerprint collision
    # probability, per-level frontier table, out-degree summary,
    # seen-set load), emitted as a ``statespace`` run event, rendered
    # as the TLC-style stderr block on progress-enabled runs, and
    # surfaced on ``EngineResult.report`` / bench JSON / the server
    # ``check`` response + ``statespace/*`` gauges.  Purely
    # observational — engine counts are bit-identical with the report
    # on or off (tested); False drops every surface.
    statespace_report: bool = True
    # Where the rendered counterexample (counterexample.txt + .json,
    # engine/explain.py) is written automatically when a traced run
    # finds a violation.  None defers to checkpoint_dir; with neither
    # set the auto-write is disabled (CLI `check --render-trace` and
    # the `explain` subcommand still render from the in-memory trace).
    counterexample_dir: Optional[str] = None
    # -- graceful degradation (resilience/) ----------------------------
    # Catch RESOURCE_EXHAUSTED from the run (chunk dispatch, buffer
    # allocation, seen-set growth): rebuild the engine at HALF the batch
    # and continue from the newest intact snapshot (or from scratch when
    # none exists) instead of aborting — the round-5 tunnel-wedge
    # failure mode becomes a slow-but-correct run, recorded as a
    # ``degraded`` obs event.  Halving stops at min_batch; multi-host
    # process groups re-raise instead (one controller cannot rebuild
    # alone while its siblings wait in collectives — crash-level
    # recovery there is the supervisor's job).
    degrade_on_oom: bool = True
    min_batch: int = 32


@dataclasses.dataclass
class Violation:
    invariant: str
    state: PyState
    fingerprint: int


@dataclasses.dataclass
class EngineResult:
    distinct: int = 0
    generated: int = 0
    diameter: int = 0
    levels: List[int] = dataclasses.field(default_factory=list)
    # Enabled-successor count per action family (TLC's per-action
    # statistics; family name -> count; sums to ``generated``).
    action_counts: Dict[str, int] = dataclasses.field(default_factory=dict)
    # TLC-coverage snapshot (obs/coverage.py): {family: {generated,
    # distinct, disabled}}.  ``generated`` here is the same series as
    # ``action_counts`` (one packed-stats source), ``distinct`` counts
    # first FPSet insertions per family, ``disabled`` the false guard
    # evaluations.  Populated by the engines at run end.
    coverage: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    # Mean seconds per sampled chunk stage ({stage: s} + "total"), when
    # --profile-chunks ran (obs/profile.py); {} otherwise.
    chunk_stages: Dict[str, float] = dataclasses.field(default_factory=dict)
    violation: Optional[Violation] = None
    deadlock: Optional[PyState] = None
    stop_reason: str = "exhausted"
    wall_seconds: float = 0.0
    # Seen-set growth events as (capacity-after, stall-seconds) — off the
    # duration clock, recorded as evidence for up-front SEEN_CAPACITY
    # sizing (each is a rehash + retrace on the growing engine).
    growth_stalls: List = dataclasses.field(default_factory=list)
    # Which successor pipeline actually ran ("v1"/"v2"/"v3"/"v4") —
    # makes an ``auto`` fallback observable instead of a silent slowdown.
    pipeline: str = ""
    # v3/v4 only: the resolved per-stage lowering plan ({stage: "xla"|
    # "pallas"|"fused"}, ops/pipeline_v3.py / pipeline_v4.py) — a stage
    # that fell back to XLA is visible here, never a silent
    # degradation.  {} for v1/v2.
    fused_stages: Dict[str, str] = dataclasses.field(default_factory=dict)
    # ...and WHY each non-Pallas stage is what it is ({stage: reason}):
    # distinguishes a policy choice / explicit force from a kernel that
    # FAILED its build-probe ("... failed to build/probe: ...") — the
    # operator-facing half of the no-silent-degradation contract.
    fused_reasons: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Certified ample instances the run's POR table carried (0 = POR off
    # or an all-conservative certificate — either way, full expansion).
    por_instances: int = 0
    # BLEST-batched expansion grouping (models/actions.py
    # family_groups): which action families share each stacked dense
    # kernel and how many lanes each group contributes — static
    # metadata, recorded so the batched-expansion win is attributable
    # per family in the statespace report and the history ledger
    # (ROADMAP item 2a's coverage tables).  [] before the grouping.
    family_groups: List = dataclasses.field(default_factory=list)
    # Host-side per-phase wall-time breakdown for this run
    # ({phase: seconds}; obs/metrics.py phase timers): chunk dispatch,
    # stats fetch, trace flush, spill, fpset growth, checkpoint, ... —
    # embedded in bench JSON and the run_end event.
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    # TLC-parity statespace report (obs/report.py build_report):
    # collision probability, per-level table, out-degree, seen-set
    # load.  {} when EngineConfig.statespace_report is off.
    report: Dict = dataclasses.field(default_factory=dict)
    # Per-level boundary snapshots feeding the report's level table
    # ({level, frontier, distinct, generated, seen_size,
    # seen_capacity}), appended by _emit_level_event.  A resumed run's
    # pre-resume levels appear in the report with frontier width only.
    level_stats: List = dataclasses.field(default_factory=list)
    # Paths of the auto-rendered counterexample artifacts
    # (engine/explain.py write_counterexample): {"txt": ..., "json":
    # ..., "depth": n}, {} when no traced violation was rendered.
    counterexample: Dict = dataclasses.field(default_factory=dict)
    # Performance observatory block (obs/perf.py; EngineConfig.perf):
    # launch accounting, static roofline rows with achieved-bandwidth
    # fractions, and the fusion advisor's verdict.  {} when perf is
    # off; embedded in bench JSON and gated by scripts/bench_diff.py
    # --launch-drift.
    perf: Dict = dataclasses.field(default_factory=dict)

    @property
    def states_per_second(self) -> float:
        return self.distinct / self.wall_seconds if self.wall_seconds else 0.0


# Trace stores (C++-backed with Python fallback) live in engine/trace.py;
# re-exported here for compatibility.
from .trace import PyTraceStore as TraceStore  # noqa: E402
from .trace import make_trace_store  # noqa: E402


def _progress_line(res, t0, queue_rows, level_frontier, metrics=None):
    """TLC-style progress line (its ~per-minute report: states generated,
    distinct states, states left on queue), written to stderr by the
    engines when progress_interval_seconds is set, with the TLC-parity
    extras: distinct/s, generated/s, queue depth, and the fpset load
    factor.  Totals render from THIS run's result object — the registry
    can be shared across runs (the server's process-global one, warm
    engines) and its counters are cumulative, which is exactly what a
    per-run progress line must not print.  The per-run rates/gauges are
    pushed to the registry first; the load factor reads the seen-set
    gauges the engines keep current (run-scoped by construction)."""
    import sys as _sys
    dt = max(time.time() - t0, 1e-9)
    load = 0.0
    if metrics is not None:
        metrics.gauge("engine/queue_rows", queue_rows)
        metrics.gauge("engine/level_frontier", level_frontier)
        metrics.gauge("engine/states_per_sec", res.distinct / dt)
        metrics.gauge("engine/generated_per_sec", res.generated / dt)
        seen_cap = metrics.gauge_value("engine/seen_capacity")
        load = (metrics.gauge_value("engine/seen_size") / seen_cap
                if seen_cap else 0.0)
    print(f"progress: {res.generated:,} generated "
          f"({res.generated / dt:,.0f}/s), "
          f"{res.distinct:,} distinct ({res.distinct / dt:,.0f}/s), "
          f"diameter {res.diameter} (expanding {level_frontier:,}), queue "
          f"{queue_rows:,}, fpset load {load:.2f}, elapsed {dt:,.0f}s",
          file=_sys.stderr)


def _exit_condition_hit(conds, res, queue_rows):
    """First tripped TLCGet budget, as its stop_reason — or None.
    ``conds`` holds only the counters without native budget fields
    (utils/cfg.py routes duration/diameter to max_seconds/max_diameter)."""
    live = {"distinct": res.distinct, "generated": res.generated,
            "queue": queue_rows}
    for counter, threshold in conds:
        if live[counter] > threshold:
            return f"{counter}_budget"
    return None


def build_root_check(inv_fns, fingerprint):
    """jit'd ``StateBatch batch -> (inv ids, fp_hi, fp_lo)``.

    Root states are invariant-checked on their *unpacked* int32 encoding:
    the uint8 row packing wraps out-of-range values (a hand-crafted or
    randomized root with matchIndex = -1 becomes 255, a legal Nat), so a
    post-packing TypeOK check would miss them.  TLC checks invariants on
    initial states before exploration; the engines do the same, on the
    exact values given.  Kernel-produced successors are in-range by
    construction and need no such pass."""
    def check(batch):
        inv = jax.vmap(build_inv_id(inv_fns))(batch)
        fph, fpl = jax.vmap(fingerprint)(batch)
        return inv, fph, fpl
    return jax.jit(check)


def _auto_capacities(sw: int, batch: int,
                     record_trace: bool) -> Tuple[int, int]:
    """(queue rows, seen keys) sized from the device's reported HBM.

    Budget (after a 25% headroom for XLA temporaries and the candidate
    buffers): half to the three level queues (current, next, and the
    async-spill spare; + trace buffer when tracing), a quarter to the
    fingerprint table (8 B/slot).  TLC has no equivalent — its queue and
    FPSet page to disk; here the spill path plays that role and these
    sizes only set the device-resident working set.  Falls back to modest
    defaults when the backend reports no limit (virtual CPU devices)."""
    limit = None
    try:
        stats = jax.devices()[0].memory_stats()
        if stats:
            limit = int(stats.get("bytes_limit", 0)) or None
    except Exception:
        limit = None
    if limit is None:
        try:
            is_tpu = jax.devices()[0].platform == "tpu"
        except Exception:
            is_tpu = False
        if is_tpu:
            # Tunnel backends (axon) report no memory stats; assume a
            # v5e-class 16 GB HBM rather than collapsing to CPU-test
            # sizes — an undersized seen-set costs a growth-rehash (and a
            # chunk recompile) per doubling on big runs.
            limit = 16 << 30
        else:
            return 1 << 20, 1 << 22
    usable = int(limit * 0.75)
    row_cost = 3 * sw + (20 if record_trace else 0)   # queues + trace row
    q = max(batch, min(usable // 2 // row_cost, 1 << 25))
    s = max(1 << 18, min(usable // 4 // 8, 1 << 28))
    return q, s


def _resolve_insert(requested: str):
    """EngineConfig.insert_method -> the FPSet insert function."""
    if requested == "xla":
        return fpset.insert
    if requested == "pallas":
        from ..ops import fpset_pallas
        return fpset_pallas.insert
    raise ValueError(f"insert_method must be xla/pallas, got {requested!r}")


def resolve_por(cfg: EngineConfig, dims, invariants, constraint):
    """EngineConfig.por/por_table -> a verified analysis.por.PorTable or
    None (POR off).  Shared by the single-chip and mesh engines.

    A path loads the versioned artifact (fingerprint-checked — a
    hand-edited mask is rejected there); ``por=True`` without a table
    certifies in-process against exactly this run's invariants and
    constraint.  Either way ``check_table`` gates admission: model
    signature, instance count, and predicate coverage must match the
    run, so a certificate can never be applied outside the conditions
    it was proved under."""
    if not cfg.por and cfg.por_table is None:
        return None
    from ..analysis import por as por_mod
    table = cfg.por_table
    if isinstance(table, str):
        table = por_mod.load_table(table)
    if table is None:
        table = por_mod.build_table(dims, invariants=dict(invariants),
                                    constraint=constraint)
    por_mod.check_table(table, dims,
                        invariant_names=list(invariants),
                        has_constraint=constraint is not None)
    return table


def por_device_arrays(table):
    """(mask, priority) jnp arrays for a verified table, or (None, None)
    when there is nothing to mask — an all-conservative certificate
    (certified == 0) compiles the EXACT pre-POR chunk program, paying
    zero hot-path arithmetic for a mask that provably changes nothing.
    Shared by both engines so the fast-path rule can never drift."""
    if table is None or not table.certified:
        return None, None
    return jnp.asarray(table.ample_mask), jnp.asarray(table.priority)


def _resolve_pipeline(requested: str, dims):
    """EngineConfig.pipeline -> a v2 pipeline object or None (v1).

    Under ``auto``, only :class:`~..models.actions2.V2Unavailable` (the
    variant genuinely lacks v2 kernels) selects v1 — any other error from
    kernel construction propagates, so a bug in a variant's
    ``build_extra_v2`` can never silently degrade to the slow path.  The
    resolved choice is recorded on ``EngineResult.pipeline``.

    "v3"/"v4" share v2's delta kernels (same semantics, hence the same
    variant requirement and the same hard failure on one without v2
    kernels); the fused-stage plan on top is the engines' business
    (ops/pipeline_v3.py / ops/pipeline_v4.py)."""
    from ..models.actions2 import V2Unavailable, build_v2
    if requested == "v1":
        return None
    if requested in ("v2", "v3", "v4"):
        return build_v2(dims)   # raises if a variant lacks v2 kernels
    if requested != "auto":
        raise ValueError(
            f"pipeline must be auto/v1/v2/v3/v4, got {requested!r}")
    try:
        return build_v2(dims)
    except V2Unavailable:
        return None             # variant without build_extra_v2 -> v1


def _family_groups_meta(dims, _v2=None):
    """Static BLEST grouping metadata (models/actions.py
    family_groups) for EngineResult/report/ledger attribution.
    Fail-soft: a variant the grouper cannot describe yields [] — the
    grouping is observability, never a failed engine build."""
    try:
        from ..models.actions import family_groups
        return family_groups(dims)
    except Exception:  # noqa: BLE001 — metadata only
        return []


def find_root_violation(root_check, encoded, init_states, batch_size,
                        inv_names) -> Optional[Violation]:
    """Run ``build_root_check``'s program over the encoded roots in
    fixed-size chunks (padding by repeating the last root so one program
    shape serves any root count); first violation wins, like TLC."""
    from ..models.schema import stack_states
    for base in range(0, len(encoded), batch_size):
        chunk = encoded[base:base + batch_size]
        pad = [chunk[-1]] * (batch_size - len(chunk))
        inv, fph, fpl = root_check(stack_states(chunk + pad))
        inv = np.asarray(inv)[:len(chunk)]
        if (inv >= 0).any():
            i = int(np.argmax(inv >= 0))
            fp = (int(np.asarray(fph)[i]) << 32) | int(np.asarray(fpl)[i])
            return Violation(invariant=inv_names[int(inv[i])],
                             state=init_states[base + i], fingerprint=fp)
    return None


class BFSEngine:
    """Exhaustive checker for one compiled (dims, invariants, constraint)."""

    def __init__(self, dims: RaftDims,
                 invariants: Optional[Dict[str, Callable]] = None,
                 constraint: Optional[Callable] = None,
                 config: Optional[EngineConfig] = None):
        self.dims = dims
        self.config = config or EngineConfig()
        cfg = self.config
        # Telemetry spine (obs/): one registry per engine unless the
        # caller shares one; the event log is opened per run.
        # ``_rebuild_at_batch`` re-enters __init__ MID-RUN (OOM
        # degradation), so an existing registry and open event log must
        # survive the re-init (parallel/mesh.py growth-path rule).
        self.metrics = (cfg.metrics or getattr(self, "metrics", None)
                        or MetricsRegistry())
        if not hasattr(self, "_evlog"):
            self._evlog = RunEventLog(None)
            self._phase_base = {}
        # Span tracer (obs/tracing.py): survives re-entrant re-inits like
        # the registry; attaching it to the registry mirrors every
        # phase_timer block into a Chrome-trace span.
        if not hasattr(self, "tracer"):
            self.tracer = SpanTracer(cfg.trace_out)
        self.metrics.tracer = self.tracer
        # Device-profiler capture is created per run (_telemetry_run);
        # the attribute must exist (and survive re-entrant re-inits) so
        # the chunk loop can always read it.
        if not hasattr(self, "_xla_capture"):
            self._xla_capture = None
        # Per-stage chunk profiler (obs/profile.py; --profile-chunks).
        # Rebuilt on re-entrant init: its stage programs are shaped by
        # the (possibly halved) batch.  --perf implies sparse profiling
        # (every 16th call) when no cadence was chosen: the roofline's
        # achieved-bandwidth fractions need measured stage means.
        # None = unset (perf may imply a cadence); 0 = explicitly OFF
        # (BENCH_PROFILE_CHUNKS=0) — perf must not re-enable it.
        prof_every = (cfg.profile_chunks_every
                      if cfg.profile_chunks_every is not None
                      else (16 if cfg.perf else None))
        if prof_every:
            from ..obs import ChunkProfiler
            prof_k = compact_mod.choose_k(cfg.batch, dims.n_instances,
                                          cfg.compact_lanes)
            self._profiler = ChunkProfiler(
                dims, batch=cfg.batch, lanes=prof_k,
                # Same 8*K floor the engine's own table gets (below):
                # a table smaller than one sample's K keys would saturate
                # from the first insert and time a pathological probe.
                seen_capacity=max(
                    min(cfg.seen_capacity or (1 << 20), 1 << 22),
                    8 * prof_k),
                compact_method=cfg.compact_method,
                # v3/v4 runs are profiled at the fused-stage
                # granularity (v3: masks / compact / fingerprint /
                # insert_enqueue; v4: front / insert_enqueue); v1/v2
                # keep the classical decomposition so the NORTHSTAR
                # budget rows stay comparable across PRs.
                pipeline=(cfg.pipeline
                          if cfg.pipeline in ("v3", "v4") else "v1"),
                v3_force=(cfg.v4_force_stages if cfg.pipeline == "v4"
                          else cfg.v3_force_stages),
                every=prof_every, metrics=self.metrics)
        else:
            self._profiler = None
        if cfg.checkpoint_dir:
            # Fail at construction, not at the first level-boundary write.
            from . import checkpoint as _ckpt
            _ckpt.check_dims_checkpointable(dims)
        self.inv_names = list((invariants or {}).keys())
        self._inv_fns = inv_fns = list((invariants or {}).values())
        self._constraint = constraint
        expand = build_expand(dims)
        fingerprint = build_fingerprint(dims)
        pack_ok = build_pack_guard(dims)
        self._v2 = _resolve_pipeline(cfg.pipeline, dims)
        insert_fn = _resolve_insert(cfg.insert_method)
        # Partial-order reduction table (analysis/por.py): verified
        # before any mask is applied; None = full expansion.  Survives
        # the re-entrant OOM-degrade __init__ (same rule as the registry
        # above): the verified table is batch-independent, and
        # re-resolving mid-degrade would re-trace every kernel — or
        # re-read an artifact file that may be gone — exactly while the
        # process is under memory pressure.
        if not hasattr(self, "_por_table"):
            self._por_table = resolve_por(
                cfg, dims, dict(zip(self.inv_names, inv_fns)), constraint)
        por_mask, por_priority = por_device_arrays(self._por_table)
        sw = state_width(dims)
        B, G = cfg.batch, dims.n_instances
        # Compacted-candidate lanes (ops/compact.py owns the invariants).
        K = compact_mod.choose_k(B, G, cfg.compact_lanes)
        qreq, sreq = cfg.queue_capacity, cfg.seen_capacity
        if qreq is None or sreq is None:
            auto_q, auto_s = _auto_capacities(sw, B, cfg.record_trace)
            qreq = auto_q if qreq is None else qreq
            sreq = auto_s if sreq is None else sreq
        # The table is floored at 8 worst-case batches of keys: the device
        # loop stops for growth at half-full, so a single batch can then
        # push the load at most to 1/2 + 1/8 — far from where double-hash
        # probes start failing.  (fpset rounds up to a power of two.)
        self._seen_cap = max(sreq, 8 * K)
        # Queue capacity: floored at one worst-case batch (K rows, every
        # compacted candidate new) — a batch entering at/below the spill
        # watermark (Q - K) can then never overflow.  Rounded to a multiple
        # of B for tidy level slicing.  The device allocation carries PAD
        # extra rows past Q: B so the batch dynamic_slice near the queue
        # end never clamps (a clamp would silently re-window the slice),
        # and K of scatter "trash" so masked-off enqueue lanes each write
        # to their own distinct address beyond the live region — a shared
        # drop index serializes the TPU scatter (ops/fpset.py design note
        # 3).  Rounded copies kept on self — the config is not mutated.
        Q = max(-(-qreq // B) * B, K)
        PAD = max(B, K)
        self._sw, self._B, self._G, self._Q = sw, B, G, Q
        self._K, self._PAD = K, PAD

        def absorb(crows, en, parent_hi, parent_lo, actions,
                   qnext, next_count, seen):
            """Shared tail: hash-insert candidates (which both dedups the
            batch and probes/updates the FPSet in one pass — no sorts),
            enqueue, report.  ``crows`` [K,SW] flat rows, ``en`` [K]
            validity.  The StateBatch views are re-sliced from ``crows`` so
            the rows are the only materialized candidate buffer."""
            k = crows.shape[0]
            cands = jax.vmap(unflatten_state, (0, None))(crows, dims)
            fph, fpl = jax.vmap(fingerprint)(cands)
            seen, new, fail = insert_fn(seen, fph, fpl, en)
            n_new = jnp.sum(new, dtype=_I32)

            if inv_fns:
                inv = jax.vmap(build_inv_id(inv_fns))(cands)
            else:
                inv = jnp.full((k,), -1, _I32)
            viol = new & (inv >= 0)
            viol_any = jnp.any(viol)
            vpos = jnp.argmax(viol)

            if constraint is not None:
                cons_ok = jax.vmap(constraint)(cands)
            else:
                cons_ok = jnp.ones((k,), bool)
            enq = new & cons_ok
            pos = next_count + jnp.cumsum(enq.astype(_I32)) - 1
            # Disabled lanes scatter to distinct trash rows past Q (PAD =
            # max(B, K) >= k guarantees room) — a single shared trash index
            # would serialize the scatter on TPU (ops/fpset.py design note 3).
            pos = jnp.where(enq, pos, Q + jnp.arange(k, dtype=_I32))
            qnext = qnext.at[pos].set(crows, mode="drop")
            next_count = next_count + jnp.sum(enq, dtype=_I32)

            # Compacted trace records for the n_new fresh states.  Non-new
            # lanes spread over k..2k-1 trash slots (sliced off below) — a
            # single shared drop index would serialize the five scatters
            # (ops/fpset.py design note 3).
            tpos = jnp.where(new, jnp.cumsum(new.astype(_I32)) - 1,
                             k + jnp.arange(k, dtype=_I32))

            def compact(x):
                return jnp.zeros((2 * k,), x.dtype).at[tpos].set(x)[:k]

            tr = (compact(fph), compact(fpl),
                  compact(parent_hi), compact(parent_lo), compact(actions))
            vinfo = (viol_any, inv[vpos], crows[vpos], fph[vpos], fpl[vpos])
            return qnext, next_count, seen, n_new, fail, tr, vinfo

        def ingest(rows, valid, qnext, next_count, seen):
            sent = jnp.zeros(rows.shape[:1], jnp.uint32)
            acts = jnp.full(rows.shape[:1], -1, _I32)
            return absorb(rows, valid, sent, sent, acts,
                          qnext, next_count, seen)

        # -- the device-resident level loop --------------------------------
        # One host round-trip over the TPU tunnel costs orders of magnitude
        # more than one batch of device work, so the per-level batch loop
        # runs ON DEVICE as a lax.while_loop processing up to
        # ``sync_every`` batches per call, accumulating every scalar the
        # host needs into ONE packed int32 stats vector (a single fetch).
        # Trace records accumulate in a device buffer flushed per chunk.
        # The loop exits early on violation / deadlock / overflow /
        # trace-buffer pressure; the host inspects the packed stats and
        # fetches the few relevant rows only when a flag is set.
        CH = self._CH = max(1, cfg.sync_every)
        # Trace-buffer rows: enough that a fresh chunk (tcount=0) always
        # has room for >= 1 batch (<= K new states), else the loop could
        # make no progress.  With tracing off the buffers shrink to stubs
        # and every trace scatter (and the parents-only fingerprint pass)
        # compiles out — raw-throughput runs pay nothing for the feature.
        record_static = cfg.record_trace
        TQ = Q + K if record_static else 8
        # None (config default) = TLC's default: deadlock checking on.
        self._check_deadlock = (True if cfg.check_deadlock is None
                                else cfg.check_deadlock)
        check_deadlock_static = self._check_deadlock
        # The next-level queue must always have room for one worst-case
        # batch (every compacted candidate new): the device loop stops at
        # this watermark and the host spills the queue to its memory
        # (TLC's disk-backed state queue, SURVEY §2.4 R8).  Q >= K, so a
        # batch always runs when the count is at/below the watermark and
        # can never overflow; when Q == K exactly (tiny test configs)
        # every batch triggers a spill — correct, just not fast.
        QTH = Q - K
        self._QTH = QTH
        compactor = compact_mod.build_compactor(
            B, G, K, method=cfg.compact_method)
        # v3: resolve the fused-stage plan (ops/pipeline_v3.py) — Pallas
        # compact + the fused insert->enqueue tail where they lower,
        # automatic per-stage XLA fallback (with recorded reasons)
        # everywhere else.  The split stages below stay exactly the v2
        # lowerings, so a fully-fallen-back v3 compiles the v2 program.
        fused_tail = None
        fused_front = None
        enqueue_method = cfg.enqueue_method
        if cfg.pipeline == "v3":
            from ..ops import pipeline_v3
            self._v3_plan = pipeline_v3.resolve_plan(
                B, G, K, Q=Q, sw=sw, mesh=False,
                enqueue_method=cfg.enqueue_method,
                force=cfg.v3_force_stages)
            if self._v3_plan.compactor is not None:
                compactor = self._v3_plan.compactor
            fused_tail = self._v3_plan.tail
            enqueue_method = self._v3_plan.enqueue_method
        elif cfg.pipeline == "v4":
            # v4: the whole-chunk plan (ops/pipeline_v4.py) — the front
            # megakernel needs the run's model context (v2 kernels,
            # constraint, invariant list, POR arrays), which only this
            # build site has.
            from ..ops import pipeline_v4
            self._v3_plan = pipeline_v4.resolve_plan(
                B, G, K, Q=Q, sw=sw, mesh=False,
                enqueue_method=cfg.enqueue_method,
                force=cfg.v4_force_stages,
                front_ctx={"dims": dims, "v2": self._v2,
                           "constraint": constraint, "inv_fns": inv_fns,
                           "por_mask": por_mask,
                           "por_priority": por_priority})
            if self._v3_plan.compactor is not None:
                compactor = self._v3_plan.compactor
            fused_front = self._v3_plan.front
            fused_tail = self._v3_plan.tail
            enqueue_method = self._v3_plan.enqueue_method
        else:
            self._v3_plan = None

        # The per-batch pipeline body is shared with the mesh engine
        # (engine/chunk.py) — only the insert function differs.
        chunk_body = build_chunk_body(
            dims=dims, expand=expand, fingerprint=fingerprint,
            pack_ok=pack_ok, inv_fns=inv_fns, constraint=constraint,
            B=B, G=G, K=K, Q=Q, TQ=TQ, record_static=record_static,
            compactor=compactor, insert_fn=insert_fn, v2=self._v2,
            enqueue_method=enqueue_method,
            por_mask=por_mask, por_priority=por_priority,
            fused_tail=fused_tail, fused_front=fused_front)

        def chunk(qcur, cur_count, offset0, qnext, next_count, seen,
                  tbuf, tcount0, max_steps):
            # ``max_steps`` (<= CH) is a runtime argument: near a duration
            # budget the host shrinks it so the deadline is honored to
            # within ~one batch, not one whole chunk (TLCGet("duration")
            # promptness — Smokeraft.tla:90).
            init = (offset0, jnp.int32(0), qnext, next_count, seen, tbuf,
                    tcount0, jnp.int32(0), jnp.int32(0), jnp.int32(0),
                    jnp.bool_(False), jnp.zeros((sw,), jnp.uint8),
                    jnp.bool_(False), jnp.int32(-1),
                    jnp.zeros((sw,), jnp.uint8),
                    jnp.uint32(0), jnp.uint32(0), jnp.bool_(False),
                    jnp.zeros((len(dims.family_sizes),), _I32),
                    jnp.zeros((len(dims.family_sizes),), _I32),
                    jnp.int32(0),
                    jnp.zeros((len(dims.family_sizes),), _I32))

            def cond(c):
                (offset, steps, _qn, next_count, seen_c, _tb, tcount,
                 _g, _n, ovfc, dead_any, _dr, viol_any, _vi, _vr, _vh,
                 _vl, fail_any, _fam, _famn, _exp, _famp) = c
                more = (offset < cur_count) & (steps < max_steps)
                qroom = next_count <= QTH       # host spills past this
                # Stop for growth at half-full: the host doubles the table
                # before the load can reach probe-failure territory.  A
                # chunk always enters at <= half-full (growth guarantees
                # it), so its first batch always runs.
                sroom = seen_c.size <= seen_c.hi.shape[0] // 2
                stop = viol_any | (ovfc > 0) | fail_any
                if check_deadlock_static:
                    stop = stop | dead_any
                cont = more & qroom & sroom & ~stop
                if record_static:
                    cont = cont & (tcount <= TQ - K)
                return cont

            out = jax.lax.while_loop(
                cond, lambda c: chunk_body(qcur, cur_count, c), init)
            (offset, steps, qnext, next_count, seen, tbuf, tcount,
             gen, newc, ovfc, dead_any, drow, viol_any, vinv, vrow,
             vhi, vlo, fail_any, fam_counts, fam_new, expanded,
             fam_pruned) = out
            # fam_counts/fam_new/expanded/fam_pruned ride in the SAME
            # packed vector — the loop's one-fetch-per-call contract is
            # load-bearing over the tunnel.  Layout: 13 scalars, then
            # the per-family generated counts, then the per-family novel
            # counts, then the per-family POR-pruned counts
            # (obs/coverage.py reads the host side).
            stats = jnp.concatenate([jnp.stack([
                offset, steps, next_count, seen.size, tcount, gen, newc,
                ovfc, dead_any.astype(_I32), viol_any.astype(_I32), vinv,
                fail_any.astype(_I32), expanded]), fam_counts, fam_new,
                fam_pruned])
            return (qnext, seen, tbuf, stats, drow, vrow,
                    jnp.stack([vhi, vlo]))

        def fp_rows(rows):
            return jax.vmap(fingerprint)(
                jax.vmap(unflatten_state, (0, None))(rows, dims))

        self._chunk = jax.jit(chunk, donate_argnums=(3, 5, 6))
        self._ingest = jax.jit(ingest, donate_argnums=(2, 4))
        # Performance observatory (obs/perf.py; EngineConfig.perf):
        # trace THE chunk program just built — the exact jaxpr the jit
        # above compiles, v2/v3/POR/fused-tail included — for the
        # static launch model, plus the shared stage programs for the
        # roofline traffic floors.  Fail-soft: a model that cannot be
        # built (exotic jaxpr the walk has no rule for) degrades to a
        # null perf block at run end, never a failed engine build.
        self._perf = None
        if cfg.perf:
            from ..obs import perf as perf_mod
            i32 = jax.ShapeDtypeStruct((), jnp.int32)
            qav = jax.ShapeDtypeStruct((Q + PAD, sw), jnp.uint8)
            seen_av = jax.eval_shape(
                lambda: fpset.empty(self._seen_cap))
            ta = TQ + K if record_static else 8
            tbuf_av = tuple(
                jax.ShapeDtypeStruct((ta,), d)
                for d in (jnp.uint32, jnp.uint32, jnp.uint32,
                          jnp.uint32, _I32))
            self._perf = perf_mod.build_accounting(
                pipeline=(cfg.pipeline
                          if cfg.pipeline in ("v3", "v4")
                          else "v2" if self._v2 is not None
                          else "v1"),
                chunk_fn=chunk,
                chunk_avals=(qav, i32, i32, qav, i32, seen_av,
                             tbuf_av, i32, i32),
                dims=dims, B=B, K=K,
                compact_method=cfg.compact_method,
                v3_force=(cfg.v4_force_stages if cfg.pipeline == "v4"
                          else cfg.v3_force_stages),
                plan=self._v3_plan,
                metrics=self.metrics)
        self._fp_rows = jax.jit(fp_rows)
        self._expand1 = jax.jit(expand)
        self._fp_batch = jax.jit(jax.vmap(fingerprint))
        self._root_check = (build_root_check(inv_fns, fingerprint)
                            if inv_fns else None)
        self._TQ = TQ
        # Allocated trace rows: live region + K trash slots for the
        # masked-off scatter lanes (stub when tracing is off).
        self._TA = TQ + K if record_static else 8

    # ------------------------------------------------------------------
    def run(self, init_states: Optional[List[PyState]] = None,
            resume=None) -> EngineResult:
        """Run to exhaustion (or budget/violation).  Pass either
        ``init_states`` for a fresh run or ``resume`` (a
        ``checkpoint.Checkpoint`` or a path to one) to continue an
        interrupted run from its last level-boundary snapshot.

        Telemetry wrapper: opens the run event log (EngineConfig.
        events_out), brackets the run with run_start/run_end events, and
        scopes the per-phase wall-time breakdown to this run
        (``EngineResult.phases``) even on a warm, reused engine."""
        return self._telemetry_run(self._run_degradable, init_states,
                                   resume=resume)

    # ------------------------------------------------------------------
    def _run_degradable(self, init_states, resume=None):
        """Graceful degradation under resource exhaustion (resilience/):
        retry ``_run_impl`` at half the batch when the device reports
        RESOURCE_EXHAUSTED, continuing from the newest intact snapshot —
        slow-but-correct instead of dead.  Shared with the mesh engine
        via duck typing (``_rebuild_at_batch`` is per-class).

        Restarting from a checkpoint is the only SAFE recovery: the
        chunk/ingest programs donate the next-queue, seen-set, and trace
        buffers, so after a failed dispatch the in-flight device state
        is gone — a level-boundary snapshot (or the original roots) is
        the nearest consistent image."""
        from . import checkpoint as ckpt_mod
        from ..parallel import multihost as mh
        cfg = self.config
        # Stale-dir guard (supervisor.py rule): snapshot names already in
        # the dir belong to a PREVIOUS run unless the caller asked to
        # resume — a fresh run must never degrade into a foreign image
        # (load() validates only dims, not cfg/bounds).  Names, not
        # contents: listdir is cheap enough to pay on every run.
        user_resume = resume is not None
        preexisting = (set(os.listdir(cfg.checkpoint_dir))
                       if cfg.checkpoint_dir
                       and os.path.isdir(cfg.checkpoint_dir) else set())
        while True:
            try:
                return self._run_impl(init_states, resume=resume)
            except Exception as e:
                if not (cfg.degrade_on_oom and is_resource_exhausted(e)):
                    raise
                if mh.is_multiprocess():
                    # One controller rebuilding alone would deadlock its
                    # siblings' collectives; the supervisor restarts the
                    # whole process group instead.
                    raise
                new_batch = self.config.batch // 2
                if new_batch < max(1, cfg.min_batch):
                    raise
                ck = (ckpt_mod.latest(cfg.checkpoint_dir)
                      if cfg.checkpoint_dir else None)
                if ck is not None and not user_resume \
                        and os.path.basename(ck) in preexisting:
                    ck = None          # foreign snapshot: scratch restart
                if ck is not None:
                    resume = ck
                elif resume is None and init_states is None:
                    raise       # resumed run, snapshot gone: nothing left
                self._evlog.emit(
                    "degraded", reason="resource_exhausted",
                    error=f"{type(e).__name__}: {str(e)[:300]}",
                    batch=self.config.batch, new_batch=new_batch,
                    resume_from=ck, memory=device_memory_stats())
                self.metrics.counter("engine/degraded")
                import sys as _sys
                print(f"degraded: RESOURCE_EXHAUSTED; retrying at batch "
                      f"{new_batch}"
                      + (f", resuming {ck}" if ck else ""),
                      file=_sys.stderr)
                with self.metrics.phase_timer("degrade_rebuild"):
                    self._rebuild_at_batch(new_batch)

    def _rebuild_at_batch(self, new_batch: int) -> None:
        """Recompile every program at a smaller batch (re-entrant
        __init__, the parallel/mesh.py growth-path pattern); the open
        event log / metrics registry survive."""
        BFSEngine.__init__(
            self, self.dims,
            invariants=dict(zip(self.inv_names, self._inv_fns)),
            constraint=self._constraint,
            config=dataclasses.replace(self.config, batch=new_batch))

    def _telemetry_run(self, impl, init_states, resume=None):
        """Shared run_start/run_end bracketing (single-chip and mesh):
        event log, run/level spans, coverage + chunk-profile run-end
        reporting, the Chrome-trace write-out — and the flight
        recorder's arm/disarm cycle: the black box is armed for the
        whole run (postmortem on any abnormal death), and disarmed on
        every completed run regardless of stop_reason."""
        cfg, mt = self.config, self.metrics
        self._evlog = evlog = RunEventLog(self._events_path())
        self._phase_base = mt.phase_seconds()
        # Observed-collision base: the counter is process-cumulative
        # (shared registries — server, warm engines), the report's
        # "observed dual-key collisions" is per-run.
        self._collision_base = mt.counter_value("engine/fp_collisions")
        self.coverage = None        # _run_impl installs this run's own
        prof = getattr(self, "_profiler", None)
        if prof is not None:
            prof.reset()            # warm engines: samples are per-run
        pf = getattr(self, "_perf", None)
        if pf is not None:
            pf.reset()              # launch/level accumulators per run
        # Device-HBM watermark (level-correlated OOM evidence): per-run
        # high-water mark, re-armed here so a warm shared registry
        # never carries a previous run's peak into this run's levels.
        self._hbm_watermark = 0
        if self.tracer.enabled:
            self.tracer.reset()     # one trace file = one run
        # Black box armed before the first event so run_start itself is
        # in the ring; the context snapshot is what the watch console
        # shows as "what is running" (pipeline + resolved fused plan).
        _FLIGHT.arm(
            self._postmortem_path(), metrics=mt,
            context={
                "engine": type(self).__name__, "dims": repr(self.dims),
                "batch": cfg.batch, "resume": resume is not None,
                "pipeline": (cfg.pipeline
                             if getattr(self, "_v3_plan", None)
                             is not None
                             else "v2" if getattr(self, "_v2", None)
                             is not None else "v1"),
                "fused_stages": (dict(self._v3_plan.stages)
                                 if getattr(self, "_v3_plan", None)
                                 is not None else {}),
                # Caller-attributed identity (job/tenant tags from the
                # serving layer) rides the same context record.
                **dict(cfg.run_context_extra or {})})
        _FLIGHT.set_live_evlog(evlog)
        # Device-profiler capture is per-run (the window opens at the
        # first chunk call, after warm-up compilation).
        if cfg.xla_profile_chunks:
            from ..obs import XlaProfileCapture
            self._xla_capture = XlaProfileCapture(
                self._xla_profile_dir(), cfg.xla_profile_chunks)
        else:
            self._xla_capture = None
        run_t0 = self._lvl_t0 = time.perf_counter()
        evlog.emit(
            "run_start", engine=type(self).__name__, dims=repr(self.dims),
            batch=cfg.batch, sync_every=cfg.sync_every,
            record_trace=cfg.record_trace, resume=resume is not None,
            memory=device_memory_stats())
        self._cur_res = None
        err = None
        try:
            res = impl(init_states, resume=resume)
            return res
        except BaseException as e:
            err = e
            raise
        finally:
            res = self._cur_res
            phases = phase_delta(mt.phase_seconds(), self._phase_base)
            if res is not None:
                res.phases = phases
            cov = self.coverage
            if res is not None and cov is not None:
                res.coverage = cov.snapshot()
                cov.feed_metrics(mt)
                if cov.total_generated:
                    # Final coverage snapshot: the series the progress-
                    # interval events sampled, closed at run end.
                    evlog.emit("coverage", final=True,
                               level=res.diameter, actions=res.coverage)
                if cfg.progress_interval_seconds:
                    # TLC prints its coverage statistics at the end of a
                    # run with reporting enabled; same cadence knob here.
                    import sys as _sys
                    print(cov.render_table(), file=_sys.stderr)
            # Counterexample auto-render (engine/explain.py): a traced
            # violation writes <workdir>/counterexample.{txt,json}
            # BEFORE the run_end emit so the event carries the path.
            # A render failure (e.g. a detected fingerprint collision
            # diverging the replay) is reported, never allowed to mask
            # the run's own verdict.
            ce_path = None
            ce_dir = cfg.counterexample_dir or cfg.checkpoint_dir
            if (err is None and res is not None
                    and res.violation is not None
                    and cfg.record_trace and ce_dir):
                try:
                    from .explain import write_counterexample
                    res.counterexample = write_counterexample(
                        self, res, ce_dir,
                        basename=self._counterexample_base())
                    ce_path = res.counterexample["txt"]
                except Exception as e:
                    import sys as _sys
                    print(f"counterexample render failed: "
                          f"{type(e).__name__}: {e}", file=_sys.stderr)
            # TLC-parity statespace report (obs/report.py): host-side
            # assembly over counters the loop already fetched — its own
            # ``statespace`` event, ``statespace/*`` gauges, and the
            # TLC-style stderr block on progress-enabled runs (the same
            # cadence rule as the coverage table above).
            if cfg.statespace_report and res is not None and err is None:
                from ..obs import report as report_mod
                observed = int(mt.counter_value("engine/fp_collisions")
                               - self._collision_base)
                res.report = report_mod.build_report(
                    res, coverage=cov, level_stats=res.level_stats,
                    seen_capacity=int(mt.gauge_value(
                        "engine/seen_capacity")) or None,
                    seen_size=int(mt.gauge_value("engine/seen_size")),
                    observed_collisions=observed)
                report_mod.feed_metrics(res.report, mt)
                evlog.emit("statespace", report=res.report)
                if cfg.progress_interval_seconds:
                    import sys as _sys
                    print(report_mod.render_report(res.report),
                          file=_sys.stderr)
            # Re-read the profiler: OOM degradation re-enters __init__,
            # which rebuilds it for the halved batch — the run-end
            # report must come from the object that took the most
            # recent samples, not the pre-degrade one captured above.
            prof = getattr(self, "_profiler", None)
            if prof is not None:
                if res is not None:
                    res.chunk_stages = prof.stage_means()
                prof.finish(evlog)
            # Performance observatory (obs/perf.py): assemble the perf
            # block AFTER the profiler lands its means (the roofline's
            # measured half), emit the ``perf`` event + gauges, print
            # the run-end table.  Skipped on error exits — a crashed
            # run's perf numbers would price a partial loop.
            pf = getattr(self, "_perf", None)
            if pf is not None and err is None and res is not None:
                try:
                    res.perf = pf.finish(evlog,
                                         chunk_stages=res.chunk_stages)
                except Exception as e:
                    import sys as _sys
                    print(f"perf: block assembly failed "
                          f"({type(e).__name__}: {e})", file=_sys.stderr)
            # Device-profiler window: close it (early-exit runs) and
            # land the xla_profile event whether the run lived or died.
            cap = getattr(self, "_xla_capture", None)
            if cap is not None:
                cap.finish(evlog)
            # Postmortem: an exception escaping the run is an ABNORMAL
            # end — dump the black box and stamp the path into run_end
            # so the dump is discoverable from the event log alone.
            # (SIGTERM / fault-kill deaths never reach here; their
            # dumps come from the signal handler / faults._die.)
            pm_path = None
            if err is not None:
                pm_path = _FLIGHT.dump(
                    f"run error: {type(err).__name__}: {err}")
                if pm_path is not None:
                    evlog.emit("postmortem", dump={
                        "path": pm_path, "reason": "run_error"})
            evlog.emit(
                "run_end",
                stop_reason=(getattr(res, "stop_reason", None)
                             if err is None else "error"),
                error=(f"{type(err).__name__}: {err}" if err is not None
                       else None),
                postmortem_path=pm_path,
                # Where the rendered counterexample landed (None when no
                # traced violation was rendered) — the event log alone
                # locates the artifact, like postmortem_path.
                counterexample_path=ce_path,
                distinct=getattr(res, "distinct", None),
                generated=getattr(res, "generated", None),
                diameter=getattr(res, "diameter", None),
                # Full per-level frontier sizes: chaos_check.py compares
                # supervised vs. uninterrupted runs on this field.
                levels=list(getattr(res, "levels", None) or []),
                wall_seconds=getattr(res, "wall_seconds", None),
                growth_stalls=len(getattr(res, "growth_stalls", ())),
                phase_seconds=phases, memory=device_memory_stats(),
                # Peak host RSS + one probe per visible device; CPU-only
                # platforms report {} per device rather than omitting
                # the field (obs/events.py guards).
                host_rss_peak_bytes=peak_host_rss_bytes(),
                devices_memory=all_device_memory_stats())
            _FLIGHT.set_live_evlog(None)
            _FLIGHT.disarm()     # completed or already-dumped: no atexit dump
            evlog.close()
            self._evlog = RunEventLog(None)
            if self.tracer.enabled:
                self.tracer.complete(
                    "run", run_t0, engine=type(self).__name__,
                    stop_reason=getattr(res, "stop_reason", None))
                self.tracer.write()

    def _events_path(self):
        """Single-controller resolution; the mesh engine overrides with
        per-host piece suffixes."""
        return events_path(self.config.events_out,
                           self.config.checkpoint_dir)

    def _postmortem_path(self):
        """Where the flight recorder dumps on an abnormal death: next to
        the checkpoints unless postmortem_dir overrides; None (no dir at
        all) disables the dump.  The mesh engine overrides with per-host
        piece suffixes, like the event log."""
        d = self.config.postmortem_dir or self.config.checkpoint_dir
        return os.path.join(d, "postmortem.json") if d else None

    def _xla_profile_dir(self):
        """--xla-profile artifact directory: explicit > next to the
        checkpoints > ./xla_profile."""
        cfg = self.config
        if cfg.xla_profile_dir:
            return cfg.xla_profile_dir
        return os.path.join(cfg.checkpoint_dir or ".", "xla_profile")

    def _counterexample_base(self) -> str:
        """Basename stem for the auto-rendered counterexample files;
        the mesh engine suffixes the controller piece id (the event-log
        model) so two controllers on a shared filesystem never race one
        file."""
        return "counterexample"

    def _emit_level_event(self, res, frontier_rows):
        """level_complete: live counters + cumulative per-phase wall-time
        breakdown.  ``unattributed_seconds`` closes the accounting —
        phases + unattributed == elapsed since run_start — so a phase
        that silently stops being timed shows up as growing slack, not a
        plausible-looking breakdown.  Also closes this level's span in
        the Chrome trace (one ``level`` span per BFS level)."""
        if self.tracer.enabled:
            self.tracer.complete("level", self._lvl_t0, level=res.diameter,
                                 frontier_rows=frontier_rows,
                                 distinct=res.distinct,
                                 generated=res.generated)
            # Level-boundary durability: a crash loses at most the
            # current level's spans (atomic rewrite, off the hot loop).
            self.tracer.write()
        self._lvl_t0 = time.perf_counter()
        evlog = self._evlog
        # Launch accounting level boundary (obs/perf.py): snapshot this
        # level's launch total so OOM/skew events correlate with launch
        # pressure per level.
        pf = getattr(self, "_perf", None)
        if pf is not None:
            pf.end_level(res.diameter)
        # Per-level device-HBM watermark: run_end's one-shot
        # devices_memory probe cannot say WHICH level drove the peak —
        # sampling here lets an OOM-degradation event be correlated
        # with the level that caused it.  Caveat jaxlib semantics:
        # ``peak_bytes_in_use`` is a PROCESS-LIFETIME allocator peak
        # (a warm engine inherits a bigger previous run's value and
        # the column then never moves), so the per-level CURRENT
        # ``bytes_in_use`` is recorded alongside it — within one run
        # the peak column says where the high-water rose, and on warm
        # processes the bytes_in_use series is the level-correlatable
        # signal.  CPU/virtual devices report no stats: the fields
        # stay None, the gauge untouched.
        mem = device_memory_stats()
        hbm_peak = mem.get("peak_bytes_in_use")
        if hbm_peak is not None:
            self._hbm_watermark = max(
                getattr(self, "_hbm_watermark", 0), int(hbm_peak))
            self.metrics.gauge("engine/device_hbm_peak_bytes",
                               self._hbm_watermark)
        # Mesh skew telemetry (parallel/mesh.py stamps _last_skew just
        # before the boundary; None on the single-chip engine).
        skew = getattr(self, "_last_skew", None)
        # Level snapshot for the statespace report's per-level table
        # (obs/report.py): frontier width + cumulative counters + the
        # seen-set gauges the chunk loop keeps current.  Host-side dict
        # appends — observational by construction.
        if self.config.statespace_report:
            row = {
                "level": res.diameter,
                "frontier": int(frontier_rows),
                "distinct": res.distinct,
                "generated": res.generated,
                "seen_size": int(self.metrics.gauge_value(
                    "engine/seen_size")),
                "seen_capacity": int(self.metrics.gauge_value(
                    "engine/seen_capacity")),
                "hbm_peak_bytes": (int(hbm_peak)
                                   if hbm_peak is not None else None),
                "hbm_bytes_in_use": (int(mem["bytes_in_use"])
                                     if mem.get("bytes_in_use")
                                     is not None else None)}
            if skew is not None:
                row["frontier_skew"] = skew.get("frontier_skew")
                row["seen_skew"] = skew.get("seen_skew")
                row["shard_frontier"] = skew.get("shard_frontier")
            res.level_stats.append(row)
        # No enabled-check: emit() mirrors every event into the flight
        # ring even on a file-less log, and the watch console's level
        # rows come from exactly this record.  The per-level phase_delta
        # below is a dict subtraction — noise next to a level of chunks.
        phases = phase_delta(self.metrics.phase_seconds(),
                             self._phase_base)
        elapsed = evlog.elapsed()
        extra = {}
        if skew is not None:
            extra = {"frontier_skew": skew.get("frontier_skew"),
                     "seen_skew": skew.get("seen_skew"),
                     "shard_frontier": skew.get("shard_frontier")}
        evlog.emit(
            "level_complete", level=res.diameter,
            frontier_rows=frontier_rows, distinct=res.distinct,
            generated=res.generated, phase_seconds=phases,
            unattributed_seconds=round(
                elapsed - sum(phases.values()), 6),
            memory=mem, **extra)

    def _run_impl(self, init_states: Optional[List[PyState]] = None,
                  resume=None) -> EngineResult:
        from . import checkpoint as ckpt_mod
        dims, cfg = self.dims, self.config
        sw, B, Q = self._sw, self._B, self._Q
        if resume is not None:
            if isinstance(resume, str):
                resume = ckpt_mod.load(resume)
            if resume.dims != dims:
                raise ValueError(
                    f"checkpoint dims {resume.dims} != engine dims {dims}")
        elif init_states is None:
            raise ValueError("need init_states or resume")
        res = EngineResult(
            pipeline=(cfg.pipeline if self._v3_plan is not None
                      else "v2" if self._v2 is not None else "v1"),
            fused_stages=(dict(self._v3_plan.stages)
                          if self._v3_plan is not None else {}),
            fused_reasons=(dict(self._v3_plan.reasons)
                           if self._v3_plan is not None else {}),
            por_instances=(self._por_table.certified
                           if self._por_table is not None else 0),
            family_groups=_family_groups_meta(dims, self._v2))
        self._cur_res = res     # run_end event reads it on error exits
        mt, evlog = self.metrics, self._evlog
        self._growth_stalls = res.growth_stalls
        # TLC-style per-action coverage for this run (obs/coverage.py):
        # fed from the packed chunk stats, reported at every progress
        # interval and at run end (_telemetry_run).
        coverage = self.coverage = ActionCoverage(dims.family_names,
                                                  dims.family_sizes)
        t_enter = time.time()   # for early returns before the budget clock
        # Trace recording off => plain dict store (never written); avoids
        # triggering the native build for runs that measure raw throughput.
        trace = make_trace_store() if cfg.record_trace else TraceStore()
        self.trace = trace

        if resume is None:
            # Root handling before warm-up: neither the root check's XLA
            # compile nor a violating root charges the duration budget (TLC
            # reports an init-state violation without starting the clock).
            encoded = [encode_state(s, dims) for s in init_states]
            if self._root_check is not None:
                with mt.phase_timer("root_check"):
                    v = find_root_violation(self._root_check, encoded,
                                            init_states, B, self.inv_names)
                if v is not None:
                    if cfg.record_trace:
                        # Depth-0 counterexample: register the violating
                        # root under the fingerprint the Violation carries
                        # so replay() yields the one-state trace instead
                        # of a KeyError.
                        trace.roots.setdefault(v.fingerprint, v.state)
                    res.violation = v
                    res.stop_reason = "violation"
                    res.levels.append(0)
                    res.wall_seconds = time.time() - t_enter
                    evlog.emit("violation", invariant=v.invariant,
                               fingerprint=hex(v.fingerprint), level=0)
                    return res
            # Only now reject unpackable roots (see schema.check_packable:
            # an invariant-flagged root is a violation, not an error).
            for e in encoded:
                check_packable(e, self.dims)
            rows_np = np.stack([flatten_state(e, dims) for e in encoded])
            # Root fingerprints for the trace store — computed (and their
            # program compiled) BEFORE the duration clock starts; root
            # registration is setup, like the warm-up below.
            if cfg.record_trace:
                with mt.phase_timer("root_check"):
                    rhi, rlo = (np.asarray(x) for x in
                                self._fp_rows(jnp.asarray(rows_np)))
                    for idx, s in enumerate(init_states):
                        fp = (int(rhi[idx]) << 32) | int(rlo[idx])
                        trace.roots.setdefault(fp, s)

        # Queues carry PAD rows past Q: slice overrun + scatter trash
        # (see the capacity comment in __init__).  Every queue buffer is
        # COMMITTED to the device explicitly: the jit cache keys on arg
        # placement, so an uncommitted jnp.zeros entering _chunk (e.g.
        # the async-spill spare at the first swap) retraces and RECOMPILES
        # the whole chunk program mid-run — ~10 s of silently charged
        # wall time on a cold compilation cache.
        dev = jax.devices()[0]
        QA = Q + self._PAD
        qcur = jax.device_put(jnp.zeros((QA, sw), jnp.uint8), dev)
        qnext = jax.device_put(jnp.zeros((QA, sw), jnp.uint8), dev)
        seen = jax.device_put(fpset.empty(self._seen_cap), dev)
        next_count = jnp.int32(0)
        # Host-resident level segments: the part of the current level that
        # does not fit the device queue (``pending``) and next-level
        # overflow drained mid-level (``spill_next``) — TLC's disk-backed
        # state queue (host RAM by default; memory-mapped files under
        # ``spill_dir`` for frontiers beyond host memory).
        from .spillpool import SpillPool
        pending = SpillPool(cfg.spill_dir)
        spill_next = SpillPool(cfg.spill_dir)
        # Async spill: a watermark drain kicks off a non-blocking D2H of
        # the full next-queue and swaps in a spare buffer, so the drain
        # overlaps the following chunks' compute; the transfer is resolved
        # (and the buffer recycled) at the next drain or level boundary.
        free_q: List = [jax.device_put(jnp.zeros((QA, sw), jnp.uint8), dev)]
        inflight: List = []        # [(device array, row count)]

        def resolve_spill():
            while inflight:
                with mt.phase_timer("spill"):
                    arr, cnt = inflight.pop(0)
                    host = np.asarray(arr)  # completes the async copy
                    # copy=True: on CPU backends np.asarray can be a
                    # zero-copy VIEW of the device buffer, which is about
                    # to be recycled and donated — and a view would also
                    # pin all QA rows.  (Disk-backed pools copy into
                    # their memmap regardless.)
                    spill_next.append(host[:cnt], copy=True)
                    free_q.append(arr)
        TA = self._TA
        tbuf = jax.device_put(
            (jnp.zeros((TA,), jnp.uint32), jnp.zeros((TA,), jnp.uint32),
             jnp.zeros((TA,), jnp.uint32), jnp.zeros((TA,), jnp.uint32),
             jnp.zeros((TA,), _I32)), dev)

        # Warm-up: run both programs once with empty inputs (no semantic
        # effect: all-invalid masks insert nothing, zero-trip chunk) so XLA
        # compilation does not count against the StopAfter duration budget —
        # TLC's TLCGet("duration") measures checking, not compilation.
        # Timed as phase "warmup": compilation is off the budget clock but
        # on the telemetry one, so event phase sums still cover the wall.
        with mt.phase_timer("warmup"):
            out = self._ingest(jnp.zeros((B, sw), jnp.uint8),
                               jnp.zeros((B,), bool),
                               qnext, next_count, seen)
            qnext, next_count, seen = out[0], out[1], out[2]
            # Placement-fixpoint second ingest (same rationale as the
            # chunk's fixpoint call below): the first real ingest passes
            # the warm-up's COMMITTED outputs back in, a different
            # argument placement than the fresh jnp.int32(0) above —
            # without this call that variant compiled ON the StopAfter
            # clock (~5 s on a cold cache, measured 2026-07-31: the whole
            # reason the literal Smokeraft.cfg's 1-second budget landed
            # at ~4 s, VERDICT r4 weak #4).
            out = self._ingest(jnp.zeros((B, sw), jnp.uint8),
                               jnp.zeros((B,), bool),
                               qnext, next_count, seen)
            qnext, next_count, seen = out[0], out[1], out[2]
            out = self._chunk(qcur, jnp.int32(0), jnp.int32(0),
                              qnext, next_count, seen, tbuf, jnp.int32(0),
                              jnp.int32(self._CH))
            qnext, seen, tbuf = out[0], out[1], out[2]
            # Second zero-trip call with the first call's OUTPUTS: jit
            # caches key on argument placement, and outputs carry
            # committed shardings that fresh allocations may not —
            # without this fixpoint call, the first real batch silently
            # recompiles the whole chunk program (~10 s) inside the
            # budget window.
            out = self._chunk(qcur, jnp.int32(0), jnp.int32(0),
                              qnext, jnp.int32(0), seen, tbuf,
                              jnp.int32(0), jnp.int32(self._CH))
            qnext, seen, tbuf = out[0], out[1], out[2]
        t0 = time.time()
        last_progress = t0
        self._batch_ema = 0.0   # measured seconds per device batch

        if resume is not None:
            # Restore the level-boundary image: re-insert the saved keys
            # into a fresh hash table, reload the frontier, counters, and
            # trace records/roots.
            n_keys = resume.seen_hi.shape[0]
            cap = self._seen_cap
            while n_keys > fpset._capacity(cap) // 2:
                cap *= 2
            seen = fpset.from_host_keys(resume.seen_hi, resume.seen_lo, cap)
            fr = np.ascontiguousarray(resume.frontier).astype(
                ROW_DTYPE, casting="safe")
            # A frontier larger than the device queue resumes as device
            # rows + host segments (same split the spill path produces).
            for i in range(Q, len(fr), Q):
                # Views, not copies: the disk-backed pool copies into its
                # memmap anyway, and the RAM pool holding views keeps the
                # resume peak at one frontier (fr stays pinned via fr[:Q]).
                pending.append(fr[i:i + Q])
            fr = fr[:Q]
            qcur = jax.device_put(
                jnp.zeros((QA, sw), jnp.uint8).at[:len(fr)].set(
                    jnp.asarray(fr)), dev)
            cur_count = len(fr)
            res.distinct = resume.distinct
            res.generated = resume.generated
            res.diameter = resume.diameter
            res.levels = list(resume.levels)
            res.action_counts = dict(resume.action_counts)
            # Coverage resumes its generated series from the checkpoint
            # so the run-end table still matches generated_by_action
            # (distinct/expanded are not checkpointed; see
            # coverage.disabled).  The registry counters are NOT seeded:
            # they are process-cumulative, and an in-process degrade
            # resume already accumulated the pre-crash increments — the
            # progress line renders per-run totals from res instead.
            coverage.seed_generated(resume.action_counts)
            # Duration (TLCGet("duration")-style) accumulates across
            # restarts: back-date t0 so wall_seconds, states/sec, and the
            # max_seconds budget all measure total checking time.
            t0 -= resume.wall_seconds
            if cfg.record_trace:
                if resume.distinct > 0 and resume.trace_fps.size == 0:
                    raise ValueError(
                        "checkpoint was written with trace recording "
                        "disabled; counterexample replay could never reach "
                        "a root — resume with record_trace=False "
                        "(--no-trace) or restart from scratch")
                trace.add_batch(resume.trace_fps, resume.trace_parents,
                                resume.trace_actions)
                trace.roots.update(resume.roots)
            elif resume.trace_fps.size > 0 and cfg.checkpoint_dir is not None:
                raise ValueError(
                    "resuming a trace-carrying checkpoint with trace "
                    "recording disabled would write trace-less snapshots "
                    "into the same directory, shadowing the intact ones "
                    "for any later trace-on resume; use a different "
                    "checkpoint_dir or keep tracing enabled")
        else:
            # Ingest initial states in B-sized chunks (roots registered
            # above, before the clock).
            for base in range(0, len(rows_np), B):
                # StopAfter applies during root ingest too (a k=4 smoke
                # run has 262k roots — TLCGet("duration") doesn't wait
                # for them).  The first wave always runs: TLC generates
                # initial states before any constraint can stop it.
                if base and cfg.max_seconds is not None \
                        and time.time() - t0 > cfg.max_seconds:
                    res.stop_reason = "duration_budget"
                    break
                if base and cfg.exit_conditions:
                    # "queue" during ingest: enqueued rows + landed spills
                    # + the roots not yet ingested.
                    hit = _exit_condition_hit(
                        cfg.exit_conditions, res,
                        int(next_count) + spill_next.total_rows()
                        + (len(rows_np) - base))
                    if hit:
                        res.stop_reason = hit
                        break
                with mt.phase_timer("ingest"):
                    chunk = rows_np[base:base + B]
                    pad = np.zeros((B - len(chunk), sw), ROW_DTYPE)
                    valid = np.arange(B) < len(chunk)
                    (qnext, next_count, seen, n_new, fail, tr,
                     vinfo) = self._ingest(
                        jnp.asarray(np.concatenate([chunk, pad])),
                        jnp.asarray(valid), qnext, next_count, seen)
                    res.distinct += int(n_new)
                mt.counter("engine/distinct", int(n_new))
                with mt.phase_timer("trace_flush"):
                    self._record(trace, tr, int(n_new))
                if bool(fail):
                    raise RuntimeError(
                        "seen-set probe failure during ingest; raise "
                        "seen_capacity")
                seen, qnext, tbuf, t0 = self._grow_precompiled(
                    seen, int(seen.size), qcur, qnext, int(next_count),
                    tbuf, t0)
                nc = int(next_count)
                if nc > self._QTH:      # spill: ingest adds <= B per call,
                    with mt.phase_timer("spill"):
                        spill_next.append(  # watermark is never blown
                            np.asarray(qnext[:nc]), copy=True)
                        next_count = jnp.int32(0)
                    evlog.emit("spill", rows=nc, level=0, where="ingest")
                if self._check_violation(res, vinfo):
                    break

            # levels[] counts enqueued (constraint-passing) states per
            # level, mirroring the oracle's frontier sizes.
            res.levels.append(int(next_count)
                              + spill_next.total_rows())
            # Seen gauges refreshed BEFORE the level-0 emit: its
            # level_stats snapshot reads them, and on a warm shared
            # registry the stale previous-run values would otherwise
            # leak into this run's level-0 row.
            mt.gauge("engine/seen_capacity", len(seen.hi))
            mt.gauge("engine/seen_size", int(seen.size))
            self._emit_level_event(res, res.levels[-1])
            qcur, qnext = qnext, qcur
            cur_count = int(next_count)
            pending, spill_next = spill_next, pending
            next_count = jnp.int32(0)

        # Seen-set gauges for the registry-rendered progress line (load
        # factor = seen_size / seen_capacity); kept current per chunk.
        mt.gauge("engine/seen_capacity", len(seen.hi))
        mt.gauge("engine/seen_size", int(seen.size))
        # A resumed run must not rewrite the snapshot it just loaded (a
        # trace-off resume would overwrite a trace-carrying file with an
        # empty trace), and its interval clock starts at the restart.
        skip_ckpt_level = resume.diameter if resume is not None else -1
        last_ckpt = time.time() if resume is not None else float("-inf")
        while (cur_count > 0 or pending) and res.violation is None \
                and res.stop_reason == "exhausted":
            if cfg.checkpoint_dir is not None \
                    and res.diameter % max(1, cfg.checkpoint_every) == 0 \
                    and res.diameter != skip_ckpt_level \
                    and (time.time() - last_ckpt
                         >= cfg.checkpoint_interval_seconds):
                with mt.phase_timer("checkpoint"):
                    self._write_checkpoint(qcur, cur_count, pending, seen,
                                           res, trace,
                                           wall=time.time() - t0)
                last_ckpt = time.time()
                evlog.emit("checkpoint", level=res.diameter,
                           distinct=res.distinct)
            if cfg.max_diameter is not None \
                    and res.diameter >= cfg.max_diameter:
                res.stop_reason = "diameter_budget"
                break
            # Level loop: each _chunk call runs up to sync_every batches on
            # device; ONE packed stats fetch (plus a trace flush) per call
            # is the only host traffic — the tunnel round-trip no longer
            # bounds states/sec.  The outer loop walks the level's
            # segments: first the device-resident rows, then any host
            # segments left by the previous level's spill.
            next_count_h = 0
            # Budgeted runs slow-start each level: batch cost is
            # data-dependent (probe-round early exits, frontier density)
            # and roughly homogeneous WITHIN a level but can jump 100x
            # between levels — so the first call of a level probes with
            # two batches (amortizing the host round-trip) to re-measure,
            # then the ramp doubles under the remaining-time bound.
            # Overshoot is thereby bounded by ~two batches at the current
            # level's cost.
            calls_in_level = 0
            while True:
                offset = 0
                while offset < cur_count:
                    # Duration-budget promptness: size this chunk call (in
                    # batches) from the measured per-batch cost so the run
                    # stops within ~one batch of the deadline, not one
                    # whole sync_every chunk past it.
                    allowed = self._CH
                    if cfg.max_seconds is not None:
                        remaining = cfg.max_seconds - (time.time() - t0)
                        if remaining <= 0:
                            res.stop_reason = "duration_budget"
                            break
                        if self._batch_ema:
                            # Half the remaining budget per call, capped
                            # by the per-level slow-start ramp.  The ramp
                            # starts at 2 batches so the per-call host
                            # round-trip amortizes over the probe and
                            # does not lock the (jump-up, decay-slow)
                            # estimator at RTT-dominated cost.
                            allowed = max(1, min(
                                self._CH,
                                int(remaining / (2 * self._batch_ema)),
                                2 << min(calls_in_level, 9)))
                        else:
                            # No cost estimate yet: probe with one batch
                            # so the first call can't blow the deadline
                            # by a whole sync_every chunk.
                            allowed = 1
                    calls_in_level += 1
                    prof = self._profiler
                    if prof is not None and prof.want():
                        # Observational per-stage sample of the batch
                        # this call will expand first (obs/profile.py):
                        # the real fused chunk below still does all the
                        # work — results stay bit-identical.
                        with mt.phase_timer("profile"):
                            prof.sample(
                                qcur[offset:offset + B],
                                (offset + np.arange(B)) < cur_count)
                    if _faults.ACTIVE:
                        # Deterministic injection sites (resilience/):
                        # "kill" dies here (mid-level, past the level's
                        # snapshot), "oom" raises a simulated
                        # RESOURCE_EXHAUSTED into the degradation path.
                        _faults.fire("kill", level=res.diameter,
                                     chunk=calls_in_level)
                        _faults.fire("oom", level=res.diameter,
                                     chunk=calls_in_level)
                    t_call = time.time()
                    # Device-profiler window (--xla-profile): bracket
                    # the dispatch in a StepTraceAnnotation sharing the
                    # SpanTracer's "chunk" span name; the capture stops
                    # itself after N steps (obs/profile.py).  One call
                    # site: the profiled and unprofiled paths must
                    # never diverge.
                    cap = self._xla_capture
                    step_cm = (cap.step() if cap is not None
                               and not cap.done
                               else contextlib.nullcontext())
                    with mt.phase_timer("chunk"), step_cm:
                        out = self._chunk(qcur, jnp.int32(cur_count),
                                          jnp.int32(offset), qnext,
                                          jnp.int32(next_count_h), seen,
                                          tbuf, jnp.int32(0),
                                          jnp.int32(allowed))
                        qnext, seen, tbuf = out[0], out[1], out[2]
                    # The packed-stats fetch is the loop's one blocking
                    # device sync — its phase time IS the device compute
                    # the dispatch above overlapped.
                    with mt.phase_timer("stats_fetch"):
                        st = np.asarray(out[3])
                    if self._perf is not None and int(st[1]):
                        # Launch accounting's dynamic half: batches +
                        # measured seconds for this chunk call — host
                        # arithmetic on values already fetched.
                        self._perf.add_chunk(int(st[1]),
                                             time.time() - t_call)
                    if int(st[1]):       # st fetch synced: timing is real
                        per = (time.time() - t_call) / int(st[1])
                        # Conservative estimator: jumps up to the latest
                        # cost instantly, decays slowly — per-batch cost
                        # grows with level depth (fuller probe chains,
                        # busier frontiers), and an under-estimate lets
                        # one deadline-sized chunk call overshoot the
                        # duration budget by the whole error factor.
                        self._batch_ema = (
                            per if not self._batch_ema else
                            max(per, 0.5 * self._batch_ema + 0.5 * per))
                    offset, next_count_h = int(st[0]), int(st[2])
                    seen_size, tcount = int(st[3]), int(st[4])
                    n_gen, n_new, n_ovf = int(st[5]), int(st[6]), int(st[7])
                    dead_any, viol_any = bool(st[8]), bool(st[9])
                    vinv, fail = int(st[10]), bool(st[11])
                    res.distinct += n_new
                    res.generated += n_gen
                    # The packed-stats fetch feeds the registry — the one
                    # place every consumer (progress line, events, bench,
                    # server stats) reads live engine counters from.
                    mt.counter("engine/distinct", n_new)
                    mt.counter("engine/generated", n_gen)
                    mt.gauge("engine/seen_size", seen_size)
                    mt.gauge("engine/seen_capacity", len(seen.hi))
                    mt.gauge("engine/next_count", next_count_h)
                    mt.gauge("engine/diameter", res.diameter)
                    F = len(dims.family_sizes)
                    if n_gen:
                        for name, c in zip(dims.family_names,
                                           st[13:13 + F]):
                            res.action_counts[name] = (
                                res.action_counts.get(name, 0) + int(c))
                    # TLC-style coverage (obs/coverage.py): same packed
                    # stats, attributed per family — generated/distinct/
                    # disabled/pruned all derive from this one fetch.
                    coverage.add_chunk(int(st[12]), st[13:13 + F],
                                       st[13 + F:13 + 2 * F],
                                       st[13 + 2 * F:13 + 3 * F])
                    # Black-box progress snapshot (obs/flight.py):
                    # rate-limited inside progress(), so the always-on
                    # cost is a couple of dict appends per second — and
                    # the watch console / postmortem dump always have a
                    # current view, with or without --progress-interval.
                    _FLIGHT.progress(
                        distinct=res.distinct, generated=res.generated,
                        diameter=res.diameter, frontier=cur_count,
                        offset=offset, next_count=next_count_h,
                        seen_size=seen_size,
                        elapsed=round(time.time() - t0, 3))
                    if cfg.record_trace and tcount:
                        with mt.phase_timer("trace_flush"):
                            self._flush_trace(trace, tbuf, tcount)
                    if n_ovf:
                        raise RuntimeError(
                            f"{n_ovf} successors exceeded fixed-width "
                            f"capacity (max_log={dims.max_log}, n_msg_slots"
                            f"={dims.n_msg_slots}) or wrapped the uint8 "
                            f"row; rerun with larger capacities/bounds")
                    if fail:
                        raise RuntimeError(
                            "seen-set probe failure (load spiked past the "
                            "growth threshold within one chunk); raise "
                            "seen_capacity or lower sync_every")
                    seen, qnext, tbuf, t0 = self._grow_precompiled(
                        seen, seen_size, qcur, qnext, next_count_h, tbuf,
                        t0)
                    if next_count_h > self._QTH \
                            and (offset < cur_count or pending):
                        # Next-level queue at the watermark with more of
                        # this level still to expand: drain it to host
                        # (TLC's disk queue) asynchronously — swap in the
                        # spare buffer and let the D2H ride behind the
                        # next chunks' compute.
                        resolve_spill()
                        with mt.phase_timer("spill"):
                            qnext.copy_to_host_async()
                            inflight.append((qnext, next_count_h))
                            qnext = free_q.pop()
                        evlog.emit("spill", rows=next_count_h,
                                   level=res.diameter, where="chunk_loop")
                        next_count_h = 0
                    if viol_any:
                        vrow, vhl = np.asarray(out[5]), np.asarray(out[6])
                        res.violation = Violation(
                            invariant=self.inv_names[vinv],
                            state=decode_state(
                                unflatten_state(vrow, dims), dims),
                            fingerprint=(int(vhl[0]) << 32) | int(vhl[1]))
                        res.stop_reason = "violation"
                        evlog.emit(
                            "violation",
                            invariant=res.violation.invariant,
                            fingerprint=hex(res.violation.fingerprint),
                            level=res.diameter)
                        break
                    if dead_any and self._check_deadlock:
                        res.deadlock = decode_state(
                            unflatten_state(np.asarray(out[4]), dims), dims)
                        res.stop_reason = "deadlock"
                        evlog.emit("deadlock", level=res.diameter)
                        break
                    want_progress = bool(
                        cfg.progress_interval_seconds
                        and time.time() - last_progress
                        >= cfg.progress_interval_seconds)
                    if cfg.exit_conditions or want_progress:
                        # TLC's "queue" counter is the FULL unexplored-
                        # state queue: the unexpanded remainder of this
                        # level (device rows + host segments) plus
                        # everything enqueued for the next (device rows +
                        # landed and in-flight spills).
                        # offset advances in batch multiples and may
                        # overshoot cur_count on the level's last chunk.
                        queue_rows = (
                            max(0, cur_count - offset)
                            + pending.total_rows()
                            + next_count_h + spill_next.total_rows()
                            + sum(c for _b, c in inflight))
                        if want_progress:
                            _progress_line(res, t0, queue_rows, cur_count,
                                           metrics=mt)
                            # Coverage rides the same cadence (TLC's
                            # -coverage interval): registry gauges plus
                            # one structured event per interval.
                            coverage.feed_metrics(mt)
                            evlog.emit("coverage", level=res.diameter,
                                       actions=coverage.snapshot())
                            last_progress = time.time()
                        # Checked last: a violation or deadlock in the same
                        # chunk outranks a budget stop (TLC reports the
                        # error, not the exit).
                        hit = _exit_condition_hit(
                            cfg.exit_conditions, res, queue_rows)
                        if hit:
                            res.stop_reason = hit
                            break
                if res.stop_reason != "exhausted" \
                        or res.violation is not None or not pending:
                    break
                # Upload the next host segment of this level.
                with mt.phase_timer("upload"):
                    seg = pending.pop(0)
                    buf = np.zeros((QA, sw), ROW_DTYPE)
                    buf[:len(seg)] = seg
                    qcur = jax.device_put(buf, qcur.devices().pop())
                    cur_count = len(seg)
            if res.stop_reason != "exhausted" or res.violation is not None:
                break  # aborted mid-level: diameter counts completed levels
            resolve_spill()      # level boundary: all drains must land
            res.diameter += 1
            res.levels.append(next_count_h
                              + spill_next.total_rows())
            self._emit_level_event(res, res.levels[-1])
            qcur, qnext = qnext, qcur
            cur_count = next_count_h
            pending, spill_next = spill_next, pending

        res.wall_seconds = time.time() - t0
        # Final frontier snapshot (empty when exhausted): profiling tools
        # use it as a representative mid-level workload.
        self._last_frontier = (np.asarray(qcur[:cur_count]) if cur_count
                               else np.zeros((0, sw), ROW_DTYPE))
        return res

    # ------------------------------------------------------------------
    def replay(self, fp: int) -> List[Tuple[int, PyState]]:
        """Counterexample reconstruction: walk the trace back to a root,
        then re-run the expand kernel forward, selecting at each step the
        candidate whose fingerprint matches the recorded child fingerprint.
        Returns [(action_id, state)] root-first (root action = -1).

        Matching by fingerprint (not by recorded action id alone) matters:
        queue rows keep the kernel's message-slot arrangement, while replay
        re-encodes states canonically (sorted slots, schema.encode_state),
        so a recorded slot-indexed action (Receive/Duplicate/Drop) may map
        to a different slot of the canonical parent.  The recorded id is
        preferred when it still matches, so labels stay stable."""
        chain = self.trace.chain(fp)
        if not chain:
            if fp in self.trace.roots:
                # Depth-0 counterexample: the violating state IS a root —
                # the one-state trace, no kernel replay needed.
                return [(-1, self.trace.roots[fp])]
            raise KeyError(f"fingerprint {fp:#x} not in trace")
        root_fp, g0 = chain[0]
        if g0 >= 0:
            raise KeyError("trace chain does not reach a root")
        state = self.trace.roots[root_fp]
        out = [(-1, state)]
        for child_fp, g_rec in chain[1:]:
            st = encode_state(state, self.dims)
            cands, en, _ovf = self._expand1(st)
            fph, fpl = self._fp_batch(cands)
            fps = (np.asarray(fph).astype(np.uint64) << np.uint64(32)) \
                | np.asarray(fpl).astype(np.uint64)
            ok = np.asarray(en) & (fps == np.uint64(child_fp))
            if not ok.any():
                # A replay that cannot reproduce a recorded child is the
                # one place a 64-bit fingerprint collision becomes HOST-
                # OBSERVABLE — counted so the statespace report's
                # "observed dual-key collisions" reflects detections,
                # not just the calculated probability.
                self.metrics.counter("engine/fp_collisions")
                raise RuntimeError(
                    f"replay divergence: no enabled candidate matches "
                    f"fp {child_fp:#018x} (recorded action {g_rec})")
            g = g_rec if 0 <= g_rec < ok.shape[0] and ok[g_rec] \
                else int(np.argmax(ok))
            row = jax.tree.map(lambda a: np.asarray(a)[g], cands)
            state = decode_state(StateBatch(*row), self.dims)
            out.append((g, state))
        return out

    # ------------------------------------------------------------------
    def _grow_precompiled(self, seen, size, qcur, qnext, next_count, tbuf,
                          t0):
        """Grow the seen set when loaded past threshold, pre-compile the
        chunk program at the new table shape with a zero-trip call, and
        keep the rehash + compile off the duration clock — the StopAfter
        budget measures checking time, not compilation (same rule as the
        warm-up).  Returns (seen, qnext, tbuf, t0)."""
        t_grow = time.time()
        grown = self._maybe_grow_seen(seen, size)
        if grown is not seen:
            seen = grown
            out = self._chunk(qcur, jnp.int32(0), jnp.int32(0), qnext,
                              jnp.int32(next_count), seen, tbuf,
                              jnp.int32(0), jnp.int32(1))
            qnext, seen, tbuf = out[0], out[1], out[2]
            stall = time.time() - t_grow
            t0 += stall
            # Off the clock, but recorded: a run that starts undersized
            # pays one of these per doubling — the evidence for sizing
            # SEEN_CAPACITY up front.  The stall IS the phase time
            # (rehash + precompile), observed directly.
            self._growth_stalls.append((len(seen.hi), round(stall, 3)))
            from ..obs import PHASE_PREFIX
            self.metrics.observe(PHASE_PREFIX + "fpset_grow", stall)
            self.metrics.counter("engine/fpset_resizes")
            # The growth_stall event BENCH_r05 had to infer from outside:
            # capacity after, off-clock stall, live memory.
            self._evlog.emit("fpset_resize", capacity=len(seen.hi),
                             stall_seconds=round(stall, 3),
                             memory=device_memory_stats())
        return seen, qnext, tbuf, t0

    def _maybe_grow_seen(self, seen, size=None):
        """Double the FPSet (rehash through host keys) once load passes
        0.5 — early enough that the insertions of the next chunk (checked
        only at host sync points) fit the free half without pushing the
        load where probes start failing.  The chunk program recompiles for
        the new table shape, so growth costs one compile per doubling;
        auto-sized tables (seen_capacity=None) start large enough that
        most runs never grow."""
        C = seen.hi.shape[0]
        if (int(seen.size) if size is None else size) <= C // 2:
            return seen
        hi, lo = fpset.to_host_keys(seen)
        self._grow_attempts = getattr(self, "_grow_attempts", 0) + 1
        try:
            if _faults.ACTIVE:
                _faults.fire("oom", grow=self._grow_attempts)
            return fpset.from_host_keys(hi, lo, 2 * C)
        except Exception as e:
            if not (self.config.degrade_on_oom
                    and is_resource_exhausted(e)):
                raise
            # Degraded growth retry: the keys are already host-resident,
            # so the OLD device table can be released before the new
            # allocation — the retry's peak is the new table alone
            # instead of old + new.  (Capacities are power-of-two
            # (ops/fpset.py masked indexing), so the "smaller factor"
            # here is a smaller allocation PEAK, not a non-pow2 table.)
            # A second failure propagates to _run_degradable, which
            # halves the batch — shrinking queues and trace buffers —
            # and resumes from the last intact snapshot.
            self._evlog.emit(
                "degraded", reason="oom_grow_retry", capacity=2 * C,
                error=f"{type(e).__name__}: {str(e)[:300]}",
                memory=device_memory_stats())
            self.metrics.counter("engine/degraded")
            for arr in (seen.hi, seen.lo):
                try:
                    arr.delete()
                except Exception:
                    pass
            return fpset.from_host_keys(hi, lo, 2 * C)

    def _write_checkpoint(self, qcur, cur_count, pending, seen, res, trace,
                          wall):
        from . import checkpoint as ckpt_mod
        import os
        if self.config.record_trace:
            tf, tp, ta = trace.export()
            roots = dict(trace.roots)
        else:
            tf = np.empty(0, np.uint64)
            tp = np.empty(0, np.uint64)
            ta = np.empty(0, np.int32)
            roots = {}
        seen_hi, seen_lo = fpset.to_host_keys(seen)
        frontier, cleanup = pending.concat_with(
            np.asarray(qcur[:cur_count]))
        ck = ckpt_mod.Checkpoint(
            dims=self.dims,
            frontier=frontier,
            seen_hi=seen_hi, seen_lo=seen_lo,
            distinct=res.distinct, generated=res.generated,
            diameter=res.diameter, levels=tuple(res.levels),
            action_counts=dict(res.action_counts),
            wall_seconds=wall,
            trace_fps=tf, trace_parents=tp, trace_actions=ta, roots=roots)
        try:
            ckpt_mod.save(os.path.join(self.config.checkpoint_dir,
                                       f"level_{res.diameter:05d}.npz"), ck)
        finally:
            cleanup()
        # Retention AFTER the successful write: the newest snapshot must
        # land before any older one is considered surplus.
        removed = ckpt_mod.gc(self.config.checkpoint_dir,
                              self.config.keep_checkpoints)
        if removed:
            self.metrics.counter("engine/checkpoints_gcd", removed)

    def _record(self, trace, tr, n_new):
        if n_new == 0 or not self.config.record_trace:
            return
        sh, sl, ph, pl, ac = (np.asarray(x[:n_new]) for x in tr)
        fps = (sh.astype(np.uint64) << np.uint64(32)) | sl.astype(np.uint64)
        parents = (ph.astype(np.uint64) << np.uint64(32)) \
            | pl.astype(np.uint64)
        trace.add_batch(fps, parents, ac)

    def _flush_trace(self, trace, tbuf, tcount):
        """Drain the device trace buffer (one chunk's records) to the host
        store — one transfer per column slice."""
        self._record(trace, tbuf, tcount)

    def _check_violation(self, res, vinfo) -> bool:
        viol_any, vinv, vrow, vhi, vlo = vinfo
        if not bool(viol_any):
            return False
        st = decode_state(unflatten_state(np.asarray(vrow), self.dims),
                          self.dims)
        fp = (int(vhi) << 32) | int(vlo)
        name = self.inv_names[int(vinv)]
        res.violation = Violation(invariant=name, state=st, fingerprint=fp)
        res.stop_reason = "violation"
        self._evlog.emit("violation", invariant=name, fingerprint=hex(fp),
                         level=res.diameter)
        return True
