"""Front-end: resolved cfg -> engine run (the ``tlc <cfg> <module>`` path).

Maps a ``CheckSetup`` (utils/cfg.py) onto the BFS engine: invariant names
resolve through the registry below (TypeOK today; the raft.tla dead-region
safety suite registers here as it lands), constraint names resolve to
predicate builders (``BoundedSpace`` reads the MaxTerm/MaxLogLen/MaxMsgCount
constants), ``Init <- SmokeInit`` selects the randomized smoke roots
(Smokeraft.cfg:43-44), and StopAfter budgets land in EngineConfig.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..models import smoke
from ..models.dims import RaftDims
from ..models.invariants import (Bounds, build_constraint,
                                 invariant_registry)
from ..models.pystate import PyState, init_state
from ..utils.cfg import CheckSetup, load_config
from .bfs import BFSEngine, EngineConfig, EngineResult

# name -> builder(dims) -> kernel(state)->bool.  TypeOK (raft.tla:482-492)
# plus the whole dead-region safety suite (raft.tla:896-1180; SURVEY §2.3),
# checkable by naming them as INVARIANT in any cfg.  The registry itself
# lives in models/invariants.py (invariant_registry) so the analyzer's
# POR visibility condition and this cfg resolution can never drift.
INVARIANT_REGISTRY: Dict[str, Callable[[RaftDims], Callable]] = \
    invariant_registry()

CONSTRAINT_REGISTRY: Dict[str, Callable[[RaftDims, Bounds], Callable]] = {
    "BoundedSpace": build_constraint,
}


def resolve_invariants(setup: CheckSetup) -> Dict[str, Callable]:
    invs = {}
    for name in setup.invariants:
        if name not in INVARIANT_REGISTRY:
            raise ValueError(
                f"unknown INVARIANT {name!r}; registered: "
                f"{sorted(INVARIANT_REGISTRY)}")
        invs[name] = INVARIANT_REGISTRY[name](setup.dims)
    return invs


def resolve_constraint(setup: CheckSetup) -> Optional[Callable]:
    constraint = None
    for name in setup.constraints:
        if name not in CONSTRAINT_REGISTRY:
            raise ValueError(
                f"unknown CONSTRAINT {name!r}; registered: "
                f"{sorted(CONSTRAINT_REGISTRY)}")
        if constraint is not None:
            raise ValueError("multiple constraints not yet supported")
        constraint = CONSTRAINT_REGISTRY[name](setup.dims, setup.bounds)
    return constraint


def engine_config_from_backend(setup: CheckSetup) -> EngineConfig:
    """EngineConfig seeded from the cfg's ``\\* TPU:`` backend directives
    (utils/cfg.py).  Used whenever the caller does not supply an explicit
    EngineConfig, so the precedence chain (caller > cfg directive >
    built-in default) holds for the API entry points, not just the CLI."""
    be = setup.backend
    return EngineConfig(
        batch=be.get("BATCH", EngineConfig.batch),
        queue_capacity=be.get("QUEUE_CAPACITY", EngineConfig.queue_capacity),
        seen_capacity=be.get("SEEN_CAPACITY", EngineConfig.seen_capacity),
        checkpoint_dir=be.get("CHECKPOINT_DIR"),
        checkpoint_every=be.get("CHECKPOINT_EVERY",
                                EngineConfig.checkpoint_every),
        checkpoint_interval_seconds=float(
            be.get("CHECKPOINT_INTERVAL",
                   EngineConfig.checkpoint_interval_seconds)),
        keep_checkpoints=be.get("KEEP_CHECKPOINTS"),
        spill_dir=be.get("SPILL_DIR"),
        trace_dir=be.get("TRACE_DIR"),
        events_out=be.get("EVENTS_OUT"),
        trace_out=be.get("TRACE_OUT"),
        profile_chunks_every=be.get("PROFILE_CHUNKS"),
        xla_profile_chunks=be.get("XLA_PROFILE"),
        pipeline=be.get("PIPELINE", EngineConfig.pipeline),
        por=bool(be.get("POR", False)),
        por_table=be.get("POR_TABLE"),
        perf=bool(be.get("PERF", False)),
        statespace_report=bool(be.get("REPORT", True)),
        counterexample_dir=be.get("COUNTEREXAMPLE_DIR"))


def make_engine(setup: CheckSetup,
                engine_config: Optional[EngineConfig] = None,
                engine_cls=None):
    """Build a checker engine with the cfg-file fallbacks applied
    (CHECK_DEADLOCK, StopAfter budgets).  ``engine_cls`` selects the
    implementation — BFSEngine (default), parallel.mesh.MeshBFSEngine,
    or the string ``"auto"`` (mesh iff running on more than one
    accelerator device, e.g. a v5e-8 slice) — so every entry point
    resolves the engine and config identically."""
    import dataclasses as _dc
    if engine_cls == "auto":
        import jax
        devs = jax.devices()
        if len(devs) > 1 and devs[0].platform != "cpu":
            from ..parallel.mesh import MeshBFSEngine
            engine_cls = MeshBFSEngine
        else:
            engine_cls = None
    base = engine_config or engine_config_from_backend(setup)
    cfg = _dc.replace(          # never mutate the caller's config
        base,
        check_deadlock=(base.check_deadlock
                        if base.check_deadlock is not None
                        else setup.check_deadlock),
        max_seconds=(base.max_seconds if base.max_seconds is not None
                     else setup.max_seconds),
        max_diameter=(base.max_diameter if base.max_diameter is not None
                      else setup.max_diameter),
        exit_conditions=(base.exit_conditions or setup.exit_conditions))
    cls = engine_cls or BFSEngine
    return cls(setup.dims, invariants=resolve_invariants(setup),
               constraint=resolve_constraint(setup), config=cfg)


def initial_states(setup: CheckSetup, seed: int = 0) -> List[PyState]:
    if setup.smoke:
        return smoke.smoke_init_states(setup.dims, k=setup.smoke_k,
                                       seed=seed)
    return [init_state(setup.dims)]


def path_to_state(dims: RaftDims, target: PyState,
                  constraint: Optional[Callable] = None,
                  init_states: Optional[List[PyState]] = None,
                  config: Optional[EngineConfig] = None):
    """Minimal action path from Init to ``target`` — the counterexample
    extractor for runs that had no trace store (multi-host runs record no
    traces; their Violation still carries the concrete state).  Runs a
    single-host BFS with an injected "never reaches target" invariant and
    replays the hit: BFS order makes the result a minimal-depth path.

    Returns ``[(grid_index, PyState), ...]`` (root first, grid_index -1
    for the root) — pretty-print actions with ``dims.describe_instance``.
    Raises if ``target`` is unreachable inside the constraint bounds."""
    import dataclasses as _dc

    import jax.numpy as jnp

    from ..models.schema import encode_state
    from ..ops.fingerprint import build_fingerprint
    fingerprint = build_fingerprint(dims)
    thi, tlo = (int(x) for x in fingerprint(encode_state(target, dims)))

    roots = init_states or [init_state(dims)]
    if target in roots:
        return [(-1, target)]           # trivial path: target IS a root

    def not_target(st):
        h, l = fingerprint(st)
        return ~((h == jnp.uint32(thi)) & (l == jnp.uint32(tlo)))

    # The extractor needs its own trace store regardless of how the
    # original (possibly trace-less multi-host) run was configured, and
    # only cares about reachability — a reachable dead-end state at a
    # shallower level must not abort the search.
    cfg = _dc.replace(config or EngineConfig(),
                      record_trace=True, check_deadlock=False)
    eng = BFSEngine(dims, invariants={"__NotTarget": not_target},
                    constraint=constraint, config=cfg)
    res = eng.run(roots)
    if res.violation is None:
        raise ValueError(
            f"target state unreachable within the explored space "
            f"({res.distinct} states, stop: {res.stop_reason})")
    assert res.violation.state == target, \
        "fingerprint collision: matched state differs from target"
    return eng.replay(res.violation.fingerprint)


def run_check(cfg_path: str, engine_config: Optional[EngineConfig] = None,
              seed: int = 0, max_log: Optional[int] = None,
              n_msg_slots: Optional[int] = None) -> EngineResult:
    """One-call path: parse cfg, build engine, run.  The reference configs
    (/root/reference/MCraft.cfg, Smokeraft.cfg) run unmodified."""
    setup = load_config(cfg_path, max_log=max_log, n_msg_slots=n_msg_slots)
    engine = make_engine(setup, engine_config)
    res = engine.run(initial_states(setup, seed=seed))
    res.engine = engine
    return res


def format_result(res: EngineResult) -> str:
    lines = [
        f"distinct states    {res.distinct}",
        f"states generated   {res.generated}",
        f"diameter           {res.diameter}",
        f"stop reason        {res.stop_reason}",
        f"wall seconds       {res.wall_seconds:.2f}",
        f"states/sec         {res.states_per_second:.0f}",
    ]
    if res.report:
        col = res.report["collision"]
        lines.append(
            f"fp collision prob  {col['calculated']:.2e} calculated "
            f"(optimistic); {col['observed_dual_key']} observed")
        peak = res.report.get("frontier_peak")
        if peak:
            lines.append(f"widest level       {peak['level']} "
                         f"({peak['frontier']:,} states)")
    if res.pipeline:
        line = f"pipeline           {res.pipeline}"
        if res.fused_stages:
            line += " (" + " ".join(
                f"{s}={impl}" for s, impl in res.fused_stages.items()) + ")"
        lines.append(line)
        # A stage that FAILED its build-probe (vs a policy/forced XLA
        # choice) is operator-actionable — say so in the result block.
        for s, why in sorted(res.fused_reasons.items()):
            if "failed to build/probe" in why:
                lines.append(f"  {s} fell back: {why}")
    if res.action_counts:
        lines.append("generated by action family:")
        for name, c in sorted(res.action_counts.items(),
                              key=lambda kv: -kv[1]):
            lines.append(f"  {name:22s} {c}")
    if res.growth_stalls:
        total = sum(s for _c, s in res.growth_stalls)
        lines.append(
            f"seen-set growths   {len(res.growth_stalls)} "
            f"(off-clock stalls {total:.1f}s: "
            + ", ".join(f"{c}@{s}s" for c, s in res.growth_stalls) + ")")
    if res.violation is not None:
        lines.append(f"VIOLATION          {res.violation.invariant} "
                     f"(fp {res.violation.fingerprint:#018x})")
    if res.deadlock is not None:
        lines.append("DEADLOCK reached")
    return "\n".join(lines)
