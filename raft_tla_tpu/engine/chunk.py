"""The per-batch BFS pipeline body, shared by both engines.

One batch = slice B rows off the level queue -> expand all G action
instances -> fingerprint -> compact enabled lanes to K slots
(ops/compact.py) -> hash-insert the K keys -> materialize rows, evaluate
invariants + the state constraint, enqueue, record trace rows — all on the
K compacted lanes.  engine/bfs.py (single chip) and parallel/mesh.py
(sharded) run the IDENTICAL body; they differ only in

- ``insert_fn``: the single-chip FPSet insert vs the mesh's owner-routed
  all_to_all insert (mesh.py route_insert), and
- the loop wrapper around the body (plain while_loop vs shard_map with
  psum-replicated stop conditions), which stays in each engine.

Keeping the body in one place is load-bearing: the two engines must stay
bit-identical per batch (same candidate order, same compaction, same
trace layout) for checkpoints to be portable across engines and for the
differential tests to mean anything.

The carry tuple layout (22 fields) is:
    (offset, steps, qnext, next_count, seen, tbuf, tcount,
     gen, newc, ovfc, dead_any, drow, viol_any, vinv, vrow, vhi, vlo,
     fail_any, fam_counts, fam_new, expanded, fam_pruned)

``fam_counts`` [n_families] accumulates enabled-successor counts per
action family (TLC's per-action statistics; SURVEY §5.1) — a handful of
static-slice reduces per batch.  ``fam_new`` [n_families] accumulates
per-family NOVEL-state counts (the insert's novelty mask attributed to
the compacted lane's action family — TLC coverage's "distinct"),
``expanded`` counts parents actually advanced past (valid, inside the
taken prefix) — the exact base for host-side disabled-guard counts
(``expanded * family_size - generated - pruned``) — and ``fam_pruned``
counts enabled lanes the partial-order reduction masked out before
fingerprinting (zero with POR off; the reduced-vs-full accounting
obs/coverage.py renders).  All ride the same packed stats vector;
obs/coverage.py is the host-side consumer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.invariants import build_inv_id
from ..models.schema import flatten_state, unflatten_state

_I32 = jnp.int32


def build_chunk_body(*, dims, expand, fingerprint, pack_ok, inv_fns,
                     constraint, B, G, K, Q, TQ, record_static, compactor,
                     insert_fn, v2=None, enqueue_method="scatter",
                     por_mask=None, por_priority=None, fused_tail=None,
                     fused_front=None):
    """Returns ``chunk_body(qcur, cur_count, carry) -> carry'``.

    ``Q`` is the live next-queue capacity (per chip for the mesh); masked
    enqueue lanes write trash slots [Q, Q+K), masked trace lanes write
    [TQ, TQ+K) — the caller allocates the padding (engine/bfs.py capacity
    comment).

    ``v2`` (models/actions2.build_v2 result, or None) selects the delta
    pipeline: guards-only masks over the B*G lanes, then delta
    fingerprints + sparse successor construction on the K compacted lanes
    only.  Bit-identical to the v1 path in every carry field (enabled/
    overflow masks, fingerprints, successor rows, per-family stats) —
    property-tested in tests/test_actions2.py — so the two paths share
    checkpoints and differential baselines freely.

    ``por_mask``/``por_priority`` ([G] bool / [G] int32 device arrays,
    or both None = off) enable the statically-certified partial-order
    reduction (analysis/por.py): when a state's enabled set contains a
    certified ample instance, every OTHER expansion of that state is
    masked out before fingerprinting — the lowest-priority-value
    certified enabled lane is the one kept.  Deadlock detection is
    unaffected (masking only fires on non-empty enabled sets), and
    masked lanes' overflow flags are dropped with them (a pruned
    successor is never materialized, so its capacity overflow cannot
    abort the reduced run).

    ``fused_tail`` (the v3 pipeline, ops/pipeline_v3.py) replaces the
    separate insert + enqueue stages with ONE fused Pallas kernel
    ``(seen, kh, kl, kvalid, krows, cons_ok, next_count, qnext) ->
    (seen, new, fail, qnext)`` (ops/fused_tail_pallas.py).  Requires
    ``v2`` (the fused kernel consumes the delta fingerprints); the
    constraint and row materialization move BEFORE the insert — they
    depend only on the compacted candidates, so every carry field stays
    bit-identical to the split path (the tests' contract).

    ``fused_front`` (the v4 pipeline, ops/pipeline_v4.py) replaces the
    masks -> POR -> compact -> fingerprint/constraint/invariant section
    with ONE Pallas megakernel ``(rows, valid) -> (en, ovf, pruned, P,
    total, lane_id, kvalid, kh, kl, krows, cons_ok, inv, parent_hi,
    parent_lo)`` (ops/chunk_front_pallas.py) whose body runs the SAME
    model functions on the VMEM-resident parent window; ``en``/``ovf``
    arrive already progress-limited, ``pruned`` pre-limit (this body
    applies ``& ptaken`` when accounting, like the split path).
    Requires ``v2``; the kernel bakes in the POR arrays and the
    constraint/invariant dispatch, so those arguments must describe the
    same run."""
    if enqueue_method not in ("scatter", "window", "pallas"):
        raise ValueError(f"unknown enqueue method {enqueue_method!r}")
    if (por_mask is None) != (por_priority is None):
        raise ValueError("por_mask and por_priority must be given together")
    if por_mask is not None:
        # Last-line admission re-check at the compilation boundary: a
        # reduction mask that does not cover the instance grid exactly
        # (or a non-bool mask, which jnp.where would happily treat as
        # weights) must fail HERE, not silently mis-mask lanes.  The
        # table-level checks (fingerprint, model signature, predicate
        # coverage, encoding version) live in analysis/por.check_table;
        # this guards the raw arrays actually baked into the program.
        if tuple(por_mask.shape) != (G,) \
                or tuple(por_priority.shape) != (G,):
            raise ValueError(
                f"POR mask/priority must be [{G}] (the action-instance "
                f"grid), got {tuple(por_mask.shape)} / "
                f"{tuple(por_priority.shape)}")
        if por_mask.dtype != jnp.bool_ \
                or por_priority.dtype != jnp.int32:
            raise ValueError(
                f"POR mask/priority must be bool/int32, got "
                f"{por_mask.dtype} / {por_priority.dtype}")
    if fused_tail is not None and v2 is None:
        raise ValueError("fused_tail (v3) requires the v2 delta pipeline")
    if fused_front is not None and v2 is None:
        raise ValueError("fused_front (v4) requires the v2 delta pipeline")
    BG = B * G
    inv_id = build_inv_id(inv_fns) if inv_fns else None

    fam_slices = tuple(zip(dims.family_offsets, dims.family_sizes))

    def chunk_body(qcur, cur_count, carry):
        (offset, steps, qnext, next_count, seen, tbuf, tcount,
         gen, newc, ovfc, dead_any, drow, viol_any, vinv, vrow,
         vhi, vlo, fail_any, fam_counts, fam_new, expanded,
         fam_pruned) = carry
        rows = jax.lax.dynamic_slice_in_dim(qcur, offset, B, axis=0)
        valid = (offset + jnp.arange(B, dtype=_I32)) < cur_count
        parent_hi = parent_lo = None
        if fused_front is not None:
            # v4: one Pallas megakernel runs masks -> POR -> compact ->
            # delta fingerprints -> constraint/invariants on the
            # VMEM-resident parent window.  en/ovf arrive already
            # progress-limited; pruned is pre-limit (accounted below
            # like the split path); the per-lane parent fingerprints
            # feed the trace recorder without re-reading the parents.
            (en, ovf, pruned, P, total, lane_id, kvalid, kh, kl, krows,
             cons_ok, inv, parent_hi, parent_lo) = fused_front(
                 rows, valid)
            if por_mask is None:
                pruned = None
            ptaken = jnp.arange(B, dtype=_I32) < P
        else:
            states = jax.vmap(unflatten_state, (0, None))(rows, dims)
            if v2 is None:
                cands, en, ovf = jax.vmap(expand)(states)
                en = en & valid[:, None]
                # A successor whose term/bag count outgrew the uint8 row
                # is an overflow too (schema.build_pack_guard): stop,
                # never alias.
                ovf = (ovf | (en & ~jax.vmap(jax.vmap(pack_ok))(cands))) \
                    & valid[:, None]
            else:
                # Masks fold the pack guard in at the same lanes
                # (actions2).
                en, ovf = jax.vmap(v2.masks)(states)
                en = en & valid[:, None]
                ovf = ovf & valid[:, None]

            if por_mask is not None:
                # Partial-order reduction (analysis/por.py table): keep
                # ONE certified ample lane per state that has any,
                # masking its siblings before compaction/fingerprinting
                # — the reduction the coverage tables account as
                # "pruned".  Rows with no certified enabled instance are
                # untouched, so a state with an empty enabled set still
                # reads as a deadlock.
                amp = en & por_mask[None, :]
                any_amp = jnp.any(amp, axis=1)
                pri = jnp.where(amp, por_priority[None, :],
                                jnp.int32(2147483647))
                sel = jnp.argmin(pri, axis=1)
                keep = jnp.where(
                    any_amp[:, None],
                    jnp.arange(G, dtype=_I32)[None, :] == sel[:, None],
                    jnp.ones((B, G), bool))
                pruned = en & ~keep
                en = en & keep
                ovf = ovf & keep
            else:
                pruned = None

            # Progress limiting + lane compaction (ops/compact.py): take
            # the longest parent prefix whose fan-out fits K, compact
            # the enabled lanes to K slots — nothing is ever dropped, a
            # fan-out burst just advances fewer parents this step.
            P, total, lane_id, kvalid = compactor(en)
            ptaken = jnp.arange(B, dtype=_I32) < P
            en = en & ptaken[:, None]
            ovf = ovf & ptaken[:, None]

            # Everything below — fingerprinting included — runs on the K
            # compacted lanes only: gather the candidate structs first,
            # hash after (identical to hashing the packed rows whenever
            # pack_ok holds, and any overflow aborts the run above).
            # Hashing before compaction would read every field of all
            # B*G lanes for the ~94% that are disabled.
            if v2 is None:
                cflat = jax.tree.map(
                    lambda a: a.reshape((BG,) + a.shape[2:]), cands)
                kstates = jax.tree.map(lambda a: a[lane_id], cflat)
                kh, kl = jax.vmap(fingerprint)(kstates)     # [K]
            else:
                # Gather K parent structs (from B parents, not B*G
                # candidate lanes) and construct only those successors,
                # with their fingerprints coming from the parents' hash
                # sums + per-lane deltas (models/actions2.py).
                ph = jax.vmap(v2.parent_hash)(states)
                pidx = lane_id // G
                kparents = jax.tree.map(lambda a: a[pidx], states)
                kph = jax.tree.map(lambda a: a[pidx], ph)
                kh, kl, kstates = jax.vmap(v2.lane_out)(
                    kparents, kph, lane_id % G)

            if constraint is not None:
                cons_ok = jax.vmap(constraint)(kstates)
            else:
                cons_ok = jnp.ones((K,), bool)
            krows = jax.vmap(flatten_state, (0, None))(kstates, dims)
            # Invariant dispatch depends only on the candidates, so it
            # sits before the insert on both paths (the v4 kernel
            # computes it in-kernel; values are insert-independent).
            if inv_id is not None:
                inv = jax.vmap(inv_id)(kstates)
            else:
                inv = jnp.full((K,), -1, _I32)
            if record_static:
                if v2 is None:
                    php, plp = jax.vmap(fingerprint)(states)  # [B]
                else:
                    php, plp = jax.vmap(v2.parent_fp)(ph)
                parent_hi = php[lane_id // G]
                parent_lo = plp[lane_id // G]

        dead_b = valid & ptaken & ~jnp.any(en, axis=1) \
            & ~jnp.any(ovf, axis=1)
        dead_any_b = jnp.any(dead_b)
        drow_b = rows[jnp.argmax(dead_b)]

        if fused_tail is not None:
            # v3: one Pallas kernel probes/inserts the K keys and
            # appends each novel constraint-passing row at the running
            # cursor — the novelty bit never returns to HBM between the
            # stages.  The constraint/rows above moved BEFORE the
            # insert (they depend only on the candidates), so every
            # value below is bit-identical to the split path.
            seen, new, fail, qnext = fused_tail(
                seen, kh, kl, kvalid, krows, cons_ok, next_count, qnext)
        else:
            seen, new, fail = insert_fn(seen, kh, kl, kvalid)
        viol = new & (inv >= 0)
        viol_any_b = jnp.any(viol)
        vpos = jnp.argmax(viol)

        enq = new & cons_ok
        if fused_tail is not None:
            pass                        # rows already placed in-kernel
        elif enqueue_method == "scatter":
            epos = next_count + jnp.cumsum(enq.astype(_I32)) - 1
            epos = jnp.where(enq, epos, Q + jnp.arange(K, dtype=_I32))
            qnext = qnext.at[epos].set(krows)
        elif enqueue_method == "pallas":
            # Run-coalesced DMA append (ops/enqueue_pallas.py): the enq
            # destination is contiguous, so the rows go out as ~new_n/SEG
            # HBM-to-HBM segment copies instead of K row-scatters.  Live
            # rows bit-identical; trash region simply untouched (the
            # "window" precedent).
            from ..ops import enqueue_pallas
            qnext = enqueue_pallas.enqueue(qnext, next_count, krows, enq)
        else:
            # "window": invert the placement instead of scattering 473-
            # byte rows (the TPU profile's 14.5 ms enqueue stage).  The
            # enq lanes land contiguously at [next_count, next_count +
            # new_n); a K-row window at next_count is rebuilt with a
            # searchsorted gather and written back with ONE
            # dynamic_update_slice.  Live rows are bit-identical to the
            # scatter path; the former trash region [Q, Q+K) is simply
            # left untouched.  The batch watermark (next_count <= Q - K)
            # plus PAD >= B keeps the window in-bounds.
            from ..ops.compact import inv_positions
            new_n = jnp.sum(enq, dtype=_I32)
            w = jnp.arange(K, dtype=_I32)
            src = inv_positions(enq, K)
            win = jax.lax.dynamic_slice(
                qnext, (next_count, jnp.int32(0)), (K, qnext.shape[1]))
            win = jnp.where((w < new_n)[:, None], krows[src], win)
            qnext = jax.lax.dynamic_update_slice(
                qnext, win, (next_count, jnp.int32(0)))
        next_count = next_count + jnp.sum(enq, dtype=_I32)

        if record_static:
            actions = lane_id % G
            if enqueue_method == "scatter":
                tpos = jnp.where(
                    new, tcount + jnp.cumsum(new.astype(_I32)) - 1,
                    TQ + jnp.arange(K, dtype=_I32))
                tbuf = tuple(
                    buf.at[tpos].set(col)
                    for buf, col in zip(
                        tbuf, (kh, kl, parent_hi, parent_lo, actions)))
            else:
                from ..ops.compact import inv_positions
                tn = jnp.sum(new, dtype=_I32)
                tw = jnp.arange(K, dtype=_I32)
                tsrc = inv_positions(new, K)
                out = []
                for buf, col in zip(
                        tbuf, (kh, kl, parent_hi, parent_lo, actions)):
                    twin = jax.lax.dynamic_slice(buf, (tcount,), (K,))
                    twin = jnp.where(tw < tn, col[tsrc], twin)
                    out.append(jax.lax.dynamic_update_slice(
                        buf, twin, (tcount,)))
                tbuf = tuple(out)
            tcount = tcount + jnp.sum(new, dtype=_I32)

        take_v = ~viol_any & viol_any_b
        vinv = jnp.where(take_v, inv[vpos], vinv)
        vrow = jnp.where(take_v, krows[vpos], vrow)
        vhi = jnp.where(take_v, kh[vpos], vhi)
        vlo = jnp.where(take_v, kl[vpos], vlo)
        drow = jnp.where(dead_any | ~dead_any_b, drow, drow_b)
        fam_counts = fam_counts + jnp.stack(
            [jnp.sum(en[:, off:off + sz], dtype=_I32)
             for off, sz in fam_slices])
        # Per-family novelty (coverage "distinct"): attribute each novel
        # compacted lane to the family of the action that produced it.
        kact = lane_id % G
        fam_new = fam_new + jnp.stack(
            [jnp.sum(new & (kact >= off) & (kact < off + sz), dtype=_I32)
             for off, sz in fam_slices])
        expanded = expanded + jnp.sum(valid & ptaken, dtype=_I32)
        if pruned is not None:
            # Reduced-vs-full accounting (obs/coverage.py): enabled lanes
            # the POR mask dropped, counted only for parents this step
            # actually advanced past (same base as ``expanded``).
            ptr = pruned & ptaken[:, None]
            fam_pruned = fam_pruned + jnp.stack(
                [jnp.sum(ptr[:, off:off + sz], dtype=_I32)
                 for off, sz in fam_slices])
        return (offset + P, steps + 1, qnext, next_count, seen, tbuf,
                tcount, gen + total,
                newc + jnp.sum(new, dtype=_I32),
                ovfc + jnp.sum(ovf, dtype=_I32),
                dead_any | dead_any_b, drow,
                viol_any | viol_any_b, vinv, vrow, vhi, vlo,
                fail_any | fail, fam_counts, fam_new, expanded,
                fam_pruned)

    return chunk_body
