"""Host spill pool — TLC's disk-backed state queue for level segments.

The engines drain over-watermark next-level queues to host segments and
re-upload them later (engine/bfs.py, parallel/mesh.py).  In-RAM segments
are fine until the frontier outgrows host memory: MCraft_bounded's level
14 alone is ~45M rows x 403 B ~ 18 GB.  TLC pages its state queue to
disk [TLC semantics — external]; this pool does the same when given a
directory: each segment is written to its own .npy-like raw file via
``np.memmap`` and read back memory-mapped, so the OS page cache — not
the Python heap — holds whatever fits and evicts the rest.

``SpillPool(None)`` degrades to a plain in-RAM list (the default;
identical behavior to the previous List[np.ndarray] plumbing).  The API
is the small subset the engines use: append / pop(0) / len / total rows
/ iteration (for checkpoints) / truthiness / clear.
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

import numpy as np

from ..resilience import faults as _faults


class SpillPool:
    """FIFO of row-array segments, RAM- or disk-backed."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory
        self._ram: List[np.ndarray] = []
        self._files: List[tuple] = []     # (path, shape, dtype)
        self._seq = 0
        if directory is not None:
            os.makedirs(directory, exist_ok=True)

    # -- writers -------------------------------------------------------
    def append(self, rows: np.ndarray, copy: bool = False) -> None:
        """Queue a segment.  ``copy=True`` detaches RAM-mode segments
        from the caller's buffer (drain paths recycle theirs); disk mode
        always copies into the memmap, so the flag costs nothing there.
        Default False keeps zero-copy views (resume pre-splits)."""
        if len(rows) == 0:
            return
        if self.directory is None:
            self._ram.append(np.array(rows, copy=True) if copy else rows)
            return
        # A spilled segment IS engine state — a lost write is a lost
        # slice of the frontier, so a transient disk error (injectable:
        # resilience/ "spill_write") gets one retry through a fresh
        # tempfile before the failure is allowed to surface.
        last_err = None
        for attempt in (1, 2):
            path = None
            try:
                if _faults.ACTIVE:
                    _faults.fire("spill_write", attempt=attempt)
                fd, path = tempfile.mkstemp(
                    prefix=f"seg_{self._seq:06d}_", suffix=".rows",
                    dir=self.directory)
                os.close(fd)
                self._seq += 1
                mm = np.memmap(path, dtype=rows.dtype, mode="w+",
                               shape=rows.shape)
                mm[:] = rows
                mm.flush()
                del mm                     # drop the writable mapping
                self._files.append((path, rows.shape, rows.dtype))
                return
            except OSError as e:
                last_err = e
                if path is not None:
                    try:
                        os.unlink(path)    # never leave a torn segment
                    except OSError:
                        pass
        raise OSError(
            f"spill segment write failed twice in {self.directory!r} "
            f"({len(rows)} rows): {last_err}") from last_err

    # -- readers -------------------------------------------------------
    def pop(self, index: int = 0) -> np.ndarray:
        """Remove and return a segment (read-only memmap when
        disk-backed; the file is unlinked once the array is garbage
        collected — the open mapping keeps it readable meanwhile)."""
        if self.directory is None:
            return self._ram.pop(index)
        path, shape, dtype = self._files.pop(index)
        arr = np.memmap(path, dtype=dtype, mode="r", shape=shape)
        os.unlink(path)                    # POSIX: mapping stays valid
        return arr

    def insert(self, index: int, rows: np.ndarray) -> None:
        """Put a (partial) segment back at the front — the balanced
        re-upload path splits oversized segments."""
        if len(rows) == 0:
            return           # disk mode: append() wrote no file to rotate
        if self.directory is None:
            self._ram.insert(index, rows)
            return
        # Re-append through a fresh file, then rotate it into place.
        self.append(np.asarray(rows))
        self._files.insert(index, self._files.pop())

    # -- introspection -------------------------------------------------
    def __len__(self) -> int:
        return (len(self._ram) if self.directory is None
                else len(self._files))

    def __bool__(self) -> bool:
        return len(self) > 0

    def segments(self):
        """Iterate segments WITHOUT consuming them (checkpoint writer)."""
        if self.directory is None:
            yield from self._ram
            return
        for path, shape, dtype in self._files:
            yield np.memmap(path, dtype=dtype, mode="r", shape=shape)

    def total_rows(self) -> int:
        if self.directory is None:
            return sum(len(s) for s in self._ram)
        return sum(shape[0] for _p, shape, _d in self._files)

    def clear(self) -> None:
        self._ram.clear()
        for path, _s, _d in self._files:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._files.clear()

    def __del__(self):
        # Runs stopped early (violation, deadlock, budgets, exceptions)
        # drop their pools with segments still queued; without this the
        # files leak for the life of the host — at design scale that is
        # gigabytes per interrupted run.
        try:
            self.clear()
        except Exception:
            pass

    def concat_with(self, head: np.ndarray):
        """``head`` + every queued segment as one array (checkpoint
        writer).  RAM pools concatenate; disk pools assemble into a
        memmap tempfile so the result's pages are OS-evictable —
        checkpointing a beyond-host-RAM frontier (the workload this pool
        exists for) must not OOM.  Returns ``(array, cleanup)``; call
        ``cleanup()`` once the array has been consumed."""
        segs = list(self.segments())
        if not segs:
            return head, (lambda: None)
        if self.directory is None:
            return np.concatenate([head] + segs), (lambda: None)
        total = len(head) + sum(len(s) for s in segs)
        fd, path = tempfile.mkstemp(prefix="ckfront_", suffix=".rows",
                                    dir=self.directory)
        os.close(fd)
        mm = np.memmap(path, dtype=head.dtype, mode="w+",
                       shape=(total,) + head.shape[1:])
        off = 0
        for part in [head] + segs:
            mm[off:off + len(part)] = part
            off += len(part)
        mm.flush()

        def cleanup():
            try:
                os.unlink(path)
            except OSError:
                pass

        return mm, cleanup
