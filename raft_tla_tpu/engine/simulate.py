"""Simulation mode — TLC's ``-simulate`` as a vmap'd random-walk kernel.

TLC simulation generates random traces: from an initial state, repeatedly
pick a *uniformly random enabled* action instance, check invariants along the
way, and restart when the trace reaches the depth bound or cannot be extended
[TLC semantics — external; SURVEY §3.4].  The TPU shape is B independent
walkers advanced in lockstep by one ``lax.scan``:

    states [B] -> vmap(expand) -> enabled [B,G]
               -> masked categorical draw (one PRNG key per step)
               -> tree-gather the chosen successor per walker
               -> invariant ids; constraint/dead-end/depth-bound restarts

Each walker carries its current root index and a [depth] ring of the action
ids taken since its last restart, so the first violation latches a complete
(root, action sequence) pair on device; the host replays it through the
expand kernel into a full counterexample trace — the same replay mechanism
the BFS engine uses.  There is no seen-set — simulation never dedups — so
this mode exercises the pure expansion throughput of the machine.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.actions import build_expand
from ..models.dims import RaftDims
from ..models.invariants import build_inv_id
from ..models.pystate import PyState
from ..obs import MetricsRegistry
from ..models.schema import (StateBatch, build_pack_guard, check_packable,
                             decode_state, encode_state, flatten_state,
                             stack_states, state_width, unflatten_state)

_I32 = jnp.int32


@dataclasses.dataclass
class SimResult:
    steps: int = 0                  # states visited (one per walker-step)
    traces: int = 0                 # traces started (initial B + restarts)
    wall_seconds: float = 0.0
    violation_invariant: Optional[str] = None
    violation_state: Optional[PyState] = None
    violation_trace: Optional[List[Tuple[int, PyState]]] = None

    @property
    def states_per_second(self) -> float:
        return self.steps / self.wall_seconds if self.wall_seconds else 0.0


def build_sim_chunk(dims: RaftDims, inv_fns, constraint, B: int, D: int,
                    chunk: int, pipeline: str = "auto"):
    """Returns ``chunk_fn(rows, roots, tstep, cur_root, abuf, key)`` — the
    scan'd walker advance both the single-chip Simulator and the sharded
    parallel.simulate.MeshSimulator run (each chip is just an independent
    walker fleet with its own PRNG key; simulation never communicates).

    With the v2 pipeline (models/actions2.py; ``pipeline`` as in
    EngineConfig), each walker step computes guard masks only and
    constructs ONE successor — the drawn action — instead of all G
    candidates; masks/choice/successors are bit-identical to the v1
    path, so seeded runs agree across pipelines."""
    expand = build_expand(dims)
    pack_ok = build_pack_guard(dims)
    inv_id = build_inv_id(inv_fns)
    from .bfs import _resolve_pipeline
    v2 = _resolve_pipeline(pipeline, dims)

    def body(carry, key):
        (rows, roots, tstep, cur_root, abuf, restarts, latch) = carry
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        if v2 is None:
            cands, en, ovf = jax.vmap(expand)(states)
            # uint8-row wrap counts as overflow (schema.build_pack_guard):
            # the walker restarts rather than stepping through an aliased
            # row.  Invariants are still checked on the pre-pack candidate.
            ovf = ovf | (en & ~jax.vmap(jax.vmap(pack_ok))(cands))
        else:
            en, ovf = jax.vmap(v2.masks)(states)    # pack guard folded in
        # Uniform choice among enabled instances (masked categorical).
        logits = jnp.where(en, 0.0, -jnp.inf)
        choice = jax.random.categorical(key, logits, axis=-1)    # [B]
        can_step = jnp.any(en, axis=1)
        if v2 is None:
            nxt = jax.tree.map(lambda a: a[jnp.arange(B), choice], cands)
        else:
            ph = jax.vmap(v2.parent_hash)(states)   # DCE'd: hashes unused
            _h, _l, nxt = jax.vmap(v2.lane_out)(states, ph,
                                                choice.astype(_I32))
        nrows = jax.vmap(flatten_state, (0, None))(nxt, dims)

        if inv_fns:
            inv = jax.vmap(inv_id)(nxt)
        else:
            inv = jnp.full((B,), -1, _I32)
        bad = can_step & (inv >= 0)
        vf, vinv, vroot, vlen, vacts, vchoice = latch
        any_new = jnp.any(bad) & ~vf
        w = jnp.argmax(bad)
        latch = (vf | jnp.any(bad),
                 jnp.where(any_new, inv[w], vinv),
                 jnp.where(any_new, cur_root[w], vroot),
                 jnp.where(any_new, tstep[w], vlen),
                 jnp.where(any_new, abuf[w], vacts),
                 jnp.where(any_new, choice[w].astype(_I32), vchoice))

        if constraint is not None:
            cons_ok = jax.vmap(constraint)(nxt)
        else:
            cons_ok = jnp.ones((B,), bool)
        # Record the action taken since the last restart.
        abuf = abuf.at[jnp.arange(B),
                       jnp.clip(tstep, 0, D - 1)].set(
            jnp.where(can_step, choice.astype(_I32), -1))
        # Restart on: dead end, overflow, constraint stop, depth bound.
        restart = (~can_step | jnp.any(ovf, axis=1) | ~cons_ok
                   | (tstep + 1 >= D))
        root_idx = jax.random.randint(jax.random.fold_in(key, 1),
                                      (B,), 0, roots.shape[0])
        rows = jnp.where(restart[:, None], roots[root_idx],
                         jnp.where(can_step[:, None], nrows, rows))
        cur_root = jnp.where(restart, root_idx.astype(_I32), cur_root)
        tstep = jnp.where(restart, 0, tstep + 1)
        restarts = restarts + jnp.sum(restart, dtype=_I32)
        return (rows, roots, tstep, cur_root, abuf, restarts,
                latch), None

    def chunk_fn(rows, roots, tstep, cur_root, abuf, key):
        keys = jax.random.split(key, chunk)
        latch0 = (jnp.bool_(False), jnp.int32(-1), jnp.int32(0),
                  jnp.int32(0), jnp.zeros((D,), _I32), jnp.int32(-1))
        carry0 = (rows, roots, tstep, cur_root, abuf,
                  jnp.int32(0), latch0)
        carry, _ = jax.lax.scan(body, carry0, keys)
        return carry

    return chunk_fn


class Simulator:
    def __init__(self, dims: RaftDims,
                 invariants: Optional[Dict[str, Callable]] = None,
                 constraint: Optional[Callable] = None,
                 batch: int = 256, depth: int = 100, chunk: int = 128,
                 pipeline: str = "auto", metrics=None):
        self.dims = dims
        # Same telemetry spine as the BFS engines (obs/): phase timers
        # around the walker-advance dispatch and the latch fetch, live
        # step/trace counters.
        self.metrics = metrics or MetricsRegistry()
        self.inv_names = list((invariants or {}).keys())
        inv_fns = list((invariants or {}).values())
        self.batch, self.depth, self.chunk = batch, depth, chunk
        self._sw = state_width(dims)
        inv_id = build_inv_id(inv_fns)
        chunk_fn = build_sim_chunk(dims, inv_fns, constraint, batch, depth,
                                   chunk, pipeline=pipeline)

        def roots_inv(batch):
            # Takes the *unpacked* int32 StateBatch, not packed rows: uint8
            # packing wraps out-of-range root values (engine/bfs.py
            # build_root_check), which would mask a root TypeOK violation.
            if inv_fns:
                return jax.vmap(inv_id)(batch)
            return jnp.full(batch.term.shape[:1], -1, _I32)

        self._chunk = jax.jit(chunk_fn, donate_argnums=(0, 4))
        self._roots_inv = jax.jit(roots_inv)
        self._expand1 = jax.jit(build_expand(dims))

    # ------------------------------------------------------------------
    def _prepare_roots(self, roots: List[PyState], res: SimResult, t0):
        """Shared root handling (single-chip and mesh): TLC checks
        invariants on initial states too — a violating root ends the run
        immediately; otherwise reject silently-aliasing roots and return
        the packed root rows."""
        dims = self.dims
        encoded = [encode_state(s, dims) for s in roots]
        rinv = np.asarray(self._roots_inv(stack_states(encoded)))
        if (rinv >= 0).any():
            idx = int(np.argmax(rinv >= 0))
            res.violation_state = roots[idx]
            res.violation_trace = [(-1, roots[idx])]
            res.violation_invariant = self.inv_names[int(rinv[idx])]
            res.wall_seconds = time.time() - t0
            return None
        for e in encoded:
            check_packable(e, self.dims)
        return np.stack([flatten_state(e, dims) for e in encoded])

    def run(self, roots: List[PyState], num_steps: int, seed: int = 0,
            max_seconds: Optional[float] = None) -> SimResult:
        dims, B, D = self.dims, self.batch, self.depth
        res = SimResult()
        t0 = time.time()
        roots_np = self._prepare_roots(roots, res, t0)
        if roots_np is None:
            return res
        roots_j = jnp.asarray(roots_np)
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        start = jax.random.randint(sub, (B,), 0, len(roots)).astype(_I32)
        # Initial walker arrays are COMMITTED to the device: the jit
        # cache keys on placement, and uncommitted first-call inputs vs
        # committed carry outputs would recompile the whole scan program
        # on the second call (engine/bfs.py run() rationale).
        dev = jax.devices()[0]
        rows = jax.device_put(roots_j[start], dev)
        cur_root = jax.device_put(start, dev)
        tstep = jax.device_put(jnp.zeros((B,), _I32), dev)
        abuf = jax.device_put(jnp.zeros((B, D), _I32), dev)
        res.traces = B

        mt = self.metrics
        while res.steps < num_steps:
            key, sub = jax.random.split(key)
            with mt.phase_timer("sim_chunk"):
                carry = self._chunk(rows, roots_j, tstep, cur_root, abuf,
                                    sub)
                rows, _roots, tstep, cur_root, abuf, restarts, latch = carry
            res.steps += B * self.chunk
            # int(restarts) below is the blocking device sync of this
            # loop — the "sim_fetch" phase is the walkers' compute time.
            with mt.phase_timer("sim_fetch"):
                res.traces += int(restarts)
                vf, vinv, vroot, vlen, vacts, vchoice = latch
                vf = bool(vf)
            mt.counter("sim/steps", B * self.chunk)
            mt.gauge("sim/traces", res.traces)
            if vf:
                self._reconstruct(res, roots, int(vinv), int(vroot),
                                  int(vlen), np.asarray(vacts),
                                  int(vchoice))
                break
            if max_seconds is not None and time.time() - t0 > max_seconds:
                break
        res.wall_seconds = time.time() - t0
        return res

    # ------------------------------------------------------------------
    def _reconstruct(self, res: SimResult, roots, vinv, vroot, vlen,
                     vacts, vchoice):
        """Replay the latched (root, action sequence) through the kernels.

        The encoded candidate row is threaded through the loop directly:
        re-encoding each decoded PyState would reassign message slots
        (frozenset order), and slot-indexed action ids (Receive /
        Duplicate / Drop) recorded against the walker's slot layout
        would then address the wrong message mid-replay."""
        state = roots[vroot]
        st = encode_state(state, self.dims)
        trace = [(-1, state)]
        for g in list(vacts[:vlen]) + [vchoice]:
            g = int(g)
            cands, en, _ovf = self._expand1(st)
            if g < 0 or not bool(np.asarray(en)[g]):
                break
            row = jax.tree.map(lambda a: np.asarray(a)[g], cands)
            st = StateBatch(*row)
            state = decode_state(st, self.dims)
            trace.append((g, state))
        res.violation_state = state
        res.violation_trace = trace
        res.violation_invariant = (self.inv_names[vinv]
                                   if 0 <= vinv < len(self.inv_names)
                                   else "?")
