"""Swarm mode — W deterministic randomized walks per device in lockstep.

The second product tier (ROADMAP item 5): where the exhaustive engines
prove, the swarm *hunts*.  A swarm run advances W independent walkers
one action per step through the same BLEST-grouped expand kernels the
BFS engines use, with three structural differences that remove every
host round-trip from the hot loop:

- **no global seen-set** — each walk dedups against a fixed-size ring
  of its own last R accepted fingerprints (ops/walk_kernels.py), so
  throughput never pays the sorted-FPSet merge or its growth stalls;
- **counter-based PRNG** — every decision (successor draw, restart
  root) is a pure hash of ``(seed, walk, step)``, never a split-chain
  key.  A (seed, walks, depth) run therefore has a bit-identical
  visited-fingerprint multiset and an identical verdict across runs
  AND across device batch-size changes (tests/test_swarm.py pins it),
  and a violating walk is exactly replayable;
- **per-walk violation latch** — the same (root, action-ring) latch the
  simulator carries, extended with the global step index so the
  reported violation is the *globally first* one in (step, walk) order
  — partition-invariant, not a race between device slices.

Checking semantics match the simulator's TLC ``-simulate`` shape: every
step evaluates the registered invariants on the chosen successor,
walks restart on dead ends / pack overflow / constraint stops / ring
revisits / the depth bound, and a latched violation replays host-side
through the expand kernel into a full ``[(action, PyState)]`` trace —
``engine/explain.py`` renders it through the identical
``write_counterexample`` path as the exhaustive engines (this class
duck-types ``replay``/``dims``).

Telemetry speaks the swarm dialect of the house schema: ``swarm/steps``
/ ``swarm/walks`` / ``swarm/visited`` counters, ``swarm_progress`` run
events (payload object ``swarm``; registered in obs/events.py), a
statespace report with an embedded ``swarm`` block, and a ``run_end``
carrying the same ``swarm`` payload — so ``validate_run_events``, the
history ledger (``kind=swarm``) and the serving layer's job surface
consume swarm runs unchanged.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.actions import build_expand
from ..models.dims import RaftDims
from ..models.invariants import build_inv_id
from ..models.pystate import PyState
from ..models.schema import (StateBatch, build_pack_guard, check_packable,
                             decode_state, encode_state, flatten_state,
                             stack_states, unflatten_state)
from ..obs import (MetricsRegistry, RunEventLog, device_memory_stats,
                   events_path, phase_delta)
from ..obs.flight import RECORDER as _FLIGHT
from ..ops.fingerprint import build_fingerprint
from ..ops.walk_kernels import (CHOICE_STREAM, FAMILY_STREAM, INIT_STREAM,
                                ROOT_STREAM, bloom_init, bloom_probe,
                                bloom_push, family_subset, preferred_choice,
                                ring_init, ring_probe, ring_push, ring_reset,
                                walk_bits)
from .bfs import Violation, _resolve_pipeline

_I32 = jnp.int32
_U32 = jnp.uint32


@dataclasses.dataclass
class SwarmResult:
    """Swarm run outcome — swarm-native counters plus the EngineResult
    surface (stop_reason/distinct/generated/diameter/wall_seconds/
    pipeline/fused_stages/report/violation/counterexample) the history
    ledger, serving layer, and explainer already consume.  The ledger
    dialect: ``distinct`` is accepted (ring-fresh) state visits,
    ``generated`` is lockstep walk-steps executed."""
    walks: int = 0
    steps: int = 0              # lockstep walk-steps executed (W x rounds)
    visited: int = 0            # accepted state visits (ring-deduped)
    traces: int = 0             # walks started (W + restarts)
    distinct: int = 0           # = visited
    generated: int = 0          # = steps
    diameter: int = 0           # deepest trace depth any walk reached
    levels: List[int] = dataclasses.field(default_factory=list)
    stop_reason: str = "steps"
    wall_seconds: float = 0.0
    pipeline: str = ""
    fused_stages: Dict[str, str] = dataclasses.field(default_factory=dict)
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    report: Dict = dataclasses.field(default_factory=dict)
    violation: Optional[Violation] = None
    violation_trace: Optional[List[Tuple[int, PyState]]] = None
    #: Wall-clock seconds into the run when the violation latched — the
    #: swarm's headline "time to first counterexample" metric.
    violation_at_seconds: Optional[float] = None
    counterexample: Dict = dataclasses.field(default_factory=dict)
    #: Performance observatory block (obs/perf.py; ``perf=True``) —
    #: same shape as ``EngineResult.perf``.
    perf: Dict = dataclasses.field(default_factory=dict)
    #: ChunkProfiler stage means (``profile_chunks_every``) at the
    #: swarm granularity (choose/expand/ring_probe/latch).
    chunk_stages: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: The visited-fingerprint multiset as an [N, 2] uint32 (hi, lo)
    #: array, ONLY when the engine was built with
    #: ``collect_fingerprints=True`` (the determinism tests) — a
    #: throughput run must not ship every fingerprint to the host.
    visited_fingerprints: Optional[np.ndarray] = None

    @property
    def steps_per_second(self) -> float:
        return self.steps / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def walks_per_second(self) -> float:
        return self.traces / self.wall_seconds if self.wall_seconds else 0.0

    @property
    def states_per_second(self) -> float:
        return (self.visited / self.wall_seconds
                if self.wall_seconds else 0.0)


def build_swarm_chunk(dims: RaftDims, inv_fns, constraint, D: int, R: int,
                      chunk: int, pipeline: str = "auto",
                      hunt: bool = False):
    """Returns ``chunk_fn(rows, roots, tstep, cur_root, abuf, ring_hi,
    ring_lo, ring_pos, epoch, walk_ids, seed, k0, k_limit)`` — one
    jitted scan advancing every lane ``chunk`` lockstep steps from
    global step ``k0``.  Lane count is taken from ``rows``, so one
    builder serves the full slices and the remainder slice alike.
    Steps at or past ``k_limit`` are frozen no-ops (carry unchanged,
    nothing accepted, nothing latched): the host can run an exact
    ``num_steps`` budget in chunk-sized dispatches without a remainder
    recompile.

    With ``hunt=True`` (the hunt observatory, obs/hunt.py) the
    signature grows two trailing args ``(bloom1, bloom2)`` — the
    persistent seen>=1 / seen>=2 Bloom filters — the carry gains a
    13th element of analytics tallies (updated filters, fresh/promote/
    restart-reason scalars, the final-depth histogram, per-family
    efficacy counters), and ``ys`` gains per-step fresh/accept counts.
    Every hunt value is DERIVED from the walk decisions and feeds
    nothing back: choice, accept, latch and the fingerprint stream are
    bit-identical with hunt off (tests/test_swarm.py pins it).
    Per-species observation counts are exact within a dispatch (an
    O(lanes^2) same-fingerprint prior count joins the filters), so the
    Good-Turing totals are partition-invariant up to Bloom collisions.

    The successor draw is **family-diversified** (Holzmann swarm
    style): each (walk, trace) draws a keep-subset of the model's
    action families from the ``FAMILY_STREAM`` counter hash keyed on
    the lane's trace ``epoch`` (restart count), and chooses uniformly
    among enabled instances of kept families, falling back to all
    enabled when the subset is empty there.  A uniform instance draw
    lets the biggest families (raft's 96 message-handling lanes of 132)
    flood the hunt; the per-trace subset makes each trace a focused
    walk through a random sub-model — time-to-counterexample on the
    NoLeaderElected canary drops ~20x.  The mask is a pure function of
    (seed, walk, epoch), so replayability and partition invariance are
    untouched."""
    expand = build_expand(dims)
    pack_ok = build_pack_guard(dims)
    inv_id = build_inv_id(inv_fns)
    fingerprint = build_fingerprint(dims)
    v2 = _resolve_pipeline(pipeline, dims)
    fam = jnp.asarray(np.repeat(
        np.arange(len(dims.family_sizes), dtype=np.int32),
        dims.family_sizes))
    n_fam = len(dims.family_sizes)

    def chunk_fn(rows, roots, tstep, cur_root, abuf, ring_hi, ring_lo,
                 ring_pos, epoch, walk_ids, seed, k0, k_limit,
                 *hunt_state):
        B = rows.shape[0]
        lanes = jnp.arange(B)

        def body(carry, k):
            (rows, tstep, cur_root, abuf, rh, rl, rp, epoch, restarts,
             visited, depth_max, latch) = carry[:12]
            act = k < k_limit
            states = jax.vmap(unflatten_state, (0, None))(rows, dims)
            if v2 is None:
                cands, en, ovf = jax.vmap(expand)(states)
                # uint8-row wrap counts as overflow (simulator rule):
                # restart rather than step through an aliased row.
                ovf = ovf | (en & ~jax.vmap(jax.vmap(pack_ok))(cands))
            else:
                en, ovf = jax.vmap(v2.masks)(states)  # pack guard folded
            bits = walk_bits(seed, walk_ids, k, CHOICE_STREAM)
            mbits = walk_bits(seed, walk_ids, epoch, FAMILY_STREAM)
            choice = preferred_choice(bits, en, family_subset(mbits, fam))
            can_step = jnp.any(en, axis=1) & act
            if v2 is None:
                nxt = jax.tree.map(lambda a: a[lanes, choice], cands)
            else:
                ph = jax.vmap(v2.parent_hash)(states)  # DCE'd: unused
                _h, _l, nxt = jax.vmap(v2.lane_out)(states, ph,
                                                    choice.astype(_I32))
            nrows = jax.vmap(flatten_state, (0, None))(nxt, dims)
            fp_hi, fp_lo = jax.vmap(fingerprint)(nxt)

            if inv_fns:
                inv = jax.vmap(inv_id)(nxt)
            else:
                inv = jnp.full((B,), -1, _I32)
            bad = can_step & (inv >= 0)
            # Latch the slice's FIRST violation: first step with any bad
            # lane, lowest lane at that step.  The step index rides
            # along so the host can pick the global (step, walk) minimum
            # across slices — the partition-invariant verdict.
            (vf, vinv, vroot, vlen, vacts, vchoice,
             vwalk, vstep, vhi, vlo) = latch
            any_new = jnp.any(bad) & ~vf
            w = jnp.argmax(bad)
            latch = (vf | jnp.any(bad),
                     jnp.where(any_new, inv[w], vinv),
                     jnp.where(any_new, cur_root[w], vroot),
                     jnp.where(any_new, tstep[w], vlen),
                     jnp.where(any_new, abuf[w], vacts),
                     jnp.where(any_new, choice[w].astype(_I32), vchoice),
                     jnp.where(any_new, walk_ids[w].astype(_I32), vwalk),
                     jnp.where(any_new, k.astype(_I32), vstep),
                     jnp.where(any_new, fp_hi[w], vhi),
                     jnp.where(any_new, fp_lo[w], vlo))

            if constraint is not None:
                cons_ok = jax.vmap(constraint)(nxt)
            else:
                cons_ok = jnp.ones((B,), bool)
            seen = ring_probe(rh, rl, fp_hi, fp_lo)
            accept = (can_step & ~jnp.any(ovf, axis=1) & cons_ok & ~seen)
            # Record the action taken since the last restart (before the
            # restart decision, mirroring the simulator's abuf contract).
            abuf = abuf.at[lanes, jnp.clip(tstep, 0, D - 1)].set(
                jnp.where(can_step, choice.astype(_I32), -1))
            rh, rl, rp = ring_push(rh, rl, rp, fp_hi, fp_lo, accept)
            # Restart on: dead end, overflow, constraint stop, ring
            # revisit (all folded into ~accept) or the depth bound.
            restart = (~accept | (tstep + 1 >= D)) & act

            if hunt:
                # Hunt observatory tallies — every value below is
                # derived from the decisions already made above and
                # feeds NOTHING back into them (the on/off bit-identity
                # contract).  Species accounting: the two persistent
                # Bloom filters give each accepted visit's prior
                # observation count (capped at 2), exact within this
                # dispatch via the same-fingerprint prior count over
                # earlier lanes of the same step.
                (b1, b2, fresh_t, promote_t, revisit_t, dead_t, povf_t,
                 cons_t, dbound_t, dhist, fch, fac, ffr) = carry[12]
                in1 = bloom_probe(b1, fp_hi, fp_lo)
                in2 = bloom_probe(b2, fp_hi, fp_lo)
                eqm = ((fp_hi[:, None] == fp_hi[None, :])
                       & (fp_lo[:, None] == fp_lo[None, :])
                       & accept[None, :])
                prior = jnp.sum(jnp.tril(eqm, -1), axis=1, dtype=_I32)
                nobs = in1.astype(_I32) + in2.astype(_I32) + prior
                fresh = accept & (nobs == 0)
                promote = accept & (nobs == 1)
                b1 = bloom_push(b1, fp_hi, fp_lo, accept)
                b2 = bloom_push(b2, fp_hi, fp_lo, accept & (nobs >= 1))
                # Restart-reason census, in the engine's decision order
                # (the first failing rule owns the restart): together
                # with the depth bound these partition ``restart``.
                anyovf = jnp.any(ovf, axis=1)
                deadend = ~can_step & act
                ovfstop = can_step & anyovf
                consstop = can_step & ~anyovf & ~cons_ok
                revisit = can_step & ~anyovf & cons_ok & seen
                dbound = accept & (tstep + 1 >= D)
                # Final depth of each completed trace (masked lanes
                # contribute an add of 0 — scatter-add, never a branch).
                dfin = jnp.clip(jnp.where(accept, tstep + 1, tstep),
                                0, D)
                dhist = dhist.at[dfin].add(restart.astype(_I32))
                # Per-family efficacy: which diversification families
                # get chosen, land accepted states, and find FRESH ones.
                fidx = fam[choice]
                fch = fch.at[fidx].add(can_step.astype(_I32))
                fac = fac.at[fidx].add(accept.astype(_I32))
                ffr = ffr.at[fidx].add(fresh.astype(_I32))
                hcarry = (b1, b2,
                          fresh_t + jnp.sum(fresh, dtype=_I32),
                          promote_t + jnp.sum(promote, dtype=_I32),
                          revisit_t + jnp.sum(revisit, dtype=_I32),
                          dead_t + jnp.sum(deadend, dtype=_I32),
                          povf_t + jnp.sum(ovfstop, dtype=_I32),
                          cons_t + jnp.sum(consstop, dtype=_I32),
                          dbound_t + jnp.sum(dbound, dtype=_I32),
                          dhist, fch, fac, ffr)
                hys = (jnp.sum(fresh, dtype=_I32),
                       jnp.sum(accept, dtype=_I32))
            root_idx = (walk_bits(seed, walk_ids, k, ROOT_STREAM)
                        % _U32(roots.shape[0])).astype(_I32)
            rows = jnp.where(restart[:, None], roots[root_idx],
                             jnp.where(accept[:, None], nrows, rows))
            cur_root = jnp.where(restart, root_idx, cur_root)
            rh, rl, rp = ring_reset(rh, rl, rp, restart)
            depth_max = jnp.maximum(
                depth_max, jnp.max(jnp.where(accept, tstep + 1, 0)))
            tstep = jnp.where(restart, 0,
                              jnp.where(accept, tstep + 1, tstep))
            # A restart begins the walk's next trace: bump its epoch so
            # the FAMILY_STREAM mask re-draws — every trace hunts a
            # fresh random sub-model.
            epoch = epoch + restart.astype(_I32)
            restarts = restarts + jnp.sum(restart, dtype=_I32)
            visited = visited + jnp.sum(accept, dtype=_I32)
            out = (rows, tstep, cur_root, abuf, rh, rl, rp, epoch,
                   restarts, visited, depth_max, latch)
            if hunt:
                return out + (hcarry,), (fp_hi, fp_lo, accept) + hys
            return out, (fp_hi, fp_lo, accept)

        latch0 = (jnp.bool_(False), jnp.int32(-1), jnp.int32(0),
                  jnp.int32(0), jnp.zeros((D,), _I32), jnp.int32(-1),
                  jnp.int32(-1), jnp.int32(-1), _U32(0), _U32(0))
        carry0 = (rows, tstep, cur_root, abuf, ring_hi, ring_lo, ring_pos,
                  epoch, jnp.int32(0), jnp.int32(0), jnp.int32(0), latch0)
        if hunt:
            bloom1, bloom2 = hunt_state
            z = jnp.int32(0)
            carry0 = carry0 + ((bloom1, bloom2, z, z, z, z, z, z, z,
                                jnp.zeros((D + 1,), _I32),
                                jnp.zeros((n_fam,), _I32),
                                jnp.zeros((n_fam,), _I32),
                                jnp.zeros((n_fam,), _I32)),)
        ks = k0 + jnp.arange(chunk, dtype=_I32)
        return jax.lax.scan(body, carry0, ks)

    return chunk_fn


class SwarmEngine:
    """W lockstep randomized walks; see the module docstring.

    ``batch`` caps lanes per device dispatch (walks are sliced across
    dispatches; slicing never changes any walk's trajectory).  ``ring``
    is the per-walk dedup capacity R.  ``chunk`` is scan steps per
    dispatch — it bounds how far past a violation the run computes, but
    neither the verdict nor an exact ``num_steps`` multiset depends on
    it."""

    def __init__(self, dims: RaftDims,
                 invariants: Optional[Dict[str, Callable]] = None,
                 constraint: Optional[Callable] = None, *,
                 walks: int = 1024, max_depth: int = 128,
                 batch: Optional[int] = None, chunk: int = 32,
                 ring: int = 16, pipeline: str = "auto", metrics=None,
                 events_out: Optional[str] = None,
                 checkpoint_dir: Optional[str] = None,
                 postmortem_dir: Optional[str] = None,
                 counterexample_dir: Optional[str] = None,
                 collect_fingerprints: bool = False,
                 progress_seconds: float = 5.0,
                 run_context_extra: Optional[dict] = None,
                 hunt: bool = True, hunt_cells: int = 1 << 20,
                 perf: bool = False,
                 profile_chunks_every: Optional[int] = None,
                 xla_profile_chunks: Optional[int] = None,
                 xla_profile_dir: Optional[str] = None):
        if walks < 1:
            raise ValueError(f"walks must be >= 1, got {walks}")
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.dims = dims
        self.metrics = metrics or MetricsRegistry()
        self.inv_names = list((invariants or {}).keys())
        inv_fns = list((invariants or {}).values())
        self.walks, self.max_depth, self.ring = walks, max_depth, ring
        self.batch = min(batch or walks, walks)
        self.chunk = chunk
        self.events_out = events_out
        self.checkpoint_dir = checkpoint_dir
        self.postmortem_dir = postmortem_dir
        self.counterexample_dir = counterexample_dir
        self.collect_fingerprints = collect_fingerprints
        self.progress_seconds = progress_seconds
        self.run_context_extra = run_context_extra
        #: Hunt observatory (obs/hunt.py): ON by default — the tallies
        #: are a handful of scalars per chunk and the saturation gauge
        #: is the product's whole "when to stop" answer.  ``hunt=False``
        #: builds the bare chunk program (the bit-identity reference
        #: and the throughput ceiling).
        self.hunt = hunt
        self.hunt_cells = int(hunt_cells)
        self._hunt_acc = None
        self.pipeline_name = ("v2" if _resolve_pipeline(pipeline, dims)
                              is not None else "v1")
        inv_id = build_inv_id(inv_fns)
        self._chunk = jax.jit(build_swarm_chunk(
            dims, inv_fns, constraint, max_depth, ring, chunk,
            pipeline=pipeline, hunt=hunt))
        # Per-stage chunk profiler at the swarm granularity
        # (choose/expand/ring_probe/latch; obs/profile.py).  Same
        # cadence contract as the BFS engine: --perf implies sparse
        # sampling (every 16th) when no cadence was chosen; an explicit
        # 0 keeps it off.
        prof_every = (profile_chunks_every
                      if profile_chunks_every is not None
                      else (16 if perf else None))
        self._profiler = None
        if prof_every:
            from ..obs import ChunkProfiler
            self._profiler = ChunkProfiler(
                dims, batch=self.batch, lanes=dims.n_instances,
                seen_capacity=1 << 10, pipeline="swarm",
                swarm_pipeline=self.pipeline_name, ring=ring,
                every=prof_every, metrics=self.metrics)
        # Performance observatory (obs/perf.py): trace THE jitted chunk
        # program above — scan body, hunt tallies and all — for the
        # CI-pinned static launch model, plus the walk-kernel stage
        # traffic floors for the roofline.  Fail-soft like the BFS
        # engine's: a failed model degrades to nulls, never a failed
        # engine build.
        self._perf = None
        if perf:
            from ..models.schema import state_width
            from ..obs import perf as perf_mod
            B = self.batch
            sw = state_width(dims)
            i32 = jax.ShapeDtypeStruct((), jnp.int32)
            u32 = jax.ShapeDtypeStruct((), jnp.uint32)
            li32 = jax.ShapeDtypeStruct((B,), jnp.int32)
            avals = (jax.ShapeDtypeStruct((B, sw), jnp.uint8),
                     jax.ShapeDtypeStruct((2, sw), jnp.uint8),
                     li32, li32,
                     jax.ShapeDtypeStruct((B, max_depth), jnp.int32),
                     jax.ShapeDtypeStruct((B, ring), jnp.uint32),
                     jax.ShapeDtypeStruct((B, ring), jnp.uint32),
                     li32, li32, li32, u32, i32, i32)
            if hunt:
                bl = jax.ShapeDtypeStruct((self.hunt_cells,), jnp.uint8)
                avals = avals + (bl, bl)
            self._perf = perf_mod.build_accounting(
                pipeline="swarm", chunk_fn=self._chunk,
                chunk_avals=avals, dims=dims, B=B, K=dims.n_instances,
                ring=ring, swarm_pipeline=self.pipeline_name,
                metrics=self.metrics, engine="swarm")
        self._xla_chunks = xla_profile_chunks
        self._xla_dir = xla_profile_dir
        self._xla_capture = None

        def roots_inv(batch):
            # Unpacked int32 StateBatch (simulator rule): uint8 packing
            # wraps out-of-range roots, masking a root TypeOK violation.
            if inv_fns:
                return jax.vmap(inv_id)(batch)
            return jnp.full(batch.term.shape[:1], -1, _I32)

        self._roots_inv = jax.jit(roots_inv)
        self._expand1 = jax.jit(build_expand(dims))
        self._fp1 = jax.jit(build_fingerprint(dims))
        self._last_trace: Optional[List[Tuple[int, PyState]]] = None

    # -- explain.py duck-type surface ----------------------------------
    def replay(self, fp: int) -> List[Tuple[int, PyState]]:
        """The explainer contract (engine/bfs.py replay): the traced
        violation's full ``[(action_id, PyState)]`` path root-first.
        The swarm reconstructs its single latched trace at violation
        time; only that fingerprint is replayable."""
        if self._last_trace is None:
            raise KeyError(f"no traced violation to replay ({fp:#x})")
        return list(self._last_trace)

    def _postmortem_path(self):
        d = self.postmortem_dir or self.checkpoint_dir
        return os.path.join(d, "postmortem.json") if d else None

    # -- run -----------------------------------------------------------
    def run(self, roots: List[PyState], *, seed: int = 0,
            num_steps: Optional[int] = None,
            max_seconds: Optional[float] = None) -> SwarmResult:
        """Run the swarm: every walk advances in lockstep until the
        first latched violation, the ``max_seconds`` budget, or
        ``num_steps`` steps per walk (default ``max_depth`` when no
        time budget is given — one depth-budget's worth of steps)."""
        res = SwarmResult(walks=self.walks, pipeline=self.pipeline_name)
        mt = self.metrics
        if num_steps is None and max_seconds is None:
            num_steps = self.max_depth
        # Per-run telemetry state (warm engines reuse the static
        # halves: compiled programs, launch model, stage programs).
        self._hunt_acc = None
        if self._profiler is not None:
            self._profiler.reset()
        if self._perf is not None:
            self._perf.reset()
        self._xla_capture = None
        if self._xla_chunks:
            from ..obs import XlaProfileCapture
            self._xla_capture = XlaProfileCapture(
                self._xla_dir or os.path.join(
                    self.checkpoint_dir or ".", "xla_profile"),
                self._xla_chunks)
        t0 = time.time()
        evlog = RunEventLog(events_path(self.events_out,
                                        self.checkpoint_dir))
        phase_base = mt.phase_seconds()
        _FLIGHT.arm(self._postmortem_path(), metrics=mt, context={
            "engine": type(self).__name__, "mode": "swarm",
            "dims": repr(self.dims), "walks": self.walks,
            "max_depth": self.max_depth, "batch": self.batch,
            "ring": self.ring, "pipeline": self.pipeline_name,
            **dict(self.run_context_extra or {})})
        _FLIGHT.set_live_evlog(evlog)
        evlog.emit("run_start", engine=type(self).__name__, mode="swarm",
                   dims=repr(self.dims), walks=self.walks,
                   max_depth=self.max_depth, batch=self.batch,
                   ring=self.ring, seed=seed, num_steps=num_steps,
                   memory=device_memory_stats())
        err = None
        try:
            self._run_impl(roots, res, seed, num_steps, max_seconds,
                           evlog, t0)
            return res
        except BaseException as e:
            err = e
            raise
        finally:
            res.wall_seconds = time.time() - t0
            res.distinct, res.generated = res.visited, res.steps
            res.phases = phase_delta(mt.phase_seconds(), phase_base)
            ce_path = None
            ce_dir = self.counterexample_dir or self.checkpoint_dir
            if err is None and res.violation is not None and ce_dir:
                try:
                    from .explain import write_counterexample
                    res.counterexample = write_counterexample(
                        self, res, ce_dir)
                    ce_path = res.counterexample["txt"]
                except Exception as e:
                    import sys as _sys
                    print(f"counterexample render failed: "
                          f"{type(e).__name__}: {e}", file=_sys.stderr)
            # Profiler / perf / device-capture run-end hooks, the BFS
            # engine's order: the profiler lands its means first (the
            # roofline's measured half), perf prices them, the capture
            # window closes whether the run lived or died.
            if self._profiler is not None:
                res.chunk_stages = self._profiler.stage_means()
                self._profiler.finish(evlog)
            if self._perf is not None and err is None:
                try:
                    res.perf = self._perf.finish(
                        evlog, chunk_stages=res.chunk_stages)
                except Exception as e:
                    import sys as _sys
                    print(f"perf: block assembly failed "
                          f"({type(e).__name__}: {e})", file=_sys.stderr)
            if self._xla_capture is not None:
                self._xla_capture.finish(evlog)
            # The hunt report (obs/hunt.py): the swarm sibling of the
            # statespace report, riding the same surfaces — its own
            # ``hunt`` run event, the report dict, gauges, flight ring.
            hunt_report = None
            if self._hunt_acc is not None and err is None:
                from ..obs import hunt as hunt_mod
                hunt_report = hunt_mod.build_report(
                    self._hunt_acc,
                    violation_at_seconds=res.violation_at_seconds,
                    wall_seconds=res.wall_seconds)
                evlog.emit("hunt", hunt=hunt_report)
                hunt_mod.feed_metrics(hunt_report, mt)
                _FLIGHT.record("hunt", **self._hunt_acc.snapshot())
            swarm_block = self._swarm_block(res)
            if err is None:
                res.report = {
                    "collision": {"calculated": 0.0},
                    "diameter": res.diameter,
                    "verdict": ("violation" if res.violation is not None
                                else "ok"),
                    "levels": [],
                    "mode": "swarm",
                    "swarm": swarm_block,
                }
                if hunt_report is not None:
                    res.report["hunt"] = hunt_report
                evlog.emit("statespace", report=res.report)
            pm_path = None
            if err is not None:
                pm_path = _FLIGHT.dump(
                    f"swarm run error: {type(err).__name__}: {err}")
            evlog.emit(
                "run_end",
                stop_reason=(res.stop_reason if err is None else "error"),
                error=(f"{type(err).__name__}: {err}"
                       if err is not None else None),
                postmortem_path=pm_path,
                counterexample_path=ce_path,
                distinct=res.distinct, generated=res.generated,
                diameter=res.diameter, levels=[],
                wall_seconds=res.wall_seconds,
                phase_seconds=res.phases, swarm=swarm_block,
                memory=device_memory_stats())
            _FLIGHT.set_live_evlog(None)
            _FLIGHT.disarm()
            evlog.close()

    def _swarm_block(self, res: SwarmResult) -> dict:
        """The ``swarm`` payload object shared by ``swarm_progress``,
        ``run_end``, and the statespace report.  Hunt-enabled runs
        embed the live hunt snapshot (saturation, unseen mass, recent
        novelty) so a ``watch`` stream answers "when to stop" from the
        progress line alone."""
        out = {"walks": res.walks, "steps": res.steps,
               "visited": res.visited, "traces": res.traces,
               "max_depth": self.max_depth, "ring": self.ring,
               "steps_per_sec": round(res.steps_per_second, 1),
               "walks_per_sec": round(res.walks_per_second, 1),
               "visited_per_sec": round(res.states_per_second, 1),
               "violation_at_seconds": res.violation_at_seconds}
        if self._hunt_acc is not None:
            out["hunt"] = self._hunt_acc.snapshot()
        return out

    def _prepare_roots(self, roots: List[PyState], res: SwarmResult):
        """TLC checks invariants on initial states too: a violating
        root ends the run immediately with a length-1 trace."""
        dims = self.dims
        encoded = [encode_state(s, dims) for s in roots]
        rinv = np.asarray(self._roots_inv(stack_states(encoded)))
        if (rinv >= 0).any():
            idx = int(np.argmax(rinv >= 0))
            hi, lo = self._fp1(encoded[idx])
            fp = (int(hi) << 32) | int(lo)
            res.violation = Violation(
                invariant=self.inv_names[int(rinv[idx])],
                state=roots[idx], fingerprint=fp)
            res.violation_trace = [(-1, roots[idx])]
            self._last_trace = res.violation_trace
            res.stop_reason = "violation"
            res.violation_at_seconds = 0.0
            return None
        for e in encoded:
            check_packable(e, self.dims)
        return np.stack([flatten_state(e, dims) for e in encoded])

    def _run_impl(self, roots, res, seed, num_steps, max_seconds,
                  evlog, t0):
        W, D, B = self.walks, self.max_depth, self.batch
        mt = self.metrics
        roots_np = self._prepare_roots(roots, res)
        if roots_np is None:
            return
        dev = jax.devices()[0]
        roots_j = jax.device_put(jnp.asarray(roots_np), dev)
        n_roots = roots_np.shape[0]
        k_limit = jnp.int32(num_steps if num_steps is not None
                            else np.iinfo(np.int32).max)
        seed_j = _U32(np.uint32(seed & 0xFFFFFFFF))

        # Walk slices: global walk ids 0..W-1 in ``batch``-lane device
        # dispatches.  Everything per-walk depends only on (seed,
        # walk_id, step), so the slicing is invisible to the walks.
        slices = []
        for off in range(0, W, B):
            ids = np.arange(off, min(off + B, W), dtype=np.int32)
            lanes = len(ids)
            walk_ids = jax.device_put(jnp.asarray(ids), dev)
            root0 = (np.asarray(walk_bits(seed_j, walk_ids, 0,
                                          INIT_STREAM))
                     % n_roots).astype(np.int32)
            rh, rl, rp = ring_init(lanes, self.ring)
            slices.append({
                "walk_ids": walk_ids,
                "rows": jax.device_put(roots_j[jnp.asarray(root0)], dev),
                "tstep": jax.device_put(jnp.zeros((lanes,), _I32), dev),
                "cur_root": jax.device_put(jnp.asarray(root0), dev),
                "abuf": jax.device_put(jnp.zeros((lanes, D), _I32), dev),
                "ring_hi": jax.device_put(rh, dev),
                "ring_lo": jax.device_put(rl, dev),
                "ring_pos": jax.device_put(rp, dev),
                "epoch": jax.device_put(jnp.zeros((lanes,), _I32), dev),
                "visited": 0, "latch": None, "ys": None,
            })
        res.traces = W
        mt.counter("swarm/walks", W)
        mt.gauge("swarm/active_walks", W)

        hunt_args = ()
        if self.hunt:
            from ..obs import hunt as hunt_mod
            self._hunt_acc = hunt_mod.HuntAccumulator(
                self.dims.family_names, D,
                bloom_cells=self.hunt_cells)
            # The filters are SHARED across slices, threaded through
            # the sequential dispatches: the Good-Turing totals then
            # see one global observation stream regardless of how the
            # walks were sliced (only the per-step series reorders).
            hunt_args = (jax.device_put(bloom_init(self.hunt_cells), dev),
                         jax.device_put(bloom_init(self.hunt_cells), dev))
        hacc = self._hunt_acc
        prof = self._profiler
        cap = self._xla_capture

        fps_acc: List[np.ndarray] = []
        k0 = 0
        depth_max = 0
        last_progress = t0
        while True:
            if prof is not None and prof.want():
                # Observational side-channel: re-run the first (always
                # full-width) slice's current rows through the staged
                # walk-kernel programs for per-stage timings.
                prof.sample(slices[0]["rows"],
                            np.ones((self.batch,), bool))
            tc0 = time.perf_counter()
            with mt.phase_timer("swarm_chunk"):
                step_cm = cap.step() if cap is not None else None
                if step_cm is not None:
                    step_cm.__enter__()
                try:
                    for s in slices:
                        carry, ys = self._chunk(
                            s["rows"], roots_j, s["tstep"],
                            s["cur_root"], s["abuf"], s["ring_hi"],
                            s["ring_lo"], s["ring_pos"], s["epoch"],
                            s["walk_ids"], seed_j, jnp.int32(k0),
                            k_limit, *hunt_args)
                        (s["rows"], s["tstep"], s["cur_root"], s["abuf"],
                         s["ring_hi"], s["ring_lo"], s["ring_pos"],
                         s["epoch"], s["restarts"], s["visited_d"],
                         s["depth_d"], s["latch"]) = carry[:12]
                        s["ys"] = ys
                        if self.hunt:
                            s["hunt"] = carry[12]
                            hunt_args = carry[12][:2]
                finally:
                    if step_cm is not None:
                        step_cm.__exit__(None, None, None)
            stepped = min(self.chunk,
                          max(0, int(k_limit) - k0)) if num_steps \
                else self.chunk
            if self._perf is not None:
                self._perf.add_chunk(len(slices),
                                     time.perf_counter() - tc0)
            k_start = k0
            k0 += self.chunk
            res.steps += W * stepped
            fired = []
            novel_steps = accept_steps = None
            with mt.phase_timer("swarm_fetch"):
                for s in slices:
                    res.traces += int(s["restarts"])
                    mt.counter("swarm/walks", int(s["restarts"]))
                    v = int(s["visited_d"])
                    res.visited += v
                    mt.counter("swarm/visited", v)
                    depth_max = max(depth_max, int(s["depth_d"]))
                    vf = bool(s["latch"][0])
                    if vf:
                        fired.append(s["latch"])
                    if hacc is not None:
                        hc = s["hunt"]
                        hacc.add_slice(
                            fresh=int(hc[2]), promote=int(hc[3]),
                            # RESTART_REASONS order: deadend, overflow,
                            # constraint, revisit, depth_bound.
                            reasons=(int(hc[5]), int(hc[6]), int(hc[7]),
                                     int(hc[4]), int(hc[8])),
                            depth_hist=np.asarray(hc[9]),
                            fam_chosen=np.asarray(hc[10]),
                            fam_accept=np.asarray(hc[11]),
                            fam_fresh=np.asarray(hc[12]))
                        nv = np.asarray(s["ys"][3])
                        av = np.asarray(s["ys"][4])
                        novel_steps = (nv if novel_steps is None
                                       else novel_steps + nv)
                        accept_steps = (av if accept_steps is None
                                        else accept_steps + av)
                    if self.collect_fingerprints:
                        hi, lo, acc = (np.asarray(a)
                                       for a in s["ys"][:3])
                        m = acc.reshape(-1)
                        fps_acc.append(np.stack(
                            [hi.reshape(-1)[m], lo.reshape(-1)[m]],
                            axis=1))
            if hacc is not None and stepped:
                hacc.add_steps(k_start + stepped, W * stepped,
                               novel_steps[:stepped],
                               accept_steps[:stepped])
            mt.counter("swarm/steps", W * stepped)
            res.diameter = depth_max
            now = time.time()
            if (k0 == self.chunk
                    or now - last_progress >= self.progress_seconds):
                last_progress = now
                res.wall_seconds = now - t0
                evlog.emit("swarm_progress", depth=k0,
                           swarm=self._swarm_block(res))
                flight_extra = {}
                if hacc is not None:
                    snap = hacc.snapshot()
                    mt.gauge("hunt/saturation", snap["saturation"])
                    mt.gauge("hunt/unseen_mass", snap["unseen_mass"])
                    mt.gauge("hunt/distinct_observed",
                             snap["distinct_observed"])
                    mt.gauge("hunt/novel_rate",
                             snap["novel_rate_recent"])
                    mt.gauge("hunt/revisit_rate", snap["revisit_rate"])
                    _FLIGHT.record("hunt", steps=res.steps, **snap)
                    flight_extra["saturation"] = snap["saturation"]
                _FLIGHT.progress(mode="swarm", steps=res.steps,
                                 visited=res.visited, traces=res.traces,
                                 **flight_extra)
            if fired:
                # Globally first violation in (step, walk) order — the
                # partition-invariant pick across slices.
                latch = min(fired, key=lambda lt: (int(lt[7]),
                                                   int(lt[6])))
                self._reconstruct(res, roots, latch)
                res.stop_reason = "violation"
                res.violation_at_seconds = round(time.time() - t0, 6)
                evlog.emit("violation",
                           invariant=(res.violation.invariant
                                      if res.violation else "?"),
                           fingerprint=(hex(res.violation.fingerprint)
                                        if res.violation else None),
                           walk=int(latch[6]), step=int(latch[7]),
                           at_seconds=res.violation_at_seconds)
                break
            if max_seconds is not None and time.time() - t0 > max_seconds:
                res.stop_reason = "max_seconds"
                break
            if num_steps is not None and k0 >= num_steps:
                res.stop_reason = "steps"
                break
        if hacc is not None and hunt_args:
            b1 = np.asarray(hunt_args[0])
            hacc.bloom_load = float(np.count_nonzero(b1)) / b1.size
        if self.collect_fingerprints:
            res.visited_fingerprints = (
                np.concatenate(fps_acc, axis=0) if fps_acc
                else np.zeros((0, 2), np.uint32))

    def _reconstruct(self, res: SwarmResult, roots, latch):
        """Replay the latched (root, action sequence) through the expand
        kernel — the simulator's reconstruction, including its
        slot-aliasing rule: thread the ENCODED candidate row, never
        re-encode the decoded state (re-encoding reassigns message
        slots and slot-indexed action ids would then address the wrong
        message mid-replay)."""
        (_vf, vinv, vroot, vlen, vacts, vchoice, _vwalk, _vstep,
         vhi, vlo) = latch
        vinv, vroot, vlen = int(vinv), int(vroot), int(vlen)
        vacts = np.asarray(vacts)
        state = roots[vroot]
        st = encode_state(state, self.dims)
        trace = [(-1, state)]
        for g in list(vacts[:vlen]) + [int(vchoice)]:
            g = int(g)
            cands, en, _ovf = self._expand1(st)
            if g < 0 or not bool(np.asarray(en)[g]):
                break
            row = jax.tree.map(lambda a: np.asarray(a)[g], cands)
            st = StateBatch(*row)
            state = decode_state(st, self.dims)
            trace.append((g, state))
        fp = (int(vhi) << 32) | int(vlo)
        res.violation = Violation(
            invariant=(self.inv_names[vinv]
                       if 0 <= vinv < len(self.inv_names) else "?"),
            state=state, fingerprint=fp)
        res.violation_trace = trace
        self._last_trace = trace
