"""Checkpoint/resume — TLC's ``states/`` snapshot dir rebuilt (SURVEY §2.4 R8).

TLC periodically writes its FPSet + unexplored-queue to the ``states/``
directory so an interrupted run can resume (acknowledged by the reference's
``.gitignore:1``).  The TPU engine's equivalent is a *level-boundary*
snapshot: because the BFS is level-synchronous, the complete engine state
between levels is exactly

    (frontier rows, FPSet keys, counters, trace records, trace roots)

and all of it is host-materializable as flat numpy arrays.  One compressed
``.npz`` per snapshot, written atomically (tmp + rename) so a crash during
write never corrupts the latest good checkpoint.

Resume restores the FPSet by sentinel-padding the saved (already lex-sorted)
key arrays back to capacity — no re-hashing, no re-exploration: the run
continues from the exact level it stopped at, and counterexample replay
still reaches roots discovered before the interruption.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
import re
from typing import Dict, Optional, Tuple

import numpy as np

from ..models.dims import RaftDims
from ..models.pystate import PyState
from ..resilience import faults

# v2: frontier rows are packed uint8 (v1 stored int32 rows with no value
# bounds; loading them into the packed engine could wrap silently, so v1
# files are rejected rather than converted).
# v3: the fingerprint function changed (ops/fingerprint.py hardening,
# 2026-07-31) — v2 snapshots' seen-keys and trace fingerprints are keyed
# by the old hash; resuming them would re-count explored states as new,
# so they are rejected rather than silently mis-resumed.
# v4: metadata carries the dims *class* and the packed row width.  v3
# restore rebuilt every checkpoint as base RaftDims, so a ReconfigDims
# snapshot could not round-trip (TypeError on its 'targets' key), and the
# variant's 2-byte value lanes changed state_width with no version signal
# — a stale variant snapshot would have died with an opaque shape error.
# v3 base-dims files still load; v3 *variant* files (written before the
# class was recorded) are rejected with a clear message rather than
# guessed at.
FORMAT_VERSION = 4

# Restorable dims classes.  An allowlist, not pickle: checkpoint metadata
# is JSON and the class name in it must map to a known, audited schema.
def _dims_registry():
    from ..models.reconfig import ReconfigDims
    return {"RaftDims": RaftDims, "ReconfigDims": ReconfigDims}


def check_dims_checkpointable(dims) -> None:
    """Raise at engine CONSTRUCTION time if ``dims`` could not be saved —
    otherwise the TypeError would first fire at the level-boundary
    snapshot write, after a full level of expansion work is already
    done and about to be lost."""
    name = type(dims).__name__
    if name not in _dims_registry():
        raise TypeError(
            f"dims class {name!r} is not checkpoint-restorable; add it "
            "to engine/checkpoint._dims_registry or run without "
            "checkpoint_dir")


@dataclasses.dataclass
class Checkpoint:
    """Host-side image of a BFS engine paused at a level boundary."""

    dims: RaftDims
    frontier: np.ndarray           # [cur_count, state_width] uint8 rows
    seen_hi: np.ndarray            # [size] uint32, lex-sorted with seen_lo
    seen_lo: np.ndarray            # [size] uint32
    distinct: int
    generated: int
    diameter: int
    levels: Tuple[int, ...]
    # Per-action-family generated counts (may be {} for snapshots written
    # before the field existed; the engines then under-report pre-resume
    # action stats but all other counters stay exact).
    action_counts: Dict[str, int]
    wall_seconds: float          # cumulative checking time before the snapshot
    trace_fps: np.ndarray          # [T] uint64
    trace_parents: np.ndarray      # [T] uint64
    trace_actions: np.ndarray      # [T] int32
    roots: Dict[int, PyState]


def _level_of(path: str) -> Optional[int]:
    """BFS level encoded in a snapshot filename (single or piece), or
    None for non-snapshot paths — fault-plan params match on it."""
    name = os.path.basename(path)
    m = _PIECE_RE.match(name)
    if m:
        return int(m.group(1)[len("level_"):])
    if name.startswith("level_") and name.endswith(".npz"):
        try:
            return int(name[len("level_"):-len(".npz")])
        except ValueError:
            return None
    return None


def save(path: str, ckpt: Checkpoint) -> None:
    """Atomically write ``ckpt`` to ``path`` (a ``.npz`` file)."""
    from ..models.schema import state_width
    if faults.ACTIVE:
        m = _PIECE_RE.match(os.path.basename(path))
        if faults.fire("ckpt_piece_missing", level=_level_of(path),
                       piece=int(m.group(2)) if m else 0, path=path):
            # Injected: this controller died before its piece landed.
            return
    check_dims_checkpointable(ckpt.dims)
    cls_name = type(ckpt.dims).__name__
    meta = {
        "version": FORMAT_VERSION,
        "dims_class": cls_name,
        "state_width": state_width(ckpt.dims),
        "dims": dataclasses.asdict(ckpt.dims),
        "distinct": ckpt.distinct,
        "generated": ckpt.generated,
        "diameter": ckpt.diameter,
        "levels": list(ckpt.levels),
        "action_counts": dict(ckpt.action_counts),
        "wall_seconds": ckpt.wall_seconds,
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
            frontier=np.ascontiguousarray(ckpt.frontier).astype(
                np.uint8, casting="safe"),
            seen_hi=np.ascontiguousarray(ckpt.seen_hi, np.uint32),
            seen_lo=np.ascontiguousarray(ckpt.seen_lo, np.uint32),
            trace_fps=np.ascontiguousarray(ckpt.trace_fps, np.uint64),
            trace_parents=np.ascontiguousarray(ckpt.trace_parents, np.uint64),
            trace_actions=np.ascontiguousarray(ckpt.trace_actions, np.int32),
            roots=np.frombuffer(pickle.dumps(ckpt.roots), np.uint8))
        f.flush()
        os.fsync(f.fileno())     # the rename must never land a torn file
    if faults.ACTIVE:
        # The torn-write crash window: tmp is complete on disk, the
        # rename has not happened — exactly what a power cut here leaves.
        faults.fire("ckpt_torn_write", level=_level_of(path), path=path)
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


# Multi-host runs write one PIECE per controller (its frontier slice +
# its seen-key shards; counters are psum-replicated so every piece
# carries identical metadata): level_00012.p0of2.npz, .p1of2.npz, ...
# load() on any piece merges the complete group, so a checkpoint written
# by M controllers resumes on 1 or N controllers and vice versa (the
# merged image is exactly the single-file format).  A shared filesystem
# across hosts is assumed, as with TLC's distributed states/ dir.
_PIECE_RE = re.compile(r"^(level_\d+)\.p(\d+)of(\d+)\.npz$")


def piece_path(checkpoint_dir: str, diameter: int, pid: int,
               nproc: int) -> str:
    return os.path.join(checkpoint_dir,
                        f"level_{diameter:05d}.p{pid}of{nproc}.npz")


def _merge(pieces) -> Checkpoint:
    base = pieces[0]
    for p in pieces[1:]:
        if p.dims != base.dims:
            raise ValueError("checkpoint pieces disagree on dims")
        # The counters are psum-replicated at write time, so every piece
        # of one generation carries identical metadata.  A mismatch means
        # the group mixes pieces from different run generations (a crash
        # between piece overwrites) — merging would silently produce a
        # frontier/seen-set belonging to neither run.
        if (p.distinct, p.generated, p.diameter, p.levels) != \
                (base.distinct, base.generated, base.diameter,
                 base.levels):
            raise ValueError(
                "checkpoint piece group mixes run generations "
                f"(counters disagree: {p.diameter}/{p.distinct} vs "
                f"{base.diameter}/{base.distinct}); delete the stale "
                "pieces or resume an older complete snapshot")
    hi = np.concatenate([p.seen_hi for p in pieces])
    lo = np.concatenate([p.seen_lo for p in pieces])
    order = np.lexsort((lo, hi))
    return dataclasses.replace(
        base,
        frontier=np.concatenate([p.frontier for p in pieces]),
        seen_hi=hi[order], seen_lo=lo[order],
        trace_fps=np.concatenate([p.trace_fps for p in pieces]),
        trace_parents=np.concatenate([p.trace_parents for p in pieces]),
        trace_actions=np.concatenate([p.trace_actions for p in pieces]),
        roots={k: v for p in pieces for k, v in p.roots.items()})


def load(path: str) -> Checkpoint:
    m = _PIECE_RE.match(os.path.basename(path))
    if m:
        base, nproc = m.group(1), int(m.group(3))
        d = os.path.dirname(os.path.abspath(path))
        paths = [os.path.join(d, f"{base}.p{i}of{nproc}.npz")
                 for i in range(nproc)]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                f"incomplete checkpoint piece group: missing {missing}")
        return _merge([_load_one(p) for p in paths])
    return _load_one(path)


def _load_one(path: str) -> Checkpoint:
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta["version"] not in (3, FORMAT_VERSION):
            # Both loadable versions in the message: "!= v4" used to send
            # v3 holders hunting for a nonexistent problem (ADVICE r5).
            raise ValueError(
                f"checkpoint format v{meta['version']} not in "
                f"(v3, v{FORMAT_VERSION})")
        # v3 snapshots predate dims_class; a v3 file carrying variant-only
        # keys (e.g. 'targets') cannot be restored to the right class with
        # confidence, so it is rejected rather than guessed at.
        cls_name = meta.get("dims_class")
        if cls_name is None:
            extra = set(meta["dims"]) - set(
                f.name for f in dataclasses.fields(RaftDims))
            if extra:
                # Only the UNEXPECTED keys: listing the full dims dict
                # buried the one key that mattered (ADVICE r5).
                raise ValueError(
                    "v3 checkpoint was written by a dims VARIANT "
                    f"(unexpected dims keys {sorted(extra)}); v3 metadata "
                    "does not record the class — re-run the variant from "
                    "scratch to produce a v4 snapshot")
            cls_name = "RaftDims"
        registry = _dims_registry()
        if cls_name not in registry:
            raise ValueError(
                f"checkpoint dims class {cls_name!r} is not in this "
                f"build's registry ({sorted(registry)}); it was written "
                "by a build with more dims variants")
        cls = registry[cls_name]
        dims = cls(**{k: tuple(v) if isinstance(v, list) else v
                      for k, v in meta["dims"].items()})
        if "state_width" in meta:
            from ..models.schema import state_width
            if state_width(dims) != meta["state_width"]:
                raise ValueError(
                    f"checkpoint row width {meta['state_width']} != "
                    f"{state_width(dims)} for {cls.__name__}: the packed "
                    "layout changed since this snapshot was written")
        return Checkpoint(
            dims=dims,
            frontier=z["frontier"],
            seen_hi=z["seen_hi"],
            seen_lo=z["seen_lo"],
            distinct=meta["distinct"],
            generated=meta["generated"],
            diameter=meta["diameter"],
            levels=tuple(meta["levels"]),
            action_counts=dict(meta.get("action_counts", {})),
            wall_seconds=float(meta.get("wall_seconds", 0.0)),
            trace_fps=z["trace_fps"],
            trace_parents=z["trace_parents"],
            trace_actions=z["trace_actions"],
            roots=pickle.loads(bytes(z["roots"])))


def _list_snapshots(checkpoint_dir: str):
    """``[(level, [names])]`` of single snapshots and COMPLETE piece
    groups in ``checkpoint_dir`` (no health check — callers decide)."""
    singles, groups = [], {}
    for name in os.listdir(checkpoint_dir):
        m = _PIECE_RE.match(name)
        if m:
            lvl = int(m.group(1)[len("level_"):])
            groups.setdefault((lvl, int(m.group(3))), []).append(name)
            continue
        if name.startswith("level_") and name.endswith(".npz"):
            try:
                singles.append((int(name[len("level_"):-len(".npz")]),
                                [name]))
            except ValueError:
                continue
    return singles + [(lvl, sorted(names))
                      for (lvl, nproc), names in groups.items()
                      if len(names) == nproc]


def _group_is_intact(checkpoint_dir: str, names) -> bool:
    """Every piece readable AND one run generation: pieces write their
    psum-replicated counters into the metadata, so disagreement means
    the group mixes pieces from different runs (a crash between piece
    overwrites) — load() would raise on it, which is exactly the crash
    pattern auto-resume exists for, so it must be skipped HERE."""
    counters = set()
    try:
        for name in names:
            with np.load(os.path.join(checkpoint_dir, name)) as z:
                meta = json.loads(bytes(z["meta"]).decode())
            counters.add((meta["distinct"], meta["generated"],
                          meta["diameter"], tuple(meta["levels"])))
    except Exception:
        return False
    return len(counters) == 1


def latest(checkpoint_dir: str) -> Optional[str]:
    """Path of the newest *resumable* checkpoint in ``checkpoint_dir`` —
    a single-file snapshot, or any piece of a COMPLETE multi-host piece
    group (load() resolves the siblings).  Unreadable/truncated files
    (e.g. a crash mid-write), incomplete groups, and groups whose pieces
    disagree on counters (mixed run generations — a crash between piece
    overwrites) are skipped, falling back to the next-newest intact
    snapshot."""
    if not os.path.isdir(checkpoint_dir):
        return None
    for _lvl, names in sorted(_list_snapshots(checkpoint_dir),
                              reverse=True):
        if _group_is_intact(checkpoint_dir, names):
            return os.path.join(checkpoint_dir, names[0])
    return None


# Any file retention may touch: single/piece snapshots and their .tmp
# leftovers.  Group 1 is the level — the only retention criterion.
_SNAP_FILE_RE = re.compile(r"^level_(\d+)(?:\.p\d+of\d+)?\.npz(?:\.tmp)?$")


def gc(checkpoint_dir: str, keep: Optional[int]) -> int:
    """Retention: once ``keep`` intact snapshots/piece groups exist,
    delete EVERY snapshot file strictly older than the oldest kept one —
    surplus good snapshots, incomplete piece groups, and orphaned
    ``.tmp`` leftovers of torn writes alike (crash debris is exactly
    what a long supervised run accumulates).  Called by the engines
    after each successful snapshot write (``EngineConfig.
    keep_checkpoints``; None/0/negative = keep all).  Torn or
    mixed-generation entries never count toward the ``keep`` quota —
    retention must not evict the last good snapshot because garbage
    outnumbers it — and nothing at or above the oldest kept level is
    ever touched (a sibling controller may still be renaming its piece
    of the newest group).  Returns the number of files removed."""
    if not keep or keep < 0 or not os.path.isdir(checkpoint_dir):
        return 0
    intact = [lvl for lvl, names in sorted(_list_snapshots(checkpoint_dir),
                                           reverse=True)
              if _group_is_intact(checkpoint_dir, names)]
    if len(intact) < keep:
        return 0             # quota not yet filled: nothing is surplus
    cutoff = intact[keep - 1]          # oldest kept level
    removed = 0
    for name in os.listdir(checkpoint_dir):
        m = _SNAP_FILE_RE.match(name)
        if m is None or int(m.group(1)) >= cutoff:
            continue
        try:
            os.unlink(os.path.join(checkpoint_dir, name))
            removed += 1
        except OSError:
            pass             # a sibling controller's gc got there first
    return removed
