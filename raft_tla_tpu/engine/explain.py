"""Counterexample explainer — TLC's decoded error trace, three ways.

A violation leaves the engines holding raw material: a fingerprint, a
predecessor chain in the trace store, and ``replay()`` (engine/bfs.py),
which re-runs the expand kernel along the chain and yields the exact
``[(action id, PyState)]`` path root-first.  TLC users never see any of
that — they see numbered states with the taking action's name and the
fields it changed.  This module is that rendering layer:

- :func:`decode_steps` — replay output -> structured step records, each
  carrying the action label (``dims.describe_instance``), the canonical
  decoded state (``models/pystate.state_fields`` — the ONE formatter the
  oracle/debug printouts also use), and the changed-field diff against
  the previous step (``diff_states``);
- :func:`render_text` — TLC's numbered-state error trace (``State 1:
  <Initial predicate>`` ...), each state printed by ``format_state``
  with a ``changed:`` summary line per step;
- :func:`render_json` / :func:`render_html` — the same decoded trace as
  a machine-readable document / a standalone self-contained HTML page;
- :func:`write_counterexample` — the engines call this automatically on
  any traced violation: ``<workdir>/counterexample.txt`` + ``.json``,
  atomically written, path stamped into the ``run_end`` event;
- :func:`export_graph` — for small spaces (``cap``-bounded), the FULL
  reached state graph from the trace store as DOT or GraphML (node per
  fingerprint, edge per recorded (parent, action) discovery).

CLI surfaces: ``python -m raft_tla_tpu explain <cfg>`` and
``check --render-trace`` (cli.py).  Strictly observational: everything
here reads finished-run artifacts.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

from ..models.pystate import PyState, diff_states, format_state, state_fields

#: Default node cap for full-graph export — past this a DOT file stops
#: being readable or layoutable, and the export loop stops being cheap.
GRAPH_CAP_DEFAULT = 50_000


def action_label(g: int, dims) -> str:
    """TLC's angle-bracket action name for a replay step (-1 = root)."""
    return "Initial predicate" if g < 0 else dims.describe_instance(g)


def decode_steps(steps: List[Tuple[int, PyState]], dims) -> List[dict]:
    """Replay output -> structured, JSON-able step records (root first).

    Each record: ``index`` (1-based, TLC numbering), ``action`` /
    ``action_id``, ``state`` (the canonical ``state_fields`` view), and
    ``changed`` (the ``diff_states`` delta against the previous step;
    ``{}`` for the root)."""
    out = []
    prev: Optional[PyState] = None
    for idx, (g, st) in enumerate(steps, 1):
        out.append({
            "index": idx,
            "action_id": int(g),
            "action": action_label(g, dims),
            "state": state_fields(st, dims),
            "changed": diff_states(prev, st, dims) if prev is not None
            else {},
        })
        prev = st
    return out


def _fmt_changed(changed: dict) -> List[str]:
    parts = []
    for k, v in changed.items():
        if k.startswith("messages."):
            parts.append(f"{k}: {'; '.join(v)}")
        else:
            parts.append(f"{k}: {v[0]} -> {v[1]}")
    return parts


def render_text(steps: List[Tuple[int, PyState]], dims,
                violation=None) -> str:
    """TLC-style numbered error trace.  ``violation`` (an engine
    ``Violation`` or None) heads the block the way TLC's "Error:
    Invariant ... is violated" does."""
    lines = []
    if violation is not None:
        lines.append(f"Error: Invariant {violation.invariant} is "
                     f"violated (fingerprint "
                     f"{violation.fingerprint:#018x}).")
        lines.append("Error: The behavior up to this point is:")
    prev: Optional[PyState] = None
    for idx, (g, st) in enumerate(steps, 1):
        lines.append(f"State {idx}: <{action_label(g, dims)}>")
        if prev is not None:
            changed = diff_states(prev, st, dims)
            if changed:
                lines.append("  changed: "
                             + "; ".join(_fmt_changed(changed)))
        lines.append(format_state(st, dims))
        lines.append("")
        prev = st
    return "\n".join(lines).rstrip() + "\n"


def render_json(steps: List[Tuple[int, PyState]], dims,
                violation=None) -> dict:
    doc = {
        "counterexample": True,
        "length": len(steps),
        "depth": max(0, len(steps) - 1),
        "states": decode_steps(steps, dims),
    }
    if violation is not None:
        doc["invariant"] = violation.invariant
        doc["fingerprint"] = hex(violation.fingerprint)
    return doc


_HTML_HEAD = """<!doctype html>
<html><head><meta charset="utf-8"><title>{title}</title><style>
body {{ font-family: ui-monospace, monospace; margin: 2em;
        background: #fafafa; color: #1a1a1a; }}
h1 {{ font-size: 1.1em; }}
.err {{ color: #b00020; font-weight: bold; }}
.step {{ border: 1px solid #ddd; border-radius: 6px; background: #fff;
         margin: 0.8em 0; padding: 0.6em 1em; }}
.act {{ font-weight: bold; color: #0b57d0; }}
.chg {{ color: #7a5c00; margin: 0.3em 0; }}
pre {{ margin: 0.4em 0 0 0; white-space: pre-wrap; }}
</style></head><body>
"""


def render_html(steps: List[Tuple[int, PyState]], dims,
                violation=None, title="counterexample") -> str:
    """Standalone single-file HTML rendering (no external assets — the
    artifact must open from a CI artifacts tab or an email)."""
    import html as _html
    out = [_HTML_HEAD.format(title=_html.escape(title))]
    out.append(f"<h1>{_html.escape(title)}</h1>")
    if violation is not None:
        out.append(f"<p class=err>Invariant "
                   f"{_html.escape(violation.invariant)} is violated "
                   f"(fingerprint {violation.fingerprint:#018x}).</p>")
    prev: Optional[PyState] = None
    for idx, (g, st) in enumerate(steps, 1):
        out.append("<div class=step>")
        out.append(f"<div>State {idx}: <span class=act>&lt;"
                   f"{_html.escape(action_label(g, dims))}&gt;"
                   f"</span></div>")
        if prev is not None:
            changed = diff_states(prev, st, dims)
            if changed:
                out.append("<div class=chg>changed: "
                           + _html.escape(
                               "; ".join(_fmt_changed(changed)))
                           + "</div>")
        out.append(f"<pre>{_html.escape(format_state(st, dims))}</pre>")
        out.append("</div>")
        prev = st
    out.append("</body></html>\n")
    return "\n".join(out)


RENDERERS = {"text": render_text, "json": render_json, "html": render_html}


def _atomic_write(path: str, text: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)


def write_counterexample(engine, res, workdir: str,
                         basename: str = "counterexample") -> dict:
    """Render the violation's replayed trace and write
    ``<workdir>/<basename>.txt`` + ``.json`` (atomic).  Called by the
    engines' shared telemetry wrapper on every traced violation —
    single-chip and mesh alike (the mesh's ``replay`` merges its trace
    pieces first, and under a process group each controller's files get
    its piece suffix via ``engine._counterexample_base``).  Returns
    ``{"txt": path, "json": path, "depth": n}``."""
    steps = engine.replay(res.violation.fingerprint)
    txt = os.path.join(workdir, f"{basename}.txt")
    jsn = os.path.join(workdir, f"{basename}.json")
    _atomic_write(txt, render_text(steps, engine.dims,
                                   violation=res.violation))
    doc = render_json(steps, engine.dims, violation=res.violation)
    _atomic_write(jsn, json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return {"txt": txt, "json": jsn, "depth": doc["depth"]}


# ---------------------------------------------------------------------------
# Full reached-graph export (small spaces).

def _graph_edges(trace, dims):
    """Iterate the trace store's recorded discovery edges as
    ``(fp, parent_fp, action_id)`` numpy columns plus the root set."""
    fps, parents, actions = trace.edges()
    return fps, parents, actions, set(trace.roots)


def export_graph(trace, dims, fmt: str = "dot",
                 cap: Optional[int] = GRAPH_CAP_DEFAULT) -> str:
    """The full reached state graph (one node per recorded fingerprint,
    one edge per (parent, action) discovery record — the BFS tree TLC's
    ``-dump dot`` would draw) as DOT or GraphML text.

    ``cap`` guards the export: a store larger than it raises ValueError
    (the caller sees the real size and can raise the cap deliberately);
    None disables the guard."""
    if fmt not in ("dot", "graphml"):
        raise ValueError(f"graph format must be dot/graphml, got {fmt!r}")
    n = len(trace)
    if cap is not None and n > cap:
        raise ValueError(
            f"trace store holds {n} states, over the graph-export cap "
            f"{cap}; raise the cap explicitly for a graph this big")
    fps, parents, actions, roots = _graph_edges(trace, dims)
    if fmt == "dot":
        lines = ["digraph statespace {",
                 "  node [shape=box, fontname=monospace];"]
        for fp in sorted(roots):
            lines.append(f'  "{fp:#018x}" [style=filled, '
                         f'fillcolor=lightblue, label="root\\n{fp:#x}"];')
        for fp, par, g in zip(fps.tolist(), parents.tolist(),
                              actions.tolist()):
            if g < 0:
                continue          # root records have no incoming edge
            lines.append(f'  "{par:#018x}" -> "{fp:#018x}" '
                         f'[label="{dims.describe_instance(int(g))}"];')
        lines.append("}")
        return "\n".join(lines) + "\n"
    # GraphML
    import html as _html
    out = [
        '<?xml version="1.0" encoding="UTF-8"?>',
        '<graphml xmlns="http://graphml.graphdrawing.org/xmlns">',
        '  <key id="action" for="edge" attr.name="action" '
        'attr.type="string"/>',
        '  <key id="root" for="node" attr.name="root" '
        'attr.type="boolean"/>',
        '  <graph id="statespace" edgedefault="directed">',
    ]
    seen_nodes = set()

    def node(fp: int):
        if fp in seen_nodes:
            return
        seen_nodes.add(fp)
        r = ('<data key="root">true</data>' if fp in roots else "")
        out.append(f'    <node id="n{fp:x}">{r}</node>')

    for fp in sorted(roots):
        node(fp)
    for i, (fp, par, g) in enumerate(zip(fps.tolist(), parents.tolist(),
                                         actions.tolist())):
        node(fp)
        if g < 0:
            continue
        node(par)
        label = _html.escape(dims.describe_instance(int(g)))
        out.append(f'    <edge id="e{i}" source="n{par:x}" '
                   f'target="n{fp:x}">'
                   f'<data key="action">{label}</data></edge>')
    out.append("  </graph>")
    out.append("</graphml>")
    return "\n".join(out) + "\n"
