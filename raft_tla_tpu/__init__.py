"""raft_tla_tpu — a TPU-native explicit-state model checker.

This package re-implements, TPU-first, the runtime that the reference
TLA+ repository (`lemmy/raft.tla`, mounted at /root/reference) is written
against: TLC's exhaustive breadth-first state-space exploration, randomized
smoke testing, simulation mode, invariant evaluation, state constraints,
deadlock detection, counterexample traces, and checkpoint/resume — for the
Raft consensus specification (/root/reference/raft.tla).

Layout
------
- ``models/``   the Raft transition system itself: state schema (struct-of-
                arrays tensors), the vmap'd action kernels for every ``Next``
                disjunct (raft.tla:421-430), invariant kernels, initial-state
                generators, and a pure-Python reference interpreter used as
                the differential oracle.
- ``ops/``      checker primitives: two-lane 32-bit multiset fingerprinting,
                the sorted fingerprint set (TLC's FPSet equivalent), and
                mask-compaction utilities.
- ``parallel/`` device-mesh sharding: fingerprint-owner partitioned BFS with
                all-to-all dedup over ICI (TLC worker-pool / distributed-TLC
                equivalent).
- ``engine/``   the host-side drivers: level-synchronous BFS, simulation
                mode, trace reconstruction, checkpoint/resume, stats.
- ``utils/``    TLC ``.cfg`` grammar parser, model-value interning, misc.

The reference's ``MCraft.cfg``/``Smokeraft.cfg`` remain the source of truth:
the cfg parser consumes them verbatim (they are *read*, never copied).
"""

__version__ = "0.1.0"
