"""Canonical pure-Python state representation (oracle side).

This module defines an immutable, hashable mirror of the reference spec's
state vector ``vars == <<messages, serverVars, candidateVars, leaderVars,
logVars>>`` (/root/reference/raft.tla:74), using the integer encodings from
``dims.py``.  It is the ground-truth representation for the differential
oracle and for decoding/pretty-printing device tensors.

Messages: the spec models the network as a *bag* (multiset) of records
(raft.tla:29-31).  Here a message is a flat tuple

    (mtype, msource, mdest, mterm, payload...)

with payload per type (schemas raft.tla:443-475):

    RVQ: (mlastLogTerm, mlastLogIndex)
    RVR: (mvoteGranted, mlog)          mlog = ((term, value), ...)
    AEQ: (mprevLogIndex, mprevLogTerm, mentries, mcommitIndex)
                                       mentries = () or ((term, value),)
    AER: (msuccess, mmatchIndex)

and the bag is a ``frozenset`` of ``(message, count)`` pairs — canonical and
hashable.  Servers here are 0-based ints; values are 1..V; roles/Nil per
``dims``.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple, FrozenSet

from .dims import FOLLOWER, NIL, RVQ, RVR, AEQ, MSG_TYPE_NAMES, RaftDims

Entry = Tuple[int, int]                 # (term, value)
Log = Tuple[Entry, ...]
Message = Tuple                          # as documented above
Bag = FrozenSet[Tuple[Message, int]]


@dataclasses.dataclass(frozen=True)
class PyState:
    """One global state of the Raft spec (raft.tla:27-74)."""

    current_term: Tuple[int, ...]        # [N]  raft.tla:37
    role: Tuple[int, ...]                # [N]  raft.tla:39 ("state" in spec)
    voted_for: Tuple[int, ...]           # [N]  raft.tla:42; 0=Nil, j+1=server j
    log: Tuple[Log, ...]                 # [N]  raft.tla:48
    commit_index: Tuple[int, ...]        # [N]  raft.tla:50
    votes_responded: Tuple[int, ...]     # [N] bitmask  raft.tla:56
    votes_granted: Tuple[int, ...]       # [N] bitmask  raft.tla:59
    next_index: Tuple[Tuple[int, ...], ...]   # [N][N]  raft.tla:64
    match_index: Tuple[Tuple[int, ...], ...]  # [N][N]  raft.tla:67
    messages: Bag                        # raft.tla:31

    def bag_dict(self):
        return dict(self.messages)

    def replace(self, **kw) -> "PyState":
        return dataclasses.replace(self, **kw)


def init_state(dims: RaftDims) -> PyState:
    """The unique initial state — ``Init`` raft.tla:113-129."""
    n = dims.n_servers
    return PyState(
        current_term=(1,) * n,                       # raft.tla:113
        role=(FOLLOWER,) * n,                        # raft.tla:114
        voted_for=(NIL,) * n,                        # raft.tla:115
        log=((),) * n,                               # raft.tla:123
        commit_index=(0,) * n,                       # raft.tla:124
        votes_responded=(0,) * n,                    # raft.tla:116
        votes_granted=(0,) * n,                      # raft.tla:117
        next_index=tuple((1,) * n for _ in range(n)),   # raft.tla:121
        match_index=tuple((0,) * n for _ in range(n)),  # raft.tla:122
        messages=frozenset(),                        # raft.tla:125 (EmptyBag)
    )


# ---------------------------------------------------------------------------
# Bag helpers — WithMessage/WithoutMessage raft.tla:88-92.

def bag_add(bag: Bag, m: Message) -> Bag:
    d = dict(bag)
    d[m] = d.get(m, 0) + 1
    return frozenset(d.items())


def bag_remove(bag: Bag, m: Message) -> Bag:
    d = dict(bag)
    c = d.get(m, 0)
    if c <= 1:
        d.pop(m, None)
    else:
        d[m] = c - 1
    return frozenset(d.items())


def bag_reply(bag: Bag, response: Message, request: Message) -> Bag:
    """Reply == add response, remove request, atomically (raft.tla:102-103)."""
    return bag_remove(bag_add(bag, response), request)


# ---------------------------------------------------------------------------
# Pretty-printing (for counterexample traces; mirrors TLC's state dumps).
#
# ONE formatter: ``state_fields`` is the canonical decoded view of a state
# (JSON-able, per-server fields + the message bag), ``format_state`` and
# the counterexample explainer (engine/explain.py) both render FROM it,
# and ``diff_states`` computes changed-field deltas over the same keys —
# so the oracle/debug printouts and the explainer can never drift apart.

ROLE_LETTERS = {0: "F", 1: "C", 2: "L"}
ROLE_NAMES = {0: "Follower", 1: "Candidate", 2: "Leader"}


def format_message(m: Message, dims: RaftDims) -> str:
    t = m[0]
    head = f"{MSG_TYPE_NAMES[t]} r{m[1]+1}->r{m[2]+1} term={m[3]}"
    if t == RVQ:
        return head + f" lastLogTerm={m[4]} lastLogIndex={m[5]}"
    if t == RVR:
        return head + f" granted={bool(m[4])} mlog={list(m[5])}"
    if t == AEQ:
        return (head + f" prevLogIndex={m[4]} prevLogTerm={m[5]}"
                f" entries={list(m[6])} commitIndex={m[7]}")
    return head + f" success={bool(m[4])} matchIndex={m[5]}"


def state_fields(s: PyState, dims: RaftDims) -> dict:
    """Canonical decoded view of one state: ``{"r<i>.<field>": value}``
    per server plus the sorted message bag under ``"messages"`` —
    JSON-able, and the shared substrate for ``format_state``,
    ``diff_states``, and the counterexample explainer."""
    n = dims.n_servers
    out = {}
    for i in range(n):
        r = f"r{i+1}"
        out[f"{r}.term"] = s.current_term[i]
        out[f"{r}.role"] = ROLE_LETTERS.get(s.role[i], str(s.role[i]))
        out[f"{r}.votedFor"] = ("Nil" if s.voted_for[i] == NIL
                                else f"r{s.voted_for[i]}")
        out[f"{r}.log"] = [list(e) for e in s.log[i]]
        out[f"{r}.commitIndex"] = s.commit_index[i]
        out[f"{r}.votesResponded"] = f"{s.votes_responded[i]:0{n}b}"
        out[f"{r}.votesGranted"] = f"{s.votes_granted[i]:0{n}b}"
        out[f"{r}.nextIndex"] = list(s.next_index[i])
        out[f"{r}.matchIndex"] = list(s.match_index[i])
    out["messages"] = [{"count": c, "msg": format_message(m, dims)}
                       for m, c in sorted(s.messages)]
    return out


def diff_states(a: PyState, b: PyState, dims: RaftDims) -> dict:
    """Changed fields ``a -> b`` as ``{key: [old, new]}`` over the
    ``state_fields`` keys; the message bag diffs as added/removed
    rendered messages.  The explainer's per-step "what this action
    changed" column comes from exactly this."""
    fa, fb = state_fields(a, dims), state_fields(b, dims)
    out = {}
    for k in fa:
        if k == "messages":
            continue
        if fa[k] != fb[k]:
            out[k] = [fa[k], fb[k]]
    da = dict(a.messages)
    db = dict(b.messages)
    added = [f"{db[m] - da.get(m, 0)}x {format_message(m, dims)}"
             for m in sorted(db) if db[m] > da.get(m, 0)]
    removed = [f"{da[m] - db.get(m, 0)}x {format_message(m, dims)}"
               for m in sorted(da) if da[m] > db.get(m, 0)]
    if added:
        out["messages.added"] = added
    if removed:
        out["messages.removed"] = removed
    return out


def format_state(s: PyState, dims: RaftDims) -> str:
    n = dims.n_servers
    f = state_fields(s, dims)
    lines = []
    for i in range(n):
        r = f"r{i+1}"
        log = [tuple(e) for e in f[f"{r}.log"]]
        lines.append(
            f"  {r}: term={f[f'{r}.term']} role={f[f'{r}.role']}"
            f" votedFor={f[f'{r}.votedFor']} log={log}"
            f" commit={f[f'{r}.commitIndex']}"
            f" resp={f[f'{r}.votesResponded']} gran={f[f'{r}.votesGranted']}"
            f" nextIndex={f[f'{r}.nextIndex']}"
            f" matchIndex={f[f'{r}.matchIndex']}")
    msgs = f["messages"]
    lines.append(f"  messages ({len(msgs)} distinct):")
    for m in msgs:
        lines.append(f"    {m['count']}x {m['msg']}")
    return "\n".join(lines)


def probe_states(dims: RaftDims):
    """Type-correct probe states for the POR pass's concrete
    closure-refutation search (analysis/por.py): a handful of states
    that together enable every base action instance, so the pass can
    exhibit a CONCRETE two-action non-commutation witness per instance.
    The states need not be reachable — action independence (and hence
    the C1 closure condition) is a property over the declared state
    domain, so any type-correct witness refutes it for every sound
    footprint abstraction.  All values stay inside
    ``analysis.lane_map.field_domains``."""
    from .dims import CANDIDATE, LEADER
    n = dims.n_servers
    base = init_state(dims)
    full = (1 << n) - 1
    out = [base]
    # Every server a candidate holding a quorum of granted votes (and no
    # recorded responses, so RequestVote(i, j) stays enabled for all j):
    # enables BecomeLeader/RequestVote/Timeout everywhere.
    out.append(base.replace(role=(CANDIDATE,) * n,
                            current_term=(2,) * n,
                            votes_granted=(full,) * n))
    # Every server a leader with log headroom: enables ClientRequest,
    # AdvanceCommitIndex and AppendEntries(i != j) everywhere.
    out.append(base.replace(role=(LEADER,) * n, current_term=(2,) * n))
    # Every message slot occupied by a distinct single-copy message with
    # mterm above every server term (the UpdateTerm case of Receive is
    # enabled regardless of roles): enables Receive / DuplicateMessage /
    # DropMessage on every slot.
    msgs = []
    for s in range(dims.n_msg_slots):
        src = s % n
        dst = (s // n) % n
        last_idx = s // (n * n)
        msgs.append(((RVQ, src, dst, 2, 0, last_idx), 1))
    out.append(base.replace(messages=frozenset(msgs)))
    return out
