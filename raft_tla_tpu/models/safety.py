"""The spec's correctness-invariant suite as vectorized TPU kernels.

The reference keeps its safety properties *outside* the module terminator
(/root/reference/raft.tla:505) — dead text for TLC, live TLAPS proof goals
(SURVEY §2.3).  Here they are first-class, runtime-checkable invariants: each
is a branch-free predicate over one ``StateBatch`` (vmap'd over the frontier
by the engine, exactly like ``TypeOK``), with a pure-Python mirror for
differential testing.

Transcribed semantics, with reference citations:

- ``Committed(i) == SubSeq(log[i], 1, commitIndex[i])`` — raft.tla:896.
- ``RequestVoteResponseInv`` — raft.tla:903-910.  The reference's ``m.dest``
  at :910 is a typo for ``m.mdest`` (it would crash TLC if enabled naively;
  SURVEY §2.3); fixed here.
- ``RequestVoteRequestInv`` — raft.tla:915-920.
- ``AppendEntriesRequestInv`` — raft.tla:924-930.  Note the TLA+ operator
  precedence: the second conjunct is ``(prev > 0 /\\ prev <= Len) =>
  term-match``; the first (``log[src][prev+1] = mentries[1]``) is an
  *unguarded* access — out-of-domain evaluates to a TLC error, which this
  engine reports as a violation of the invariant.
- ``MessageTermsLtCurrentTerm`` — raft.tla:934-935.
- ``MessagesInv`` — raft.tla:941-946 (conjunction over all in-flight
  messages; multiplicities are irrelevant, only the support matters).
- ``LeaderVotesQuorum`` — raft.tla:1033-1037.
- ``CandidateTermNotInLog`` — raft.tla:1041-1047.
- ``ElectionSafety`` — raft.tla:1124-1129.  ``Max`` over a possibly-empty
  index set is taken as 0 (the natural total extension; both sides empty
  ⇒ trivially true, leader-side empty with follower-side occupied ⇒
  violation — the intended reading).
- ``LogMatching`` — raft.tla:1132-1136 (``SubSeq`` equality compares whole
  records: term *and* value).
- ``VotesGrantedInv`` — raft.tla:1145-1153 (needs ``IsPrefix`` from the
  community SequencesExt module [external]: ``IsPrefix(s, t) ==
  Len(s) <= Len(t) /\\ SubSeq(t, 1, Len(s)) = s``).
- ``QuorumLogInv`` — raft.tla:1157-1161.  Quantifying over all quorums
  compiles to a popcount: ``\\A S \\in Quorum : \\E j \\in S : ok(j)`` holds
  iff the NOT-ok set contains no majority, i.e. ``2*|bad| <= N``.
- ``MoreUpToDateCorrect`` — raft.tla:1167-1172.
- ``LeaderCompleteness`` — raft.tla:1176-1180.

Every kernel returns a scalar bool: True = invariant holds in this state.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

from .dims import AEQ, CANDIDATE, LEADER, NIL, RVQ, RVR, RaftDims
from .pystate import PyState

# ---------------------------------------------------------------------------
# Shared tensor helpers (single state, no batch axis).


def _last_terms(st, L):
    """LastTerm(log[i]) for all i — raft.tla:84.  [N]."""
    n = st.log_len.shape[0]
    at = jnp.clip(st.log_len - 1, 0, L - 1)
    return jnp.where(st.log_len > 0, st.log_term[jnp.arange(n), at], 0)


def _entry_eq(st):
    """E[a,b,l] = log[a][l+1] and log[b][l+1] are the same record.  [N,N,L]."""
    te = st.log_term[:, None, :] == st.log_term[None, :, :]
    ve = st.log_val[:, None, :] == st.log_val[None, :, :]
    return te & ve


def _committed_prefix(st, L):
    """P[a,b] = IsPrefix(Committed(a), log[b]) — raft.tla:896 + SequencesExt.
    Committed(a) with commitIndex[a] > Len(log[a]) is undefined in the spec;
    reported as not-a-prefix (the TLC-error reading).  [N,N]."""
    lane = jnp.arange(L)[None, None, :]
    c = st.commit[:, None, None]
    within = lane < c
    match = jnp.all(~within | _entry_eq(st), axis=2)
    well_def = st.commit <= st.log_len
    return well_def[:, None] & (st.commit[:, None] <= st.log_len[None, :]) \
        & match


# ---------------------------------------------------------------------------
# Per-invariant kernel builders (signature matches build_type_ok).


def build_messages_inv(dims: RaftDims):
    """MessagesInv — raft.tla:941-946: the four per-message invariants
    conjoined over every in-flight message."""
    N, L = dims.n_servers, dims.max_log

    def messages_inv(st):
        occ = st.msg_cnt > 0                              # [M]
        mt = st.msg[:, 0] - 1
        src = jnp.clip(st.msg[:, 1] - 1, 0, N - 1)
        dst = jnp.clip(st.msg[:, 2] - 1, 0, N - 1)
        mterm = st.msg[:, 3]
        lt = _last_terms(st, L)                           # [N]
        len_src, len_dst = st.log_len[src], st.log_len[dst]
        lt_src, lt_dst = lt[src], lt[dst]
        t_src, t_dst = st.term[src], st.term[dst]

        # MessageTermsLtCurrentTerm — raft.tla:934-935 (all message types).
        terms_ok = mterm <= t_src

        # RequestVoteResponseInv — raft.tla:903-910 (:910 typo fixed).
        rvr_ante = (mt == RVR) & (st.msg[:, 4] > 0) \
            & (t_src == t_dst) & (t_src == mterm)
        rvr_cons = (lt_dst > lt_src) \
            | ((lt_dst == lt_src) & (len_dst >= len_src))
        rvr_ok = ~rvr_ante | rvr_cons

        # RequestVoteRequestInv — raft.tla:915-920.
        rvq_ante = (mt == RVQ) & (st.role[src] == CANDIDATE) \
            & (t_src == mterm)
        rvq_cons = (st.msg[:, 5] == len_src) & (st.msg[:, 4] == lt_src)
        rvq_ok = ~rvq_ante | rvq_cons

        # AppendEntriesRequestInv — raft.tla:924-930.
        prev, pterm = st.msg[:, 4], st.msg[:, 5]
        n_ent, eterm, eval_ = st.msg[:, 6], st.msg[:, 7], st.msg[:, 8]
        aeq_ante = (mt == AEQ) & (n_ent > 0) & (mterm == t_src)
        at1 = jnp.clip(prev, 0, L - 1)                    # prev+1, 0-based
        entry1_ok = (prev + 1 >= 1) & (prev + 1 <= len_src) \
            & (st.log_term[src, at1] == eterm) \
            & (st.log_val[src, at1] == eval_)
        atp = jnp.clip(prev - 1, 0, L - 1)
        prev_in = (prev > 0) & (prev <= len_src)
        pterm_ok = ~prev_in | (st.log_term[src, atp] == pterm)
        aeq_ok = ~aeq_ante | (entry1_ok & pterm_ok)

        return jnp.all(~occ | (terms_ok & rvr_ok & rvq_ok & aeq_ok))

    return messages_inv


def build_leader_votes_quorum(dims: RaftDims):
    """LeaderVotesQuorum — raft.tla:1033-1037."""
    N = dims.n_servers

    def leader_votes_quorum(st):
        # voters[i,j]: j counts toward i's leadership quorum.
        higher = st.term[None, :] > st.term[:, None]
        voted = (st.term[None, :] == st.term[:, None]) \
            & (st.voted_for[None, :] == jnp.arange(N)[:, None] + 1)
        cnt = jnp.sum(higher | voted, axis=1)
        return jnp.all((st.role != LEADER) | (2 * cnt > N))

    return leader_votes_quorum


def build_candidate_term_not_in_log(dims: RaftDims):
    """CandidateTermNotInLog — raft.tla:1041-1047."""
    N, L = dims.n_servers, dims.max_log

    def candidate_term_not_in_log(st):
        same_term = st.term[None, :] == st.term[:, None]
        votable = (st.voted_for[None, :] == jnp.arange(N)[:, None] + 1) \
            | (st.voted_for[None, :] == NIL)
        cnt = jnp.sum(same_term & votable, axis=1)
        electable = (st.role == CANDIDATE) & (2 * cnt > N)      # [N] over i
        lane = jnp.arange(L)[None, None, :]
        in_log = lane < st.log_len[None, :, None]               # [1,N,L]
        term_hit = st.log_term[None, :, :] == st.term[:, None, None]
        in_any_log = jnp.any(in_log & term_hit, axis=(1, 2))    # [N] over i
        return jnp.all(~electable | ~in_any_log)

    return candidate_term_not_in_log


def build_election_safety(dims: RaftDims):
    """ElectionSafety — raft.tla:1124-1129 (empty Max = 0)."""
    L = dims.max_log

    def election_safety(st):
        lane = jnp.arange(L)[None, None, :]
        in_log = lane < st.log_len[None, :, None]               # [1,N,L]
        hit = in_log & (st.log_term[None, :, :] == st.term[:, None, None])
        # A[i,j] = greatest index in log[j] whose term is currentTerm[i].
        A = jnp.max(jnp.where(hit, lane + 1, 0), axis=2)        # [N,N]
        own = jnp.diagonal(A)                                   # A[i,i]
        return jnp.all((st.role != LEADER)[:, None] | (own[:, None] >= A))

    return election_safety


def build_log_matching(dims: RaftDims):
    """LogMatching — raft.tla:1132-1136."""
    L = dims.max_log

    def log_matching(st):
        lane = jnp.arange(L)[None, None, :]
        eq = _entry_eq(st)                                      # [N,N,L]
        # prefix_eq[i,j,l]: SubSeq(log[i],1,l+1) = SubSeq(log[j],1,l+1).
        prefix_eq = jnp.cumprod(eq, axis=2).astype(bool)
        in_both = lane < jnp.minimum(st.log_len[:, None],
                                     st.log_len[None, :])[:, :, None]
        term_eq = st.log_term[:, None, :] == st.log_term[None, :, :]
        return jnp.all(~in_both | ~term_eq | prefix_eq)

    return log_matching


def build_votes_granted_inv(dims: RaftDims):
    """VotesGrantedInv — raft.tla:1145-1153."""
    N, L = dims.n_servers, dims.max_log

    def votes_granted_inv(st):
        granted = ((st.votes_gran[:, None] >> jnp.arange(N)[None, :])
                   & 1) > 0                                     # [N i, N j]
        same_term = st.term[:, None] == st.term[None, :]
        # IsPrefix(Committed(j), log[i]) — P[j,i] with P from the helper.
        pref = _committed_prefix(st, L).T                       # [i,j]
        return jnp.all(~granted | ~same_term | pref)

    return votes_granted_inv


def build_quorum_log_inv(dims: RaftDims):
    """QuorumLogInv — raft.tla:1157-1161 via the popcount reduction."""
    N, L = dims.n_servers, dims.max_log

    def quorum_log_inv(st):
        pref = _committed_prefix(st, L)                         # [i,j]
        bad = jnp.sum(~pref, axis=1)                            # per i
        return jnp.all(2 * bad <= N)

    return quorum_log_inv


def build_more_up_to_date_correct(dims: RaftDims):
    """MoreUpToDateCorrect — raft.tla:1167-1172."""
    L = dims.max_log

    def more_up_to_date_correct(st):
        lt = _last_terms(st, L)
        newer = (lt[:, None] > lt[None, :]) \
            | ((lt[:, None] == lt[None, :])
               & (st.log_len[:, None] >= st.log_len[None, :]))  # [i,j]
        pref = _committed_prefix(st, L).T                       # [i,j]
        return jnp.all(~newer | pref)

    return more_up_to_date_correct


def build_leader_completeness(dims: RaftDims):
    """LeaderCompleteness — raft.tla:1176-1180."""
    L = dims.max_log

    def leader_completeness(st):
        pref = _committed_prefix(st, L).T                       # [i,j]
        return jnp.all(~(st.role == LEADER)[:, None] | pref)

    return leader_completeness


# Registry fragment: name -> builder, in the reference's order of definition.
SAFETY_INVARIANTS: Dict[str, Callable] = {
    "MessagesInv": build_messages_inv,
    "LeaderVotesQuorum": build_leader_votes_quorum,
    "CandidateTermNotInLog": build_candidate_term_not_in_log,
    "ElectionSafety": build_election_safety,
    "LogMatching": build_log_matching,
    "VotesGrantedInv": build_votes_granted_inv,
    "QuorumLogInv": build_quorum_log_inv,
    "MoreUpToDateCorrect": build_more_up_to_date_correct,
    "LeaderCompleteness": build_leader_completeness,
}


# ---------------------------------------------------------------------------
# Pure-Python mirrors (oracle side, for differential tests).


def _py_last_term(log):
    return log[-1][0] if log else 0


def _py_committed(s: PyState, a: int):
    """Committed(a); None marks the undefined commitIndex > Len case."""
    if s.commit_index[a] > len(s.log[a]):
        return None
    return s.log[a][:s.commit_index[a]]


def _py_is_prefix_committed(s: PyState, a: int, b: int) -> bool:
    c = _py_committed(s, a)
    return c is not None and s.log[b][:len(c)] == c


def messages_inv_py(s: PyState, dims: RaftDims) -> bool:
    for (m, _cnt) in s.messages:
        mt, src, dst, mterm = m[0], m[1], m[2], m[3]
        if mterm > s.current_term[src]:                 # :934-935
            return False
        if mt == RVR and m[4] \
                and s.current_term[src] == s.current_term[dst] \
                and s.current_term[src] == mterm:       # :903-910
            lts, ltd = _py_last_term(s.log[src]), _py_last_term(s.log[dst])
            if not (ltd > lts or (ltd == lts
                                  and len(s.log[dst]) >= len(s.log[src]))):
                return False
        if mt == RVQ and s.role[src] == CANDIDATE \
                and s.current_term[src] == mterm:       # :915-920
            if m[5] != len(s.log[src]) or m[4] != _py_last_term(s.log[src]):
                return False
        if mt == AEQ and m[6] and mterm == s.current_term[src]:  # :924-930
            prev, pterm, entries = m[4], m[5], m[6]
            if not (1 <= prev + 1 <= len(s.log[src])
                    and s.log[src][prev] == entries[0]):
                return False
            if 0 < prev <= len(s.log[src]) \
                    and s.log[src][prev - 1][0] != pterm:
                return False
    return True


def leader_votes_quorum_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers
    for i in range(n):
        if s.role[i] != LEADER:
            continue
        cnt = sum(
            1 for j in range(n)
            if s.current_term[j] > s.current_term[i]
            or (s.current_term[j] == s.current_term[i]
                and s.voted_for[j] == i + 1))
        if not 2 * cnt > n:
            return False
    return True


def candidate_term_not_in_log_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers
    for i in range(n):
        if s.role[i] != CANDIDATE:
            continue
        cnt = sum(
            1 for j in range(n)
            if s.current_term[j] == s.current_term[i]
            and s.voted_for[j] in (i + 1, NIL))
        if 2 * cnt > n:
            for j in range(n):
                if any(t == s.current_term[i] for (t, _v) in s.log[j]):
                    return False
    return True


def election_safety_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers

    def max_idx(j, t):
        hits = [k + 1 for k, (et, _v) in enumerate(s.log[j]) if et == t]
        return max(hits) if hits else 0

    for i in range(n):
        if s.role[i] != LEADER:
            continue
        for j in range(n):
            if max_idx(i, s.current_term[i]) < max_idx(j, s.current_term[i]):
                return False
    return True


def log_matching_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers
    for i in range(n):
        for j in range(n):
            for k in range(min(len(s.log[i]), len(s.log[j]))):
                if s.log[i][k][0] == s.log[j][k][0] \
                        and s.log[i][:k + 1] != s.log[j][:k + 1]:
                    return False
    return True


def votes_granted_inv_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers
    for i in range(n):
        for j in range(n):
            if (s.votes_granted[i] >> j) & 1 \
                    and s.current_term[i] == s.current_term[j] \
                    and not _py_is_prefix_committed(s, j, i):
                return False
    return True


def quorum_log_inv_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers
    for i in range(n):
        bad = sum(1 for j in range(n)
                  if not _py_is_prefix_committed(s, i, j))
        if 2 * bad > n:
            return False
    return True


def more_up_to_date_correct_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers
    for i in range(n):
        for j in range(n):
            lti, ltj = _py_last_term(s.log[i]), _py_last_term(s.log[j])
            if (lti > ltj or (lti == ltj
                              and len(s.log[i]) >= len(s.log[j]))) \
                    and not _py_is_prefix_committed(s, j, i):
                return False
    return True


def leader_completeness_py(s: PyState, dims: RaftDims) -> bool:
    n = dims.n_servers
    for i in range(n):
        if s.role[i] == LEADER:
            for j in range(n):
                if not _py_is_prefix_committed(s, j, i):
                    return False
    return True


SAFETY_INVARIANTS_PY: Dict[str, Callable] = {
    "MessagesInv": messages_inv_py,
    "LeaderVotesQuorum": leader_votes_quorum_py,
    "CandidateTermNotInLog": candidate_term_not_in_log_py,
    "ElectionSafety": election_safety_py,
    "LogMatching": log_matching_py,
    "VotesGrantedInv": votes_granted_inv_py,
    "QuorumLogInv": quorum_log_inv_py,
    "MoreUpToDateCorrect": more_up_to_date_correct_py,
    "LeaderCompleteness": leader_completeness_py,
}
