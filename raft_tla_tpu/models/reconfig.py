"""Joint-consensus membership reconfiguration — the configs[4] spec variant.

The reference spec models a *fixed* membership (``Server`` is constant —
/root/reference/raft.tla:11, and the changelog note at raft.tla:1188-1190
says membership changes were removed from the dissertation spec).  The
BASELINE.json target list nonetheless names "Raft + joint-consensus
reconfiguration (dynamic membership) state space" as a checking
configuration, so this module extends the transition system with the Raft
paper's joint-consensus (C_old,new) scheme, the way a TLA+ author would
extend the module — new log-entry kind + two new actions — while every
existing action stays textually untouched (they dispatch through the
``RaftDims`` variant hooks).

Modeling rules (standard joint consensus):

- **Configurations ride in the log.**  A config entry's value encodes one
  or two membership bitmasks: ``CFG_BASE + (old << 8) + new`` is the joint
  configuration C_old,new, and ``CFG_BASE + new`` (old bits zero) is a
  final configuration C_new.  Client values 1..V are untouched, so config
  entries replicate, conflict, and truncate through ``AppendEntries``
  exactly like any other entry — no new message machinery.
- **A server uses the latest configuration in its log** (committed or not;
  the Raft rule), falling back to the initial full membership when its log
  has none.  Truncation by ``ConflictAppendEntriesRequest`` reverts it.
- **Quorums**: under a joint configuration, elections and commitment both
  require a majority of C_old *and* a majority of C_new; under a final
  configuration, a majority of that configuration.  This replaces the
  simple-majority ``Quorum`` (raft.tla:79-81) via ``build_quorum``/
  ``quorum_py``.
- **InitiateReconfig(i, c)**: a leader whose current configuration is
  final (no change in progress — the one-at-a-time rule) appends the joint
  entry C_current,c for a target configuration ``c != current``.
- **FinalizeReconfig(i)**: a leader whose current configuration is the
  joint C_old,new *and whose commitIndex has reached that entry* appends
  the final entry C_new.
- Deliberately permissive (like the base spec): servers outside the
  current configuration still time out, campaign, and vote — their votes
  simply only count toward quorums of configurations that include them;
  a leader excluded by C_new keeps acting until some other action (e.g.
  a higher term) displaces it.  Allowed target configurations are the
  model constant ``TargetConfigs`` (a finite set of bitmasks), the
  analogue of binding ``Server``/``Value`` in MCraft.tla:15-21.

The state schema, fingerprints, and engines are unchanged: a
``ReconfigDims`` is a ``RaftDims`` whose hooks widen the action grid, the
quorum rule, and the TypeOK value domain.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from .dims import LEADER, RaftDims

# Log-entry values >= CFG_BASE are configuration entries; below are client
# values.  Layout: CFG_BASE + (old_mask << 8) + new_mask, old_mask == 0
# meaning a final (non-joint) configuration.  Masks fit 7 bits (N <= 7,
# enforced by ReconfigDims.__post_init__) so the joint encoding fits the
# 2-byte packed value lanes.
CFG_BASE = 1 << 12

A_INITRECONFIG = 10
A_FINALIZE = 11


def joint_value(old_mask: int, new_mask: int) -> int:
    """Log value of the joint entry C_old,new."""
    return CFG_BASE + (old_mask << 8) + new_mask


def final_value(new_mask: int) -> int:
    """Log value of the final entry C_new."""
    return CFG_BASE + new_mask


def config_of_py(log, n: int) -> Tuple[int, int, int]:
    """(old_mask, new_mask, index) of the latest config entry in ``log``;
    old_mask == 0 means final.  Default: initial full membership at
    index 0."""
    for idx in range(len(log), 0, -1):
        v = log[idx - 1][1]
        if v >= CFG_BASE:
            enc = v - CFG_BASE
            return (enc >> 8) & 0xFF, enc & 0xFF, idx
    return 0, (1 << n) - 1, 0


@dataclasses.dataclass(frozen=True)
class ReconfigDims(RaftDims):
    """RaftDims + joint-consensus reconfiguration over ``targets`` (the
    TargetConfigs membership bitmasks a leader may move to)."""

    targets: Tuple[int, ...] = ()

    def __post_init__(self):
        full = (1 << self.n_servers) - 1
        if self.n_servers > 7:
            # joint_value(old, new) = CFG_BASE + (old << 8) + new must fit
            # the 2-byte value lanes (value_bytes below): with 8-bit masks
            # the joint encoding needs 17 bits, so cap membership at 7.
            # Checked BEFORE super().__post_init__ so this message (the
            # rule) is what the user sees, not the generic lane audit's
            # (which would also catch it via max_log_value > 65535).
            raise ValueError("ReconfigDims supports at most 7 servers "
                             "(2-byte log-value packing)")
        super().__post_init__()
        if not self.targets:
            raise ValueError("ReconfigDims needs at least one target config")
        for c in self.targets:
            if not (1 <= c <= full):
                raise ValueError(
                    f"target config {c:#x} not a nonempty subset of the "
                    f"{self.n_servers} servers")

    @property
    def max_log_value(self) -> int:
        """Largest encoded value: a joint entry with both masks full —
        CFG_BASE + (full << 8) + full <= 36,735 for n <= 7.  The lane
        audit (schema.audit_lane_widths) checks this against the 2-byte
        value lanes at construction."""
        full = (1 << self.n_servers) - 1
        return CFG_BASE + (full << 8) + full

    @property
    def value_bytes(self) -> int:
        """Configuration entries (CFG_BASE + (old << 8) + new <= 36,735
        for n <= 7) exceed uint8: the packed row carries value high
        bytes.  Without this, config entries WRAP mod 256 in the queue
        rows — old<<8 and CFG_BASE are multiples of 256, so a joint or
        final entry silently aliases to the client value ``new_mask``,
        corrupting every state past a leader's first InitiateReconfig
        (caught 2026-07-31 by a leader-seeded depth-2 differential)."""
        return 2

    # -- grid -------------------------------------------------------------
    @property
    def extra_families(self) -> tuple:
        n, c = self.n_servers, len(self.targets)
        return (("InitiateReconfig", n * c), ("FinalizeReconfig", n))

    def instance_info(self, g: int) -> tuple:
        base = sum(sz for _n, sz in zip(
            range(10), RaftDims.family_sizes.fget(self)[:10]))
        if g < base:
            return super().instance_info(g)
        k = g - base
        nc = self.n_servers * len(self.targets)
        if k < nc:
            i, t = divmod(k, len(self.targets))
            return A_INITRECONFIG, {"i": i, "c": self.targets[t]}
        k -= nc
        if k < self.n_servers:
            return A_FINALIZE, {"i": k}
        raise IndexError(g)

    # -- quorum (joint rule) ----------------------------------------------
    def build_quorum(self):
        import jax.numpy as jnp

        config_scan = _build_config_scan(self)
        N = self.n_servers

        def maj(member, mask):
            bits = ((mask >> jnp.arange(N, dtype=jnp.int32)) & 1) > 0
            return (2 * jnp.sum((member & bits).astype(jnp.int32))
                    > jnp.sum(bits.astype(jnp.int32)))

        def quorum(st, i, member):
            old, new, _idx = config_scan(st, i)
            return jnp.where(old > 0, maj(member, old) & maj(member, new),
                             maj(member, new))

        return quorum

    def quorum_py(self, s, i: int, mask: int) -> bool:
        old, new, _idx = config_of_py(s.log[i], self.n_servers)

        def maj(cfg: int) -> bool:
            return 2 * bin(mask & cfg).count("1") > bin(cfg).count("1")

        return (maj(old) and maj(new)) if old else maj(new)

    # -- new actions ------------------------------------------------------
    def _append_entry(self, st, i, val):
        """Shared log-append used by BOTH pipelines' extra kernels:
        (fits, successor) for appending ``(term[i], val)`` to log[i]."""
        import jax.numpy as jnp

        from .actions import _add1, _set2
        L = self.max_log
        ln = st.log_len[i]
        kpos = jnp.clip(ln, 0, L - 1)
        return ln < L, st._replace(
            log_term=_set2(st.log_term, i, kpos, st.term[i]),
            log_val=_set2(st.log_val, i, kpos, val),
            log_len=_add1(st.log_len, i, 1))

    def _build_guards(self):
        """Shared (enabled, appended-value) closures for the two extra
        actions — the ONE source of the guard expressions, used by all
        three kernel builders (v1 kernels, v2 lanes, v2 guards-only
        masks) so the pipelines cannot drift."""
        config_scan = _build_config_scan(self)

        def initiate(st, i, c):
            """Leader with a final config appends C_current,c."""
            old, new, _idx = config_scan(st, i)
            en = (st.role[i] == LEADER) & (old == 0) & (c != new)
            return en, CFG_BASE + (new << 8) + c

        def finalize(st, i):
            """Leader whose committed joint config C_old,new appends
            C_new."""
            old, new, idx = config_scan(st, i)
            en = ((st.role[i] == LEADER) & (old > 0)
                  & (st.commit[i] >= idx))
            return en, CFG_BASE + new

        return initiate, finalize

    def build_extra_kernels(self):
        import jax.numpy as jnp

        init_g, fin_g = self._build_guards()
        N = self.n_servers
        i32 = jnp.int32

        def initiate(st, i, c):
            en, val = init_g(st, i, c)
            fits, new_st = self._append_entry(st, i, val)
            return en & fits, en & ~fits, new_st

        def finalize(st, i):
            en, val = fin_g(st, i)
            fits, new_st = self._append_entry(st, i, val)
            return en & fits, en & ~fits, new_st

        targets = jnp.asarray(self.targets, i32)
        c_count = len(self.targets)
        ii = jnp.repeat(jnp.arange(N, dtype=i32), c_count)
        cc = jnp.tile(targets, N)
        servers = jnp.arange(N, dtype=i32)
        return [((ii, cc), initiate), ((servers,), finalize)]

    def build_extra_v2(self, fp):
        """Delta-pipeline kernels (models/actions2.py contract: one
        lane_fn per extra family; param arrays come from
        ``build_extra_kernels``): both extra actions append ONE log entry
        at (i, Len(log[i])) — the same footprint as ClientRequest — so
        the fingerprint delta is three ordered-position shifts and the
        bag is untouched.  The successor comes from the SAME
        ``_append_entry`` the v1 kernels use (no drift between
        pipelines)."""
        import jax.numpy as jnp

        init_g, fin_g = self._build_guards()
        L = self.max_log

        def append_delta_succ(st, i, val):
            ln = st.log_len[i]
            k = jnp.clip(ln, 0, L - 1)
            d_base = fp.dsum(
                fp.dpos(fp.O_LT + i * L + k, st.log_term[i, k],
                        st.term[i]),
                fp.dpos(fp.O_LV + i * L + k, st.log_val[i, k], val),
                fp.dpos(fp.O_LL + i, ln, ln + 1))
            _fits, succ = self._append_entry(st, i, val)
            return d_base, fp.ZD, succ

        def initiate(st, i, c):
            _en, val = init_g(st, i, c)
            return append_delta_succ(st, i, val)

        def finalize(st, i):
            _en, val = fin_g(st, i)
            return append_delta_succ(st, i, val)

        return [initiate, finalize]

    def build_extra_masks_v2(self):
        """Guards-only masks (dims.build_extra_masks_v2 contract): both
        extras append one log entry whose written fields always fit their
        lanes — the value is <= CFG_BASE + (127 << 8) + 127 = 36,735
        against 2-byte value lanes, the entry term is ``term[i]`` which
        the whole-state pack guard already bounds, and ``log_len`` is
        capped by ``max_log`` — so ``pack_ok(successor) ==
        pack_ok(parent)`` exactly and the per-lane successor + pack-guard
        evaluation of the v1 fallback is pure overhead.  Bit-identity
        with that fallback is property-tested (tests/test_actions2.py)."""
        init_g, fin_g = self._build_guards()
        L = self.max_log

        def _append_masks(en, st, i, pk_parent):
            fits = st.log_len[i] < L
            return en & fits, (en & ~fits) | (en & fits & ~pk_parent)

        def initiate(st, pk_parent, i, c):
            en, _val = init_g(st, i, c)
            return _append_masks(en, st, i, pk_parent)

        def finalize(st, pk_parent, i):
            en, _val = fin_g(st, i)
            return _append_masks(en, st, i, pk_parent)

        return [initiate, finalize]

    def extra_successors_py(self, s):
        n = self.n_servers
        out = []
        for i in range(n):
            if s.role[i] != LEADER:
                continue
            old, new, idx = config_of_py(s.log[i], n)
            if old == 0:
                for c in self.targets:
                    if c != new:
                        t = s.replace(log=_append(
                            s.log, i, (s.current_term[i],
                                       joint_value(new, c))))
                        out.append(((A_INITRECONFIG, (i, c)), t))
            elif s.commit_index[i] >= idx:
                t = s.replace(log=_append(
                    s.log, i, (s.current_term[i], final_value(new))))
                out.append(((A_FINALIZE, (i,)), t))
        return out

    # -- TypeOK value domain ----------------------------------------------
    def build_value_ok(self):
        import jax.numpy as jnp

        v, n = self.n_values, self.n_servers
        full = (1 << n) - 1

        def value_ok(vals):
            client = (vals >= 1) & (vals <= v)
            enc = vals - CFG_BASE
            old = (enc >> 8) & 0xFF
            new = enc & 0xFF
            cfg = ((vals >= CFG_BASE)
                   & (enc <= (full << 8) + full)
                   & (new >= 1) & (new <= full) & (old <= full))
            return client | cfg

        return value_ok

    def value_ok_py(self, val: int) -> bool:
        if 1 <= val <= self.n_values:
            return True
        if val >= CFG_BASE:
            enc = val - CFG_BASE
            old, new = (enc >> 8) & 0xFF, enc & 0xFF
            full = (1 << self.n_servers) - 1
            return enc >> 16 == 0 and 1 <= new <= full and old <= full
        return False


def _build_config_scan(dims: "ReconfigDims"):
    """JAX kernel: latest config entry of server i's log ->
    (old_mask, new_mask, 1-based index); default (0, full, 0)."""
    import jax.numpy as jnp

    N, L = dims.n_servers, dims.max_log
    i32 = jnp.int32
    full = (1 << N) - 1

    def config_scan(st, i):
        vals = st.log_val[i]
        lanes = jnp.arange(L, dtype=i32)
        is_cfg = (lanes < st.log_len[i]) & (vals >= CFG_BASE)
        has = jnp.any(is_cfg)
        k = jnp.max(jnp.where(is_cfg, lanes, -1))
        enc = vals[jnp.clip(k, 0, L - 1)] - CFG_BASE
        old = jnp.where(has, (enc >> 8) & 0xFF, 0)
        new = jnp.where(has, enc & 0xFF, full)
        return old, new, jnp.where(has, k + 1, 0)

    return config_scan


def _append(logs, i, entry):
    return logs[:i] + (logs[i] + (entry,),) + logs[i + 1:]
