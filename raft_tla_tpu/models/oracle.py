"""Pure-Python reference interpreter of the Raft spec — the differential oracle.

This is a direct, deliberately naive transcription of the transition system in
/root/reference/raft.tla (actions :136-430).  It exists so the vectorized JAX
kernels (``models/actions.py``) and the full BFS engine have an independent
implementation to be differentially tested against: successor sets must match
state-for-state, and explored-state counts must match run-for-run.

Faithfulness notes (things that MUST match TLC's semantics, per SURVEY §2.2):

- ``AppendEntriesAlreadyDone`` (raft.tla:301-317) conjoins
  ``commitIndex' = m.mcommitIndex`` (:309) with ``UNCHANGED logVars`` (:317,
  the known upstream bug) and ``logVars`` includes ``commitIndex`` (:51) —
  so the action is enabled only when ``m.mcommitIndex = commitIndex[i]``.
  We replicate the bug; "fixing" it changes the state count.
- ``UpdateTerm`` (raft.tla:373-379) leaves the message in flight (:378).
- ``ReturnToFollowerState`` (raft.tla:295-299) does not consume the message.
- ``ConflictAppendEntriesRequest`` (raft.tla:319-325) truncates exactly ONE
  trailing entry (:323-324), independent of where the conflict index is.
- ``Timeout`` does not self-vote (:149-151).
- ``Min``/``Max`` (raft.tla:106-108) are only applied to sets guaranteed
  non-empty at call sites.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .dims import (A_ADVANCECOMMIT, A_APPENDENTRIES, A_BECOMELEADER,
                   A_CLIENTREQUEST, A_DROP, A_DUPLICATE, A_RECEIVE,
                   A_REQUESTVOTE, A_RESTART, A_TIMEOUT, AEQ, AER, CANDIDATE,
                   FOLLOWER, LEADER, NIL, RVQ, RVR, RaftDims)
from .pystate import Message, PyState, bag_add, bag_remove, bag_reply

# An action instance: (family, params tuple) — params are (i,), (i, j),
# (i, v), or (message,) for the three network families.
Action = Tuple[int, Tuple]


def last_term(log) -> int:
    """LastTerm(xlog) — raft.tla:84."""
    return log[-1][0] if log else 0


def quorum(mask: int, n: int) -> bool:
    """votesGranted[i] \\in Quorum — raft.tla:81 (simple majority)."""
    return 2 * bin(mask).count("1") > n


# ---------------------------------------------------------------------------
# Spontaneous server actions (raft.tla:136-236).

def restart(s: PyState, dims: RaftDims, i: int) -> Optional[PyState]:
    """Restart(i) — raft.tla:136-143. Keeps currentTerm, votedFor, log."""
    n = dims.n_servers
    return s.replace(
        role=_set(s.role, i, FOLLOWER),
        votes_responded=_set(s.votes_responded, i, 0),
        votes_granted=_set(s.votes_granted, i, 0),
        next_index=_setrow(s.next_index, i, (1,) * n),
        match_index=_setrow(s.match_index, i, (0,) * n),
        commit_index=_set(s.commit_index, i, 0),
    )


def timeout(s: PyState, dims: RaftDims, i: int) -> Optional[PyState]:
    """Timeout(i) — raft.tla:146-154."""
    if s.role[i] not in (FOLLOWER, CANDIDATE):
        return None
    return s.replace(
        role=_set(s.role, i, CANDIDATE),
        current_term=_set(s.current_term, i, s.current_term[i] + 1),
        voted_for=_set(s.voted_for, i, NIL),          # no self-vote :149-151
        votes_responded=_set(s.votes_responded, i, 0),
        votes_granted=_set(s.votes_granted, i, 0),
    )


def request_vote(s: PyState, dims: RaftDims, i: int, j: int) -> Optional[PyState]:
    """RequestVote(i, j) — raft.tla:157-166.  i = j is allowed."""
    if s.role[i] != CANDIDATE or (s.votes_responded[i] >> j) & 1:
        return None
    m: Message = (RVQ, i, j, s.current_term[i],
                  last_term(s.log[i]), len(s.log[i]))
    return s.replace(messages=bag_add(s.messages, m))


def append_entries(s: PyState, dims: RaftDims, i: int, j: int) -> Optional[PyState]:
    """AppendEntries(i, j) — raft.tla:171-192.  Sends <= 1 entry."""
    if i == j or s.role[i] != LEADER:
        return None
    log_i = s.log[i]
    ni = s.next_index[i][j]
    prev_index = ni - 1
    prev_term = (log_i[prev_index - 1][0]
                 if 0 < prev_index <= len(log_i) else 0)     # :177-180
    last_entry = min(len(log_i), ni)                          # :182
    entries = tuple(log_i[ni - 1:last_entry])                 # SubSeq :183
    m: Message = (AEQ, i, j, s.current_term[i],
                  prev_index, prev_term, entries,
                  min(s.commit_index[i], last_entry))         # :189
    return s.replace(messages=bag_add(s.messages, m))


def become_leader(s: PyState, dims: RaftDims, i: int) -> Optional[PyState]:
    """BecomeLeader(i) — raft.tla:195-203 (quorum via dims.quorum_py, so
    spec variants like joint consensus plug in their rule)."""
    if s.role[i] != CANDIDATE or not dims.quorum_py(s, i, s.votes_granted[i]):
        return None
    n = dims.n_servers
    return s.replace(
        role=_set(s.role, i, LEADER),
        next_index=_setrow(s.next_index, i, (len(s.log[i]) + 1,) * n),
        match_index=_setrow(s.match_index, i, (0,) * n),
    )


def client_request(s: PyState, dims: RaftDims, i: int, v: int) -> Optional[PyState]:
    """ClientRequest(i, v) — raft.tla:206-213."""
    if s.role[i] != LEADER:
        return None
    new_log = s.log[i] + ((s.current_term[i], v),)
    return s.replace(log=_set(s.log, i, new_log))


def advance_commit_index(s: PyState, dims: RaftDims, i: int) -> Optional[PyState]:
    """AdvanceCommitIndex(i) — raft.tla:219-236."""
    if s.role[i] != LEADER:
        return None
    n = dims.n_servers
    log_i = s.log[i]

    def agree(index: int) -> bool:
        mask = (1 << i) | sum(
            1 << k for k in range(n) if s.match_index[i][k] >= index)
        return dims.quorum_py(s, i, mask)                     # :222-226

    agree_indexes = [idx for idx in range(1, len(log_i) + 1) if agree(idx)]
    if agree_indexes and log_i[max(agree_indexes) - 1][0] == s.current_term[i]:
        new_commit = max(agree_indexes)                       # :229-232
    else:
        new_commit = s.commit_index[i]
    return s.replace(commit_index=_set(s.commit_index, i, new_commit))


# ---------------------------------------------------------------------------
# Message handlers (raft.tla:244-403).

def receive(s: PyState, dims: RaftDims, m: Message) -> Optional[PyState]:
    """Receive(m) — raft.tla:388-403.

    The disjuncts are pairwise mutually exclusive (the mterm comparisons
    partition </=/>, role guards partition Follower/Candidate, logOk splits
    Reject/Accept, and the three Accept sub-cases are disjoint), so at most
    one successor exists per message.
    """
    mtype, j, i, mterm = m[0], m[1], m[2], m[3]   # i=mdest, j=msource :389-390

    # UpdateTerm(i, j, m) — raft.tla:373-379.  Message NOT consumed.
    if mterm > s.current_term[i]:
        return s.replace(
            current_term=_set(s.current_term, i, mterm),
            role=_set(s.role, i, FOLLOWER),
            voted_for=_set(s.voted_for, i, NIL),
        )

    if mtype == RVQ:
        return _handle_request_vote_request(s, dims, i, j, m)
    if mtype == RVR:
        if mterm < s.current_term[i]:                 # DropStaleResponse :382
            return s.replace(messages=bag_remove(s.messages, m))
        return _handle_request_vote_response(s, i, j, m)
    if mtype == AEQ:
        return _handle_append_entries_request(s, dims, i, j, m)
    if mtype == AER:
        if mterm < s.current_term[i]:                 # DropStaleResponse :402
            return s.replace(messages=bag_remove(s.messages, m))
        return _handle_append_entries_response(s, i, j, m)
    raise AssertionError(f"bad mtype {mtype}")


def _handle_request_vote_request(s, dims, i, j, m) -> Optional[PyState]:
    """HandleRequestVoteRequest — raft.tla:244-263 (guard mterm <= currentTerm
    established by caller)."""
    _, _, _, mterm, m_last_term, m_last_index = m
    log_ok = (m_last_term > last_term(s.log[i])
              or (m_last_term == last_term(s.log[i])
                  and m_last_index >= len(s.log[i])))          # :245-247
    grant = (mterm == s.current_term[i] and log_ok
             and s.voted_for[i] in (NIL, j + 1))               # :248-250
    resp: Message = (RVR, i, j, s.current_term[i], int(grant),
                     s.log[i])                # full log copy in mlog :257-259
    return s.replace(
        voted_for=_set(s.voted_for, i, j + 1) if grant else s.voted_for,
        messages=bag_reply(s.messages, resp, m),
    )


def _handle_request_vote_response(s, i, j, m) -> PyState:
    """HandleRequestVoteResponse — raft.tla:267-279 (mterm = currentTerm[i]).
    Tallies even when not Candidate (:268-269)."""
    granted = m[4]
    return s.replace(
        votes_responded=_set(s.votes_responded, i,
                             s.votes_responded[i] | (1 << j)),
        votes_granted=_set(s.votes_granted, i,
                           s.votes_granted[i] | (1 << j) if granted
                           else s.votes_granted[i]),
        messages=bag_remove(s.messages, m),
    )


def _handle_append_entries_request(s, dims, i, j, m) -> Optional[PyState]:
    """HandleAppendEntriesRequest — raft.tla:347-356 and its three branches."""
    _, _, _, mterm, prev_index, prev_term, entries, m_commit = m
    log_i = s.log[i]
    log_ok = (prev_index == 0
              or (0 < prev_index <= len(log_i)
                  and prev_term == log_i[prev_index - 1][0]))  # :348-351

    # RejectAppendEntriesRequest — raft.tla:281-293.
    if (mterm < s.current_term[i]
            or (mterm == s.current_term[i] and s.role[i] == FOLLOWER
                and not log_ok)):
        resp: Message = (AER, i, j, s.current_term[i], 0, 0)
        return s.replace(messages=bag_reply(s.messages, resp, m))

    # ReturnToFollowerState — raft.tla:295-299. Message not consumed.
    if mterm == s.current_term[i] and s.role[i] == CANDIDATE:
        return s.replace(role=_set(s.role, i, FOLLOWER))

    # AcceptAppendEntriesRequest — raft.tla:333-341.
    if mterm == s.current_term[i] and s.role[i] == FOLLOWER and log_ok:
        index = prev_index + 1                                  # :338
        already_done = (entries == ()
                        or (len(log_i) >= index
                            and log_i[index - 1][0] == entries[0][0]))
        if already_done:
            # AppendEntriesAlreadyDone — raft.tla:301-317, including the
            # :317 UNCHANGED-logVars bug: enabled only if mcommitIndex equals
            # the current commitIndex (hidden guard).
            if m_commit != s.commit_index[i]:
                return None
            resp = (AER, i, j, s.current_term[i], 1,
                    prev_index + len(entries))                  # :313
            return s.replace(messages=bag_reply(s.messages, resp, m))
        if len(log_i) >= index and log_i[index - 1][0] != entries[0][0]:
            # ConflictAppendEntriesRequest — raft.tla:319-325: drop exactly
            # one trailing entry; no reply, message stays in flight.
            return s.replace(log=_set(s.log, i, log_i[:-1]))
        if len(log_i) == prev_index:
            # NoConflictAppendEntriesRequest — raft.tla:327-331.
            return s.replace(log=_set(s.log, i, log_i + (entries[0],)))
        return None

    return None  # e.g. Leader receiving same-term AEQ: no branch enabled.


def _handle_append_entries_response(s, i, j, m) -> PyState:
    """HandleAppendEntriesResponse — raft.tla:360-370 (mterm = currentTerm)."""
    success, mmatch = m[4], m[5]
    if success:
        ni = _setcell(s.next_index, i, j, mmatch + 1)
        mi = _setcell(s.match_index, i, j, mmatch)
    else:
        ni = _setcell(s.next_index, i, j, max(s.next_index[i][j] - 1, 1))
        mi = s.match_index
    return s.replace(next_index=ni, match_index=mi,
                     messages=bag_remove(s.messages, m))


def duplicate_message(s: PyState, m: Message) -> PyState:
    """DuplicateMessage(m) — raft.tla:410-412."""
    return s.replace(messages=bag_add(s.messages, m))


def drop_message(s: PyState, m: Message) -> PyState:
    """DropMessage(m) — raft.tla:415-417."""
    return s.replace(messages=bag_remove(s.messages, m))


# ---------------------------------------------------------------------------
# Next — raft.tla:421-430.

def successors(s: PyState, dims: RaftDims) -> List[Tuple[Action, PyState]]:
    """All (action, successor) pairs of the Next disjunction for state s."""
    n, v = dims.n_servers, dims.n_values
    out: List[Tuple[Action, PyState]] = []

    def add(fam, params, t):
        if t is not None:
            out.append(((fam, params), t))

    for i in range(n):
        add(A_RESTART, (i,), restart(s, dims, i))
        add(A_TIMEOUT, (i,), timeout(s, dims, i))
        add(A_BECOMELEADER, (i,), become_leader(s, dims, i))
        add(A_ADVANCECOMMIT, (i,), advance_commit_index(s, dims, i))
        for j in range(n):
            add(A_REQUESTVOTE, (i, j), request_vote(s, dims, i, j))
            add(A_APPENDENTRIES, (i, j), append_entries(s, dims, i, j))
        for val in range(1, v + 1):
            add(A_CLIENTREQUEST, (i, val), client_request(s, dims, i, val))
    for m, _count in s.messages:          # \E m \in DOMAIN messages
        add(A_RECEIVE, (m,), receive(s, dims, m))
        add(A_DUPLICATE, (m,), duplicate_message(s, m))
        add(A_DROP, (m,), drop_message(s, m))
    out.extend(dims.extra_successors_py(s))   # spec-variant families
    return out


def successor_set(s: PyState, dims: RaftDims) -> set:
    return {t for _a, t in successors(s, dims)}


# ---------------------------------------------------------------------------
# Oracle BFS — mirrors TLC's exhaustive mode [TLC semantics — external] with
# TLC's constraint behavior: a state violating CONSTRAINT is still generated,
# invariant-checked, and counted as distinct, but never expanded.

class OracleResult:
    def __init__(self):
        self.distinct_states = 0
        self.generated_states = 0   # successor evaluations (incl. duplicates)
        self.diameter = 0           # number of completed BFS levels
        self.invariant_violation: Optional[Tuple[str, PyState]] = None
        self.deadlock_state: Optional[PyState] = None
        self.levels: List[int] = []  # new distinct states per level
        self.parent: Dict[PyState, Tuple[Optional[PyState], Optional[Action]]] = {}

    def trace_to(self, s: PyState) -> List[Tuple[Optional[Action], PyState]]:
        """Walk parent links back to an initial state; returns root-first."""
        chain = []
        cur: Optional[PyState] = s
        while cur is not None:
            par, act = self.parent[cur]
            chain.append((act, cur))
            cur = par
        return list(reversed(chain))


def bfs(init_states: Iterable[PyState], dims: RaftDims,
        invariants: Optional[Dict[str, Callable[[PyState, RaftDims], bool]]] = None,
        constraint: Optional[Callable[[PyState, RaftDims], bool]] = None,
        check_deadlock: bool = True,
        max_levels: Optional[int] = None,
        stop_predicate: Optional[Callable[[OracleResult], bool]] = None,
        ) -> OracleResult:
    """Exhaustive BFS with TLC semantics.  Small models only (oracle)."""
    invariants = invariants or {}
    res = OracleResult()
    seen: set = set()
    frontier: List[PyState] = []

    def admit(t: PyState, parent: Optional[PyState], act: Optional[Action]) -> bool:
        """Insert a generated state; returns True if it should be expanded."""
        if t in seen:
            return False
        seen.add(t)
        res.parent[t] = (parent, act)
        res.distinct_states += 1
        for name, pred in invariants.items():
            if not pred(t, dims):
                if res.invariant_violation is None:
                    res.invariant_violation = (name, t)
        return constraint is None or constraint(t, dims)

    for s0 in init_states:
        if admit(s0, None, None):
            frontier.append(s0)
    res.levels.append(len(frontier))

    while frontier:
        if res.invariant_violation is not None:
            break
        if max_levels is not None and res.diameter >= max_levels:
            break
        if stop_predicate is not None and stop_predicate(res):
            break
        next_frontier: List[PyState] = []
        for s in frontier:
            succ = successors(s, dims)
            res.generated_states += len(succ)
            if not succ and check_deadlock and res.deadlock_state is None:
                res.deadlock_state = s
            for act, t in succ:
                if admit(t, s, act):
                    next_frontier.append(t)
        res.diameter += 1
        res.levels.append(len(next_frontier))
        frontier = next_frontier
    return res


# ---------------------------------------------------------------------------
# tuple-surgery helpers

def _set(tup: Tuple, i: int, val) -> Tuple:
    return tup[:i] + (val,) + tup[i + 1:]


def _setrow(mat: Tuple[Tuple, ...], i: int, row: Tuple) -> Tuple:
    return mat[:i] + (row,) + mat[i + 1:]


def _setcell(mat: Tuple[Tuple, ...], i: int, j: int, val) -> Tuple:
    return _setrow(mat, i, _set(mat[i], j, val))
