"""Vectorized successor kernels — the ``Next`` relation compiled for TPU.

Each disjunct of ``Next`` (/root/reference/raft.tla:421-430) becomes a
branch-free JAX function ``(state, params) -> (enabled, overflow, state')``
operating on one ``StateBatch`` (no batch axis).  ``build_expand`` statically
unrolls the full action-instance grid (``dims.family_sizes``) with ``vmap``
over the parameter arrays, and the engine vmaps the result over the frontier
axis — so one XLA program evaluates every action of every frontier state as
pure tensor arithmetic on the MXU/VPU, with no data-dependent control flow.

Semantics are transcribed from the spec with the same faithfulness notes as
``oracle.py`` (hidden AppendEntriesAlreadyDone guard raft.tla:309+:317,
UpdateTerm leaving the message in flight :378, single-entry truncation
:323-324).  The mutual exclusivity of the ``Receive`` disjuncts (term
comparisons partition </=/>, role guards partition F/C, the three Accept
sub-cases are disjoint) lets ``Receive`` compile to a single ``jnp.where``
cascade emitting at most one successor per message slot.

``overflow`` reports states the fixed-width encoding cannot represent (log
beyond capacity L, more than M distinct messages).  The engine surfaces any
overflow as a hard error so a run can be repeated with larger capacities —
results are never silently truncated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .dims import (AEQ, AER, CANDIDATE, FOLLOWER, LEADER, NIL, RVQ, RVR,
                   RaftDims)
from .schema import StateBatch

_TRUE = jnp.bool_(True)
_FALSE = jnp.bool_(False)

# BLEST-style expansion groups (PAPERS.md #1; ROADMAP item 2a): base
# families sharing a parameter shape are STACKED into one dense
# dispatch — one vmap over the concatenated parameter grid with a
# per-lane family selector — instead of one vmapped kernel per family.
# Indices are positions in the build_kernels list; the grouping is
# sound because the grouped kernels are pure functions of (state,
# params) — the slot-precise dependence matrices (analysis/effects.py,
# PR 11's 3469 proven-independent pairs) certify the families never
# observe each other within a step, so evaluating them jointly on the
# stacked grid and masking by selector is value-identical to the
# per-family loop.  Kept module-level so family_groups() (the
# report/ledger metadata) and _build (the executed dispatch) cannot
# drift apart.
_BASE_GROUPS = (
    ("server", (0, 1, 3, 5)),         # Restart/Timeout/BecomeLeader/ACI (i,)
    ("server_pair", (2, 6)),          # RequestVote/AppendEntries (i, j)
    ("server_value", (4,)),           # ClientRequest (i, v)
    ("slot", (7, 8, 9)),              # Receive/Duplicate/Drop (s,)
)
_BASE_FAMILY_NAMES = ("Restart", "Timeout", "RequestVote", "BecomeLeader",
                      "ClientRequest", "AdvanceCommitIndex",
                      "AppendEntries", "Receive", "DuplicateMessage",
                      "DropMessage")


def family_groups(dims: RaftDims):
    """Static description of the batched-expansion grouping: one dict
    per stacked dispatch, ``{"group", "families", "kernels", "lanes"}``
    with ``kernels`` the number of family kernels stacked into the
    group's dense dispatch and ``lanes`` its instance-grid width.
    Recorded on EngineResult/report/history so the BLEST win stays
    attributable per family.  Extra (variant) families are singleton
    groups — their parameter grids are theirs alone."""
    names = list(dims.family_names)
    sizes = list(dims.family_sizes)
    if tuple(names[:10]) != _BASE_FAMILY_NAMES:
        # A variant that rewrites the base alphabet gets the honest
        # ungrouped description rather than a mislabeled stacking.
        return [{"group": n, "families": [n], "kernels": 1,
                 "lanes": int(s)} for n, s in zip(names, sizes)]
    out = [{"group": gname, "families": [names[m] for m in members],
            "kernels": len(members),
            "lanes": int(sum(sizes[m] for m in members))}
           for gname, members in _BASE_GROUPS]
    out += [{"group": names[k], "families": [names[k]], "kernels": 1,
             "lanes": int(sizes[k])} for k in range(10, len(names))]
    return out


def _sel(cond, then_tree, else_tree):
    """Tree-wide where on a scalar bool."""
    return jax.tree.map(lambda a, b: jnp.where(cond, a, b), then_tree,
                        else_tree)


# Point updates are written as arange-mask selects, NOT ``.at[i].set``:
# with a traced index under the (instance x frontier) double vmap the
# latter lowers to one scatter per field per action — hundreds of tiny
# serializing scatters per batch on TPU — while a mask select lowers to
# pure elementwise VPU code that XLA fuses across the whole where-cascade.

def _set1(arr, i, v):
    """arr[i] = v for a 1-D field ([N] or [M])."""
    return jnp.where(jnp.arange(arr.shape[0]) == i, v, arr)


def _add1(arr, i, d):
    return jnp.where(jnp.arange(arr.shape[0]) == i, arr + d, arr)


def _setrow(arr, i, row):
    """arr[i, :] = row for a 2-D field ([N,N] or [M,W])."""
    return jnp.where((jnp.arange(arr.shape[0]) == i)[:, None],
                     row[None, :], arr)


def _set2(arr, i, k, v):
    """arr[i, k] = v for a 2-D field ([N,L] or [N,N])."""
    mask = (jnp.arange(arr.shape[0]) == i)[:, None] \
        & (jnp.arange(arr.shape[1]) == k)[None, :]
    return jnp.where(mask, v, arr)


def build_kernels(dims: RaftDims):
    """Per-family successor kernels: ``[(name, kernel, param_arrays)]`` in
    ``dims.family_names`` order, each ``kernel(state, *params) ->
    (enabled, overflow, state')`` for ONE action instance.

    This is the seam the static analyzers (``analysis/``) trace through:
    every family is exposed individually so effect extraction and interval
    bound analysis can build one jaxpr per action instance instead of
    dissecting the fused ``build_expand`` program.  ``build_expand``
    assembles the grid from exactly this list, so the analyzed kernels and
    the executed ones cannot drift apart."""
    return _build(dims)[0]


def build_expand(dims: RaftDims):
    """Returns ``expand(state) -> (cands, enabled, overflow)`` where
    ``cands`` stacks ``dims.n_instances`` candidate successors."""
    return _build(dims)[1]


def _build(dims: RaftDims):
    N, V, L, M, W = (dims.n_servers, dims.n_values, dims.max_log,
                     dims.n_msg_slots, dims.msg_width)
    i32 = jnp.int32
    # Quorum evaluation dispatches through the dims hook so spec variants
    # (models/reconfig.py joint consensus) change it without touching the
    # kernels; the base spec is the simple majority of raft.tla:79-81.
    quorum = dims.build_quorum()

    # -- helpers ----------------------------------------------------------
    def last_term(st: StateBatch, i):
        """LastTerm(log[i]) — raft.tla:84."""
        ln = st.log_len[i]
        return jnp.where(ln > 0, st.log_term[i, jnp.clip(ln - 1, 0, L - 1)], 0)

    def bag_send(st: StateBatch, mvec):
        """Send(m) — raft.tla:95: bag count +1, allocating a slot if new.
        Returns (state', ok); ok=False means slot overflow."""
        eq = jnp.all(st.msg == mvec[None, :], axis=1) & (st.msg_cnt > 0)
        has_eq = jnp.any(eq)
        free = st.msg_cnt == 0
        ok = has_eq | jnp.any(free)
        idx = jnp.where(has_eq, jnp.argmax(eq), jnp.argmax(free))
        row = jnp.where(has_eq | ~ok, st.msg[idx], mvec)
        return st._replace(
            msg=_setrow(st.msg, idx, row),
            msg_cnt=_add1(st.msg_cnt, idx, jnp.where(ok, 1, 0))), ok

    def bag_discard_slot(st: StateBatch, s):
        """Discard one copy of the message in slot s — raft.tla:99.  Zeroes
        the row when the count hits 0 (canonical free slot)."""
        new_cnt = _add1(st.msg_cnt, s, -1)
        row = jnp.where(new_cnt[s] > 0, st.msg[s], jnp.zeros((W,), i32))
        return st._replace(msg=_setrow(st.msg, s, row), msg_cnt=new_cnt)

    def reply_slot(st: StateBatch, resp, s):
        """Reply(resp, m@slot s) — raft.tla:102-103 (atomic discard+send)."""
        return bag_send(bag_discard_slot(st, s), resp)

    def base_msg(mtype, src, dst, mterm):
        m = jnp.zeros((W,), i32)
        return m.at[0].set(mtype + 1).at[1].set(src + 1).at[2].set(dst + 1) \
                .at[3].set(mterm)

    # -- spontaneous server actions (raft.tla:136-236) --------------------
    def restart(st: StateBatch, i):
        """Restart(i) — raft.tla:136-143."""
        new = st._replace(
            role=_set1(st.role, i, FOLLOWER),
            votes_resp=_set1(st.votes_resp, i, 0),
            votes_gran=_set1(st.votes_gran, i, 0),
            next_idx=_setrow(st.next_idx, i, jnp.ones((N,), i32)),
            match_idx=_setrow(st.match_idx, i, jnp.zeros((N,), i32)),
            commit=_set1(st.commit, i, 0))
        return _TRUE, _FALSE, new

    def timeout(st: StateBatch, i):
        """Timeout(i) — raft.tla:146-154 (no self-vote)."""
        en = (st.role[i] == FOLLOWER) | (st.role[i] == CANDIDATE)
        new = st._replace(
            role=_set1(st.role, i, CANDIDATE),
            term=_add1(st.term, i, 1),
            voted_for=_set1(st.voted_for, i, NIL),
            votes_resp=_set1(st.votes_resp, i, 0),
            votes_gran=_set1(st.votes_gran, i, 0))
        return en, _FALSE, new

    def request_vote(st: StateBatch, i, j):
        """RequestVote(i, j) — raft.tla:157-166 (i = j allowed)."""
        en = (st.role[i] == CANDIDATE) & (((st.votes_resp[i] >> j) & 1) == 0)
        m = base_msg(RVQ, i, j, st.term[i]) \
            .at[4].set(last_term(st, i)).at[5].set(st.log_len[i])
        new, ok = bag_send(st, m)
        return en & ok, en & ~ok, new

    def append_entries(st: StateBatch, i, j):
        """AppendEntries(i, j) — raft.tla:171-192 (<= 1 entry)."""
        en = (i != j) & (st.role[i] == LEADER)
        ln = st.log_len[i]
        ni = st.next_idx[i, j]
        prev = ni - 1
        prev_term = jnp.where((prev > 0) & (prev <= ln),
                              st.log_term[i, jnp.clip(prev - 1, 0, L - 1)], 0)
        last_entry = jnp.minimum(ln, ni)                      # :182
        n_ent = (ln >= ni).astype(i32)                        # SubSeq :183
        eterm = jnp.where(n_ent > 0,
                          st.log_term[i, jnp.clip(ni - 1, 0, L - 1)], 0)
        eval_ = jnp.where(n_ent > 0,
                          st.log_val[i, jnp.clip(ni - 1, 0, L - 1)], 0)
        m = base_msg(AEQ, i, j, st.term[i]) \
            .at[4].set(prev).at[5].set(prev_term).at[6].set(n_ent) \
            .at[7].set(eterm).at[8].set(eval_) \
            .at[9].set(jnp.minimum(st.commit[i], last_entry))  # :189
        new, ok = bag_send(st, m)
        return en & ok, en & ~ok, new

    def become_leader(st: StateBatch, i):
        """BecomeLeader(i) — raft.tla:195-203; quorum = simple majority :81
        (or the variant's rule via dims.build_quorum)."""
        member = ((st.votes_gran[i] >> jnp.arange(N, dtype=i32)) & 1) > 0
        en = (st.role[i] == CANDIDATE) & quorum(st, i, member)
        new = st._replace(
            role=_set1(st.role, i, LEADER),
            next_idx=_setrow(
                st.next_idx, i,
                jnp.broadcast_to(st.log_len[i] + 1, (N,)).astype(i32)),
            match_idx=_setrow(st.match_idx, i, jnp.zeros((N,), i32)))
        return en, _FALSE, new

    def client_request(st: StateBatch, i, v):
        """ClientRequest(i, v) — raft.tla:206-213."""
        ln = st.log_len[i]
        is_leader = st.role[i] == LEADER
        fits = ln < L
        k = jnp.clip(ln, 0, L - 1)
        new = st._replace(
            log_term=_set2(st.log_term, i, k, st.term[i]),
            log_val=_set2(st.log_val, i, k, v),
            log_len=_add1(st.log_len, i, 1))
        return is_leader & fits, is_leader & ~fits, new

    def advance_commit(st: StateBatch, i):
        """AdvanceCommitIndex(i) — raft.tla:219-236 incl. the §5.4.2
        own-term rule (:229-230)."""
        en = st.role[i] == LEADER
        idxs = jnp.arange(1, L + 1, dtype=i32)                      # [L]
        # Agree(index) == {i} \cup {k : matchIndex[i][k] >= index}  :222-223
        member = ((st.match_idx[i][None, :] >= idxs[:, None])
                  | (jnp.arange(N)[None, :] == i))                  # [L,N]
        ok = jax.vmap(lambda mem: quorum(st, i, mem))(member) \
            & (idxs <= st.log_len[i])                               # :225-226
        any_ok = jnp.any(ok)
        max_agree = jnp.max(jnp.where(ok, idxs, 0))                 # Max :232
        own_term = st.log_term[i, jnp.clip(max_agree - 1, 0, L - 1)] \
            == st.term[i]
        new_commit = jnp.where(any_ok & own_term, max_agree, st.commit[i])
        return en, _FALSE, st._replace(commit=_set1(st.commit, i, new_commit))

    # -- Receive(m) (raft.tla:388-403) ------------------------------------
    def receive(st: StateBatch, s):
        """Receive of the message in slot s: a where-cascade over the
        pairwise-exclusive disjuncts; at most one fires."""
        mvec = st.msg[s]
        occ = st.msg_cnt[s] > 0
        mtype = mvec[0] - 1
        # i = mdest, j = msource (raft.tla:389-390); clipped so gathers and
        # shifts stay in range on free (all-zero) rows — every use is gated
        # on occupancy, so the clip never changes an enabled branch.
        j = jnp.clip(mvec[1] - 1, 0, N - 1)
        i = jnp.clip(mvec[2] - 1, 0, N - 1)
        mterm = mvec[3]
        t_i = st.term[i]
        role_i = st.role[i]
        ln = st.log_len[i]

        # UpdateTerm — raft.tla:373-379; message left in flight (:378).
        en_ut = occ & (mterm > t_i)
        st_ut = st._replace(term=_set1(st.term, i, mterm),
                            role=_set1(st.role, i, FOLLOWER),
                            voted_for=_set1(st.voted_for, i, NIL))

        le = occ & (mterm <= t_i)

        # HandleRequestVoteRequest — raft.tla:244-263.
        lt = last_term(st, i)
        rvq_logok = (mvec[4] > lt) | ((mvec[4] == lt) & (mvec[5] >= ln))
        grant = (mterm == t_i) & rvq_logok & \
            ((st.voted_for[i] == NIL) | (st.voted_for[i] == j + 1))
        rvr_resp = base_msg(RVR, i, j, t_i) \
            .at[4].set(grant.astype(i32)).at[5].set(ln)
        # mlog carries the full log copy (:257-259, :465).
        rvr_resp = jax.lax.dynamic_update_slice(rvr_resp, st.log_term[i], (6,))
        rvr_resp = jax.lax.dynamic_update_slice(rvr_resp, st.log_val[i],
                                                (6 + L,))
        st_rvq = st._replace(
            voted_for=jnp.where(grant,
                                _set1(st.voted_for, i, j + 1), st.voted_for))
        st_rvq, rvq_ok = reply_slot(st_rvq, rvr_resp, s)
        en_rvq = le & (mtype == RVQ)

        # RequestVoteResponse: DropStaleResponse :382-385 / Handle :267-279.
        en_rvr_drop = le & (mtype == RVR) & (mterm < t_i)
        en_rvr = le & (mtype == RVR) & (mterm == t_i)
        st_rvr = bag_discard_slot(
            st._replace(
                votes_resp=_set1(st.votes_resp, i,
                                 st.votes_resp[i] | (1 << j)),
                votes_gran=_set1(
                    st.votes_gran, i,
                    st.votes_gran[i] | (jnp.where(mvec[4] > 0, 1, 0) << j))),
            s)

        # AppendEntriesRequest — raft.tla:347-356.
        prev, pterm, n_ent = mvec[4], mvec[5], mvec[6]
        eterm, eval_, mcommit = mvec[7], mvec[8], mvec[9]
        aeq_logok = (prev == 0) | \
            ((prev > 0) & (prev <= ln)
             & (pterm == st.log_term[i, jnp.clip(prev - 1, 0, L - 1)]))
        en_aeq = le & (mtype == AEQ)
        # Reject — :281-293.
        en_rej = en_aeq & ((mterm < t_i)
                           | ((mterm == t_i) & (role_i == FOLLOWER)
                              & ~aeq_logok))
        rej_resp = base_msg(AER, i, j, t_i)        # success=0, matchIndex=0
        st_rej, rej_ok = reply_slot(st, rej_resp, s)
        # ReturnToFollowerState — :295-299 (message not consumed).
        en_rtf = en_aeq & (mterm == t_i) & (role_i == CANDIDATE)
        st_rtf = st._replace(role=_set1(st.role, i, FOLLOWER))
        # Accept — :333-341, index == mprevLogIndex + 1.
        acc = en_aeq & (mterm == t_i) & (role_i == FOLLOWER) & aeq_logok
        index = prev + 1
        have_at = ln >= index
        term_at = st.log_term[i, jnp.clip(index - 1, 0, L - 1)]
        done_shape = (n_ent == 0) | (have_at & (term_at == eterm))
        # AlreadyDone — :301-317 with the :317 hidden guard.
        en_done = acc & done_shape & (mcommit == st.commit[i])
        done_resp = base_msg(AER, i, j, t_i) \
            .at[4].set(1).at[5].set(prev + n_ent)               # :313
        st_done, done_ok = reply_slot(st, done_resp, s)
        # Conflict — :319-325: drop exactly one trailing entry, no reply.
        en_conf = acc & (n_ent > 0) & have_at & (term_at != eterm)
        k_last = jnp.clip(ln - 1, 0, L - 1)
        st_conf = st._replace(
            log_term=_set2(st.log_term, i, k_last, 0),
            log_val=_set2(st.log_val, i, k_last, 0),
            log_len=_add1(st.log_len, i, -1))
        # NoConflict — :327-331: append mentries[1].
        fits = ln < L
        en_noc = acc & (n_ent > 0) & (ln == prev)
        k_app = jnp.clip(ln, 0, L - 1)
        st_noc = st._replace(
            log_term=_set2(st.log_term, i, k_app, eterm),
            log_val=_set2(st.log_val, i, k_app, eval_),
            log_len=_add1(st.log_len, i, 1))

        # AppendEntriesResponse: DropStale :402 / Handle :360-370.
        en_aer_drop = le & (mtype == AER) & (mterm < t_i)
        en_aer = le & (mtype == AER) & (mterm == t_i)
        succ, mmatch = mvec[4] > 0, mvec[5]
        st_aer = bag_discard_slot(
            st._replace(
                next_idx=_set2(
                    st.next_idx, i, j,
                    jnp.where(succ, mmatch + 1,
                              jnp.maximum(st.next_idx[i, j] - 1, 1))),
                match_idx=_set2(st.match_idx, i, j,
                                jnp.where(succ, mmatch, st.match_idx[i, j]))),
            s)

        st_drop = bag_discard_slot(st, s)

        overflow = (en_rvq & ~rvq_ok) | (en_rej & ~rej_ok) | \
            (en_done & ~done_ok) | (en_noc & ~fits)
        enabled = (en_ut | en_rvq | en_rvr_drop | en_rvr | en_rej | en_rtf
                   | en_done | en_conf | en_noc | en_aer_drop | en_aer) \
            & ~overflow
        out = st
        for cond, branch in (
                (en_ut, st_ut), (en_rvq, st_rvq),
                (en_rvr_drop | en_aer_drop, st_drop),
                (en_rvr, st_rvr), (en_rej, st_rej), (en_rtf, st_rtf),
                (en_done, st_done), (en_conf, st_conf), (en_noc, st_noc),
                (en_aer, st_aer)):
            out = _sel(cond, branch, out)
        return enabled, overflow, out

    def duplicate(st: StateBatch, s):
        """DuplicateMessage — raft.tla:410-412 (bag count +1)."""
        occ = st.msg_cnt[s] > 0
        return occ, _FALSE, st._replace(
            msg_cnt=_add1(st.msg_cnt, s, jnp.where(occ, 1, 0)))

    def drop(st: StateBatch, s):
        """DropMessage — raft.tla:415-417 (bag count -1)."""
        occ = st.msg_cnt[s] > 0
        return occ, _FALSE, bag_discard_slot(st, s)

    # -- grid assembly (Next — raft.tla:421-430) --------------------------
    servers = jnp.arange(N, dtype=i32)
    ii = jnp.repeat(jnp.arange(N, dtype=i32), N)
    jj = jnp.tile(jnp.arange(N, dtype=i32), N)
    ci = jnp.repeat(jnp.arange(N, dtype=i32), V)
    cv = jnp.tile(jnp.arange(1, V + 1, dtype=i32), N)
    slots = jnp.arange(M, dtype=i32)
    kernels = [
        ("Restart", restart, (servers,)),
        ("Timeout", timeout, (servers,)),
        ("RequestVote", request_vote, (ii, jj)),
        ("BecomeLeader", become_leader, (servers,)),
        ("ClientRequest", client_request, (ci, cv)),
        ("AdvanceCommitIndex", advance_commit, (servers,)),
        ("AppendEntries", append_entries, (ii, jj)),
        ("Receive", receive, (slots,)),
        ("DuplicateMessage", duplicate, (slots,)),
        ("DropMessage", drop, (slots,)),
    ]
    for (params, kern), (name, _sz) in zip(dims.build_extra_kernels(),
                                           dims.extra_families):
        kernels.append((name, kern, tuple(params)))

    # -- BLEST-batched dispatch (_BASE_GROUPS) ----------------------------
    # Families sharing a parameter shape run as ONE stacked dense kernel:
    # the group's parameter grids concatenate, a per-lane selector picks
    # the family, and every member kernel is evaluated densely with the
    # result masked in by a where-cascade (branch-free, MXU/VPU-friendly
    # — the BLEST formulation).  The selected lane's value is exactly
    # ``kern(st, *its_own_params)``, so the grid stays bit-identical to
    # the per-family loop; a static permutation restores
    # dims.family_offsets order after the group-major concatenation.
    if tuple(n for n, _k, _p in kernels[:10]) == _BASE_FAMILY_NAMES:
        groups = [(g, list(m)) for g, m in _BASE_GROUPS]
        groups += [(kernels[k][0], [k]) for k in range(10, len(kernels))]
    else:   # rewritten base alphabet: honest per-family dispatch
        groups = [(kernels[k][0], [k]) for k in range(len(kernels))]
    sizes = [int(p[0].shape[0]) for _n, _k, p in kernels]

    def _make_group(members):
        kerns = [kernels[m][1] for m in members]

        def gk(st, which, *params):
            en, ovf, new = kerns[0](st, *params)
            for idx in range(1, len(kerns)):
                e2, o2, n2 = kerns[idx](st, *params)
                take = which == idx
                en = jnp.where(take, e2, en)
                ovf = jnp.where(take, o2, ovf)
                new = _sel(take, n2, new)
            return en, ovf, new

        return gk

    grouped = []
    for _gname, members in groups:
        if len(members) == 1:
            name, kern, params = kernels[members[0]]
            grouped.append((jax.vmap(kern, (None,) + (0,) * len(params)),
                            params))
        else:
            nparam = len(kernels[members[0]][2])
            stacked = tuple(
                jnp.concatenate([kernels[m][2][a] for m in members])
                for a in range(nparam))
            which = jnp.concatenate([
                jnp.full((sizes[m],), gi, i32)
                for gi, m in enumerate(members)])
            grouped.append((
                jax.vmap(_make_group(members),
                         (None, 0) + (0,) * nparam),
                (which,) + stacked))
    # Final lane f (family order) lives at perm[f] in the group-major
    # concatenation; identity when the grouping degenerates to
    # one-family-per-group.
    gorder = [m for _g, members in groups for m in members]
    starts, pos = {}, 0
    for f in gorder:
        starts[f], pos = pos, pos + sizes[f]
    perm = np.concatenate([
        np.arange(starts[f], starts[f] + sizes[f])
        for f in range(len(kernels))])

    def expand(st: StateBatch):
        """All candidate successors of one state.  Returns
        (cands [G,...], enabled [G], overflow [G]) with G = n_instances,
        ordered per dims.family_offsets."""
        outs = [gfn(st, *args) for gfn, args in grouped]
        enabled = jnp.concatenate([o[0] for o in outs])[perm]
        overflow = jnp.concatenate([o[1] for o in outs])[perm]
        cands = jax.tree.map(lambda *xs: jnp.concatenate(xs)[perm],
                             *(o[2] for o in outs))
        return cands, enabled, overflow

    return kernels, expand
