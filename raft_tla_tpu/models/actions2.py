"""The v2 (delta) successor pipeline — guards first, construction last.

The first TPU profile of the v1 chunk (artifacts/profile_step_tpu.txt,
2026-07-31, B=2048) showed 85% of the 89 ms/batch in three stages that all
scale with *full candidate-state construction over every B*G lane*:

    expand (36.6 ms)       builds a complete ~473-field successor struct
                           for all 270,336 lanes, ~88% of them masked off;
    compact (+21 ms)       a 270k-lane scatter;
    materialize (+24.6 ms) gathers the full candidate tree for K lanes.

This module restructures the work so the per-lane cost before compaction
is *guards only* (a few dozen scalar ops), and full successors are
constructed for exactly the K compacted lanes:

1. ``masks(state) -> (enabled [G], overflow [G])`` — the action guards of
   models/actions.py with zero state construction.  Bit-identical to v1's
   (enabled, overflow) by construction and by property test.
2. ``parent_hash(state) -> PH`` — the fingerprint's internal sums for one
   parent: the ordered-part sum ``base`` and the commutative bag sum
   ``msum`` per lane, plus the per-slot hashes.  The ops/fingerprint.py
   design (avalanche-then-SUM over positions; ``sum(slot_h * count)`` over
   the bag) makes the hash *incremental*: an action that changes k
   positions shifts ``base`` by k avalanche terms, and every bag edit is a
   ±``slot_h`` adjustment.  u32 modular arithmetic keeps this exact, so v2
   fingerprints are bit-identical to v1's (property-tested).
3. ``lane_out(state, ph, g) -> (hi, lo, successor)`` — for ONE compacted
   lane: the delta fingerprint plus the successor struct, written
   *sparsely* (only the fields family ``g`` touches; untouched leaves pass
   through by reference).

Semantics are transcribed from models/actions.py (same raft.tla citations,
same deliberate bug replications: the AppendEntriesAlreadyDone hidden
guard raft.tla:309+:317, UpdateTerm leaving the message in flight :378,
one-entry truncation :323-324).  Spec variants with ``extra_families``
ride the same pipeline when they implement ``dims.build_extra_v2``
(models/reconfig.py does), and the extra families' deltas/successors fold
into ``lane_out`` by family id.  Extra-family MASKS come from the
variant's guards-only ``build_extra_masks_v2`` kernels when provided
(one ``pack_ok(parent)`` per parent, no per-lane successors); absent
that, the masks pass falls back to running the variant's full v1 kernels
with ``enabled & ~pack_ok(successor)`` folded, exactly as the v1 chunk
does.  A variant without v2 kernels makes ``build_v2`` raise
:class:`V2Unavailable`, and the engines fall back to the v1 expand path
under ``pipeline="auto"``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.fingerprint import SENTINEL, fmix32
from .dims import (AEQ, AER, CANDIDATE, FOLLOWER, LEADER, NIL, RVQ, RVR,
                   RaftDims)
from .actions import _add1, _sel, _set1, _set2, _setrow
from .schema import StateBatch

_U32 = jnp.uint32
_I32 = jnp.int32


class V2Unavailable(NotImplementedError):
    """This dims variant has no v2 kernels (no/partial ``build_extra_v2``).

    A dedicated type so ``pipeline="auto"`` resolution can fall back to v1
    on exactly this condition — an *accidental* NotImplementedError deep in
    a variant's kernel construction must propagate, not silently select
    the slow path (advisor r4 finding)."""


class ParentHash(NamedTuple):
    """Fingerprint internals of one parent state (both 32-bit lanes)."""

    base0: jnp.ndarray   # [] u32 — ordered-part avalanche sum, lane 0
    base1: jnp.ndarray   # [] u32
    msum0: jnp.ndarray   # [] u32 — commutative bag sum, lane 0
    msum1: jnp.ndarray   # [] u32
    sh0: jnp.ndarray     # [M] u32 — per-slot row hash, lane 0
    sh1: jnp.ndarray     # [M] u32


class V2Pipeline(NamedTuple):
    masks: object        # state -> (enabled [G], overflow [G])
    parent_hash: object  # state -> ParentHash
    parent_fp: object    # ParentHash -> (hi, lo)
    lane_out: object     # (state, ParentHash, g) -> (hi, lo, StateBatch)


def build_v2(dims: RaftDims) -> V2Pipeline:
    N, V, L, M, W = (dims.n_servers, dims.n_values, dims.max_log,
                     dims.n_msg_slots, dims.msg_width)
    quorum = dims.build_quorum()

    # Fingerprint constants — MUST match ops/fingerprint.py exactly (same
    # fixed seed, same draw order) for bit-identical fingerprints.
    d_ordered = N * (7 + 2 * L) + 2 * N * N
    rng = np.random.RandomState(0x7A57)
    consts = {}
    for lane in (0, 1):
        consts[lane] = (
            jnp.asarray(rng.randint(0, 1 << 32, d_ordered,
                                    dtype=np.uint64).astype(np.uint32) | 1),
            jnp.asarray(rng.randint(0, 1 << 32, W,
                                    dtype=np.uint64).astype(np.uint32) | 1),
            _U32(rng.randint(1, 1 << 32, dtype=np.uint64) | 1),
        )

    # Ordered-part flat offsets (ops/fingerprint.py _flat_ordered order).
    O_TERM = 0
    O_ROLE = N
    O_VOTED = 2 * N
    O_LT = 3 * N
    O_LV = 3 * N + N * L
    O_LL = 3 * N + 2 * N * L
    O_CI = 4 * N + 2 * N * L
    O_VR = 5 * N + 2 * N * L
    O_VG = 6 * N + 2 * N * L
    O_NI = 7 * N + 2 * N * L
    O_MI = 7 * N + 2 * N * L + N * N

    def _u(x):
        return jnp.asarray(x).astype(_U32)

    # -- delta helpers ----------------------------------------------------
    # d* return the (lane0, lane1) u32 base-sum shift for changed ordered
    # positions; old == new contributes 0 automatically (terms cancel).

    def _contrib(pos, val, lane):
        c_ord, _, seed = consts[lane]
        return fmix32(_u(val) * c_ord[pos] + seed)

    def dpos(pos, old, new):
        return tuple(_contrib(pos, new, ln) - _contrib(pos, old, ln)
                     for ln in (0, 1))

    def dvec(start, olds, news, count):
        """Delta for ``count`` consecutive positions from ``start``."""
        out = []
        for ln in (0, 1):
            c_ord, _, seed = consts[ln]
            cs = jax.lax.dynamic_slice(c_ord, (start,), (count,))
            out.append(jnp.sum(fmix32(_u(news) * cs + seed)
                               - fmix32(_u(olds) * cs + seed), dtype=_U32))
        return tuple(out)

    def dsum(*deltas):
        d0 = _U32(0)
        d1 = _U32(0)
        for a, b in deltas:
            d0 = d0 + a
            d1 = d1 + b
        return d0, d1

    ZD = (_U32(0), _U32(0))

    def row_hash(mvec, lane):
        """Per-slot hash of one [W] row — ops/fingerprint.py slot_h."""
        _, c_msg, seed = consts[lane]
        return fmix32(fmix32(jnp.sum(_u(mvec) * c_msg, dtype=_U32) ^ seed)
                      * _U32(0x85EBCA6B) + seed)

    # Delta toolkit handed to spec variants (dims.build_extra_v2) so
    # their extra families can contribute exact fingerprint-sum deltas.
    import types
    fp_helpers = types.SimpleNamespace(
        dpos=dpos, dvec=dvec, dsum=dsum, ZD=ZD, L=L,
        O_TERM=O_TERM, O_ROLE=O_ROLE, O_VOTED=O_VOTED, O_LT=O_LT,
        O_LV=O_LV, O_LL=O_LL, O_CI=O_CI, O_VR=O_VR, O_VG=O_VG,
        O_NI=O_NI, O_MI=O_MI)
    extra_v2 = dims.build_extra_v2(fp_helpers)
    if extra_v2 is None or len(extra_v2) != len(dims.extra_families):
        raise V2Unavailable(
            f"dims {type(dims).__name__} does not provide v2 kernels for "
            "its extra families (build_extra_v2); use the v1 pipeline")
    extra_v1 = dims.build_extra_kernels()
    extra_masks = dims.build_extra_masks_v2()
    if extra_masks is not None and len(extra_masks) != len(extra_v1):
        raise ValueError(
            f"{type(dims).__name__}.build_extra_masks_v2 returned "
            f"{len(extra_masks)} kernels for {len(extra_v1)} extra families")
    from .schema import build_pack_guard
    pack_ok_fn = build_pack_guard(dims)

    def finalize(base, msum, lane):
        seed = consts[lane][2]
        return fmix32(base + fmix32(msum + seed) * _U32(0x9E3779B9))

    def parent_hash(st: StateBatch) -> ParentHash:
        parts = [st.term, st.role, st.voted_for, st.log_term.reshape(-1),
                 st.log_val.reshape(-1), st.log_len, st.commit,
                 st.votes_resp, st.votes_gran, st.next_idx.reshape(-1),
                 st.match_idx.reshape(-1)]
        flat = jnp.concatenate([p.astype(_I32) for p in parts]).view(_U32)
        occupied = st.msg_cnt > 0
        out = {}
        for ln in (0, 1):
            c_ord, c_msg, seed = consts[ln]
            base = jnp.sum(fmix32(flat * c_ord + seed), dtype=_U32)
            rows = st.msg.view(_U32) if st.msg.dtype != jnp.uint32 else st.msg
            sh = fmix32(fmix32(jnp.sum(rows * c_msg[None, :], axis=1,
                                       dtype=_U32) ^ seed)
                        * _U32(0x85EBCA6B) + seed)
            msum = jnp.sum(jnp.where(occupied,
                                     sh * st.msg_cnt.astype(_U32), _U32(0)),
                           dtype=_U32)
            out[ln] = (base, msum, sh)
        return ParentHash(base0=out[0][0], base1=out[1][0],
                          msum0=out[0][1], msum1=out[1][1],
                          sh0=out[0][2], sh1=out[1][2])

    def parent_fp(ph: ParentHash):
        hi = finalize(ph.base0, ph.msum0, 0)
        lo = finalize(ph.base1, ph.msum1, 1)
        is_sent = (hi == SENTINEL) & (lo == SENTINEL)
        return hi, jnp.where(is_sent, _U32(0xFFFFFFFE), lo)

    # -- shared guard/value helpers (mirroring actions.py) ----------------
    def last_term(st, i):
        ln = st.log_len[i]
        return jnp.where(ln > 0, st.log_term[i, jnp.clip(ln - 1, 0, L - 1)],
                         0)

    def base_msg(mtype, src, dst, mterm):
        m = jnp.zeros((W,), _I32)
        return m.at[0].set(mtype + 1).at[1].set(src + 1).at[2].set(dst + 1) \
                .at[3].set(mterm)

    def send_ctx(st, mvec, skip_slot=None, skip_gate=None):
        """Slot resolution for Send(mvec) — raft.tla:95 via actions.py
        bag_send — optionally on the post-Discard view of the bag
        (``skip_slot``/``skip_gate`` model Reply's atomic discard+send,
        raft.tla:102-103).  Returns a dict: ok, overflow-of-packing,
        target index, eq flag, count after, and the msum delta."""
        cnt = st.msg_cnt
        if skip_slot is not None:
            dec = jnp.where(skip_gate, 1, 0)
            cnt = _add1(cnt, skip_slot, -dec)
        # Rows are unchanged by a discard except the zeroed empty row,
        # which can never equal mvec (mvec[0] = mtype+1 > 0): gating eq on
        # cnt > 0 reproduces the post-discard comparison exactly.
        eq = jnp.all(st.msg == mvec[None, :], axis=1) & (cnt > 0)
        has_eq = jnp.any(eq)
        free = cnt == 0
        ok = has_eq | jnp.any(free)
        idx = jnp.where(has_eq, jnp.argmax(eq), jnp.argmax(free))
        new_cnt = cnt[idx] + 1          # 0 + 1 on a free slot
        # pack guard (schema.build_pack_guard): successor msg_cnt <= 255.
        pack_bad = ok & (new_cnt > 255)
        return {"ok": ok, "idx": idx, "has_eq": has_eq, "new_cnt": new_cnt,
                "pack_bad": pack_bad, "cnt_view": cnt}

    def send_dmsum(st, ph, ctx, mvec):
        """±slot_h contribution of Send: +h(existing row) when the count
        increments, +h(mvec) when a free slot is claimed."""
        out = []
        for ln, sh in ((0, ph.sh0), (1, ph.sh1)):
            fresh = row_hash(mvec, ln)
            out.append(jnp.where(ctx["has_eq"], sh[ctx["idx"]], fresh))
        return tuple(out)

    def discard_dmsum(ph, s):
        return (-ph.sh0[s], -ph.sh1[s])

    def apply_send(msg, cnt, ctx, mvec):
        """bag_send's writes on (msg, cnt) — actions.py:89-100 exactly
        (row kept when eq or not-ok; count +1 only when ok)."""
        idx, has_eq, ok = ctx["idx"], ctx["has_eq"], ctx["ok"]
        row = jnp.where(has_eq | ~ok, msg[idx], mvec)
        return (_setrow(msg, idx, row),
                _add1(cnt, idx, jnp.where(ok, 1, 0)))

    def apply_discard(msg, cnt, s):
        """bag_discard_slot — actions.py:102-107 (zero the row at 0)."""
        new_cnt = _add1(cnt, s, -1)
        row = jnp.where(new_cnt[s] > 0, msg[s], jnp.zeros((W,), _I32))
        return _setrow(msg, s, row), new_cnt

    # -- receive context (guards + derived values, no construction) -------
    def receive_ctx(st, s):
        """Everything Receive(m@slot s) needs — raft.tla:388-403 dispatch
        exactly as actions.py receive(), but split from state writes so
        the masks pass pays for guards only (XLA DCE drops the unused
        outputs there)."""
        mvec = st.msg[s]
        occ = st.msg_cnt[s] > 0
        mtype = mvec[0] - 1
        j = jnp.clip(mvec[1] - 1, 0, N - 1)
        i = jnp.clip(mvec[2] - 1, 0, N - 1)
        mterm = mvec[3]
        t_i = st.term[i]
        role_i = st.role[i]
        ln = st.log_len[i]

        en_ut = occ & (mterm > t_i)
        le = occ & (mterm <= t_i)

        # HandleRequestVoteRequest — raft.tla:244-263.
        lt = last_term(st, i)
        rvq_logok = (mvec[4] > lt) | ((mvec[4] == lt) & (mvec[5] >= ln))
        grant = (mterm == t_i) & rvq_logok & \
            ((st.voted_for[i] == NIL) | (st.voted_for[i] == j + 1))
        rvr_resp = base_msg(RVR, i, j, t_i) \
            .at[4].set(grant.astype(_I32)).at[5].set(ln)
        rvr_resp = jax.lax.dynamic_update_slice(rvr_resp, st.log_term[i],
                                                (6,))
        rvr_resp = jax.lax.dynamic_update_slice(rvr_resp, st.log_val[i],
                                                (6 + L,))
        en_rvq = le & (mtype == RVQ)
        rvq_send = send_ctx(st, rvr_resp, skip_slot=s,
                            skip_gate=st.msg_cnt[s] == 1)

        en_rvr_drop = le & (mtype == RVR) & (mterm < t_i)
        en_rvr = le & (mtype == RVR) & (mterm == t_i)

        # AppendEntriesRequest — raft.tla:347-356.
        prev, pterm, n_ent = mvec[4], mvec[5], mvec[6]
        eterm, eval_, mcommit = mvec[7], mvec[8], mvec[9]
        aeq_logok = (prev == 0) | \
            ((prev > 0) & (prev <= ln)
             & (pterm == st.log_term[i, jnp.clip(prev - 1, 0, L - 1)]))
        en_aeq = le & (mtype == AEQ)
        en_rej = en_aeq & ((mterm < t_i)
                           | ((mterm == t_i) & (role_i == FOLLOWER)
                              & ~aeq_logok))
        rej_resp = base_msg(AER, i, j, t_i)
        rej_send = send_ctx(st, rej_resp, skip_slot=s,
                            skip_gate=st.msg_cnt[s] == 1)
        en_rtf = en_aeq & (mterm == t_i) & (role_i == CANDIDATE)
        acc = en_aeq & (mterm == t_i) & (role_i == FOLLOWER) & aeq_logok
        index = prev + 1
        have_at = ln >= index
        term_at = st.log_term[i, jnp.clip(index - 1, 0, L - 1)]
        done_shape = (n_ent == 0) | (have_at & (term_at == eterm))
        en_done = acc & done_shape & (mcommit == st.commit[i])   # :317 bug
        done_resp = base_msg(AER, i, j, t_i) \
            .at[4].set(1).at[5].set(prev + n_ent)
        done_send = send_ctx(st, done_resp, skip_slot=s,
                             skip_gate=st.msg_cnt[s] == 1)
        en_conf = acc & (n_ent > 0) & have_at & (term_at != eterm)
        fits = ln < L
        en_noc = acc & (n_ent > 0) & (ln == prev)

        en_aer_drop = le & (mtype == AER) & (mterm < t_i)
        en_aer = le & (mtype == AER) & (mterm == t_i)

        overflow = (en_rvq & ~rvq_send["ok"]) | (en_rej & ~rej_send["ok"]) \
            | (en_done & ~done_send["ok"]) | (en_noc & ~fits)
        enabled = (en_ut | en_rvq | en_rvr_drop | en_rvr | en_rej | en_rtf
                   | en_done | en_conf | en_noc | en_aer_drop | en_aer) \
            & ~overflow
        # pack guard on the reply's count bump (chunk-level pack_ok in v1).
        pack_bad = (en_rvq & rvq_send["pack_bad"]) \
            | (en_rej & rej_send["pack_bad"]) \
            | (en_done & done_send["pack_bad"])
        return dict(
            mvec=mvec, i=i, j=j, mterm=mterm, t_i=t_i, ln=ln,
            grant=grant, rvr_resp=rvr_resp, rej_resp=rej_resp,
            done_resp=done_resp, rvq_send=rvq_send, rej_send=rej_send,
            done_send=done_send, prev=prev, n_ent=n_ent, eterm=eterm,
            eval_=eval_, mcommit=mcommit,
            en_ut=en_ut, en_rvq=en_rvq, en_rvr_drop=en_rvr_drop,
            en_rvr=en_rvr, en_rej=en_rej, en_rtf=en_rtf, en_done=en_done,
            en_conf=en_conf, en_noc=en_noc, en_aer_drop=en_aer_drop,
            en_aer=en_aer, enabled=enabled, overflow=overflow,
            pack_bad=pack_bad)

    # -- per-family guards (masks pass) -----------------------------------
    def masks(st: StateBatch):
        """(enabled [G], overflow [G]) — v1 expand's masks, with the
        chunk-level pack guard folded in as extra *overflow* bits exactly
        where v1's ``en & ~pack_ok(cand)`` would fire (enabled stays
        true for pack violations, as in engine/chunk.py:66-67)."""
        en_parts, ovf_parts = [], []
        # Restart — always enabled.
        en_parts.append(jnp.ones((N,), bool))
        ovf_parts.append(jnp.zeros((N,), bool))
        # Timeout — role check + term pack guard.
        roleF = st.role == FOLLOWER
        roleC = st.role == CANDIDATE
        en_t = roleF | roleC
        en_parts.append(en_t)
        ovf_parts.append(en_t & (st.term + 1 > 255))
        # RequestVote(i, j) — candidate, j not yet responded; send ok;
        # pack guard on col4 (mlastLogTerm > 127 breaks the signed row
        # packing) and on the eq-slot count bump.
        lt_all = jax.vmap(lambda i: last_term(st, i))(
            jnp.arange(N, dtype=_I32))
        def rv_one(i, j):
            en = (st.role[i] == CANDIDATE) \
                & (((st.votes_resp[i] >> j) & 1) == 0)
            m = base_msg(RVQ, i, j, st.term[i]) \
                .at[4].set(lt_all[i]).at[5].set(st.log_len[i])
            ctx = send_ctx(st, m)
            pack = ctx["pack_bad"] | (lt_all[i] > 127)
            return en & ctx["ok"], (en & ~ctx["ok"]) | (en & ctx["ok"] & pack)
        ii = jnp.repeat(jnp.arange(N, dtype=_I32), N)
        jj = jnp.tile(jnp.arange(N, dtype=_I32), N)
        en_rv, ovf_rv = jax.vmap(rv_one)(ii, jj)
        en_parts.append(en_rv)
        ovf_parts.append(ovf_rv)
        # BecomeLeader.
        def bl_one(i):
            member = ((st.votes_gran[i] >> jnp.arange(N, dtype=_I32)) & 1) > 0
            return (st.role[i] == CANDIDATE) & quorum(st, i, member)
        en_bl = jax.vmap(bl_one)(jnp.arange(N, dtype=_I32))
        en_parts.append(en_bl)
        ovf_parts.append(jnp.zeros((N,), bool))
        # ClientRequest(i, v).
        isL = st.role == LEADER
        fits = st.log_len < L
        en_cr = jnp.repeat(isL & fits, V)
        ovf_cr = jnp.repeat(isL & ~fits, V)
        en_parts.append(en_cr)
        ovf_parts.append(ovf_cr)
        # AdvanceCommitIndex.
        en_parts.append(isL)
        ovf_parts.append(jnp.zeros((N,), bool))
        # AppendEntries(i, j).
        def ae_one(i, j):
            en = (i != j) & (st.role[i] == LEADER)
            ln = st.log_len[i]
            ni = st.next_idx[i, j]
            prev = ni - 1
            prev_term = jnp.where(
                (prev > 0) & (prev <= ln),
                st.log_term[i, jnp.clip(prev - 1, 0, L - 1)], 0)
            last_entry = jnp.minimum(ln, ni)
            n_ent = (ln >= ni).astype(_I32)
            eterm = jnp.where(n_ent > 0,
                              st.log_term[i, jnp.clip(ni - 1, 0, L - 1)], 0)
            eval_ = jnp.where(n_ent > 0,
                              st.log_val[i, jnp.clip(ni - 1, 0, L - 1)], 0)
            m = base_msg(AEQ, i, j, st.term[i]) \
                .at[4].set(prev).at[5].set(prev_term).at[6].set(n_ent) \
                .at[7].set(eterm).at[8].set(eval_) \
                .at[9].set(jnp.minimum(st.commit[i], last_entry))
            ctx = send_ctx(st, m)
            return en & ctx["ok"], \
                (en & ~ctx["ok"]) | (en & ctx["ok"] & ctx["pack_bad"])
        en_ae, ovf_ae = jax.vmap(ae_one)(ii, jj)
        en_parts.append(en_ae)
        ovf_parts.append(ovf_ae)
        # Receive(slot).
        def rc_one(s):
            c = receive_ctx(st, s)
            return c["enabled"], c["overflow"] | c["pack_bad"]
        en_rc, ovf_rc = jax.vmap(rc_one)(jnp.arange(M, dtype=_I32))
        en_parts.append(en_rc)
        ovf_parts.append(ovf_rc)
        # Duplicate / Drop — occupancy; dup has the count pack guard.
        occ = st.msg_cnt > 0
        en_parts.append(occ)
        ovf_parts.append(occ & (st.msg_cnt + 1 > 255))
        en_parts.append(occ)
        ovf_parts.append(jnp.zeros((M,), bool))
        # Extra families: guards-only mask kernels when the variant
        # provides them (dims.build_extra_masks_v2 — one pack_ok over the
        # PARENT, no per-lane successor construction, preserving the
        # guards-only design of this pass); otherwise fall back to the
        # variant's full v1 kernels with the pack guard folded on their
        # successors exactly as the v1 chunk does (engine/chunk.py:
        # ovf |= en & ~pack_ok) — enforced generically so a future
        # variant whose extras touch a packed-bound field cannot
        # silently diverge between pipelines.
        if extra_masks is not None and extra_v1:
            pk_parent = pack_ok_fn(st)
            for (params, _kern), mask_fn in zip(extra_v1, extra_masks):
                in_axes = (None, None) + (0,) * len(params)
                en_e, ovf_e = jax.vmap(mask_fn, in_axes)(
                    st, pk_parent, *params)
                en_parts.append(en_e)
                ovf_parts.append(ovf_e)
        else:
            for params, kern in extra_v1:
                in_axes = (None,) + (0,) * len(params)
                en_e, ovf_e, succ_e = jax.vmap(kern, in_axes)(st, *params)
                pk_e = jax.vmap(pack_ok_fn)(succ_e)
                en_parts.append(en_e)
                ovf_parts.append(ovf_e | (en_e & ~pk_e))
        return jnp.concatenate(en_parts), jnp.concatenate(ovf_parts)

    # -- per-lane delta fingerprint + sparse successor --------------------
    # Static grid decode tables.
    offs = dims.family_offsets
    sizes = dims.family_sizes
    G = dims.n_instances
    fam_np = np.zeros(G, np.int32)
    p1_np = np.zeros(G, np.int32)   # i (server) or slot
    p2_np = np.zeros(G, np.int32)   # j, or value, or unused
    for fam, (off, size) in enumerate(zip(offs, sizes)):
        for k in range(size):
            g = off + k
            fam_np[g] = fam
            if fam in (0, 1, 3, 5):            # i-indexed families
                p1_np[g] = k
            elif fam in (2, 6):                # (i, j)
                p1_np[g], p2_np[g] = k // N, k % N
            elif fam == 4:                     # (i, v)
                p1_np[g], p2_np[g] = k // V, k % V + 1
            else:                              # slot families
                p1_np[g] = k
    fam_t = jnp.asarray(fam_np)
    p1_t = jnp.asarray(p1_np)
    p2_t = jnp.asarray(p2_np)

    def lane_out(st: StateBatch, ph: ParentHash, g):
        """Delta fingerprint + sparse successor for grid instance ``g`` of
        parent ``st``.  Only meaningful when lane ``g`` is enabled; on
        disabled lanes the outputs are arbitrary finite values (the chunk
        masks them with kvalid, as v1 masks its gathered garbage)."""
        fam = fam_t[g]
        i = p1_t[g]
        jv = p2_t[g]
        s = p1_t[g]          # slot for Receive/Duplicate/Drop lanes

        rc = receive_ctx(st, s)

        is_restart = fam == 0
        is_timeout = fam == 1
        is_rv = fam == 2
        is_bl = fam == 3
        is_cr = fam == 4
        is_ac = fam == 5
        is_ae = fam == 6
        is_recv = fam == 7
        is_dup = fam == 8
        is_drop = fam == 9

        # ---- scalar successor values per touched field ----
        term_i = st.term[i]
        role_i = st.role[i]
        ln_i = st.log_len[i]

        # Receive destination server (may differ from the grid's i).
        ri = rc["i"]
        rj = rc["j"]

        # term: Timeout(+1) on i; UpdateTerm(mterm) on ri.
        ut_fire = is_recv & rc["en_ut"]
        term_tgt = jnp.where(is_timeout, i, ri)
        term_new = jnp.where(is_timeout, term_i + 1, rc["mterm"])
        term_wr = is_timeout | ut_fire

        # role.
        role_tgt = jnp.where(is_recv, ri, i)
        role_new = jnp.where(
            is_restart, FOLLOWER,
            jnp.where(is_timeout, CANDIDATE,
                      jnp.where(is_bl, LEADER,
                                jnp.where(ut_fire, FOLLOWER, FOLLOWER))))
        role_wr = is_restart | is_timeout | is_bl \
            | (is_recv & (rc["en_ut"] | rc["en_rtf"]))

        # votedFor: Timeout -> NIL; UpdateTerm -> NIL; RVQ grant -> j+1.
        grant_fire = is_recv & rc["en_rvq"] & rc["grant"]
        voted_tgt = jnp.where(is_timeout, i, ri)
        voted_new = jnp.where(grant_fire, rj + 1, NIL)
        voted_wr = is_timeout | ut_fire | grant_fire

        # log cell + length: ClientRequest append / Conflict truncate /
        # NoConflict append.
        cr_k = jnp.clip(ln_i, 0, L - 1)
        conf_k = jnp.clip(rc["ln"] - 1, 0, L - 1)
        noc_k = jnp.clip(rc["ln"], 0, L - 1)
        conf_fire = is_recv & rc["en_conf"]
        noc_fire = is_recv & rc["en_noc"]
        log_tgt_i = jnp.where(is_cr, i, ri)
        log_k = jnp.where(is_cr, cr_k, jnp.where(conf_fire, conf_k, noc_k))
        log_t_new = jnp.where(is_cr, term_i,
                              jnp.where(conf_fire, 0, rc["eterm"]))
        log_v_new = jnp.where(is_cr, jv,
                              jnp.where(conf_fire, 0, rc["eval_"]))
        ll_new = jnp.where(conf_fire, rc["ln"] - 1,
                           jnp.where(is_cr, ln_i + 1, rc["ln"] + 1))
        log_wr = is_cr | conf_fire | noc_fire

        # commit: Restart -> 0; AdvanceCommitIndex -> rule; Done -> mcommit.
        idxs = jnp.arange(1, L + 1, dtype=_I32)
        member = ((st.match_idx[i][None, :] >= idxs[:, None])
                  | (jnp.arange(N)[None, :] == i))
        agree_ok = jax.vmap(lambda mem: quorum(st, i, mem))(member) \
            & (idxs <= ln_i)
        any_ok = jnp.any(agree_ok)
        max_agree = jnp.max(jnp.where(agree_ok, idxs, 0))
        own_term = st.log_term[i, jnp.clip(max_agree - 1, 0, L - 1)] \
            == term_i
        ac_commit = jnp.where(any_ok & own_term, max_agree, st.commit[i])
        done_fire = is_recv & rc["en_done"]
        commit_tgt = jnp.where(is_recv, ri, i)
        commit_new = jnp.where(is_restart, 0,
                               jnp.where(is_ac, ac_commit, rc["mcommit"]))
        commit_wr = is_restart | is_ac | done_fire

        # vote sets: Restart/Timeout clear; HandleRVR accumulates.
        rvr_fire = is_recv & rc["en_rvr"]
        granted_bit = jnp.where(rc["mvec"][4] > 0, 1, 0) << rj
        vr_tgt = jnp.where(is_recv, ri, i)
        vr_new = jnp.where(rvr_fire, st.votes_resp[ri] | (1 << rj), 0)
        vg_new = jnp.where(rvr_fire, st.votes_gran[ri] | granted_bit, 0)
        votes_wr = is_restart | is_timeout | rvr_fire

        # nextIndex/matchIndex rows: Restart/BecomeLeader; cell: AER.
        ni_row_new = jnp.where(is_restart,
                               jnp.ones((N,), _I32),
                               jnp.broadcast_to(ln_i + 1, (N,)).astype(_I32))
        mi_row_new = jnp.zeros((N,), _I32)
        rows_wr = is_restart | is_bl
        aer_fire = is_recv & rc["en_aer"]
        succ_flag = rc["mvec"][4] > 0
        mmatch = rc["mvec"][5]
        ni_cell_new = jnp.where(succ_flag, mmatch + 1,
                                jnp.maximum(st.next_idx[ri, rj] - 1, 1))
        mi_cell_new = jnp.where(succ_flag, mmatch, st.match_idx[ri, rj])

        # ---- bag edits ----
        # Sends (RequestVote / AppendEntries) rebuild the same mvec the
        # masks pass used; receive replies use rc's resp rows + ctxs.
        rv_m = base_msg(RVQ, i, jv, term_i) \
            .at[4].set(last_term(st, i)).at[5].set(ln_i)
        ni_ij = st.next_idx[i, jv]
        ae_prev = ni_ij - 1
        ae_pterm = jnp.where(
            (ae_prev > 0) & (ae_prev <= ln_i),
            st.log_term[i, jnp.clip(ae_prev - 1, 0, L - 1)], 0)
        ae_nent = (ln_i >= ni_ij).astype(_I32)
        ae_m = base_msg(AEQ, i, jv, term_i) \
            .at[4].set(ae_prev).at[5].set(ae_pterm).at[6].set(ae_nent) \
            .at[7].set(jnp.where(ae_nent > 0,
                                 st.log_term[i, jnp.clip(ni_ij - 1, 0,
                                                         L - 1)], 0)) \
            .at[8].set(jnp.where(ae_nent > 0,
                                 st.log_val[i, jnp.clip(ni_ij - 1, 0,
                                                        L - 1)], 0)) \
            .at[9].set(jnp.minimum(st.commit[i], jnp.minimum(ln_i, ni_ij)))

        rvq_fire = is_recv & rc["en_rvq"]
        rej_fire = is_recv & rc["en_rej"]
        reply_fire = rvq_fire | rej_fire | done_fire
        disc_only = is_recv & (rc["en_rvr_drop"] | rc["en_rvr"]
                               | rc["en_aer_drop"] | rc["en_aer"])
        do_discard = reply_fire | disc_only | is_drop
        do_send = is_rv | is_ae | reply_fire

        send_row = jnp.where(
            is_rv, rv_m,
            jnp.where(is_ae, ae_m,
                      jnp.where(rvq_fire, rc["rvr_resp"],
                                jnp.where(rej_fire, rc["rej_resp"],
                                          rc["done_resp"]))))
        plain_ctx = send_ctx(st, send_row)
        reply_ctx = {
            k: jnp.where(
                rvq_fire, rc["rvq_send"][k],
                jnp.where(rej_fire, rc["rej_send"][k],
                          rc["done_send"][k]))
            for k in ("ok", "idx", "has_eq", "new_cnt", "pack_bad",
                      "cnt_view")}
        sctx = {k: jnp.where(reply_fire, reply_ctx[k], plain_ctx[k])
                for k in reply_ctx}

        # ---- delta fingerprint ----
        d_term = dpos(O_TERM + term_tgt, st.term[term_tgt],
                      jnp.where(term_wr, term_new, st.term[term_tgt]))
        d_role = dpos(O_ROLE + role_tgt, st.role[role_tgt],
                      jnp.where(role_wr, role_new, st.role[role_tgt]))
        d_voted = dpos(O_VOTED + voted_tgt, st.voted_for[voted_tgt],
                       jnp.where(voted_wr, voted_new,
                                 st.voted_for[voted_tgt]))
        lt_pos = O_LT + log_tgt_i * L + log_k
        lv_pos = O_LV + log_tgt_i * L + log_k
        ll_pos = O_LL + log_tgt_i
        old_lt = st.log_term[log_tgt_i, log_k]
        old_lv = st.log_val[log_tgt_i, log_k]
        old_ll = st.log_len[log_tgt_i]
        d_lt = dpos(lt_pos, old_lt, jnp.where(log_wr, log_t_new, old_lt))
        d_lv = dpos(lv_pos, old_lv, jnp.where(log_wr, log_v_new, old_lv))
        d_ll = dpos(ll_pos, old_ll, jnp.where(log_wr, ll_new, old_ll))
        d_ci = dpos(O_CI + commit_tgt, st.commit[commit_tgt],
                    jnp.where(commit_wr, commit_new,
                              st.commit[commit_tgt]))
        d_vr = dpos(O_VR + vr_tgt, st.votes_resp[vr_tgt],
                    jnp.where(votes_wr, vr_new, st.votes_resp[vr_tgt]))
        d_vg = dpos(O_VG + vr_tgt, st.votes_gran[vr_tgt],
                    jnp.where(votes_wr, vg_new, st.votes_gran[vr_tgt]))
        old_ni_row = st.next_idx[i]
        old_mi_row = st.match_idx[i]
        d_ni_row = dvec(O_NI + i * N, old_ni_row,
                        jnp.where(rows_wr, ni_row_new, old_ni_row), N)
        d_mi_row = dvec(O_MI + i * N, old_mi_row,
                        jnp.where(rows_wr, mi_row_new, old_mi_row), N)
        ni_cell_pos = O_NI + ri * N + rj
        mi_cell_pos = O_MI + ri * N + rj
        old_ni_c = st.next_idx[ri, rj]
        old_mi_c = st.match_idx[ri, rj]
        d_ni_c = dpos(ni_cell_pos, old_ni_c,
                      jnp.where(aer_fire, ni_cell_new, old_ni_c))
        d_mi_c = dpos(mi_cell_pos, old_mi_c,
                      jnp.where(aer_fire, mi_cell_new, old_mi_c))
        d_base = dsum(d_term, d_role, d_voted, d_lt, d_lv, d_ll, d_ci,
                      d_vr, d_vg, d_ni_row, d_mi_row, d_ni_c, d_mi_c)

        d_disc = discard_dmsum(ph, s)
        d_send = send_dmsum(st, ph, sctx, send_row)
        d_dup = (ph.sh0[s], ph.sh1[s])
        # Drop's -slot_h rides the do_discard term; Duplicate adds +slot_h.
        dm0 = jnp.where(do_discard, d_disc[0], _U32(0)) \
            + jnp.where(do_send & sctx["ok"], d_send[0], _U32(0)) \
            + jnp.where(is_dup, d_dup[0], _U32(0))
        dm1 = jnp.where(do_discard, d_disc[1], _U32(0)) \
            + jnp.where(do_send & sctx["ok"], d_send[1], _U32(0)) \
            + jnp.where(is_dup, d_dup[1], _U32(0))

        # Extra-family lanes: on base-family lanes every *_wr gate above
        # is False, so the base deltas are zero and the base successor is
        # the parent — fold the variant kernels' deltas/successors in by
        # family id.
        db0, db1 = d_base
        extra_folds = []
        for e, ((params_e, _k1), lane_fn) in enumerate(
                zip(extra_v1, extra_v2)):
            is_e = fam == 10 + e
            off_e, size_e = offs[10 + e], sizes[10 + e]
            local = jnp.clip(g - off_e, 0, size_e - 1)
            pe = tuple(arr[local] for arr in params_e)
            dbe, dme, succ_e = lane_fn(st, *pe)
            db0 = db0 + jnp.where(is_e, dbe[0], _U32(0))
            db1 = db1 + jnp.where(is_e, dbe[1], _U32(0))
            dm0 = dm0 + jnp.where(is_e, dme[0], _U32(0))
            dm1 = dm1 + jnp.where(is_e, dme[1], _U32(0))
            extra_folds.append((is_e, succ_e))
        d_base = (db0, db1)

        hi = finalize(ph.base0 + d_base[0], ph.msum0 + dm0, 0)
        lo = finalize(ph.base1 + d_base[1], ph.msum1 + dm1, 1)
        is_sent = (hi == SENTINEL) & (lo == SENTINEL)
        lo = jnp.where(is_sent, _U32(0xFFFFFFFE), lo)

        # ---- sparse successor construction ----
        term_o = jnp.where(term_wr,
                           _set1(st.term, term_tgt, term_new), st.term)
        role_o = jnp.where(role_wr,
                           _set1(st.role, role_tgt, role_new), st.role)
        voted_o = jnp.where(voted_wr,
                            _set1(st.voted_for, voted_tgt, voted_new),
                            st.voted_for)
        lt_o = jnp.where(log_wr,
                         _set2(st.log_term, log_tgt_i, log_k, log_t_new),
                         st.log_term)
        lv_o = jnp.where(log_wr,
                         _set2(st.log_val, log_tgt_i, log_k, log_v_new),
                         st.log_val)
        ll_o = jnp.where(log_wr, _set1(st.log_len, log_tgt_i, ll_new),
                         st.log_len)
        ci_o = jnp.where(commit_wr,
                         _set1(st.commit, commit_tgt, commit_new),
                         st.commit)
        vr_o = jnp.where(votes_wr, _set1(st.votes_resp, vr_tgt, vr_new),
                         st.votes_resp)
        vg_o = jnp.where(votes_wr, _set1(st.votes_gran, vr_tgt, vg_new),
                         st.votes_gran)
        ni_o = jnp.where(rows_wr, _setrow(st.next_idx, i, ni_row_new),
                         jnp.where(aer_fire,
                                   _set2(st.next_idx, ri, rj, ni_cell_new),
                                   st.next_idx))
        mi_o = jnp.where(rows_wr, _setrow(st.match_idx, i, mi_row_new),
                         jnp.where(aer_fire,
                                   _set2(st.match_idx, ri, rj, mi_cell_new),
                                   st.match_idx))

        msg_o, cnt_o = st.msg, st.msg_cnt
        d_msg, d_cnt = apply_discard(msg_o, cnt_o, s)
        msg_o = jnp.where(do_discard, d_msg, msg_o)
        cnt_o = jnp.where(do_discard, d_cnt, cnt_o)
        s_msg, s_cnt = apply_send(msg_o, cnt_o, sctx, send_row)
        msg_o = jnp.where(do_send, s_msg, msg_o)
        cnt_o = jnp.where(do_send, s_cnt, cnt_o)
        cnt_o = jnp.where(is_dup, _add1(cnt_o, s, 1), cnt_o)

        succ = StateBatch(term=term_o, role=role_o, voted_for=voted_o,
                          log_term=lt_o, log_val=lv_o, log_len=ll_o,
                          commit=ci_o, votes_resp=vr_o, votes_gran=vg_o,
                          next_idx=ni_o, match_idx=mi_o,
                          msg=msg_o, msg_cnt=cnt_o)
        for is_e, succ_e in extra_folds:
            succ = _sel(is_e, succ_e, succ)
        return hi, lo, succ

    return V2Pipeline(masks=masks, parent_hash=parent_hash,
                      parent_fp=parent_fp, lane_out=lane_out)
