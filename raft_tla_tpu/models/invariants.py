"""Invariant and state-constraint kernels.

``build_type_ok`` is the tensor-side TypeOK (/root/reference/raft.tla:482-492).
In the fixed-width encoding most of TypeOK holds *by construction* (fields are
always int tensors of the right shape), so the kernel checks exactly the
residual content conditions that encoding does not force:

- roles in {Follower, Candidate, Leader}; votedFor in {Nil} ∪ Server;
- log entries (below log_len) have Nat terms and values in Value; tails zero;
- commitIndex ∈ Nat; nextIndex >= 1 (raft.tla:491); matchIndex ∈ Nat;
- vote bitmasks ⊆ Server; message rows well-typed per the :443-479 schemas
  with positive bag multiplicities.

``build_constraint`` builds the CONSTRAINT predicate for bounded exhaustive
runs (SURVEY §2.4 R9).  TLC semantics: a state violating the constraint is
still generated, invariant-checked and counted distinct, but not expanded —
the engine applies this predicate only when deciding what to enqueue.  The
reference's MCraft.cfg sets no constraint (the space is unbounded as
configured); bounds here (MaxTerm / MaxLogLen / per-message count cap) are
the BASELINE.json bounded configs.  The count cap also bounds
``DuplicateMessage`` (raft.tla:410), which is what keeps the bag finite.

The oracle mirrors (``*_py``) keep differential tests honest.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from .dims import RaftDims
from .pystate import PyState
from .schema import StateBatch


def build_type_ok(dims: RaftDims):
    N, L = dims.n_servers, dims.max_log
    value_ok = dims.build_value_ok()     # entries-in-Value, variant-widened

    def type_ok(st: StateBatch):
        lane = jnp.arange(L)[None, :]
        in_log = lane < st.log_len[:, None]
        occ = st.msg_cnt > 0
        mt = st.msg[:, 0]
        src, dst = st.msg[:, 1], st.msg[:, 2]
        checks = [
            jnp.all((st.role >= 0) & (st.role <= 2)),
            jnp.all((st.voted_for >= 0) & (st.voted_for <= N)),
            jnp.all(jnp.where(in_log,
                              (st.log_term >= 0) & value_ok(st.log_val),
                              (st.log_term == 0) & (st.log_val == 0))),
            jnp.all((st.log_len >= 0) & (st.log_len <= L)),
            jnp.all(st.term >= 0) & jnp.all(st.commit >= 0),
            jnp.all((st.votes_resp >= 0) & (st.votes_resp < (1 << N))),
            jnp.all((st.votes_gran >= 0) & (st.votes_gran < (1 << N))),
            jnp.all(st.next_idx >= 1),          # raft.tla:491
            jnp.all(st.match_idx >= 0),
            jnp.all(jnp.where(occ,
                              (mt >= 1) & (mt <= 4)
                              & (src >= 1) & (src <= N)
                              & (dst >= 1) & (dst <= N)
                              & (st.msg[:, 3] >= 0),
                              jnp.all(st.msg == 0, axis=1))),
            jnp.all(st.msg_cnt >= 0),
        ]
        out = checks[0]
        for c in checks[1:]:
            out = out & c
        return out

    return type_ok


def type_ok_py(s: PyState, dims: RaftDims) -> bool:
    """Oracle-side TypeOK (subset mirroring build_type_ok's content checks)."""
    n = dims.n_servers
    ok = all(0 <= r <= 2 for r in s.role)
    ok &= all(0 <= vf <= n for vf in s.voted_for)
    ok &= all(t >= 0 and dims.value_ok_py(val)
              for log in s.log for (t, val) in log)
    ok &= all(t >= 0 for t in s.current_term)
    ok &= all(c >= 0 for c in s.commit_index)
    ok &= all(0 <= m < (1 << n)
              for m in s.votes_responded + s.votes_granted)
    ok &= all(x >= 1 for row in s.next_index for x in row)
    ok &= all(x >= 0 for row in s.match_index for x in row)
    ok &= all(c >= 1 for _m, c in s.messages)
    return ok


def build_no_leader(dims: RaftDims):
    """``NoLeaderElected`` — a DELIBERATELY FALSIFIABLE canary: asserts no
    server ever reaches the Leader role, which any live election run
    violates at the first ``BecomeLeader``.  It exists for the
    counterexample tooling (engine/explain.py, the CI violation smoke):
    checking it turns "model-check the spec" into "extract a minimal
    election trace", the standard TLC trick for demonstrating the error
    reporting path on a healthy model.  Never include it in a cfg that is
    supposed to pass."""
    from .dims import LEADER

    def no_leader(st: StateBatch):
        return jnp.all(st.role != LEADER)

    return no_leader


def no_leader_py(s: PyState, dims: RaftDims) -> bool:
    from .dims import LEADER
    return LEADER not in s.role


@dataclasses.dataclass(frozen=True)
class Bounds:
    """CONSTRAINT bounds for exhaustive runs (BASELINE.json configs)."""

    max_term: Optional[int] = None       # \A i : currentTerm[i] <= MaxTerm
    max_log_len: Optional[int] = None    # \A i : Len(log[i]) <= MaxLogLen
    max_msg_count: Optional[int] = None  # \A m : messages[m] <= MaxDup
    # Cardinality(DOMAIN messages) <= MaxInFlight: bounds the number of
    # DISTINCT in-flight messages.  Without it the bag domain is the
    # dominant growth axis (the MCraft_bounded space passes 63M states by
    # level 13, BASELINE.md §b); the standard TLC recipe bounds it with
    # exactly this kind of state constraint.
    max_in_flight: Optional[int] = None


def build_inv_id(inv_fns):
    """First-failing-invariant dispatch shared by the three engines:
    returns ``inv_id(state) -> int32`` yielding the index of the first
    violated invariant in ``inv_fns`` order, or -1 when all hold."""
    import jax.numpy as _jnp

    def inv_id(st: StateBatch):
        out = _jnp.int32(-1)
        for q in range(len(inv_fns) - 1, -1, -1):
            out = _jnp.where(inv_fns[q](st), out, _jnp.int32(q))
        return out

    return inv_id


def build_constraint(dims: RaftDims, bounds: Bounds):
    def constraint(st: StateBatch):
        ok = jnp.bool_(True)
        if bounds.max_term is not None:
            ok = ok & jnp.all(st.term <= bounds.max_term)
        if bounds.max_log_len is not None:
            ok = ok & jnp.all(st.log_len <= bounds.max_log_len)
        if bounds.max_msg_count is not None:
            ok = ok & jnp.all(st.msg_cnt <= bounds.max_msg_count)
        if bounds.max_in_flight is not None:
            ok = ok & (jnp.sum((st.msg_cnt > 0).astype(jnp.int32))
                       <= bounds.max_in_flight)
        return ok

    return constraint


#: Reserved predicate name for the cfg CONSTRAINT in read-set exports and
#: POR certificates (a cfg names its constraint operator, e.g.
#: ``BoundedSpace``, but the certificate cares about the *predicate the
#: engine actually evaluates*, so one canonical name covers it).
CONSTRAINT_PREDICATE = "CONSTRAINT"


def invariant_registry():
    """THE name -> builder registry of checkable invariants: TypeOK plus
    the models/safety.py suite.  Single source of truth — both
    ``engine/check.py``'s cfg resolution and the POR pass's visibility
    condition read this, so a new invariant registers once and is
    immediately nameable in cfgs AND part of the analyzer's conservative
    default predicate set.  (A function, not a constant: safety.py is
    imported lazily to keep this module import-light.)"""
    from .safety import SAFETY_INVARIANTS
    # NoLeaderElected is the deliberately falsifiable canary (see
    # build_no_leader): registered so a cfg can name it to exercise the
    # violation/counterexample path, and part of the analyzer's
    # conservative default predicate set like every other entry (its
    # reads only make certificates MORE conservative).
    return {"TypeOK": build_type_ok, "NoLeaderElected": build_no_leader,
            **SAFETY_INVARIANTS}


def checkable_predicates(dims: RaftDims, invariant_names=None,
                         bounds: Optional[Bounds] = None,
                         constraint=None):
    """Every state predicate a check run can evaluate, as
    ``[(name, kernel)]`` — the machine-readable export the POR pass's
    invariant-visibility condition traces read sets from (analysis/por.py).

    ``invariant_names=None`` returns the CONSERVATIVE default: TypeOK plus
    the full safety suite (models/safety.py) — a certificate proved
    against every registered predicate stays valid for any cfg that
    checks a subset of them.  Passing the cfg's INVARIANT list narrows
    the set (and therefore the visibility condition) to what that model
    actually checks.  The CONSTRAINT predicate is appended (under
    :data:`CONSTRAINT_PREDICATE`) when ``constraint`` is given or
    ``bounds`` carries any bound: constraint reads gate *expansion*, so
    POR must treat them exactly like invariant reads."""
    registry = invariant_registry()
    names = (list(registry) if invariant_names is None
             else list(invariant_names))
    out = []
    for name in names:
        if name not in registry:
            raise ValueError(f"unknown invariant {name!r}; registered: "
                             f"{sorted(registry)}")
        out.append((name, registry[name](dims)))
    if constraint is not None:
        out.append((CONSTRAINT_PREDICATE, constraint))
    elif bounds is not None and any(
            getattr(bounds, f.name) is not None
            for f in dataclasses.fields(bounds)):
        out.append((CONSTRAINT_PREDICATE, build_constraint(dims, bounds)))
    return out


def constraint_py(bounds: Bounds):
    def constraint(s: PyState, dims: RaftDims) -> bool:
        ok = True
        if bounds.max_term is not None:
            ok &= max(s.current_term) <= bounds.max_term
        if bounds.max_log_len is not None:
            ok &= max(len(l) for l in s.log) <= bounds.max_log_len
        if bounds.max_msg_count is not None:
            ok &= all(c <= bounds.max_msg_count for _m, c in s.messages)
        if bounds.max_in_flight is not None:
            ok &= len(s.messages) <= bounds.max_in_flight
        return ok

    return constraint
