"""Struct-of-arrays state schema: the spec's variables as fixed-width tensors.

``vars == <<messages, serverVars, candidateVars, leaderVars, logVars>>``
(/root/reference/raft.tla:74) becomes ``StateBatch``, a NamedTuple pytree of
int32 tensors.  Used both per-state (no leading axis, inside kernels) and
batched (leading frontier axis, under vmap).  Encoding conventions are
documented in ``dims.py``; the invariants that keep states canonical for
fingerprinting are:

- log lanes at positions >= log_len are zero;
- free message slots (count == 0) are all-zero rows;
- votedFor uses 0 for Nil; bitmask bits beyond n_servers are zero.

``encode_state``/``decode_state`` convert to/from the oracle's ``PyState``
(host-side, numpy) for differential testing and trace pretty-printing.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from .dims import AEQ, RVQ, RVR, RaftDims
from .pystate import PyState


class StateBatch(NamedTuple):
    """One Raft global state (or a batch: add leading axes uniformly)."""

    term: "np.ndarray"        # [N]    currentTerm  raft.tla:37
    role: "np.ndarray"        # [N]    state        raft.tla:39
    voted_for: "np.ndarray"   # [N]    votedFor     raft.tla:42 (0=Nil)
    log_term: "np.ndarray"    # [N,L]  log entry terms   raft.tla:48
    log_val: "np.ndarray"     # [N,L]  log entry values
    log_len: "np.ndarray"     # [N]    Len(log[i])
    commit: "np.ndarray"      # [N]    commitIndex  raft.tla:50
    votes_resp: "np.ndarray"  # [N]    votesResponded bitmask  raft.tla:56
    votes_gran: "np.ndarray"  # [N]    votesGranted bitmask    raft.tla:59
    next_idx: "np.ndarray"    # [N,N]  nextIndex    raft.tla:64
    match_idx: "np.ndarray"   # [N,N]  matchIndex   raft.tla:67
    msg: "np.ndarray"         # [M,W]  distinct in-flight messages raft.tla:31
    msg_cnt: "np.ndarray"     # [M]    bag multiplicities


def audit_lane_widths(dims: RaftDims) -> None:
    """Construction-time audit: every packed field whose maximum domain
    value is STATIC must fit its lane width.  Called from
    ``RaftDims.__post_init__`` — a too-narrow lane is a build error with
    the field named, never a silent mod-256 wrap at depth (the reconfig
    value-wrap bug class: ``CFG_BASE + (old << 8) + new`` aliased to the
    plain client value the moment a state was enqueued, and no test
    shallower than a leader's first config entry could see it).

    Runtime-growing fields — terms (and the message columns that carry
    term values: mterm at column 3, and column 4's term half), bag
    counts — are NOT in this audit; ``build_pack_guard`` bounds those
    per-state on device and the engines treat an overflow as a hard
    error.  Columns 5+ carrying terms (AEReq prevLogTerm, RVResp mlog
    entry terms) are bounded by the sender's mterm <= 255 which the
    pack guard checks.
    """
    n, L = dims.n_servers, dims.max_log
    vmax = 256 ** dims.value_bytes - 1
    checks = (
        # field, static max over the spec's domain, lane limit
        ("votes_resp/votes_gran bitmask", (1 << n) - 1, 255),
        ("voted_for (0=Nil, else server+1)", n, 255),
        ("role", 2, 255),
        ("log_len / commit / match_idx", L, 255),
        ("next_idx (<= Len(log)+1)", L + 1, 255),
        # One check covers BOTH the log value lanes and the message value
        # columns (AEReq entry value, RVResp mlog values): flatten_state
        # gives them identical widths (value_bytes), and their domain is
        # the same value alphabet.
        ("log_val / msg value columns (dims.max_log_value)",
         dims.max_log_value, vmax),
        ("msg column 0 (mtype+1)", 5, 255),
        ("msg columns 1-2 (src+1, dst+1)", n, 255),
        # Column 4 is sign-extended (mprevLogIndex reaches -1); its
        # INDEX uses must fit int8.  (Its term uses are runtime-guarded.)
        ("msg column 4 index uses (mprevLogIndex)", L, 127),
        ("msg index/count columns (mlog len, nentries, mcommit)",
         L + 1, 255),
    )
    for field, domain_max, limit in checks:
        if domain_max > limit:
            raise ValueError(
                f"packed lane too narrow for {type(dims).__name__}: "
                f"field {field!r} reaches {domain_max} but its lane "
                f"holds at most {limit}; widen the lane "
                "(dims.value_bytes for value lanes) or shrink the domain")


def encode_message(m: tuple, dims: RaftDims) -> np.ndarray:
    """Message tuple (pystate.py layout) -> [W] int32 row (dims.py layout)."""
    w = np.zeros(dims.msg_width, np.int32)
    mtype, src, dst, mterm = m[0], m[1], m[2], m[3]
    w[0], w[1], w[2], w[3] = mtype + 1, src + 1, dst + 1, mterm
    if mtype == RVQ:
        w[4], w[5] = m[4], m[5]
    elif mtype == RVR:
        granted, mlog = m[4], m[5]
        w[4], w[5] = granted, len(mlog)
        for k, (t, v) in enumerate(mlog):
            w[6 + k] = t
            w[6 + dims.max_log + k] = v
    elif mtype == AEQ:
        prev, pterm, entries, mcommit = m[4], m[5], m[6], m[7]
        w[4], w[5], w[6] = prev, pterm, len(entries)
        if entries:
            w[7], w[8] = entries[0]
        w[9] = mcommit
    else:  # AER
        w[4], w[5] = m[4], m[5]
    return w


def decode_message(w: np.ndarray, dims: RaftDims) -> tuple:
    mtype = int(w[0]) - 1
    src, dst, mterm = int(w[1]) - 1, int(w[2]) - 1, int(w[3])
    if mtype == RVQ:
        return (RVQ, src, dst, mterm, int(w[4]), int(w[5]))
    if mtype == RVR:
        ln = int(w[5])
        mlog = tuple((int(w[6 + k]), int(w[6 + dims.max_log + k]))
                     for k in range(ln))
        return (RVR, src, dst, mterm, int(w[4]), mlog)
    if mtype == AEQ:
        n_ent = int(w[6])
        entries = ((int(w[7]), int(w[8])),) if n_ent else ()
        return (AEQ, src, dst, mterm, int(w[4]), int(w[5]), entries, int(w[9]))
    return (3, src, dst, mterm, int(w[4]), int(w[5]))


def check_packable(st: "StateBatch", dims: "RaftDims") -> None:
    """Raise if any field value cannot round-trip the uint8 row packing.

    Host-side, roots only; kernel-produced successors are guarded by
    ``build_pack_guard``.  Engines call this *after* the pre-pack root
    invariant check, so a root that an invariant would flag (e.g.
    matchIndex = -1 under TypeOK) is reported as the violation it is; this
    guard only rejects roots that would otherwise alias silently.  ``msg``
    column 4 — the one sign-extended field — admits [-128, 127]; value
    lanes (log values; msg value columns) admit [0, 65535] when
    ``dims.value_bytes == 2`` (reconfiguration entries); every other
    value is unsigned [0, 255]."""
    # The analyzer's lane map (analysis/lane_map.py) decodes the failing
    # lane for the error message: the field name plus, for message rows,
    # the semantic column meaning, plus the action families that write
    # the field — so the report points at the model code to look at, not
    # just a raw lane index.  Import-light by design (no jax, no cycle).
    from ..analysis import lane_map
    caps = lane_map.lane_capacities(dims)
    for name, arr in zip(StateBatch._fields, st):
        a = np.asarray(arr)
        if a.size == 0:
            continue
        lo_col, hi_col = caps[name]     # 'msg': per-column [W] arrays
        bad = (a < lo_col) | (a > hi_col)
        if bad.any():
            idx = tuple(int(i) for i in np.argwhere(bad)[0])
            if name == "msg":
                lo_b, hi_b = int(lo_col[idx[-1]]), int(hi_col[idx[-1]])
            else:
                lo_b, hi_b = int(lo_col), int(hi_col)
            raise ValueError(
                f"value {int(a[idx])} at {lane_map.describe_lane(name, idx, dims)} "
                f"is outside the packable range [{lo_b}, {hi_b}] "
                f"(uint8 row packing would alias it silently; "
                f"{int(bad.sum())} offending element(s) total)")


def encode_state(s: PyState, dims: RaftDims) -> StateBatch:
    """PyState -> single-state StateBatch (numpy int32, no leading axis)."""
    n, L, M = dims.n_servers, dims.max_log, dims.n_msg_slots
    log_term = np.zeros((n, L), np.int32)
    log_val = np.zeros((n, L), np.int32)
    log_len = np.zeros(n, np.int32)
    for i, log in enumerate(s.log):
        if len(log) > L:
            raise ValueError(f"log length {len(log)} exceeds capacity {L}")
        log_len[i] = len(log)
        for k, (t, v) in enumerate(log):
            log_term[i, k], log_val[i, k] = t, v
    bag = sorted(s.messages)
    if len(bag) > M:
        raise ValueError(f"{len(bag)} distinct messages exceed {M} slots")
    msg = np.zeros((M, dims.msg_width), np.int32)
    msg_cnt = np.zeros(M, np.int32)
    for slot, (m, c) in enumerate(bag):
        msg[slot] = encode_message(m, dims)
        msg_cnt[slot] = c
    return StateBatch(
        term=np.asarray(s.current_term, np.int32),
        role=np.asarray(s.role, np.int32),
        voted_for=np.asarray(s.voted_for, np.int32),
        log_term=log_term, log_val=log_val, log_len=log_len,
        commit=np.asarray(s.commit_index, np.int32),
        votes_resp=np.asarray(s.votes_responded, np.int32),
        votes_gran=np.asarray(s.votes_granted, np.int32),
        next_idx=np.asarray(s.next_index, np.int32),
        match_idx=np.asarray(s.match_index, np.int32),
        msg=msg, msg_cnt=msg_cnt)


def stack_states(states: List[StateBatch]) -> StateBatch:
    return StateBatch(*(np.stack(cols) for cols in zip(*states)))


def decode_state(st: StateBatch, dims: RaftDims) -> PyState:
    """Single-state StateBatch -> PyState (host-side)."""
    n = dims.n_servers
    a = StateBatch(*(np.asarray(x) for x in st))
    logs = tuple(
        tuple((int(a.log_term[i, k]), int(a.log_val[i, k]))
              for k in range(int(a.log_len[i])))
        for i in range(n))
    bag = frozenset(
        (decode_message(a.msg[s], dims), int(a.msg_cnt[s]))
        for s in range(dims.n_msg_slots) if a.msg_cnt[s] > 0)
    return PyState(
        current_term=tuple(int(x) for x in a.term),
        role=tuple(int(x) for x in a.role),
        voted_for=tuple(int(x) for x in a.voted_for),
        log=logs,
        commit_index=tuple(int(x) for x in a.commit),
        votes_responded=tuple(int(x) for x in a.votes_resp),
        votes_granted=tuple(int(x) for x in a.votes_gran),
        next_index=tuple(tuple(int(x) for x in row) for row in a.next_idx),
        match_index=tuple(tuple(int(x) for x in row) for row in a.match_idx),
        messages=bag)


# ---------------------------------------------------------------------------
# Flat row form: the BFS queues store states as [state_width] uint8 rows
# (one concatenation of every field); cheap reshape/concat both ways.
#
# uint8 is sufficient for every field under the target bounds (terms <=
# MaxTerm, log values <= |Value|, nextIndex <= Lmax+1, N<=8 vote bitmasks
# <= 255) and packs 4x more states per byte of HBM/ICI than int32.  The one
# field that can be negative is message payload column 4 (mprevLogIndex,
# raft.tla:454 — SmokeInt reaches -1, Smokeraft.tla:14-15): it is stored
# two's-complement (-1 -> 255) and sign-extended on decode; every other
# field is unsigned and < 128 under any budgeted run (a Smokeraft diameter
# budget of 100 bounds term growth at ~103).

ROW_DTYPE = np.uint8


def _msg_value_cols(dims: RaftDims):
    """Message-row columns that carry log-entry VALUES (dims.py layout):
    the AEReq entry value at 8 and the RVResp mlog value lanes at
    [6+L, 6+2L) — deduplicated (they overlap at L == 2, where column 8
    is both the AEReq entry value and an mlog value lane)."""
    L = dims.max_log
    return tuple(sorted({8, *range(6 + L, 6 + 2 * L)}))


def state_width(dims: RaftDims) -> int:
    n, L, M, W = (dims.n_servers, dims.max_log, dims.n_msg_slots,
                  dims.msg_width)
    base = n * 7 + 2 * n * L + 2 * n * n + M * W + M
    if dims.value_bytes == 2:
        # High-byte planes for log values [N,L] and the message value
        # columns [M, L+1], appended after the base layout.
        base += n * L + M * len(_msg_value_cols(dims))
    return base


def build_pack_guard(dims: RaftDims):
    """Per-state predicate: every unbounded-growth field still fits the
    uint8 row.  Terms grow via Timeout (raft.tla:146), bag counts via
    DuplicateMessage (:410), and message terms follow sender terms; all
    other fields are bounded by dims by construction.  Engines OR the
    negation into their overflow mask, so wrap-around is a hard error,
    never silent state aliasing."""
    import jax.numpy as jnp

    if dims.value_bytes == 2:
        vcols = jnp.asarray(_msg_value_cols(dims))

        def pack_ok(st: StateBatch):
            return (jnp.all(st.term <= 255)
                    & jnp.all(st.msg_cnt <= 255)
                    & jnp.all(st.msg[:, 3] <= 255)
                    & jnp.all(st.msg[:, 4] <= 127)
                    & jnp.all(st.log_val <= 65535)
                    & jnp.all(st.msg[:, vcols] <= 65535))

        return pack_ok

    def pack_ok(st: StateBatch):
        # Column 4 is sign-extended on decode (mprevLogIndex for AEReq, but
        # mlastLogTerm for RVReq), so values >= 128 there would corrupt to
        # negatives: bound it at 127, unlike the unsigned 255 elsewhere.
        return (jnp.all(st.term <= 255)
                & jnp.all(st.msg_cnt <= 255)
                & jnp.all(st.msg[:, 3] <= 255)
                & jnp.all(st.msg[:, 4] <= 127))

    return pack_ok


def flatten_state(st: StateBatch, dims: RaftDims):
    """StateBatch (single state) -> [state_width] uint8 row.  Works under
    vmap for batches.  Import-free of jax: uses the array namespace of its
    inputs (numpy or jnp).  Under ``dims.value_bytes == 2`` the row ends
    with high-byte planes for the value-carrying lanes (log values, AEReq
    entry value, RVResp mlog values) so variant values up to 65535 —
    reconfiguration entries — survive the uint8 packing."""
    parts = [st.term, st.role, st.voted_for, st.log_term.reshape(-1),
             st.log_val.reshape(-1), st.log_len, st.commit, st.votes_resp,
             st.votes_gran, st.next_idx.reshape(-1),
             st.match_idx.reshape(-1), st.msg.reshape(-1), st.msg_cnt]
    if dims.value_bytes == 2:
        cols = list(_msg_value_cols(dims))
        parts.append((st.log_val.reshape(-1) >> 8))
        parts.append((st.msg[:, cols] >> 8).reshape(-1))
    if isinstance(st.term, np.ndarray):
        return np.concatenate([np.asarray(p, np.int32).reshape(-1)
                               for p in parts]).astype(ROW_DTYPE)
    import jax.numpy as jnp  # jax arrays and tracers
    return jnp.concatenate(parts).astype(jnp.uint8)


def unflatten_state(row, dims: RaftDims) -> StateBatch:
    """[state_width] uint8 row -> StateBatch (int32 fields).  Works under
    vmap.  Tolerates int32 input rows (pre-packing callers) — the signed
    fix-up below is a no-op for values already < 128, and the value
    high-byte reassembly (value_bytes == 2) is likewise a no-op for rows
    whose high planes are zero."""
    n, L, M, W = (dims.n_servers, dims.max_log, dims.n_msg_slots,
                  dims.msg_width)
    if isinstance(row, np.ndarray):
        import numpy as xp
    else:
        import jax.numpy as xp
    row = row.astype(xp.int32)
    sizes = [n, n, n, n * L, n * L, n, n, n, n, n * n, n * n, M * W, M]
    shapes = [(n,), (n,), (n,), (n, L), (n, L), (n,), (n,), (n,), (n,),
              (n, n), (n, n), (M, W), (M,)]
    out, off = [], 0
    for sz, shp in zip(sizes, shapes):
        out.append(row[off:off + sz].reshape(shp))
        off += sz
    # Sign-extend message payload column 4 (mprevLogIndex — the only field
    # that can be negative; stored two's-complement in the uint8 row).
    msg = out[11]
    col4 = (xp.arange(W) == 4)[None, :]
    msg = xp.where(col4 & (msg >= 128), msg - 256, msg)
    if dims.value_bytes == 2:
        cols = list(_msg_value_cols(dims))
        lv_hi = row[off:off + n * L].reshape((n, L))
        off += n * L
        mv_hi = row[off:off + M * len(cols)].reshape((M, len(cols)))
        # Reassemble value = (low byte of the base lane) + (high plane
        # << 8).  Masking the base lane to its low byte keeps this a
        # no-op for int32 pre-packing rows, whose base lane carries the
        # full value AND whose high plane carries the same bits.
        vmask = np.zeros((W,), bool)
        vmask[cols] = True
        if isinstance(row, np.ndarray):
            full_hi = np.zeros((M, W), np.int32)
            full_hi[:, cols] = mv_hi
        else:
            full_hi = xp.zeros((M, W), xp.int32)
            for k, c in enumerate(cols):
                full_hi = full_hi.at[:, c].set(mv_hi[:, k])
            vmask = xp.asarray(vmask)
        msg = xp.where(vmask[None, :], (msg & 0xFF) + (full_hi << 8), msg)
        out[4] = (out[4] & 0xFF) + (lv_hi << 8)
    out[11] = msg
    return StateBatch(*out)
