"""Static model dimensions and the action-instance grid.

The reference spec's abstract constants (``Server``, ``Value`` —
/root/reference/raft.tla:11-14) are bound to finite model-value sets by the
TLC harness (/root/reference/MCraft.tla:15-21: 3 servers, 2 values).  In the
TPU build those bindings become *static dimensions*: every tensor shape and
the complete action-instance grid are known at trace time, so XLA compiles
one fixed program per (N, V, L, M) tuple.

Encoding conventions (used by both the JAX kernels and the Python oracle):

- servers are ``0..N-1`` (model values ``r1..rN`` interned in order);
- values are ``1..V`` (``0`` is reserved for "empty log slot");
- roles: ``0=Follower, 1=Candidate, 2=Leader`` (any distinct codes are
  sound per ``ASSUME DistinctRoles`` raft.tla:494-496);
- ``votedFor``: ``0=Nil, 1..N`` = server id + 1 (``Nil`` distinct: raft.tla:20);
- message types: ``0=RequestVoteRequest, 1=RequestVoteResponse,
  2=AppendEntriesRequest, 3=AppendEntriesResponse`` (distinctness:
  raft.tla:498-503);
- vote sets (``votesResponded``/``votesGranted`` raft.tla:56-59) are N-bit
  bitmasks, bit ``j`` = server ``j``;
- logs (raft.tla:48) are fixed ``[L]`` term/value lanes plus a length; slots
  ``>= len`` MUST be zero (canonical form for fingerprinting).

Message slot layout (one in-flight distinct message = one ``[MSG_WIDTH]``
int32 row plus a count; the bag of messages raft.tla:31 is the multiset
{row: count}).  Field 0 stores ``mtype + 1`` so an all-zero row is an
unambiguous free slot.  Payload union (schemas raft.tla:443-475):

  common:  [0]=mtype+1  [1]=msource+1  [2]=mdest+1  [3]=mterm
  RVReq :  [4]=mlastLogTerm  [5]=mlastLogIndex
  RVResp:  [4]=mvoteGranted  [5]=Len(mlog)  [6:6+L]=mlog terms  [6+L:6+2L]=mlog values
  AEReq :  [4]=mprevLogIndex (SmokeInt can be -1: Smokeraft.tla:14-15, type Int
           raft.tla:454)  [5]=mprevLogTerm  [6]=Len(mentries) (<=1:
           raft.tla:181-183)  [7]=entry term  [8]=entry value  [9]=mcommitIndex
  AEResp:  [4]=msuccess  [5]=mmatchIndex

``mlog`` (the full log copy in RequestVoteResponse, raft.tla:259,465) forces
the payload width to ``2 + 2L``.

Lane widths: the static analyzer (``analysis/``) is the AUTHORITY on
whether every packed lane is wide enough for this model.  ``python -m
raft_tla_tpu analyze`` proves the declared domains (machine-readable in
``analysis/lane_map.py``) fit the uint8 row per action kernel by
interval abstract interpretation, naming the witness action otherwise;
``schema.audit_lane_widths`` (construction) and ``build_pack_guard``
(runtime) are the enforcement backstops, not the source of truth.  A
variant that widens a domain should run the analyzer before trusting
the audit's static table.
"""

from __future__ import annotations

import dataclasses

# Role codes.
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2
NIL = 0

# Message-type codes.
RVQ, RVR, AEQ, AER = 0, 1, 2, 3
MSG_TYPE_NAMES = ("RequestVoteRequest", "RequestVoteResponse",
                  "AppendEntriesRequest", "AppendEntriesResponse")

# Action-family codes; order mirrors the Next disjunction raft.tla:421-430.
A_RESTART = 0        # \E i : Restart(i)            raft.tla:421 -> :136
A_TIMEOUT = 1        # \E i : Timeout(i)            raft.tla:422 -> :146
A_REQUESTVOTE = 2    # \E i,j : RequestVote(i,j)    raft.tla:423 -> :157
A_BECOMELEADER = 3   # \E i : BecomeLeader(i)       raft.tla:424 -> :195
A_CLIENTREQUEST = 4  # \E i,v : ClientRequest(i,v)  raft.tla:425 -> :206
A_ADVANCECOMMIT = 5  # \E i : AdvanceCommitIndex(i) raft.tla:426 -> :219
A_APPENDENTRIES = 6  # \E i,j : AppendEntries(i,j)  raft.tla:427 -> :171
A_RECEIVE = 7        # \E m : Receive(m)            raft.tla:428 -> :388
A_DUPLICATE = 8      # \E m : DuplicateMessage(m)   raft.tla:429 -> :410
A_DROP = 9           # \E m : DropMessage(m)        raft.tla:430 -> :415

FAMILY_NAMES = ("Restart", "Timeout", "RequestVote", "BecomeLeader",
                "ClientRequest", "AdvanceCommitIndex", "AppendEntries",
                "Receive", "DuplicateMessage", "DropMessage")


@dataclasses.dataclass(frozen=True)
class RaftDims:
    """Static shape parameters of one compiled checker instance."""

    n_servers: int           # |Server|   (MCraft.tla:20-21 -> 3)
    n_values: int            # |Value|    (MCraft.tla:15-17 -> 2)
    max_log: int = 8         # L: log tensor capacity (>= any reachable length)
    n_msg_slots: int = 32    # M: capacity for distinct in-flight messages

    def __post_init__(self):
        if not (1 <= self.n_servers <= 8):
            raise ValueError("n_servers must be in 1..8 (bitmask encoding)")
        if not (1 <= self.n_values <= 255):
            raise ValueError("n_values must be in 1..255 (uint8 row packing)")
        # Log indices (incl. mprevLogIndex, which can also be -1) must stay
        # in int8 range: the uint8 row packing sign-extends that column.
        if not (1 <= self.max_log <= 127):
            raise ValueError("max_log must be in 1..127 (uint8 row packing)")
        # Systematic lane-width audit (schema.audit_lane_widths): every
        # packed field whose maximum domain value is STATIC — value lanes
        # (incl. variant encodings like reconfig's CFG_BASE+masks), vote
        # bitmasks, index/count lanes, message header columns — must fit
        # its lane width, checked HERE at construction so the reconfig
        # value-wrap bug class (a domain silently exceeding its byte
        # width, invisible at shallow depths) can never recur in a new
        # variant.  Lazy import: schema imports this module at top level.
        from .schema import audit_lane_widths
        audit_lane_widths(self)

    # -- derived widths ----------------------------------------------------
    @property
    def max_log_value(self) -> int:
        """The largest value the spec can place in a log-entry VALUE lane
        (and hence in the message value columns — AEReq entry value,
        RVResp mlog values).  Base spec: client values are interned codes
        1..|Value|.  Variants with encoded values (reconfig's
        CFG_BASE + (old << 8) + new entries) override this; the
        construction-time lane audit (schema.audit_lane_widths) checks it
        against ``256**value_bytes - 1``, which is what makes a
        too-narrow value lane a BUILD error instead of a silent wrap at
        depth (the round-5 reconfig bug class)."""
        return self.n_values

    @property
    def value_bytes(self) -> int:
        """Bytes per log-entry VALUE in the packed uint8 row (schema.py).
        Base spec: 1 (values are interned client codes 1..V <= 255).
        Variants whose values exceed 255 — models/reconfig.py's
        configuration entries at CFG_BASE + masks — override this to 2;
        flatten/unflatten then carry high-byte planes for the log value
        lanes and the message columns that hold values (AEReq entry
        value, RVResp mlog values), appended at the END of the row so
        the base layout is unchanged."""
        return 1

    @property
    def payload_width(self) -> int:
        return max(6, 2 + 2 * self.max_log)

    @property
    def msg_width(self) -> int:
        return 4 + self.payload_width

    # -- action-instance grid ---------------------------------------------
    # Per-family instance counts; the expand kernel emits exactly one
    # candidate successor per instance with an enabled mask.  Receive yields
    # at most one successor per message because its disjuncts are pairwise
    # mutually exclusive (term comparisons partition on </=/>; see the
    # guards at raft.tla:282,296,335,361,374,383).
    @property
    def family_sizes(self) -> tuple:
        n, v, m = self.n_servers, self.n_values, self.n_msg_slots
        base = (n, n, n * n, n, n * v, n, n * n, m, m, m)
        return base + tuple(sz for _name, sz in self.extra_families)

    @property
    def family_names(self) -> tuple:
        return FAMILY_NAMES + tuple(nm for nm, _sz in self.extra_families)

    # -- model-variant hooks ----------------------------------------------
    # A spec variant (e.g. models/reconfig.py's joint-consensus extension)
    # subclasses RaftDims and overrides these; the JAX kernels
    # (models/actions.py), the Python oracle (models/oracle.py), and the
    # invariants (models/invariants.py) all dispatch through them, so every
    # engine (single-chip BFS, mesh BFS, simulation) picks up a variant
    # just by being handed its dims.

    @property
    def extra_families(self) -> tuple:
        """Extra action families beyond the raft.tla:421-430 alphabet:
        tuple of (name, instance_count)."""
        return ()

    def build_quorum(self):
        """JAX kernel ``quorum(state, i, member) -> bool`` deciding whether
        the [N]-bool ``member`` vector is a quorum from server i's point of
        view.  Base spec: simple majority of Server (raft.tla:79-81)."""
        import jax.numpy as jnp
        n = self.n_servers

        def quorum(st, i, member):
            return 2 * jnp.sum(member.astype(jnp.int32)) > n

        return quorum

    def quorum_py(self, s, i: int, mask: int) -> bool:
        """Oracle-side quorum on a membership bitmask (raft.tla:81)."""
        return 2 * bin(mask).count("1") > self.n_servers

    def build_extra_kernels(self):
        """JAX kernels for the extra families, in ``extra_families`` order:
        list of (param_arrays, kernel) with
        ``kernel(state, *params) -> (enabled, overflow, state')``."""
        return []

    def build_extra_v2(self, fp_helpers):
        """Delta-pipeline kernels for the extra families (models/
        actions2.py), in ``extra_families`` order, or ``None`` if the
        variant does not support the v2 pipeline (engines then fall back
        to v1).  Each entry is one ``lane_fn(state, *params) ->
        ((d_base0, d_base1), (d_msum0, d_msum1), successor)`` — the
        fingerprint-sum deltas plus the sparsely-constructed successor
        for ONE instance.  The parameter arrays are NOT duplicated here:
        actions2 feeds each lane_fn the ``build_extra_kernels`` param
        arrays of the same family (single source of truth for the grid
        order).  ``fp_helpers`` is actions2's delta toolkit
        (dpos/dvec/dsum/offsets...).  Masks and the pack guard come for
        free from ``build_extra_kernels`` (actions2 evaluates the v1
        kernel's guards and folds ``enabled & ~pack_ok(successor)``
        exactly as the v1 chunk does).  Base spec: no extras."""
        return []

    def build_extra_masks_v2(self):
        """OPTIONAL guards-only mask kernels for the extra families, in
        ``extra_families`` order, or ``None`` to have the v2 masks pass
        fall back to running the family's full v1 kernel (complete
        successor construction + whole-state pack guard) per lane.  Each
        entry is ``mask_fn(state, pack_ok_parent, *params) -> (enabled,
        overflow)`` and MUST be bit-identical to the v1 evaluation
        ``(en, ovf | (en & ~pack_ok(successor)))`` — actions2
        property-tests this.  ``pack_ok_parent`` is ``pack_ok(state)``
        evaluated ONCE per parent so footprints whose written values fit
        their lanes by construction can reuse it instead of re-checking
        the whole successor.  Base spec: no extras."""
        return None

    def extra_successors_py(self, s):
        """Oracle-side successors for the extra families: iterable of
        ((family_code, params), successor_state)."""
        return ()

    def build_value_ok(self):
        """JAX elementwise predicate: is a log-entry value lane well-typed
        (entries in Value — raft.tla:456/:465)?  Variants widen this."""
        import jax.numpy as jnp
        v = self.n_values

        def value_ok(vals):
            return (vals >= 1) & (vals <= v)

        return value_ok

    def value_ok_py(self, val: int) -> bool:
        return 1 <= val <= self.n_values

    @property
    def family_offsets(self) -> tuple:
        offs, acc = [], 0
        for s in self.family_sizes:
            offs.append(acc)
            acc += s
        return tuple(offs)

    @property
    def n_instances(self) -> int:
        return sum(self.family_sizes)

    def instance_info(self, g: int) -> tuple:
        """Decode grid index -> (family, params dict). Host-side helper for
        trace printing/replay."""
        n, v = self.n_servers, self.n_values
        for fam, (off, size) in enumerate(zip(self.family_offsets,
                                              self.family_sizes)):
            if off <= g < off + size:
                k = g - off
                if fam in (A_RESTART, A_TIMEOUT, A_BECOMELEADER,
                           A_ADVANCECOMMIT):
                    return fam, {"i": k}
                if fam in (A_REQUESTVOTE, A_APPENDENTRIES):
                    return fam, {"i": k // n, "j": k % n}
                if fam == A_CLIENTREQUEST:
                    return fam, {"i": k // v, "v": k % v + 1}
                return fam, {"slot": k}
        raise IndexError(g)

    def describe_instance(self, g: int) -> str:
        fam, p = self.instance_info(g)
        name = self.family_names[fam]
        return f"{name}({', '.join(f'{k}={v}' for k, v in p.items())})"
