"""Randomized initial-state generation — the SmokeInit harness.

Mirrors /root/reference/Smokeraft.tla: each state variable is drawn from a
``RandomSubset(k, <finitized domain>)`` (Smokeraft.tla:64-76), and the set of
initial states is the cartesian product of the per-variable k-subsets —
``k^9`` states (:17-19: 1/512/19683/262144 for k=1..4) — while the message
bag is one fixed random subset shared by every initial state, with all
multiplicities 1 (:76).  Finitized domains (:11-15, :4-9):

    SmokeNat = 0..2,  SmokeInt = -1..1,  logs: BoundedSeq(entries, 3),
    message sequences (mentries/mlog): length <= 1,
    nextIndex domain {n \\in SmokeNat : 1 <= n} = {1, 2}.

TLC's own RNG stream cannot be replicated (RandomSubset is
implementation-defined), so parity with the reference is *distributional*:
same domains, same subset sizes, same product structure.  Generation is
host-side numpy (512..262k tiny states, done once per run); the heavy lifting
— stepping them — is the TPU's job.
"""

from __future__ import annotations

import itertools
from typing import List

import numpy as np

from .dims import AEQ, AER, RVQ, RVR, RaftDims
from .pystate import PyState

SMOKE_NAT = (0, 1, 2)        # Smokeraft.tla:11-12
SMOKE_INT = (-1, 0, 1)       # Smokeraft.tla:14-15
SMOKE_MAX_INIT_LOG = 3       # Smokeraft.tla:70


def _rand_fn(rng, domain_sampler, n):
    return tuple(domain_sampler(rng) for _ in range(n))


def _random_subset(rng, k: int, sampler):
    """RandomSubset(k, S): k *distinct* draws (rejection-sampled)."""
    out, tries = set(), 0
    while len(out) < k and tries < 10000:
        out.add(sampler(rng))
        tries += 1
    if len(out) < k:
        raise ValueError("domain smaller than k")
    return sorted(out)


def _sample_log(rng, dims: RaftDims, max_len: int):
    ln = rng.integers(0, max_len + 1)
    return tuple((int(rng.choice(SMOKE_NAT)),
                  int(rng.integers(1, dims.n_values + 1)))
                 for _ in range(ln))


def _sample_message(rng, dims: RaftDims):
    """One element of SmokeMessageType (Smokeraft.tla:24-62)."""
    n = dims.n_servers
    mtype = int(rng.integers(0, 4))
    src, dst = int(rng.integers(0, n)), int(rng.integers(0, n))
    mterm = int(rng.choice(SMOKE_NAT))
    if mtype == RVQ:
        return (RVQ, src, dst, mterm, int(rng.choice(SMOKE_NAT)),
                int(rng.choice(SMOKE_NAT)))
    if mtype == RVR:
        return (RVR, src, dst, mterm, int(rng.integers(0, 2)),
                _sample_log(rng, dims, 1))
    if mtype == AEQ:
        return (AEQ, src, dst, mterm, int(rng.choice(SMOKE_INT)),
                int(rng.choice(SMOKE_NAT)), _sample_log(rng, dims, 1),
                int(rng.choice(SMOKE_NAT)))
    return (AER, src, dst, mterm, int(rng.integers(0, 2)),
            int(rng.choice(SMOKE_NAT)))


def smoke_init_states(dims: RaftDims, k: int = 2,
                      seed: int = 0) -> List[PyState]:
    """The full SmokeInit set: product of per-variable k-subsets (k^9
    states) sharing one random message bag — Smokeraft.tla:64-76."""
    n = dims.n_servers
    rng = np.random.default_rng(seed)

    def fn_sampler(cell):
        return lambda r: _rand_fn(r, cell, n)

    per_var = {
        "current_term": _random_subset(
            rng, k, fn_sampler(lambda r: int(r.choice(SMOKE_NAT)))),
        "role": _random_subset(
            rng, k, fn_sampler(lambda r: int(r.integers(0, 3)))),
        "voted_for": _random_subset(
            rng, k, fn_sampler(lambda r: int(r.integers(0, n + 1)))),
        "log": _random_subset(
            rng, k, fn_sampler(
                lambda r: _sample_log(r, dims, SMOKE_MAX_INIT_LOG))),
        "commit_index": _random_subset(
            rng, k, fn_sampler(lambda r: int(r.choice(SMOKE_NAT)))),
        "votes_responded": _random_subset(
            rng, k, fn_sampler(lambda r: int(r.integers(0, 1 << n)))),
        "votes_granted": _random_subset(
            rng, k, fn_sampler(lambda r: int(r.integers(0, 1 << n)))),
        # nextIndex \in [Server -> [Server -> {1, 2}]]  (SmokeNat n >= 1)
        "next_index": _random_subset(
            rng, k, fn_sampler(
                lambda r: tuple(int(r.integers(1, 3)) for _ in range(n)))),
        "match_index": _random_subset(
            rng, k, fn_sampler(
                lambda r: tuple(int(r.choice(SMOKE_NAT)) for _ in range(n)))),
    }
    # messages: one fixed bag, union of 4 k-subsets, multiplicity 1 (:58-76).
    msgs = set()
    for mt in range(4):
        msgs.update(_random_subset(
            rng, k, lambda r, _mt=mt: _until_type(r, dims, _mt)))
    bag = frozenset((m, 1) for m in msgs)

    names = list(per_var)
    states = []
    for combo in itertools.product(*(per_var[v] for v in names)):
        kw = dict(zip(names, combo))
        states.append(PyState(messages=bag, **kw))
    return states


def _until_type(rng, dims, mtype):
    while True:
        m = _sample_message(rng, dims)
        if m[0] == mtype:
            return m


def random_states(dims: RaftDims, count: int, seed: int = 0,
                  max_msgs: int = 4) -> List[PyState]:
    """Unstructured random states over the smoke domains — broader than
    SmokeInit (independent per-state message bags); used for differential
    fuzzing of the kernels, not part of TLC parity."""
    rng = np.random.default_rng(seed)
    n = dims.n_servers
    out = []
    for _ in range(count):
        n_msgs = int(rng.integers(0, max_msgs + 1))
        bag = {}
        for _k in range(n_msgs):
            bag[_sample_message(rng, dims)] = int(rng.integers(1, 3))
        out.append(PyState(
            current_term=_rand_fn(rng, lambda r: int(r.choice(SMOKE_NAT)), n),
            role=_rand_fn(rng, lambda r: int(r.integers(0, 3)), n),
            voted_for=_rand_fn(rng, lambda r: int(r.integers(0, n + 1)), n),
            log=_rand_fn(
                rng, lambda r: _sample_log(r, dims, SMOKE_MAX_INIT_LOG), n),
            commit_index=_rand_fn(rng, lambda r: int(r.choice(SMOKE_NAT)), n),
            votes_responded=_rand_fn(
                rng, lambda r: int(r.integers(0, 1 << n)), n),
            votes_granted=_rand_fn(
                rng, lambda r: int(r.integers(0, 1 << n)), n),
            next_index=tuple(
                tuple(int(rng.integers(1, 3)) for _ in range(n))
                for _ in range(n)),
            match_index=tuple(
                tuple(int(rng.choice(SMOKE_NAT)) for _ in range(n))
                for _ in range(n)),
            messages=frozenset(bag.items())))
    return out
