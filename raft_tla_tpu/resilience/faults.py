"""Deterministic fault injection — named sites, a parsed plan, fire-once.

Grammar (``--fault-plan`` flag / ``FAULT_PLAN`` env)::

    plan  := fault ("," fault)*
    fault := site ["@" param (";" param)*]
    param := key "=" value          # int values parsed as int

    ckpt_torn_write@level=3,kill@level=5,oom@grow=1

Sites and their actions:

========================  ====================================================
``kill``                  die mid-run (``engine/bfs.py`` /
                          ``parallel/mesh.py`` chunk loops; params:
                          ``level``, ``chunk``)
``ckpt_torn_write``       die between the checkpoint tmp-write and its
                          rename (``engine/checkpoint.save``; param
                          ``level``) — the torn ``.tmp`` file stays behind
``ckpt_piece_missing``    skip writing this snapshot/piece entirely
                          (``engine/checkpoint.save``; params ``level``,
                          ``piece``) — simulates a controller that died
                          before its piece landed
``oom``                   raise a simulated XLA ``RESOURCE_EXHAUSTED``
                          (chunk dispatch: params ``level``, ``chunk``;
                          seen-set growth: param ``grow``)
``spill_write``           raise ``OSError`` from the disk spill write
                          (``engine/spillpool.py``)
``trace_piece_delay``     sleep ``seconds`` before writing this
                          controller's trace piece (``parallel/mesh.py``)
========================  ====================================================

A fault fires when every one of its params is present in the call site's
context with an equal value, and each fault fires AT MOST ONCE — fired
markers persist in ``state_dir`` (``FAULT_STATE_DIR`` env) so a
supervisor-restarted child does not re-kill itself at the same level
forever.  Without a ``state_dir`` the markers are process-local (fine for
in-process tests, wrong across restarts — the supervisor always sets one).

``hard`` selects how die-class sites die: ``os._exit(EXIT_FAULT)`` (the
real crash, for subprocess harnesses; default when installed from the
environment) or :class:`FaultInjected` (for in-process unit tests — a
raise still leaves exactly the same file state behind).

Zero overhead when no plan is installed: sites guard on the module-level
``ACTIVE`` bool and never call in here.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from typing import Dict, List, Optional

#: Exit code of a hard injected crash — distinct from the engine's real
#: exit codes (0 ok, 1 violation/deadlock, 2 usage) so the supervisor and
#: the chaos harness can tell an injected death from a genuine bug.
EXIT_FAULT = 86


class FaultInjected(RuntimeError):
    """Soft-mode stand-in for an injected process death."""


class SimulatedResourceExhausted(RuntimeError):
    """Injected stand-in for jax's RESOURCE_EXHAUSTED allocation failure
    (message format matches what :func:`is_resource_exhausted` keys on)."""


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for a real XLA allocation failure OR the injected stand-in.
    XLA surfaces OOM as ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...`` — a
    string match on the status name is the stable cross-version check
    (the exception class moved between jaxlib releases)."""
    return "RESOURCE_EXHAUSTED" in str(exc) or isinstance(
        exc, SimulatedResourceExhausted)


@dataclasses.dataclass
class Fault:
    site: str
    params: Dict[str, object]
    idx: int                      # position in the plan: the marker key

    @property
    def marker(self) -> str:
        return f"fired_{self.idx:02d}_{self.site}"

    def __str__(self) -> str:
        ps = ";".join(f"{k}={v}" for k, v in self.params.items())
        return f"{self.site}@{ps}" if ps else self.site


_SITE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
KNOWN_SITES = ("kill", "ckpt_torn_write", "ckpt_piece_missing", "oom",
               "spill_write", "trace_piece_delay")
#: Plan params that configure the fault's ACTION rather than select when
#: it fires — match() must not require them in the call site's context
#: (``trace_piece_delay@seconds=2`` would otherwise never fire: no site
#: passes ``seconds``).
ACTION_PARAMS = {"trace_piece_delay": {"seconds"}}


class FaultPlan:
    """Parsed plan + fired-marker store."""

    def __init__(self, faults: List[Fault], state_dir: Optional[str] = None,
                 hard: bool = True):
        self.faults = faults
        self.state_dir = state_dir
        self.hard = hard
        self._fired_local = set()
        if state_dir is not None:
            os.makedirs(state_dir, exist_ok=True)

    @classmethod
    def parse(cls, text: str, state_dir: Optional[str] = None,
              hard: bool = True) -> "FaultPlan":
        faults = []
        for idx, part in enumerate(p for p in text.split(",") if p.strip()):
            part = part.strip()
            site, _, rest = part.partition("@")
            if not _SITE_RE.match(site) or site not in KNOWN_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} in {part!r}; known: "
                    f"{KNOWN_SITES} (grammar: site@key=val;key=val,...)")
            params: Dict[str, object] = {}
            for kv in (p for p in rest.split(";") if p):
                key, sep, val = kv.partition("=")
                if not sep:
                    raise ValueError(
                        f"fault param {kv!r} in {part!r} is not key=value")
                try:
                    params[key.strip()] = int(val)
                except ValueError:
                    params[key.strip()] = val.strip()
            faults.append(Fault(site=site, params=params, idx=idx))
        if not faults:
            raise ValueError(f"empty fault plan {text!r}")
        return cls(faults, state_dir=state_dir, hard=hard)

    # -- fired markers --------------------------------------------------
    def _has_fired(self, fault: Fault) -> bool:
        if fault.marker in self._fired_local:
            return True
        return (self.state_dir is not None
                and os.path.exists(os.path.join(self.state_dir,
                                                fault.marker)))

    def _mark_fired(self, fault: Fault) -> None:
        """Persist BEFORE acting: a die-class fault must never re-fire on
        the supervised restart (the marker, not the death, is the record)."""
        self._fired_local.add(fault.marker)
        if self.state_dir is not None:
            path = os.path.join(self.state_dir, fault.marker)
            with open(path, "w") as f:
                f.write(f"{fault}\n{time.time()}\n")
                f.flush()
                os.fsync(f.fileno())

    # -- firing ---------------------------------------------------------
    def match(self, site: str, ctx: Dict[str, object]) -> Optional[Fault]:
        skip = ACTION_PARAMS.get(site, ())
        for fault in self.faults:
            if fault.site != site or self._has_fired(fault):
                continue
            if all(k in ctx and ctx[k] == v
                   for k, v in fault.params.items() if k not in skip):
                return fault
        return None

    def _die(self, fault: Fault) -> None:
        if self.hard:
            # Flight-recorder postmortem first (obs/flight.py): a real
            # SIGKILL would get nothing, but the POINT of the injected
            # kill is to rehearse crash recovery — and the recorder's
            # contract is that crashes yield their last N seconds of
            # telemetry.  Best-effort: the dump never blocks the death.
            try:
                from ..obs.flight import RECORDER
                RECORDER.dump(f"fault_kill: {fault}")
            except Exception:
                pass
            # Real crash semantics: no atexit hooks, no finally blocks —
            # exactly what a SIGKILL / machine loss leaves behind.
            os._exit(EXIT_FAULT)
        raise FaultInjected(f"injected fault: {fault}")

    def fire(self, site: str, **ctx) -> bool:
        """Fire the first matching un-fired fault for ``site``.  Die-class
        and raise-class sites act here; returns True for sites whose
        action is the CALLER's (``ckpt_piece_missing`` => skip the write),
        False when nothing fired."""
        fault = self.match(site, ctx)
        if fault is None:
            return False
        self._mark_fired(fault)
        if site in ("kill", "ckpt_torn_write"):
            self._die(fault)
        elif site == "oom":
            raise SimulatedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected fault: {fault}")
        elif site == "spill_write":
            raise OSError(f"injected spill write failure: {fault}")
        elif site == "trace_piece_delay":
            time.sleep(float(fault.params.get("seconds", 1)))
        return True


# -- module-level singleton (the injection-site interface) ---------------
#: Sites guard with ``if faults.ACTIVE: faults.fire(...)`` — one global
#: bool read is the entire cost of an un-faulted run.
ACTIVE = False
_PLAN: Optional[FaultPlan] = None


def install(text: str, state_dir: Optional[str] = None,
            hard: bool = True) -> FaultPlan:
    global ACTIVE, _PLAN
    _PLAN = FaultPlan.parse(text, state_dir=state_dir, hard=hard)
    ACTIVE = True
    return _PLAN


def install_from_env(default_state_dir: Optional[str] = None,
                     text: Optional[str] = None) -> bool:
    """Install ``text`` (the ``--fault-plan`` flag) or, when None, the
    ``FAULT_PLAN`` env — either way with the env-resolved marker dir
    (``FAULT_STATE_DIR``, falling back to ``default_state_dir``) and
    hard mode unless ``FAULT_HARD=0``.  Returns True when a plan was
    installed.  The one resolution point for flag- and env-installed
    plans, so supervised children (which inherit the env) and direct
    CLI invocations can never diverge on state-dir/hard semantics."""
    text = text or os.environ.get("FAULT_PLAN")
    if not text:
        return False
    install(text,
            state_dir=os.environ.get("FAULT_STATE_DIR",
                                     default_state_dir),
            hard=os.environ.get("FAULT_HARD", "1") != "0")
    return True


def clear() -> None:
    global ACTIVE, _PLAN
    ACTIVE = False
    _PLAN = None


def fire(site: str, **ctx) -> bool:
    if _PLAN is None:
        return False
    return _PLAN.fire(site, **ctx)
