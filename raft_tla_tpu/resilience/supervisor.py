"""Crash-resume supervisor — bounded restarts around a child check run.

``cli check --supervise[=N]`` re-runs itself through here: the check
executes in a CHILD process, and when that child dies with a crash exit
the supervisor resumes it from ``checkpoint.latest(checkpoint_dir)``
with exponential backoff, up to N restarts.  Exit codes 0 (clean) and 1
(violation/deadlock found) are COMPLETED checks — a found counterexample
is a result, not a crash — and stop the loop immediately; anything else
(a raised engine error, an injected ``os._exit``, a signal death) is
retriable.

Exit code 1 is ambiguous on its own: the CLI returns 1 for a found
violation/deadlock, but an uncaught Python exception ALSO exits 1.  The
supervisor disambiguates through the run's event log: the engines write
a ``run_end`` event with ``stop_reason`` ``violation``/``deadlock`` on
a completed counterexample run, and ``error`` (or nothing at all, for a
hard death) on a crash — so a 1-exit WITHOUT a fresh completed
``run_end`` is retried like any other crash.  When no event log is
readable the 1-exit is conservatively treated as completed (retrying a
deterministic violation would just re-find it N times).

Each restart appends a ``restart`` event to the run's JSONL event log
(the same file the child engines append to — ``RunEventLog`` opens in
append mode and writes one flushed line per event, so supervisor and
child lines interleave cleanly).  ``scripts/chaos_check.py`` asserts a
supervised faulted run is bit-identical to an uninterrupted one.

The child resumes via ``--resume auto`` only when an intact snapshot
actually exists — a crash before the first checkpoint restarts the run
from scratch rather than dying on ``--resume auto``'s no-checkpoint
error.  ``checkpoint.latest`` already skips torn/truncated files and
mixed-generation piece groups, so the supervisor never needs to judge
snapshot health itself.
"""

from __future__ import annotations

import subprocess
import sys
import time
from typing import Dict, List, Optional


def run_supervised(child_argv: List[str], checkpoint_dir: str,
                   max_restarts: int = 3,
                   events_out: Optional[str] = None,
                   backoff_seconds: float = 1.0,
                   backoff_factor: float = 2.0,
                   backoff_cap_seconds: float = 60.0,
                   initial_resume: Optional[str] = None,
                   env: Optional[dict] = None,
                   trace_out: Optional[str] = None) -> int:
    """Run ``child_argv`` under crash-resume supervision; returns the
    final child exit code.  ``child_argv`` is the complete child command
    (e.g. ``[sys.executable, "-m", "raft_tla_tpu", "check", ...]``)
    WITHOUT any ``--supervise`` or ``--resume`` flags — the supervisor
    decides the resume point per attempt: ``initial_resume`` (the
    user's own ``--resume`` value, honored on the FIRST attempt too)
    and ``--resume auto`` on restarts.

    Restart resumes are guarded against a REUSED checkpoint dir: unless
    the user asked to resume, ``--resume auto`` is only passed once
    ``latest()`` differs from what the dir held before the first
    attempt — a child that crashed before its first snapshot must
    restart from scratch, not from a previous run's stale image (whose
    cfg may not even match; load() validates only dims).

    ``trace_out`` (the run's ``--trace-out`` value, when set): the CHILD
    keeps writing its engine trace to that path — the last completed
    attempt's trace wins, which is the one a user wants to open — while
    the supervisor records its own timeline (one ``attempt`` span per
    child run, ``restart`` instants with exit codes) to
    ``<trace_out>.supervisor.json``."""
    # Deferred: engine.checkpoint imports resilience.faults for its
    # injection sites, and this module rides in resilience/__init__ —
    # top-level imports here would close that cycle during package init.
    from ..engine import checkpoint as ckpt_mod
    from ..obs import RunEventLog, SpanTracer, events_path
    evpath = events_path(events_out, checkpoint_dir)
    evlog = RunEventLog(evpath)
    tracer = SpanTracer(f"{trace_out}.supervisor.json" if trace_out
                        else None, process_name="supervisor")
    preexisting = ckpt_mod.latest(checkpoint_dir)
    attempt = 0
    try:
        while True:
            argv = list(child_argv)
            if attempt == 0:
                if initial_resume:
                    argv += ["--resume", initial_resume]
            elif initial_resume or \
                    ckpt_mod.latest(checkpoint_dir) != preexisting:
                argv += ["--resume", "auto"]
            ends_before = _count_run_ends(evpath)
            attempt_t0 = time.perf_counter()
            attempt_wall_t0 = time.time()
            rc = subprocess.call(argv, env=env)
            tracer.complete("attempt", attempt_t0, attempt=attempt,
                            exit_code=rc)
            if rc not in (0, 1):
                # A crashed child's flight recorder dumps its black box
                # next to the checkpoints (obs/flight.py; the injected
                # hard kill dumps from faults._die).  Surface it in the
                # supervision timeline so the postmortem is
                # discoverable from the event log alone.
                for pm in _find_postmortems(checkpoint_dir,
                                            attempt_wall_t0):
                    evlog.emit("postmortem", attempt=attempt,
                               exit_code=rc, dump=pm)
            if rc == 0 or (rc == 1
                           and _completed_counterexample(evpath,
                                                         ends_before)):
                if attempt:
                    evlog.emit("supervised_done", attempts=attempt,
                               exit_code=rc)
                return rc
            if rc == 2:
                # Usage/config error (argparse): deterministic — the
                # identical command would fail N more times.
                evlog.emit("supervise_giveup", attempts=attempt,
                           exit_code=rc)
                print("supervisor: child exited 2 (usage error); not "
                      "retriable", file=sys.stderr)
                return rc
            if attempt >= max_restarts:
                evlog.emit("supervise_giveup", attempts=attempt,
                           exit_code=rc)
                print(f"supervisor: child exited {rc}; restart budget "
                      f"({max_restarts}) exhausted", file=sys.stderr)
                return rc
            delay = min(backoff_seconds * backoff_factor ** attempt,
                        backoff_cap_seconds)
            attempt += 1
            nxt = ckpt_mod.latest(checkpoint_dir)
            if not initial_resume and nxt == preexisting:
                nxt = None       # stale-dir guard: see docstring
            evlog.emit("restart", attempt=attempt, exit_code=rc,
                       resume_from=nxt, backoff_seconds=round(delay, 3))
            tracer.instant("restart", attempt=attempt, exit_code=rc,
                           resume_from=nxt)
            print(f"supervisor: child exited {rc}; restart {attempt}/"
                  f"{max_restarts} in {delay:.1f}s "
                  + (f"resuming {nxt}" if nxt else "from scratch"),
                  file=sys.stderr)
            time.sleep(delay)
    finally:
        evlog.close()
        if tracer.enabled:
            tracer.write()


def _find_postmortems(checkpoint_dir: str, since_ts: float) -> List[dict]:
    """Postmortem dumps a child wrote during the attempt that just
    crashed: ``postmortem.json`` plus any per-controller pieces
    (``postmortem.p<i>of<m>.json``) under the checkpoint dir, filtered
    by mtime so a previous attempt's dump is not re-reported.  Each
    entry is the ``dump`` payload of one ``postmortem`` event: path,
    reason, and a compact shape summary (record counts per kind) —
    never the full ring, which belongs in the file."""
    import glob
    import json
    import os
    out = []
    for path in sorted(
            glob.glob(os.path.join(checkpoint_dir, "postmortem.json"))
            + glob.glob(os.path.join(checkpoint_dir,
                                     "postmortem.p*of*.json"))):
        try:
            if os.path.getmtime(path) < since_ts - 1.0:
                continue
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            out.append({
                "path": path,
                "reason": doc.get("reason"),
                "pid": doc.get("pid"),
                "records": {k: len(v) for k, v
                            in (doc.get("records") or {}).items()},
                "last_progress": ((doc.get("records") or {})
                                  .get("progress") or [None])[-1]})
        except (OSError, ValueError):
            continue
    return out


def _run_end_reasons(evpath: Optional[str]) -> Optional[Dict[str, List[str]]]:
    """``{file: [stop_reason, ...]}`` of every ``run_end`` record, per
    event-log file — the base path AND any per-controller piece files
    next to it (``events.p<i>of<m>.jsonl``; obs/events.py events_path):
    multi-host children write run_end only into their pieces, so
    reading the base file alone would misread a completed fleet as a
    crash.  None when nothing is readable (best-effort: the log is
    evidence, not a dependency)."""
    import glob
    import json
    import os
    if not evpath:
        return None
    root, ext = os.path.splitext(evpath)
    out: Dict[str, List[str]] = {}
    for path in [evpath] + sorted(glob.glob(f"{root}.p*of*{ext}")):
        try:
            with open(path, encoding="utf-8") as f:
                out[path] = [str(rec.get("stop_reason"))
                             for line in f if line.strip()
                             for rec in (json.loads(line),)
                             if rec.get("event") == "run_end"]
        except (OSError, ValueError):
            continue
    return out or None


def _count_run_ends(evpath: Optional[str]) -> Dict[str, int]:
    reasons = _run_end_reasons(evpath)
    return ({f: len(r) for f, r in reasons.items()}
            if reasons is not None else {})


def _completed_counterexample(evpath: Optional[str],
                              ends_before: Dict[str, int]) -> bool:
    """Did the child that just exited 1 actually COMPLETE (found a
    violation/deadlock), or did it die on an uncaught exception (also
    exit 1)?  Fresh ``run_end`` records with a counterexample
    stop_reason — one per controller file — are the completion receipt;
    a crash writes ``error`` or nothing.  An unreadable log defaults to
    completed — retrying a deterministic violation would only re-find
    it."""
    reasons = _run_end_reasons(evpath)
    if reasons is None:
        return True
    fresh = [r for path, rs in reasons.items()
             for r in rs[ends_before.get(path, 0):]]
    return bool(fresh) and all(r in ("violation", "deadlock")
                               for r in fresh)


def strip_supervisor_flags(argv: List[str]) -> List[str]:
    """Child argv from the supervisor's own: drop ``--supervise[=N]``
    (the child must run the check, not recurse into supervision) and any
    ``--resume`` (the supervisor decides the resume point per attempt)."""
    out, skip = [], False
    for i, tok in enumerate(argv):
        if skip:
            skip = False
            continue
        if tok == "--supervise" or tok == "--resume":
            nxt = argv[i + 1] if i + 1 < len(argv) else ""
            # Both flags take an optional/required value: swallow it
            # unless it is clearly the next flag.
            skip = bool(nxt) and not nxt.startswith("-")
            continue
        if tok.startswith("--supervise=") or tok.startswith("--resume="):
            continue
        out.append(tok)
    return out
