"""Resilience subsystem — fault injection, crash-resume, degradation.

TLC's whole crash story is its ``states/`` directory; this package is the
layer that *exercises* ours.  Three parts, one recovery spine:

- :mod:`.faults` — a deterministic :class:`FaultPlan` (``--fault-plan`` /
  ``FAULT_PLAN`` env) with named injection sites threaded through
  ``engine/checkpoint.py`` (torn write), ``engine/bfs.py`` (mid-level
  kill, simulated RESOURCE_EXHAUSTED), ``engine/spillpool.py`` (failed
  spill write) and ``parallel/mesh.py`` (delayed trace piece).  Zero
  overhead when no plan is installed (sites guard on a module bool).
- :mod:`.supervisor` — ``cli check --supervise[=N]``: run the check in a
  child process and, on a crash exit, resume from
  ``checkpoint.latest()`` with bounded restarts and exponential
  backoff, emitting ``restart`` events into the run's JSONL log.
- graceful degradation lives in the engines themselves
  (``engine/bfs.py``): RESOURCE_EXHAUSTED from the chunk loop or a
  seen-set growth is caught, the batch halves (down to
  ``EngineConfig.min_batch``) or the growth retries after releasing the
  old table, and the run continues from its last intact snapshot —
  recorded as a ``degraded`` event instead of an abort.

``scripts/chaos_check.py`` is the end-to-end harness: a supervised run
under a fault plan must finish bit-identical to an uninterrupted one.
"""

# NOTE: faults.ACTIVE is deliberately NOT re-exported — a ``from ...
# import ACTIVE`` would freeze the bool at import time; injection sites
# must read the live ``faults.ACTIVE`` module attribute.
from .faults import (EXIT_FAULT, FaultInjected,              # noqa: F401
                     FaultPlan, SimulatedResourceExhausted, clear, fire,
                     install, install_from_env, is_resource_exhausted)
from .supervisor import run_supervised                        # noqa: F401
