"""Command-line interface — the ``java tlc2.TLC -config X.cfg X.tla`` analog.

    python -m raft_tla_tpu check    <cfg> [engine options]
    python -m raft_tla_tpu simulate <cfg> [--num-steps N --depth D]

Platform selection: by default jax picks the ambient backend (the real TPU
where available).  ``--platform cpu`` forces CPU and must be applied before
jax initializes, which is why all heavy imports here are deferred until
after argument parsing.
"""

from __future__ import annotations

import argparse
import os
import sys


def _write_metrics(path: str, registry) -> None:
    """--metrics-out: final registry snapshot as pretty JSON.  Under a
    process group every controller runs this at exit, so the path gets
    the same per-controller piece suffix as event logs/checkpoints and
    the write is atomic (tmp + rename) — two hosts must never interleave
    into one file on the shared filesystem."""
    import json
    try:
        import jax
        pi, pc = jax.process_index(), jax.process_count()
    except Exception:
        pi, pc = 0, 1
    if pc > 1:
        root, ext = os.path.splitext(path)
        path = f"{root}.p{pi}of{pc}{ext or '.json'}"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp{pi}"
    with open(tmp, "w") as f:
        json.dump(registry.snapshot(), f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _run_analyze(args) -> int:
    """``analyze``: run the static-analysis passes (analysis/) over one
    model and gate on ERROR findings — the per-PR kernel-correctness
    gate CI runs.  Exit 0 iff no (un-allowlisted) ERROR finding."""
    import json

    from .analysis import PASSES, run_analysis
    from .analysis.lane_map import FIELDS
    from .obs import MetricsRegistry, RunEventLog

    passes = None
    if args.passes is not None:
        passes = tuple(p.strip() for p in args.passes.split(",")
                       if p.strip())
        unknown = [p for p in passes if p not in PASSES]
        if unknown or not passes:
            # Exit 2 (usage error), never a silent no-op run: a typo'd
            # pass name must not report "analysis OK" on zero passes.
            print(f"analyze: unknown pass(es) "
                  f"{', '.join(unknown) or '(none given)'}; valid "
                  f"passes: {', '.join(PASSES)}", file=sys.stderr)
            return 2

    if args.cfg is not None:
        from .engine.check import initial_states
        from .utils.cfg import load_config
        setup = load_config(args.cfg, max_log=args.max_log,
                            n_msg_slots=args.n_msg_slots)
        dims, bounds = setup.dims, setup.bounds
        # The cfg's INVARIANT list narrows the POR visibility condition
        # to what this model actually checks.
        invariant_names = list(setup.invariants)
        # Randomized smoke roots say nothing about the reachable set;
        # the bounds pass then seeds from the declared domain envelope.
        roots = None if setup.smoke else initial_states(setup)
    else:
        from .models.dims import RaftDims
        from .models.pystate import init_state
        dims = RaftDims(n_servers=3, n_values=2,
                        max_log=args.max_log or 8,
                        n_msg_slots=args.n_msg_slots or 32)
        bounds, roots, invariant_names = None, [init_state(dims)], None

    lane_caps = {}
    for spec in args.shrink_lane:
        field, _, hi = spec.partition("=")
        if field not in FIELDS or not hi.lstrip("-").isdigit():
            raise SystemExit(
                f"--shrink-lane wants FIELD=HI with FIELD in {FIELDS}, "
                f"got {spec!r}")
        lane_caps[field] = (0, int(hi))

    metrics = MetricsRegistry()
    with RunEventLog(args.events_out) as evlog:
        report = run_analysis(
            dims, bounds=bounds, init_states=roots,
            **({"passes": passes} if passes else {}),
            allowlist=args.allow, lane_caps=lane_caps or None,
            invariant_names=invariant_names,
            metrics=metrics, evlog=evlog)
    if args.out:
        report.write_json(args.out)
    if args.por_artifact:
        table = report.pass_summaries.get("por", {}).get("table")
        if table is None:
            print("--por-artifact requires the 'por' pass to run "
                  "(add it to --passes)", file=sys.stderr)
            return 2
        unsound = any(f.code == "certificate-unsound"
                      for f in report.findings if f.pass_name == "por")
        if unsound:
            # The pass's certificate-unsound self-check failed: never
            # materialize a validly-fingerprinted artifact for a mask
            # whose side conditions did not verify.  Checked on the raw
            # finding code, not post-allowlist severity — --allow can
            # un-gate the EXIT status, never the artifact.
            print("--por-artifact refused: the por pass reported "
                  "certificate-unsound findings (see report)",
                  file=sys.stderr)
        else:
            with open(args.por_artifact, "w") as f:
                json.dump(table, f, indent=2, sort_keys=True)
                f.write("\n")
    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    if args.metrics_out:
        _write_metrics(args.metrics_out, metrics)
    return 0 if report.ok else 1


def _force_platform(platform: str):
    if platform == "cpu":
        from .utils.platform import force_cpu
        force_cpu()
        return
    os.environ["JAX_PLATFORMS"] = platform
    import jax
    jax.config.update("jax_platforms", platform)


def main(argv=None):
    p = argparse.ArgumentParser(prog="raft_tla_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    # Flags default to None so the resolution chain is visible at the use
    # sites: CLI flag > cfg "\* TPU:" backend directive > built-in default.
    def common(sp):
        sp.add_argument("cfg", help="TLC .cfg file (e.g. MCraft.cfg)")
        sp.add_argument("--platform", default=None,
                        help="jax platform override (e.g. cpu)")
        sp.add_argument("--batch", type=int, default=None)
        sp.add_argument("--n-msg-slots", type=int, default=None)
        sp.add_argument("--max-log", type=int, default=None)
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--engine", choices=("single", "mesh", "auto"),
                        default="auto",
                        help="mesh = shard over all visible devices (TLC "
                             "-workers / distributed TLC analog); auto = "
                             "mesh iff >1 accelerator device (default)")
        sp.add_argument("--pipeline", choices=("auto", "v1", "v2", "v3"),
                        default=None,
                        help="successor pipeline: v1 = classical expand, "
                             "v2 = delta (guards-only masks + delta "
                             "fingerprints), v3 = fused Pallas chunk "
                             "(VMEM-resident compact + probe/insert->"
                             "enqueue tail; per-stage XLA fallback, "
                             "interpret mode off-TPU).  auto = v2 where "
                             "it applies (default; flag > cfg PIPELINE "
                             "directive > auto)")

    c = sub.add_parser("check", help="exhaustive BFS check")
    common(c)
    c.add_argument("--queue-capacity", type=int, default=None)
    c.add_argument("--seen-capacity", type=int, default=None)
    c.add_argument("--max-diameter", type=int, default=None)
    c.add_argument("--max-seconds", type=float, default=None)
    c.add_argument("--no-trace", action="store_true",
                   help="disable counterexample trace recording")
    c.add_argument("--checkpoint-dir", default=None,
                   help="write level-boundary snapshots here (TLC states/)")
    c.add_argument("--checkpoint-every", type=int, default=None,
                   help="snapshot every k BFS levels (default 1)")
    c.add_argument("--checkpoint-interval", type=float, default=None,
                   help="min seconds between snapshots (snapshot cost is "
                        "O(seen states); 0 = every eligible level; "
                        "default 60)")
    c.add_argument("--keep-checkpoints", type=int, default=None,
                   help="retention: keep only the newest N intact "
                        "snapshots/piece groups, deleting older ones "
                        "after each successful write (default keep all)")
    c.add_argument("--supervise", nargs="?", const=3, type=int,
                   default=None, metavar="N",
                   help="crash-resume supervisor (resilience/): run the "
                        "check in a child process and, on a crash exit, "
                        "resume it from the latest intact checkpoint "
                        "with exponential backoff, up to N restarts "
                        "(default 3).  Requires --checkpoint-dir (or the "
                        "CHECKPOINT_DIR directive); emits 'restart' "
                        "events into the JSONL event log")
    c.add_argument("--fault-plan", default=None,
                   help="deterministic fault injection (resilience/"
                        "faults.py), e.g. 'ckpt_torn_write@level=3,"
                        "kill@level=5,oom@grow=1'; FAULT_PLAN env is the "
                        "fallback.  Testing/chaos only")
    c.add_argument("--no-degrade", action="store_true",
                   help="disable graceful OOM degradation (batch "
                        "halving + checkpoint resume on "
                        "RESOURCE_EXHAUSTED) — fail fast instead")
    c.add_argument("--resume", default=None,
                   help="checkpoint .npz to resume from, or 'auto' for the "
                        "latest one in --checkpoint-dir")
    c.add_argument("--spill-dir", default=None,
                   help="memory-map spilled level segments here (TLC's "
                        "disk-backed state queue) instead of host RAM")
    c.add_argument("--trace-dir", default=None,
                   help="shared-filesystem dir for MULTI-HOST trace "
                        "piece exchange (defaults to --checkpoint-dir; "
                        "set this alone to trace multi-host runs "
                        "without periodic snapshots)")
    c.add_argument("--progress-interval", "--progress-seconds",
                   dest="progress_interval", type=float, default=None,
                   help="stderr progress line cadence (TLC's ~per-minute "
                        "report: generated/distinct/rate/queue); 0 "
                        "disables; default 60 (flag > cfg PROGRESS_SECONDS "
                        "directive > default)")
    c.add_argument("--events-out", default=None,
                   help="JSONL run-event log (run_start / level_complete "
                        "with per-phase timings / fpset_resize / spill / "
                        "checkpoint / violation / run_end — see README "
                        "Observability).  Defaults to events.jsonl next "
                        "to --checkpoint-dir when that is set")
    c.add_argument("--metrics-out", default=None,
                   help="write the final metrics-registry snapshot "
                        "(counters/gauges/histograms JSON) here after "
                        "the run")
    c.add_argument("--trace-out", default=None,
                   help="write the run's span timeline (every phase, one "
                        "span per BFS level, the whole run) as Chrome "
                        "trace-event JSON — opens directly in Perfetto / "
                        "chrome://tracing (see README Observability)")
    c.add_argument("--por", action="store_true",
                   help="statically-certified partial-order reduction "
                        "(analysis/por.py): certify ample-set "
                        "certificates for this model in-process and "
                        "mask redundant expansions on device.  "
                        "Conservative: with no provable certificate "
                        "the run is identical to full expansion")
    c.add_argument("--por-table", default=None, metavar="FILE",
                   help="apply a pre-certified POR reduction table "
                        "(`analyze --passes por --por-artifact FILE`); "
                        "fingerprint/model/predicate-coverage checked "
                        "before any mask is applied")
    c.add_argument("--profile-chunks", nargs="?", const=1, type=int,
                   default=None, metavar="N",
                   help="sample every Nth chunk call (default 1 = every "
                        "call) through per-stage programs with device "
                        "fencing: expand / fingerprint / dedup-insert / "
                        "enqueue histograms land in --metrics-out, a "
                        "chunk_profile event in --events-out, and a "
                        "stage-budget table on stderr at run end.  "
                        "Observational: engine results are bit-identical "
                        "with profiling on or off")

    a = sub.add_parser(
        "analyze",
        help="static model analysis (no state-space run): jaxpr effect "
             "extraction, interval lane-overflow proofs, hot-loop lint")
    a.add_argument("cfg", nargs="?", default=None,
                   help="TLC .cfg file; omitted = the seed model "
                        "(3 servers, 2 values, no CONSTRAINT bounds)")
    a.add_argument("--platform", default=None,
                   help="jax platform (default cpu — analysis only "
                        "traces, it never runs the device)")
    a.add_argument("--n-msg-slots", type=int, default=None)
    a.add_argument("--max-log", type=int, default=None)
    a.add_argument("--json", action="store_true",
                   help="print the machine-readable report to stdout "
                        "instead of the text rendering")
    a.add_argument("--out", default=None, metavar="FILE",
                   help="also write the JSON report here (the CI "
                        "artifact)")
    a.add_argument("--allow", action="append", default=[],
                   metavar="CODE[:QUALIFIER]",
                   help="downgrade matching ERROR findings to WARNING "
                        "(kept visible, marked allowlisted; README "
                        "'Static analysis')")
    a.add_argument("--passes", default=None,
                   help="comma-separated subset of effects,bounds,lint,"
                        "por (default: all); an unknown pass name exits "
                        "2 with the valid list")
    a.add_argument("--por-artifact", default=None, metavar="FILE",
                   help="write the POR reduction table (versioned, "
                        "fingerprinted ample_mask + priority) here — "
                        "the artifact `check --por-table` consumes; "
                        "requires the 'por' pass")
    a.add_argument("--shrink-lane", action="append", default=[],
                   metavar="FIELD=HI",
                   help="testing: pretend FIELD's packed lane tops out "
                        "at HI — the bounds pass must then name the "
                        "witness action that overflows it")
    a.add_argument("--events-out", default=None,
                   help="append per-pass 'analysis' events to this "
                        "JSONL log (obs/)")
    a.add_argument("--metrics-out", default=None,
                   help="write the analysis/errors + analysis/warnings "
                        "counter snapshot here")

    s = sub.add_parser("simulate", help="random-trace simulation")
    common(s)
    # Default sized for the BASELINE workload (1M traces x depth 100 ~=
    # 1e8 walker-steps) — minutes on a TPU chip; use --max-seconds or a
    # smaller --num-steps on CPU.
    s.add_argument("--num-steps", type=int, default=1 << 27,
                   help="total walker-steps; default %(default)s (~1e8) is "
                        "sized for a TPU chip and takes hours on CPU — "
                        "pass --max-seconds or a smaller value there")
    s.add_argument("--depth", type=int, default=100)
    s.add_argument("--max-seconds", type=float, default=None,
                   help="wall-clock budget; stops cleanly before "
                        "--num-steps is reached")
    s.add_argument("--metrics-out", default=None,
                   help="write the final metrics-registry snapshot "
                        "(sim phase timers + step counters JSON) here")
    s.add_argument("--trace-out", default=None,
                   help="Chrome trace-event JSON of the walker loop "
                        "(sim_chunk/sim_fetch spans); opens in Perfetto")

    args = p.parse_args(argv)

    if args.cmd == "analyze":
        # Dispatched before the cfg-directive platform sniff below: the
        # cfg is optional here, and analysis defaults to CPU (it only
        # traces — touching the TPU tunnel would be pure startup cost).
        _force_platform(args.platform or "cpu")
        return _run_analyze(args)

    platform = args.platform
    if platform is None:
        # The PLATFORM backend directive must act BEFORE jax initializes,
        # i.e. before the cfg loader (which imports the kernels) runs — so
        # read just that one directive with a self-contained regex.
        import re
        try:
            with open(args.cfg) as f:
                m = re.search(r"^\s*\\\*\s*TPU:\s*PLATFORM\s*=\s*(\S+)",
                              f.read(), flags=re.M | re.I)
            platform = m.group(1) if m else None
        except OSError:
            platform = None
    if platform:
        _force_platform(platform)

    if args.cmd == "check" and args.supervise is not None:
        # Crash-resume supervision (resilience/supervisor.py): re-run
        # this same command in a child process, minus --supervise (the
        # child checks; only the parent supervises) and --resume (the
        # supervisor picks the resume point per attempt).
        from .resilience.supervisor import (run_supervised,
                                            strip_supervisor_flags)
        ckdir, events_out = args.checkpoint_dir, args.events_out
        trace_out = args.trace_out
        if ckdir is None or events_out is None or trace_out is None:
            from .utils.cfg import parse_backend_directives
            try:
                with open(args.cfg) as f:
                    be = parse_backend_directives(f.read())
            except (OSError, ValueError):
                be = {}
            ckdir = ckdir if ckdir is not None else be.get("CHECKPOINT_DIR")
            events_out = (events_out if events_out is not None
                          else be.get("EVENTS_OUT"))
            trace_out = (trace_out if trace_out is not None
                         else be.get("TRACE_OUT"))
        if not ckdir:
            p.error("--supervise requires --checkpoint-dir (or a "
                    "CHECKPOINT_DIR backend directive): crash-resume "
                    "restarts from its snapshots")
        raw = list(argv) if argv is not None else sys.argv[1:]
        child = [sys.executable, "-m", "raft_tla_tpu"] \
            + strip_supervisor_flags(raw)
        # The user's own --resume is honored on the FIRST attempt; the
        # supervisor owns the resume decision for restarts.
        return run_supervised(child, ckdir, max_restarts=args.supervise,
                              events_out=events_out,
                              initial_resume=args.resume,
                              trace_out=trace_out)

    # Persistent compilation cache (utils/platform.py: per-host keyed):
    # repeat CLI runs of the same model skip XLA compilation — which is
    # what makes supervised crash-resume restarts cheap (each restart is
    # a fresh process re-running the same programs).  Enabled below the
    # supervise branch: the supervisor parent only spawns children and
    # must not pay the jax import itself.
    from .utils.platform import enable_persistent_cache
    enable_persistent_cache()

    # Multi-host launch contract (parallel/multihost.py): export
    # RAFT_COORDINATOR / RAFT_NUM_PROCESSES / RAFT_PROCESS_ID and run the
    # SAME command on every host; the process group forms before any
    # device is touched and jax.devices() becomes the global mesh.
    if os.environ.get("RAFT_COORDINATOR"):
        from .parallel import multihost as _mh
        _mh.initialize()
        if args.engine == "single":
            # A per-process single-chip engine inside a process group
            # would run N duplicate full checks; the global mesh is the
            # multi-host mode.
            p.error("multi-host mode (RAFT_COORDINATOR) requires "
                    "--engine mesh or auto")
        args.engine = "mesh"
        if args.cmd == "check" and not args.no_trace:
            # The trace store is per-controller; the engine would refuse
            # anyway — say it in CLI terms.
            p.error("multi-host check requires --no-trace "
                    "(counterexample traces are not multi-host yet)")

    from .engine.bfs import EngineConfig
    from .engine.check import (format_result, initial_states, make_engine)
    from .models.pystate import format_state
    from .utils.cfg import load_config

    setup = load_config(args.cfg, max_log=args.max_log,
                        n_msg_slots=args.n_msg_slots)
    print(f"model: {setup.dims.n_servers} servers "
          f"{tuple(setup.server_names)}, {setup.dims.n_values} values; "
          f"smoke={setup.smoke} invariants={setup.invariants} "
          f"bounds={setup.bounds}"
          + (f" backend={setup.backend}" if setup.backend else ""))

    def resolve(flag, key, default):
        if flag is not None:
            return flag
        return setup.backend.get(key, default)

    batch = resolve(args.batch, "BATCH", 1024)

    if args.cmd == "check":
        cfgobj = EngineConfig(
            batch=batch,
            queue_capacity=resolve(args.queue_capacity,
                                   "QUEUE_CAPACITY", 1 << 20),
            seen_capacity=resolve(args.seen_capacity,
                                  "SEEN_CAPACITY", 1 << 22),
            max_diameter=args.max_diameter, max_seconds=args.max_seconds,
            record_trace=not args.no_trace,
            checkpoint_dir=resolve(args.checkpoint_dir,
                                   "CHECKPOINT_DIR", None),
            checkpoint_every=resolve(args.checkpoint_every,
                                     "CHECKPOINT_EVERY", 1),
            checkpoint_interval_seconds=float(
                resolve(args.checkpoint_interval,
                        "CHECKPOINT_INTERVAL", 60.0)),
            keep_checkpoints=resolve(args.keep_checkpoints,
                                     "KEEP_CHECKPOINTS", None),
            spill_dir=resolve(args.spill_dir, "SPILL_DIR", None),
            trace_dir=resolve(args.trace_dir, "TRACE_DIR", None),
            events_out=resolve(args.events_out, "EVENTS_OUT", None),
            trace_out=resolve(args.trace_out, "TRACE_OUT", None),
            profile_chunks_every=resolve(args.profile_chunks,
                                         "PROFILE_CHUNKS", None),
            pipeline=resolve(args.pipeline, "PIPELINE", "auto"),
            por=bool(resolve(args.por or None, "POR", False)),
            por_table=resolve(args.por_table, "POR_TABLE", None),
            degrade_on_oom=not args.no_degrade,
            progress_interval_seconds=float(
                resolve(args.progress_interval, "PROGRESS_SECONDS", 60.0)))
        # Fault injection (resilience/): the --fault-plan flag or the
        # FAULT_PLAN env a supervisor child inherits.  Fired markers
        # default next to the checkpoints so a restarted child never
        # re-fires a die-class fault at the same level forever.
        from .resilience import faults as _faults
        state_default = (os.path.join(cfgobj.checkpoint_dir,
                                      ".fault_state")
                         if cfgobj.checkpoint_dir else None)
        _faults.install_from_env(default_state_dir=state_default,
                                 text=args.fault_plan)
        engine_cls = args.engine if args.engine == "auto" else None
        if args.engine == "mesh":
            from .parallel.mesh import MeshBFSEngine
            engine_cls = MeshBFSEngine
        engine = make_engine(setup, cfgobj, engine_cls=engine_cls)
        resume = None
        if args.resume:
            if args.resume == "auto":
                if not cfgobj.checkpoint_dir:
                    p.error("--resume auto requires --checkpoint-dir "
                            "(or a CHECKPOINT_DIR backend directive)")
                from .engine import checkpoint as ckpt_mod
                resume = ckpt_mod.latest(cfgobj.checkpoint_dir)
                if resume is None:
                    p.error("--resume auto: no checkpoint found in "
                            f"{cfgobj.checkpoint_dir!r}")
                print(f"resuming from {resume}")
            else:
                resume = args.resume
        res = engine.run(
            initial_states(setup, seed=args.seed) if resume is None else None,
            resume=resume)
        print(format_result(res))
        if args.metrics_out:
            _write_metrics(args.metrics_out, engine.metrics)
        if res.violation is not None:
            if args.no_trace:
                print("\nviolating state (trace recording disabled):")
                print(format_state(res.violation.state, setup.dims))
            else:
                print("\ncounterexample trace:")
                for g, st in engine.replay(res.violation.fingerprint):
                    label = ("Initial state" if g < 0
                             else setup.dims.describe_instance(g))
                    print(f"-- {label}")
                    print(format_state(st, setup.dims))
            return 1
        if res.deadlock is not None:
            print("\ndeadlock state:")
            print(format_state(res.deadlock, setup.dims))
            return 1
        return 0

    # simulate
    from .engine.check import resolve_constraint, resolve_invariants
    use_mesh = args.engine == "mesh"
    if args.engine == "auto":
        import jax
        devs = jax.devices()
        # Multi-process: the global-mesh fleet IS the multi-host mode —
        # anything else would run N duplicate local simulations.
        use_mesh = (jax.process_count() > 1
                    or (len(devs) > 1 and devs[0].platform != "cpu"))
    if use_mesh:
        from .parallel.simulate import MeshSimulator as Simulator
    else:
        from .engine.simulate import Simulator
    sim = Simulator(setup.dims, invariants=resolve_invariants(setup),
                    constraint=resolve_constraint(setup),
                    batch=batch, depth=args.depth,
                    # "v3" is a chunk-tail story; the simulator runs its
                    # v2 (delta) semantics for it (same resolution rule).
                    pipeline=resolve(args.pipeline, "PIPELINE", "auto"))
    # Span tracing (obs/tracing.py): attaching the tracer to the sim's
    # registry mirrors every sim_chunk/sim_fetch phase into the Chrome
    # trace; one top-level span brackets the whole simulation.
    from .obs import SpanTracer
    tracer = SpanTracer(resolve(args.trace_out, "TRACE_OUT", None))
    sim.metrics.tracer = tracer
    max_seconds = (args.max_seconds if args.max_seconds is not None
                   else setup.max_seconds)   # StopAfter duration budget
    with tracer.span("simulate_run", num_steps=args.num_steps,
                     batch=batch, depth=args.depth):
        res = sim.run(initial_states(setup, seed=args.seed),
                      num_steps=args.num_steps, seed=args.seed,
                      max_seconds=max_seconds)
    tracer.write()
    if args.metrics_out:
        _write_metrics(args.metrics_out, sim.metrics)
    print(f"steps visited      {res.steps}")
    print(f"traces             {res.traces}")
    print(f"wall seconds       {res.wall_seconds:.2f}")
    print(f"states/sec         {res.states_per_second:.0f}")
    if res.violation_invariant is not None:
        print(f"VIOLATION          {res.violation_invariant}")
        if res.violation_trace:
            for g, st in res.violation_trace:
                label = ("Initial state" if g < 0
                         else setup.dims.describe_instance(g))
                print(f"-- {label}")
                print(format_state(st, setup.dims))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
