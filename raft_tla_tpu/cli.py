"""Command-line interface — the ``java tlc2.TLC -config X.cfg X.tla`` analog.

    python -m raft_tla_tpu check    <cfg> [engine options]
    python -m raft_tla_tpu simulate <cfg> [--num-steps N --depth D]

Platform selection: by default jax picks the ambient backend (the real TPU
where available).  ``--platform cpu`` forces CPU and must be applied before
jax initializes, which is why all heavy imports here are deferred until
after argument parsing.
"""

from __future__ import annotations

import argparse
import os
import sys


def _write_metrics(path: str, registry) -> None:
    """--metrics-out: final registry snapshot as pretty JSON.  Under a
    process group every controller runs this at exit, so the path gets
    the same per-controller piece suffix as event logs/checkpoints and
    the write is atomic (tmp + rename) — two hosts must never interleave
    into one file on the shared filesystem."""
    import json
    try:
        import jax
        pi, pc = jax.process_index(), jax.process_count()
    except Exception:
        pi, pc = 0, 1
    if pc > 1:
        root, ext = os.path.splitext(path)
        path = f"{root}.p{pi}of{pc}{ext or '.json'}"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp{pi}"
    with open(tmp, "w") as f:
        json.dump(registry.snapshot(), f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _run_analyze(args) -> int:
    """``analyze``: run the static-analysis passes (analysis/) over one
    model and gate on ERROR findings — the per-PR kernel-correctness
    gate CI runs.  Exit 0 iff no (un-allowlisted) ERROR finding."""
    import json

    from .analysis import PASSES, run_analysis
    from .analysis.lane_map import FIELDS
    from .obs import MetricsRegistry, RunEventLog

    passes = None
    if args.passes is not None:
        passes = tuple(p.strip() for p in args.passes.split(",")
                       if p.strip())
        unknown = [p for p in passes if p not in PASSES]
        if unknown or not passes:
            # Exit 2 (usage error), never a silent no-op run: a typo'd
            # pass name must not report "analysis OK" on zero passes.
            print(f"analyze: unknown pass(es) "
                  f"{', '.join(unknown) or '(none given)'}; valid "
                  f"passes: {', '.join(PASSES)}", file=sys.stderr)
            return 2

    if args.cfg is not None:
        from .engine.check import initial_states
        from .utils.cfg import load_config
        setup = load_config(args.cfg, max_log=args.max_log,
                            n_msg_slots=args.n_msg_slots)
        dims, bounds = setup.dims, setup.bounds
        # The cfg's INVARIANT list narrows the POR visibility condition
        # to what this model actually checks.
        invariant_names = list(setup.invariants)
        # Randomized smoke roots say nothing about the reachable set;
        # the bounds pass then seeds from the declared domain envelope.
        roots = None if setup.smoke else initial_states(setup)
    else:
        from .models.dims import RaftDims
        from .models.pystate import init_state
        dims = RaftDims(n_servers=3, n_values=2,
                        max_log=args.max_log or 8,
                        n_msg_slots=args.n_msg_slots or 32)
        bounds, roots, invariant_names = None, [init_state(dims)], None

    lane_caps = {}
    for spec in args.shrink_lane:
        field, _, hi = spec.partition("=")
        if field not in FIELDS or not hi.lstrip("-").isdigit():
            raise SystemExit(
                f"--shrink-lane wants FIELD=HI with FIELD in {FIELDS}, "
                f"got {spec!r}")
        lane_caps[field] = (0, int(hi))

    metrics = MetricsRegistry()
    with RunEventLog(args.events_out) as evlog:
        report = run_analysis(
            dims, bounds=bounds, init_states=roots,
            **({"passes": passes} if passes else {}),
            allowlist=args.allow, lane_caps=lane_caps or None,
            invariant_names=invariant_names,
            metrics=metrics, evlog=evlog)
    if args.out:
        report.write_json(args.out)
    if args.por_artifact:
        table = report.pass_summaries.get("por", {}).get("table")
        if table is None:
            print("--por-artifact requires the 'por' pass to run "
                  "(add it to --passes)", file=sys.stderr)
            return 2
        unsound = any(f.code == "certificate-unsound"
                      for f in report.findings if f.pass_name == "por")
        if unsound:
            # The pass's certificate-unsound self-check failed: never
            # materialize a validly-fingerprinted artifact for a mask
            # whose side conditions did not verify.  Checked on the raw
            # finding code, not post-allowlist severity — --allow can
            # un-gate the EXIT status, never the artifact.
            print("--por-artifact refused: the por pass reported "
                  "certificate-unsound findings (see report)",
                  file=sys.stderr)
        else:
            with open(args.por_artifact, "w") as f:
                json.dump(table, f, indent=2, sort_keys=True)
                f.write("\n")
    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    if args.metrics_out:
        _write_metrics(args.metrics_out, metrics)
    return 0 if report.ok else 1


def _render_watch_line(snap: dict) -> str:
    """One console line per watch snapshot — the TLC-style progress
    shape (obs/flight.py records), annotated with the run context."""
    run = snap.get("run") or {}
    prog = snap.get("progress") or {}
    level = snap.get("level") or {}
    parts = []
    if prog:
        parts.append(
            f"distinct {prog.get('distinct', 0):,} | generated "
            f"{prog.get('generated', 0):,} | diameter "
            f"{prog.get('diameter', 0)} | frontier "
            f"{prog.get('frontier', 0):,} | next "
            f"{prog.get('next_count', 0):,} | elapsed "
            f"{prog.get('elapsed', 0):,.0f}s")
    elif level:
        parts.append(
            f"level {level.get('level')} done | distinct "
            f"{level.get('distinct', 0):,} | generated "
            f"{level.get('generated', 0):,}")
    else:
        parts.append("no telemetry yet")
    ctx = " ".join(str(run[k]) for k in ("engine", "pipeline")
                   if run.get(k))
    live = "live" if snap.get("armed") else "idle"
    job = snap.get("job")
    if job is not None:
        # Per-job watch: the job's registry state leads; ring telemetry
        # (progress) renders only while this job owns the device.
        head = f"job {job['id']} [{job['state']}]"
        if snap.get("running"):
            if prog:
                return f"watch[{head}] {parts[0]}" \
                    + (f"  ({ctx})" if ctx else "")
            return f"watch[{head}] compiling/warming — no progress yet"
        return f"watch[{head}] tenant={job.get('tenant')}"
    return f"watch[{live}] {parts[0]}" + (f"  ({ctx})" if ctx else "")


def _watch_http(url: str, interval: float, count: int, timeout: float,
                as_json: bool) -> int:
    """Poll a --metrics-port listener's /flight endpoint and render a
    console; exits when the watched run's run_end shows up (or after
    --count polls).  Tolerates a listener that is not up YET (the watch
    is usually launched alongside the run) with a bounded retry."""
    import json
    import time
    import urllib.error
    import urllib.request
    base = url.rstrip("/")
    if not base.endswith("/flight"):
        base += "/flight"
    # Watchers render only the newest record per kind — ask the
    # listener to trim (full-ring polls would serialize hundreds of KB
    # per tick under the recorder lock the engine writes through).
    poll_url = base + "?last=8"

    def _refused(exc) -> bool:
        """Connection REFUSED (listener torn down) vs merely slow
        (timeout on a pegged host mid-compilation): only refusal means
        the run process is gone."""
        reason = getattr(exc, "reason", exc)
        return isinstance(reason, ConnectionRefusedError)

    sent = 0
    refused = 0
    attach_end_seq = None
    t_start = time.monotonic()
    t_last_ok = None
    while True:
        try:
            with urllib.request.urlopen(poll_url, timeout=timeout) as r:
                doc = json.loads(r.read().decode())
            refused = 0
            t_last_ok = time.monotonic()
        except (OSError, urllib.error.URLError, ValueError) as e:
            refused = refused + 1 if _refused(e) else 0
            if sent and refused >= 3:
                # The listener answered before and now actively refuses:
                # the run process exited (the CLI tears the listener
                # down at run end) — a completed watch, not a failure.
                # Slow/timed-out polls (host pegged by compilation) do
                # NOT count: the console must ride those out.
                print("watch: listener gone — run process exited",
                      flush=True)
                return 0
            # Give-up budgets are ELAPSED-time based (failure counts
            # would stretch with the per-poll timeout): 300 s of
            # silence after a successful poll, and a generous 600 s
            # for the listener to come up at all — it only binds after
            # jax import + backend init + engine build, which takes
            # minutes on a cold TPU tunnel (the server watch op's
            # in-process grace is shorter, 120 s, because there the
            # backend is already up).
            now = time.monotonic()
            if sent and t_last_ok is not None and now - t_last_ok > 300.0:
                print("watch: listener unresponsive too long; giving up",
                      file=sys.stderr)
                return 1
            if not sent and now - t_start > 600.0:
                print("watch: listener unreachable; giving up",
                      file=sys.stderr)
                return 1
            time.sleep(interval)
            continue
        records = doc.get("records") or {}
        events = records.get("event") or []
        run_ends = [e for e in events if e.get("event") == "run_end"]
        if attach_end_seq is None:
            # First successful poll: note the newest pre-existing
            # run_end so only a run ending AFTER attach closes the
            # console.
            attach_end_seq = run_ends[-1]["seq"] if run_ends else 0
        snap = {
            "armed": bool(doc.get("armed")),
            "run": (records.get("run_context") or [None])[-1],
            "progress": (records.get("progress") or [None])[-1],
            "level": next((e for e in reversed(events)
                           if e.get("event") == "level_complete"), None),
        }
        print(json.dumps(doc, default=str) if as_json
              else _render_watch_line(snap), flush=True)
        sent += 1
        ended = run_ends and run_ends[-1]["seq"] > attach_end_seq
        if ended:
            end = run_ends[-1]
            print(f"watch: run ended — stop_reason="
                  f"{end.get('stop_reason')} distinct="
                  f"{end.get('distinct')} generated="
                  f"{end.get('generated')}", flush=True)
            return 0
        if count and sent >= count:
            return 0
        time.sleep(interval)


def _client_call(target: str, req: dict, timeout: float) -> dict:
    """One request/response line against a checker service (pure
    client, no jax) — the submit/jobs subcommands' transport."""
    import json
    import socket
    host, _, port = target.partition(":")
    with socket.create_connection((host or "127.0.0.1",
                                   int(port or 8610)),
                                  timeout=timeout) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        s.settimeout(timeout)
        f = s.makefile("rb")
        line = f.readline()
    if not line:
        raise OSError("connection closed by server")
    return json.loads(line)


def _run_swarm(args, setup, resolve, batch) -> int:
    """``check --mode swarm``: the randomized-walk tier
    (engine/swarm.py).  Same surface contract as the exhaustive
    branch: a summary line, an optional history-ledger entry
    (``kind=swarm``), and on a violation the rendered TLC-style
    counterexample plus exit 1."""
    from .engine.check import (initial_states, resolve_constraint,
                               resolve_invariants)
    from .engine.swarm import SwarmEngine

    walks = int(resolve(args.walks, "WALKS", 1024))
    ckpt = resolve(args.checkpoint_dir, "CHECKPOINT_DIR", None)
    engine = SwarmEngine(
        setup.dims,
        invariants=resolve_invariants(setup),
        constraint=resolve_constraint(setup),
        walks=walks,
        max_depth=args.max_depth or setup.max_diameter or 128,
        batch=min(batch, walks),
        pipeline=resolve(args.pipeline, "PIPELINE", "auto"),
        events_out=resolve(args.events_out, "EVENTS_OUT", None),
        checkpoint_dir=ckpt,
        counterexample_dir=(
            resolve(args.counterexample_dir, "COUNTEREXAMPLE_DIR", None)
            or ("." if args.render_trace and not ckpt else None)),
        progress_seconds=float(
            resolve(args.progress_interval, "PROGRESS_SECONDS", 5.0)),
        # The BFS branch's observability knobs, swarm dialect: --perf
        # prices the scan-chunk launches, --profile-chunks samples the
        # walk-kernel stages, --xla-profile captures device truth.
        perf=bool(resolve(args.perf or None, "PERF", False)),
        profile_chunks_every=resolve(args.profile_chunks,
                                     "PROFILE_CHUNKS", None),
        xla_profile_chunks=resolve(args.xla_profile, "XLA_PROFILE",
                                   None),
        xla_profile_dir=args.xla_profile_dir)
    max_seconds = (args.max_seconds if args.max_seconds is not None
                   else setup.max_seconds)
    metrics_srv = None
    metrics_port = resolve(args.metrics_port, "METRICS_PORT", None)
    if metrics_port:
        from .obs import start_metrics_server
        from .obs.flight import RECORDER
        try:
            metrics_srv, _ = start_metrics_server(
                int(metrics_port), engine.metrics, flight=RECORDER)
            print(f"metrics: http://127.0.0.1:"
                  f"{metrics_srv.server_address[1]}/metrics "
                  f"(+ /flight)", file=sys.stderr)
        except OSError as e:
            metrics_srv = None
            print(f"metrics: cannot listen on port {metrics_port} "
                  f"({e}); continuing without the listener",
                  file=sys.stderr)
    try:
        res = engine.run(initial_states(setup, seed=args.seed),
                         seed=args.seed, max_seconds=max_seconds)
    finally:
        if metrics_srv is not None:
            metrics_srv.shutdown()
            metrics_srv.server_close()
    print(f"swarm: {res.walks} walks x depth {engine.max_depth} | "
          f"{res.steps} steps ({res.steps_per_second:,.0f} steps/s, "
          f"{res.walks_per_second:,.0f} walks/s) | visited "
          f"{res.visited} | traces {res.traces} | deepest "
          f"{res.diameter} | stop: {res.stop_reason} | "
          f"{res.wall_seconds:.2f}s")
    if res.report.get("hunt"):
        from .obs import hunt as hunt_mod
        print(hunt_mod.render_report(res.report["hunt"]))
    if args.metrics_out:
        _write_metrics(args.metrics_out, engine.metrics)
    history_path = resolve(args.history, "HISTORY", None)
    if history_path:
        from .obs import history as history_mod
        from .obs.flight import host_fingerprint
        with open(args.cfg) as f:
            cfg_text = f.read()
        hunt_sum = None
        if res.report.get("hunt"):
            from .obs import hunt as hunt_mod
            hunt_sum = hunt_mod.summarize(res.report["hunt"])
        history_mod.append_entry(
            history_path,
            history_mod.entry_from_result(
                "swarm", res, cfg_text=cfg_text, dims=setup.dims,
                host_fingerprint=host_fingerprint(),
                label=os.path.basename(args.cfg),
                extra={"swarm": {
                    "walks": res.walks,
                    "steps_per_sec": round(res.steps_per_second, 1),
                    "walks_per_sec": round(res.walks_per_second, 1),
                    "violation_at_seconds": res.violation_at_seconds},
                    "hunt": hunt_sum}))
        print(f"history: entry appended to {history_path}",
              file=sys.stderr)
    if res.violation is not None:
        print()
        if res.counterexample:
            with open(res.counterexample["txt"], encoding="utf-8") as f:
                print(f.read(), end="")
            print(f"\ncounterexample written: "
                  f"{res.counterexample['txt']} (+ .json)")
        else:
            from .engine import explain as explain_mod
            print(explain_mod.render_text(
                engine.replay(res.violation.fingerprint), setup.dims,
                violation=res.violation), end="")
        return 1
    return 0


def _run_submit(args) -> int:
    """``submit``: queue a check on a checker service as an async job
    (serving/).  Sends cfg CONTENT (cfg_text), so the service need not
    share a filesystem with the client.  --wait polls until the job is
    terminal and renders the result."""
    import json
    import time
    try:
        with open(args.cfg, encoding="utf-8") as f:
            cfg_text = f.read()
    except OSError as e:
        print(f"submit: cannot read {args.cfg}: {e}", file=sys.stderr)
        return 2
    inner = {"op": "simulate" if args.simulate else "check",
             "cfg_text": cfg_text}
    if args.trace and not args.simulate:
        inner["trace"] = True
    for key, val in (("batch", args.batch),
                     ("queue_capacity", args.queue_capacity),
                     ("seen_capacity", args.seen_capacity),
                     ("max_diameter", args.max_diameter),
                     ("max_seconds", args.max_seconds),
                     ("seed", args.seed or None),
                     ("engine", args.engine),
                     ("pipeline", args.pipeline),
                     ("mode", getattr(args, "mode", None)),
                     ("walks", getattr(args, "walks", None)),
                     ("max_depth", getattr(args, "max_depth", None)),
                     ("num_steps", getattr(args, "num_steps", None)),
                     ("depth", getattr(args, "depth", None))):
        if val is not None:
            inner[key] = val
    req = {"op": "submit", "tenant": args.tenant, "job": inner}
    if args.cache:
        req["cache"] = True
    if args.slo_seconds is not None:
        req["slo_seconds"] = args.slo_seconds
    try:
        resp = _client_call(args.server, req, args.timeout)
    except (OSError, ValueError) as e:
        print(f"submit: {e}", file=sys.stderr)
        return 1
    if not resp.get("ok"):
        print(f"submit: {resp.get('error')}", file=sys.stderr)
        return 1
    job = resp["job"]
    # With --json stdout is reserved for the final result document
    # (scripts pipe it); the human status lines ride stderr instead.
    status_out = sys.stderr if args.json else sys.stdout
    print(f"job {job['id']} {job['state']} "
          f"(tenant {job['tenant']}, label {job.get('label')})",
          file=status_out)
    if not args.wait:
        return 0
    # The poll loop tolerates transient network errors (a server mid-
    # restart replays its journal and the job resumes): a few failed
    # polls print a note and retry; persistent failure exits cleanly
    # instead of a traceback.
    misses = 0
    while True:
        time.sleep(args.poll_interval)
        try:
            st = _client_call(args.server,
                              {"op": "status", "job_id": job["id"]},
                              args.timeout)
        except (OSError, ValueError) as e:
            misses += 1
            if misses >= 10:
                print(f"submit: lost the server while waiting ({e}); "
                      f"job {job['id']} may still run — poll with "
                      f"'jobs' or 'watch --job'", file=sys.stderr)
                return 1
            print(f"submit: poll failed ({e}); retrying",
                  file=sys.stderr)
            continue
        misses = 0
        if not st.get("ok"):
            print(f"submit: {st.get('error')}", file=sys.stderr)
            return 1
        job = st["job"]
        if job["state"] in ("done", "failed", "cancelled"):
            break
        print(f"job {job['id']} {job['state']}...", file=sys.stderr)
    print(f"job {job['id']} {job['state']} "
          f"(queue_wait {job.get('queue_wait_seconds')}s, run "
          f"{job.get('run_seconds')}s, turnaround "
          f"{job.get('turnaround_seconds')}s"
          + (", cached" if job.get("cached") else "") + ")",
          file=status_out)
    if job["state"] != "done":
        # A cancelled job has no error string — say what happened
        # rather than printing "error: None".
        print(f"job {job['state']}"
              + (f": {job['error']}" if job.get("error") else ""),
              file=sys.stderr)
        return 1
    try:
        res = _client_call(args.server,
                           {"op": "result", "job_id": job["id"]},
                           args.timeout)
    except (OSError, ValueError) as e:
        print(f"submit: cannot fetch result ({e}); job {job['id']} is "
              f"done — retry with the 'result' op", file=sys.stderr)
        return 1
    if not res.get("ok"):
        print(f"submit: {res.get('error')}", file=sys.stderr)
        return 1
    doc = res["result"]
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True, default=str))
    else:
        print(f"distinct {doc.get('distinct')} | generated "
              f"{doc.get('generated')} | diameter "
              f"{doc.get('diameter')} | stop {doc.get('stop_reason')}"
              if "distinct" in doc else json.dumps(doc, default=str))
    violated = doc.get("violation") is not None \
        or doc.get("deadlock") is not None
    return 1 if violated else 0


def _run_jobs(args) -> int:
    """``jobs``: list the service's job registry (one row per job)."""
    req = {"op": "jobs"}
    if args.tenant:
        req["tenant"] = args.tenant
    if args.state:
        req["state"] = args.state
    try:
        resp = _client_call(args.server, req, args.timeout)
    except (OSError, ValueError) as e:
        print(f"jobs: {e}", file=sys.stderr)
        return 1
    if not resp.get("ok"):
        print(f"jobs: {resp.get('error')}", file=sys.stderr)
        return 1
    if args.json:
        import json
        print(json.dumps(resp, indent=2, sort_keys=True, default=str))
        return 0
    print(f"queue {resp['queue_depth']}/{resp.get('queue_capacity')} "
          f"| running {resp['running']} | by_state "
          + " ".join(f"{k}={v}" for k, v in resp["by_state"].items()
                     if v))
    fmt = "{:18s} {:10s} {:9s} {:>9s} {:>8s} {:24s}"
    print(fmt.format("id", "tenant", "state", "wait_s", "run_s",
                     "label"))
    for j in resp["jobs"]:
        def _s(v):
            return f"{v:.2f}" if isinstance(v, (int, float)) else "--"
        print(fmt.format(j["id"], str(j["tenant"])[:10], j["state"],
                         _s(j.get("queue_wait_seconds")),
                         _s(j.get("run_seconds")),
                         str(j.get("label") or "-")[:24])
              + (f"  [{j['error']}]" if j.get("error") else "")
              + (f"  ({j['note']})" if j.get("note") else ""))
    return 0


def _watch_server(target: str, interval: float, count: int,
                  timeout: float, as_json: bool,
                  job: "str | None" = None) -> int:
    """Attach to a checker service's streaming watch op and render each
    snapshot line until the done record."""
    import json
    import socket
    host, _, port = target.partition(":")
    try:
        s = socket.create_connection((host or "127.0.0.1",
                                      int(port or 8610)), timeout=timeout)
    except OSError as e:
        print(f"watch: cannot connect to {target}: {e}", file=sys.stderr)
        return 1
    with s:
        req = {"op": "watch", "interval": interval, "count": count}
        if job:
            req["job"] = job
        s.sendall((json.dumps(req) + "\n").encode())
        # Snapshot lines arrive one per interval — reads must outlast it.
        s.settimeout(max(timeout, interval * 3 + 5))
        f = s.makefile("rb")
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not rec.get("ok"):
                print(f"watch: {rec.get('error')}", file=sys.stderr)
                return 1
            if rec.get("done"):
                end = rec.get("run_end") or {}
                j = rec.get("job")
                if j is not None:
                    if rec.get("evicted"):
                        # Terminal-retention eviction raced the watch:
                        # the job reached a terminal state (only
                        # terminal jobs are evicted) but the final
                        # summary is gone; the last-seen state may be
                        # stale, so do not report it as the outcome.
                        print(f"watch: job {j['id']} completed and "
                              f"was evicted from the registry "
                              f"(retention cap); last seen "
                              f"{j['state']}", flush=True)
                        return 0
                    if rec.get("truncated") \
                            and j["state"] not in ("done", "failed",
                                                   "cancelled"):
                        print(f"watch: stream truncated after "
                              f"{rec.get('snapshots')} snapshot(s) — "
                              f"job {j['id']} still {j['state']}; "
                              f"re-attach to keep watching",
                              file=sys.stderr, flush=True)
                        return 1
                    print(f"watch: job {j['id']} {j['state']} after "
                          f"{rec.get('snapshots')} snapshot(s)"
                          + (f" — {j['error']}" if j.get("error")
                             else ""), flush=True)
                    return 0 if j["state"] == "done" else 1
                print(f"watch: done after {rec.get('snapshots')} "
                      f"snapshot(s)"
                      + (f" — stop_reason={end.get('stop_reason')} "
                         f"distinct={end.get('distinct')}"
                         if end else ""), flush=True)
                return 0
            print(json.dumps(rec, default=str) if as_json
                  else _render_watch_line(rec.get("watch") or {}),
                  flush=True)
    print("watch: connection closed by server", file=sys.stderr)
    return 1


def _run_watch(args) -> int:
    """``watch``: run attach.  No jax, no cfg — pure client."""
    if args.target.startswith("http://") \
            or args.target.startswith("https://"):
        if args.job:
            print("watch: --job needs a checker service target "
                  "(HOST:PORT) — the HTTP /flight listener has no job "
                  "registry", file=sys.stderr)
            return 2
        return _watch_http(args.target, args.interval, args.count,
                           args.timeout, args.json)
    return _watch_server(args.target, args.interval, args.count,
                         args.timeout, args.json, job=args.job)


def _select_engine_cls(engine_arg: str):
    """--engine -> make_engine's engine_cls: "auto" passes through (mesh
    iff >1 accelerator device), "mesh" forces the mesh class, "single"
    the default BFSEngine.  One copy for check and explain — the
    selection rule must not fork per subcommand."""
    if engine_arg == "mesh":
        from .parallel.mesh import MeshBFSEngine
        return MeshBFSEngine
    return "auto" if engine_arg == "auto" else None


def _force_platform(platform: str):
    if platform == "cpu":
        from .utils.platform import force_cpu
        force_cpu()
        return
    os.environ["JAX_PLATFORMS"] = platform
    import jax
    jax.config.update("jax_platforms", platform)


def main(argv=None):
    p = argparse.ArgumentParser(prog="raft_tla_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    # Flags default to None so the resolution chain is visible at the use
    # sites: CLI flag > cfg "\* TPU:" backend directive > built-in default.
    def common(sp):
        sp.add_argument("cfg", help="TLC .cfg file (e.g. MCraft.cfg)")
        sp.add_argument("--platform", default=None,
                        help="jax platform override (e.g. cpu)")
        sp.add_argument("--batch", type=int, default=None)
        sp.add_argument("--n-msg-slots", type=int, default=None)
        sp.add_argument("--max-log", type=int, default=None)
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--engine", choices=("single", "mesh", "auto"),
                        default="auto",
                        help="mesh = shard over all visible devices (TLC "
                             "-workers / distributed TLC analog); auto = "
                             "mesh iff >1 accelerator device (default)")
        sp.add_argument("--pipeline",
                        choices=("auto", "v1", "v2", "v3", "v4"),
                        default=None,
                        help="successor pipeline: v1 = classical expand, "
                             "v2 = delta (guards-only masks + delta "
                             "fingerprints), v3 = fused Pallas chunk "
                             "(VMEM-resident compact + probe/insert->"
                             "enqueue tail), v4 = whole-chunk VMEM "
                             "megakernel (masks+POR+compact+fingerprint "
                             "in ONE launch, then the v3 fused tail; "
                             "per-stage XLA fallback, interpret mode "
                             "off-TPU).  auto = v2 where it applies "
                             "(default; flag > cfg PIPELINE directive "
                             "> auto)")

    c = sub.add_parser("check", help="exhaustive BFS check")
    common(c)
    c.add_argument("--queue-capacity", type=int, default=None)
    c.add_argument("--seen-capacity", type=int, default=None)
    c.add_argument("--max-diameter", type=int, default=None)
    c.add_argument("--max-seconds", type=float, default=None)
    c.add_argument("--mode", choices=("exhaustive", "swarm"),
                   default=None,
                   help="checking tier: exhaustive BFS (default) or the "
                        "vmap'd randomized-walk swarm — W deterministic "
                        "walks per device, per-walk ring dedup, no "
                        "global seen-set (engine/swarm.py; flag > cfg "
                        "MODE directive > exhaustive)")
    c.add_argument("--walks", type=int, default=None,
                   help="swarm mode: concurrent walks per device (flag "
                        "> cfg WALKS directive > 1024)")
    c.add_argument("--max-depth", type=int, default=None,
                   help="swarm mode: per-trace depth bound before a "
                        "walk restarts onto a fresh root (default 128)")
    c.add_argument("--no-trace", action="store_true",
                   help="disable counterexample trace recording")
    c.add_argument("--checkpoint-dir", default=None,
                   help="write level-boundary snapshots here (TLC states/)")
    c.add_argument("--checkpoint-every", type=int, default=None,
                   help="snapshot every k BFS levels (default 1)")
    c.add_argument("--checkpoint-interval", type=float, default=None,
                   help="min seconds between snapshots (snapshot cost is "
                        "O(seen states); 0 = every eligible level; "
                        "default 60)")
    c.add_argument("--keep-checkpoints", type=int, default=None,
                   help="retention: keep only the newest N intact "
                        "snapshots/piece groups, deleting older ones "
                        "after each successful write (default keep all)")
    c.add_argument("--supervise", nargs="?", const=3, type=int,
                   default=None, metavar="N",
                   help="crash-resume supervisor (resilience/): run the "
                        "check in a child process and, on a crash exit, "
                        "resume it from the latest intact checkpoint "
                        "with exponential backoff, up to N restarts "
                        "(default 3).  Requires --checkpoint-dir (or the "
                        "CHECKPOINT_DIR directive); emits 'restart' "
                        "events into the JSONL event log")
    c.add_argument("--fault-plan", default=None,
                   help="deterministic fault injection (resilience/"
                        "faults.py), e.g. 'ckpt_torn_write@level=3,"
                        "kill@level=5,oom@grow=1'; FAULT_PLAN env is the "
                        "fallback.  Testing/chaos only")
    c.add_argument("--no-degrade", action="store_true",
                   help="disable graceful OOM degradation (batch "
                        "halving + checkpoint resume on "
                        "RESOURCE_EXHAUSTED) — fail fast instead")
    c.add_argument("--resume", default=None,
                   help="checkpoint .npz to resume from, or 'auto' for the "
                        "latest one in --checkpoint-dir")
    c.add_argument("--spill-dir", default=None,
                   help="memory-map spilled level segments here (TLC's "
                        "disk-backed state queue) instead of host RAM")
    c.add_argument("--trace-dir", default=None,
                   help="shared-filesystem dir for MULTI-HOST trace "
                        "piece exchange (defaults to --checkpoint-dir; "
                        "set this alone to trace multi-host runs "
                        "without periodic snapshots)")
    c.add_argument("--progress-interval", "--progress-seconds",
                   dest="progress_interval", type=float, default=None,
                   help="stderr progress line cadence (TLC's ~per-minute "
                        "report: generated/distinct/rate/queue); 0 "
                        "disables; default 60 (flag > cfg PROGRESS_SECONDS "
                        "directive > default)")
    c.add_argument("--events-out", default=None,
                   help="JSONL run-event log (run_start / level_complete "
                        "with per-phase timings / fpset_resize / spill / "
                        "checkpoint / violation / run_end — see README "
                        "Observability).  Defaults to events.jsonl next "
                        "to --checkpoint-dir when that is set")
    c.add_argument("--metrics-out", default=None,
                   help="write the final metrics-registry snapshot "
                        "(counters/gauges/histograms JSON) here after "
                        "the run")
    c.add_argument("--trace-out", default=None,
                   help="write the run's span timeline (every phase, one "
                        "span per BFS level, the whole run) as Chrome "
                        "trace-event JSON — opens directly in Perfetto / "
                        "chrome://tracing (see README Observability)")
    c.add_argument("--por", action="store_true",
                   help="statically-certified partial-order reduction "
                        "(analysis/por.py): certify ample-set "
                        "certificates for this model in-process and "
                        "mask redundant expansions on device.  "
                        "Conservative: with no provable certificate "
                        "the run is identical to full expansion")
    c.add_argument("--por-table", default=None, metavar="FILE",
                   help="apply a pre-certified POR reduction table "
                        "(`analyze --passes por --por-artifact FILE`); "
                        "fingerprint/model/predicate-coverage checked "
                        "before any mask is applied")
    c.add_argument("--profile-chunks", nargs="?", const=1, type=int,
                   default=None, metavar="N",
                   help="sample every Nth chunk call (default 1 = every "
                        "call) through per-stage programs with device "
                        "fencing: expand / fingerprint / dedup-insert / "
                        "enqueue histograms land in --metrics-out, a "
                        "chunk_profile event in --events-out, and a "
                        "stage-budget table on stderr at run end.  "
                        "Observational: engine results are bit-identical "
                        "with profiling on or off")
    c.add_argument("--perf", action="store_true",
                   help="performance observatory (obs/perf.py): launch "
                        "accounting over the real traced chunk program, "
                        "static roofline with achieved-bandwidth "
                        "fractions per chunk stage, and the fusion "
                        "advisor naming the next fusion target — a "
                        "'perf' event in --events-out, perf/* gauges, "
                        "and a run-end table.  Implies --profile-chunks "
                        "16 when no cadence is set.  Observational: "
                        "engine results are bit-identical with perf on "
                        "or off.  PERF directive is the cfg fallback")
    c.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve live telemetry over HTTP on 127.0.0.1:"
                        "PORT for the duration of the run: /metrics is "
                        "Prometheus text exposition of the engine's "
                        "registry (point a scraper here), /flight is "
                        "the flight-recorder ring as JSON (what "
                        "`python -m raft_tla_tpu watch http://...` "
                        "polls).  METRICS_PORT directive is the cfg "
                        "fallback")
    c.add_argument("--xla-profile", nargs="?", const=8, type=int,
                   default=None, metavar="N",
                   help="device-profiler capture (jax.profiler): trace "
                        "the first N chunk calls (default 8) into "
                        "--xla-profile-dir — XPlane protos + a "
                        "Perfetto-openable trace of the actual "
                        "XLA/Mosaic kernels, correlated with the "
                        "--trace-out host spans via the shared 'chunk' "
                        "span name.  Observational: results are "
                        "bit-identical with the capture on or off.  "
                        "XLA_PROFILE directive is the cfg fallback")
    c.add_argument("--xla-profile-dir", default=None, metavar="DIR",
                   help="where --xla-profile artifacts land (default: "
                        "<--checkpoint-dir>/xla_profile, else "
                        "./xla_profile)")
    c.add_argument("--render-trace", action="store_true",
                   help="force writing counterexample.{txt,json} even "
                        "with no --counterexample-dir/--checkpoint-dir "
                        "configured (falls back to the current "
                        "directory).  The TLC-style rendered trace "
                        "(numbered states, action names, changed-field "
                        "diffs; engine/explain.py) is printed on every "
                        "traced violation regardless")
    c.add_argument("--counterexample-dir", default=None, metavar="DIR",
                   help="where a traced violation's rendered "
                        "counterexample.{txt,json} land automatically "
                        "(default: --checkpoint-dir; neither set = no "
                        "auto-write unless --render-trace forces one "
                        "into the current directory)")
    c.add_argument("--no-report", action="store_true",
                   help="disable the TLC-parity statespace run report "
                        "(obs/report.py: collision probability, "
                        "per-level table, out-degree, seen-set load; "
                        "REPORT directive is the cfg fallback).  "
                        "Observational either way — engine counts are "
                        "bit-identical report on or off")
    c.add_argument("--history", default=None, metavar="FILE",
                   help="append one run-history ledger entry (JSONL; "
                        "obs/history.py: cfg/model/host fingerprints, "
                        "verdict, counts, rates, report summary) after "
                        "the run.  HISTORY directive is the cfg "
                        "fallback; scripts/bench_history.py renders the "
                        "trajectory")

    a = sub.add_parser(
        "analyze",
        help="static model analysis (no state-space run): jaxpr effect "
             "extraction, interval lane-overflow proofs, hot-loop lint")
    a.add_argument("cfg", nargs="?", default=None,
                   help="TLC .cfg file; omitted = the seed model "
                        "(3 servers, 2 values, no CONSTRAINT bounds)")
    a.add_argument("--platform", default=None,
                   help="jax platform (default cpu — analysis only "
                        "traces, it never runs the device)")
    a.add_argument("--n-msg-slots", type=int, default=None)
    a.add_argument("--max-log", type=int, default=None)
    a.add_argument("--json", action="store_true",
                   help="print the machine-readable report to stdout "
                        "instead of the text rendering")
    a.add_argument("--out", default=None, metavar="FILE",
                   help="also write the JSON report here (the CI "
                        "artifact)")
    a.add_argument("--allow", action="append", default=[],
                   metavar="CODE[:QUALIFIER]",
                   help="downgrade matching ERROR findings to WARNING "
                        "(kept visible, marked allowlisted; README "
                        "'Static analysis')")
    a.add_argument("--passes", default=None,
                   help="comma-separated subset of effects,bounds,lint,"
                        "por (default: all); prerequisite passes are "
                        "added automatically (por/lint pull in "
                        "effects); an unknown pass name exits 2 with "
                        "the valid list")
    a.add_argument("--por-artifact", default=None, metavar="FILE",
                   help="write the POR reduction table (versioned, "
                        "fingerprinted ample_mask + priority) here — "
                        "the artifact `check --por-table` consumes; "
                        "requires the 'por' pass")
    a.add_argument("--shrink-lane", action="append", default=[],
                   metavar="FIELD=HI",
                   help="testing: pretend FIELD's packed lane tops out "
                        "at HI — the bounds pass must then name the "
                        "witness action that overflows it")
    a.add_argument("--events-out", default=None,
                   help="append per-pass 'analysis' events to this "
                        "JSONL log (obs/)")
    a.add_argument("--metrics-out", default=None,
                   help="write the analysis/errors + analysis/warnings "
                        "counter snapshot here")

    e = sub.add_parser(
        "explain",
        help="run a check and render its counterexample the TLC way "
             "(numbered states with action names and changed-field "
             "diffs; text/json/html — engine/explain.py), and/or "
             "export the full reached state graph of a small space "
             "as DOT/GraphML")
    common(e)
    e.add_argument("--format", choices=("text", "json", "html"),
                   default="text",
                   help="counterexample rendering (default text — the "
                        "TLC numbered-state error trace)")
    e.add_argument("--out", default=None, metavar="FILE",
                   help="write the rendering here instead of stdout")
    e.add_argument("--max-diameter", type=int, default=None)
    e.add_argument("--max-seconds", type=float, default=None)
    e.add_argument("--queue-capacity", type=int, default=None)
    e.add_argument("--seen-capacity", type=int, default=None)
    e.add_argument("--graph", default=None, metavar="FILE",
                   help="ALSO export the full reached state graph from "
                        "the trace store (one node per fingerprint, one "
                        "edge per recorded discovery) — small spaces "
                        "only (see --graph-cap)")
    e.add_argument("--graph-format", choices=("dot", "graphml"),
                   default=None,
                   help="graph dialect (default: from the --graph file "
                        "extension, .graphml/.xml = GraphML, else DOT)")
    e.add_argument("--graph-cap", type=int, default=None,
                   help="refuse to export graphs larger than this many "
                        "states (default 50000); raise deliberately for "
                        "bigger spaces")

    # -- serving-layer clients (no jax, no cfg parse: pure sockets) ----
    sb = sub.add_parser(
        "submit",
        help="queue a check on a checker service as an async job "
             "(serving/): bounded admission, per-tenant fair "
             "scheduling, per-job event log + metrics; returns the "
             "job id (or --wait for the result)")
    sb.add_argument("cfg", help="TLC .cfg file (content is sent, so "
                                "the service needs no shared "
                                "filesystem)")
    sb.add_argument("--server", default="127.0.0.1:8610",
                    help="HOST:PORT of the checker service "
                         "(default %(default)s)")
    sb.add_argument("--tenant", default=None,
                    help="tenant id for fair scheduling + per-tenant "
                         "metrics (default: 'default')")
    sb.add_argument("--batch", type=int, default=None)
    sb.add_argument("--queue-capacity", type=int, default=None)
    sb.add_argument("--seen-capacity", type=int, default=None)
    sb.add_argument("--max-diameter", type=int, default=None)
    sb.add_argument("--max-seconds", type=float, default=None)
    sb.add_argument("--seed", type=int, default=0)
    sb.add_argument("--engine", choices=("single", "mesh", "auto"),
                    default=None)
    sb.add_argument("--pipeline",
                    choices=("auto", "v1", "v2", "v3", "v4"),
                    default=None)
    sb.add_argument("--trace", action="store_true",
                    help="record the counterexample trace (the server "
                         "default is off, like the check op): a "
                         "violating job's result then carries the "
                         "replayed numbered-state trace")
    sb.add_argument("--simulate", action="store_true",
                    help="submit a simulate job instead of a check")
    sb.add_argument("--mode", choices=("exhaustive", "swarm"),
                    default=None,
                    help="check-job tier: exhaustive BFS (default) or "
                         "the randomized-walk swarm — the cheap "
                         "high-QPS tier (engine/swarm.py)")
    sb.add_argument("--walks", type=int, default=None,
                    help="(swarm jobs) concurrent walks per device")
    sb.add_argument("--max-depth", type=int, default=None,
                    help="(swarm jobs) per-trace depth bound")
    sb.add_argument("--num-steps", type=int, default=None,
                    help="(simulate/swarm jobs) total walker-steps")
    sb.add_argument("--depth", type=int, default=None,
                    help="(simulate jobs) trace depth")
    sb.add_argument("--cache", action="store_true",
                    help="serve a repeat submission from the "
                         "fingerprint-keyed result cache (refused for "
                         "--max-seconds jobs — a truncated run is not "
                         "reusable)")
    sb.add_argument("--slo-seconds", type=float, default=None,
                    help="per-job turnaround SLO target (feeds the "
                         "jobs/slo_ok|slo_miss per-tenant counters; "
                         "default: the server's)")
    sb.add_argument("--wait", action="store_true",
                    help="poll until the job is terminal and print the "
                         "result (exit 1 on violation/failure)")
    sb.add_argument("--poll-interval", type=float, default=1.0)
    sb.add_argument("--timeout", type=float, default=15.0)
    sb.add_argument("--json", action="store_true",
                    help="print the full result JSON (with --wait)")

    jl = sub.add_parser(
        "jobs",
        help="list a checker service's job registry (queue depth, "
             "by-state counts, one row per job)")
    jl.add_argument("--server", default="127.0.0.1:8610",
                    help="HOST:PORT of the checker service "
                         "(default %(default)s)")
    jl.add_argument("--tenant", default=None,
                    help="only this tenant's jobs")
    jl.add_argument("--state", default=None,
                    help="only jobs in this state (queued/admitted/"
                         "running/done/failed/cancelled)")
    jl.add_argument("--timeout", type=float, default=15.0)
    jl.add_argument("--json", action="store_true")

    w = sub.add_parser(
        "watch",
        help="attach a live console to a running check (run attach): "
             "stream progress/coverage/fused-stage snapshots from a "
             "checker service's watch op, or poll a --metrics-port "
             "listener's /flight endpoint; --job scopes the stream to "
             "one async job")
    w.add_argument("target", nargs="?", default="127.0.0.1:8610",
                   help="HOST:PORT of a checker service (default "
                        "%(default)s), or http://HOST:PORT of a "
                        "--metrics-port listener")
    w.add_argument("--job", default=None, metavar="JOB_ID",
                   help="watch ONE async job (serving/): job state "
                        "snapshots while it queues, ring progress "
                        "while it runs, closed by its terminal state "
                        "— never reaped as idle while the job is "
                        "alive (exit 0 done, 1 failed/cancelled)")
    w.add_argument("--interval", type=float, default=2.0,
                   help="seconds between snapshots (default 2)")
    w.add_argument("--count", type=int, default=0,
                   help="snapshots before exiting; 0 (default) = until "
                        "the watched run ends")
    w.add_argument("--timeout", type=float, default=15.0,
                   help="connect/read timeout per request (default 15)")
    w.add_argument("--json", action="store_true",
                   help="print raw snapshot JSON lines instead of the "
                        "rendered console lines")

    s = sub.add_parser("simulate", help="random-trace simulation")
    common(s)
    # Default sized for the BASELINE workload (1M traces x depth 100 ~=
    # 1e8 walker-steps) — minutes on a TPU chip; use --max-seconds or a
    # smaller --num-steps on CPU.
    s.add_argument("--num-steps", type=int, default=1 << 27,
                   help="total walker-steps; default %(default)s (~1e8) is "
                        "sized for a TPU chip and takes hours on CPU — "
                        "pass --max-seconds or a smaller value there")
    s.add_argument("--depth", type=int, default=100)
    s.add_argument("--max-seconds", type=float, default=None,
                   help="wall-clock budget; stops cleanly before "
                        "--num-steps is reached")
    s.add_argument("--metrics-out", default=None,
                   help="write the final metrics-registry snapshot "
                        "(sim phase timers + step counters JSON) here")
    s.add_argument("--trace-out", default=None,
                   help="Chrome trace-event JSON of the walker loop "
                        "(sim_chunk/sim_fetch spans); opens in Perfetto")

    args = p.parse_args(argv)

    if args.cmd == "watch":
        # Pure client: no jax, no cfg, no platform — dispatched before
        # any heavy import so the console attaches instantly even while
        # the engine process owns the machine.
        return _run_watch(args)

    if args.cmd == "submit":
        return _run_submit(args)     # pure client, like watch

    if args.cmd == "jobs":
        return _run_jobs(args)       # pure client, like watch

    if args.cmd == "analyze":
        # Dispatched before the cfg-directive platform sniff below: the
        # cfg is optional here, and analysis defaults to CPU (it only
        # traces — touching the TPU tunnel would be pure startup cost).
        _force_platform(args.platform or "cpu")
        return _run_analyze(args)

    platform = args.platform
    if platform is None:
        # The PLATFORM backend directive must act BEFORE jax initializes,
        # i.e. before the cfg loader (which imports the kernels) runs — so
        # read just that one directive with a self-contained regex.
        import re
        try:
            with open(args.cfg) as f:
                m = re.search(r"^\s*\\\*\s*TPU:\s*PLATFORM\s*=\s*(\S+)",
                              f.read(), flags=re.M | re.I)
            platform = m.group(1) if m else None
        except OSError:
            platform = None
    if platform:
        _force_platform(platform)

    if args.cmd == "check" and args.supervise is not None:
        # Crash-resume supervision (resilience/supervisor.py): re-run
        # this same command in a child process, minus --supervise (the
        # child checks; only the parent supervises) and --resume (the
        # supervisor picks the resume point per attempt).
        from .resilience.supervisor import (run_supervised,
                                            strip_supervisor_flags)
        ckdir, events_out = args.checkpoint_dir, args.events_out
        trace_out = args.trace_out
        if ckdir is None or events_out is None or trace_out is None:
            from .utils.cfg import parse_backend_directives
            try:
                with open(args.cfg) as f:
                    be = parse_backend_directives(f.read())
            except (OSError, ValueError):
                be = {}
            ckdir = ckdir if ckdir is not None else be.get("CHECKPOINT_DIR")
            events_out = (events_out if events_out is not None
                          else be.get("EVENTS_OUT"))
            trace_out = (trace_out if trace_out is not None
                         else be.get("TRACE_OUT"))
        if not ckdir:
            p.error("--supervise requires --checkpoint-dir (or a "
                    "CHECKPOINT_DIR backend directive): crash-resume "
                    "restarts from its snapshots")
        raw = list(argv) if argv is not None else sys.argv[1:]
        child = [sys.executable, "-m", "raft_tla_tpu"] \
            + strip_supervisor_flags(raw)
        # The user's own --resume is honored on the FIRST attempt; the
        # supervisor owns the resume decision for restarts.
        return run_supervised(child, ckdir, max_restarts=args.supervise,
                              events_out=events_out,
                              initial_resume=args.resume,
                              trace_out=trace_out)

    # Persistent compilation cache (utils/platform.py: per-host keyed):
    # repeat CLI runs of the same model skip XLA compilation — which is
    # what makes supervised crash-resume restarts cheap (each restart is
    # a fresh process re-running the same programs).  Enabled below the
    # supervise branch: the supervisor parent only spawns children and
    # must not pay the jax import itself.
    from .utils.platform import enable_persistent_cache
    enable_persistent_cache()

    # Multi-host launch contract (parallel/multihost.py): export
    # RAFT_COORDINATOR / RAFT_NUM_PROCESSES / RAFT_PROCESS_ID and run the
    # SAME command on every host; the process group forms before any
    # device is touched and jax.devices() becomes the global mesh.
    if os.environ.get("RAFT_COORDINATOR"):
        from .parallel import multihost as _mh
        _mh.initialize()
        if args.engine == "single":
            # A per-process single-chip engine inside a process group
            # would run N duplicate full checks; the global mesh is the
            # multi-host mode.
            p.error("multi-host mode (RAFT_COORDINATOR) requires "
                    "--engine mesh or auto")
        args.engine = "mesh"
        if args.cmd == "check" and not args.no_trace:
            # The trace store is per-controller; the engine would refuse
            # anyway — say it in CLI terms.
            p.error("multi-host check requires --no-trace "
                    "(counterexample traces are not multi-host yet)")

    from .engine.bfs import EngineConfig
    from .engine.check import (format_result, initial_states, make_engine)
    from .models.pystate import format_state
    from .utils.cfg import load_config

    setup = load_config(args.cfg, max_log=args.max_log,
                        n_msg_slots=args.n_msg_slots)
    print(f"model: {setup.dims.n_servers} servers "
          f"{tuple(setup.server_names)}, {setup.dims.n_values} values; "
          f"smoke={setup.smoke} invariants={setup.invariants} "
          f"bounds={setup.bounds}"
          + (f" backend={setup.backend}" if setup.backend else ""))

    def resolve(flag, key, default):
        if flag is not None:
            return flag
        return setup.backend.get(key, default)

    batch = resolve(args.batch, "BATCH", 1024)

    if args.cmd == "explain":
        # Counterexample explainer (engine/explain.py): run the check
        # with trace recording FORCED on, then render the violation as
        # TLC-style numbered states (and/or export the reached graph).
        import json as _json

        from .engine import explain as explain_mod
        cfgobj = EngineConfig(
            batch=batch,
            queue_capacity=resolve(args.queue_capacity,
                                   "QUEUE_CAPACITY", 1 << 20),
            seen_capacity=resolve(args.seen_capacity,
                                  "SEEN_CAPACITY", 1 << 22),
            max_diameter=args.max_diameter, max_seconds=args.max_seconds,
            record_trace=True,
            pipeline=resolve(args.pipeline, "PIPELINE", "auto"))
        engine = make_engine(setup, cfgobj,
                             engine_cls=_select_engine_cls(args.engine))
        res = engine.run(initial_states(setup, seed=args.seed))
        rc = 0
        if res.violation is not None:
            steps = engine.replay(res.violation.fingerprint)
            if args.format == "text":
                doc = explain_mod.render_text(steps, setup.dims,
                                              violation=res.violation)
            elif args.format == "json":
                doc = _json.dumps(
                    explain_mod.render_json(steps, setup.dims,
                                            violation=res.violation),
                    indent=2, sort_keys=True) + "\n"
            else:
                doc = explain_mod.render_html(
                    steps, setup.dims, violation=res.violation,
                    title=f"counterexample: {res.violation.invariant}")
            if args.out:
                with open(args.out, "w", encoding="utf-8") as f:
                    f.write(doc)
                print(f"counterexample ({args.format}, {len(steps)} "
                      f"states) -> {args.out}")
            else:
                print(doc, end="")
            rc = 1            # same exit contract as check-on-violation
        else:
            print(format_result(res))
            print("no violation found; nothing to explain"
                  + (" (graph still exported)" if args.graph else ""))
        if args.graph:
            fmt = args.graph_format or (
                "graphml" if args.graph.endswith((".graphml", ".xml"))
                else "dot")
            try:
                text = explain_mod.export_graph(
                    engine.trace, setup.dims, fmt=fmt,
                    cap=(args.graph_cap
                         if args.graph_cap is not None
                         else explain_mod.GRAPH_CAP_DEFAULT))
            except ValueError as exc:
                print(f"explain: {exc}", file=sys.stderr)
                # A found-and-rendered violation keeps its exit-1
                # contract (same as check) — only a graph failure with
                # nothing else to report is a usage error.
                return rc or 2
            with open(args.graph, "w", encoding="utf-8") as f:
                f.write(text)
            print(f"state graph ({fmt}, {len(engine.trace)} recorded "
                  f"states) -> {args.graph}")
        return rc

    if args.cmd == "check":
        mode = resolve(args.mode, "MODE", "exhaustive")
        if mode not in ("exhaustive", "swarm"):
            p.error(f"MODE must be exhaustive or swarm, got {mode!r}")
        if mode == "swarm":
            return _run_swarm(args, setup, resolve, batch)
        cfgobj = EngineConfig(
            batch=batch,
            queue_capacity=resolve(args.queue_capacity,
                                   "QUEUE_CAPACITY", 1 << 20),
            seen_capacity=resolve(args.seen_capacity,
                                  "SEEN_CAPACITY", 1 << 22),
            max_diameter=args.max_diameter, max_seconds=args.max_seconds,
            record_trace=not args.no_trace,
            checkpoint_dir=resolve(args.checkpoint_dir,
                                   "CHECKPOINT_DIR", None),
            checkpoint_every=resolve(args.checkpoint_every,
                                     "CHECKPOINT_EVERY", 1),
            checkpoint_interval_seconds=float(
                resolve(args.checkpoint_interval,
                        "CHECKPOINT_INTERVAL", 60.0)),
            keep_checkpoints=resolve(args.keep_checkpoints,
                                     "KEEP_CHECKPOINTS", None),
            spill_dir=resolve(args.spill_dir, "SPILL_DIR", None),
            trace_dir=resolve(args.trace_dir, "TRACE_DIR", None),
            events_out=resolve(args.events_out, "EVENTS_OUT", None),
            trace_out=resolve(args.trace_out, "TRACE_OUT", None),
            profile_chunks_every=resolve(args.profile_chunks,
                                         "PROFILE_CHUNKS", None),
            xla_profile_chunks=resolve(args.xla_profile,
                                       "XLA_PROFILE", None),
            xla_profile_dir=args.xla_profile_dir,
            pipeline=resolve(args.pipeline, "PIPELINE", "auto"),
            por=bool(resolve(args.por or None, "POR", False)),
            por_table=resolve(args.por_table, "POR_TABLE", None),
            perf=bool(resolve(args.perf or None, "PERF", False)),
            degrade_on_oom=not args.no_degrade,
            statespace_report=(False if args.no_report
                               else bool(resolve(None, "REPORT", True))),
            # Auto-render workdir for counterexample.{txt,json}: flag >
            # directive > checkpoint dir (engine default); with none of
            # those, --render-trace forces the current directory so the
            # rendering it promises always lands somewhere.
            counterexample_dir=(
                resolve(args.counterexample_dir, "COUNTEREXAMPLE_DIR",
                        None)
                or ("." if args.render_trace
                    and not resolve(args.checkpoint_dir,
                                    "CHECKPOINT_DIR", None) else None)),
            progress_interval_seconds=float(
                resolve(args.progress_interval, "PROGRESS_SECONDS", 60.0)))
        # Fault injection (resilience/): the --fault-plan flag or the
        # FAULT_PLAN env a supervisor child inherits.  Fired markers
        # default next to the checkpoints so a restarted child never
        # re-fires a die-class fault at the same level forever.
        from .resilience import faults as _faults
        state_default = (os.path.join(cfgobj.checkpoint_dir,
                                      ".fault_state")
                         if cfgobj.checkpoint_dir else None)
        _faults.install_from_env(default_state_dir=state_default,
                                 text=args.fault_plan)
        engine = make_engine(setup, cfgobj,
                             engine_cls=_select_engine_cls(args.engine))
        resume = None
        if args.resume:
            if args.resume == "auto":
                if not cfgobj.checkpoint_dir:
                    p.error("--resume auto requires --checkpoint-dir "
                            "(or a CHECKPOINT_DIR backend directive)")
                from .engine import checkpoint as ckpt_mod
                resume = ckpt_mod.latest(cfgobj.checkpoint_dir)
                if resume is None:
                    p.error("--resume auto: no checkpoint found in "
                            f"{cfgobj.checkpoint_dir!r}")
                print(f"resuming from {resume}")
            else:
                resume = args.resume
        # Live exposition listener (obs/expose.py): /metrics for a
        # Prometheus scraper, /flight for the watch console — up for
        # exactly the duration of the run.
        metrics_srv = None
        metrics_port = resolve(args.metrics_port, "METRICS_PORT", None)
        # 0 disables, matching BENCH_METRICS_PORT — a cfg author writing
        # `METRICS_PORT = 0` to turn the listener off for one run must
        # not get an unannounced ephemeral-port endpoint instead.
        if metrics_port:
            from .obs import start_metrics_server
            from .obs.flight import RECORDER
            try:
                metrics_srv, _ = start_metrics_server(
                    int(metrics_port), engine.metrics, flight=RECORDER)
                print(f"metrics: http://127.0.0.1:"
                      f"{metrics_srv.server_address[1]}/metrics "
                      f"(+ /flight)", file=sys.stderr)
            except OSError as e:
                # Observability must never kill the run it observes: a
                # busy/forbidden port degrades to a port-less run, said
                # out loud.
                metrics_srv = None
                print(f"metrics: cannot listen on port {metrics_port} "
                      f"({e}); continuing without the listener",
                      file=sys.stderr)
        try:
            res = engine.run(
                initial_states(setup, seed=args.seed)
                if resume is None else None,
                resume=resume)
        finally:
            if metrics_srv is not None:
                metrics_srv.shutdown()
                # And close the socket: a merely-shut-down server still
                # accepts into the backlog, turning the watcher's clean
                # refused-means-gone exit into read timeouts.
                metrics_srv.server_close()
        print(format_result(res))
        if args.metrics_out:
            _write_metrics(args.metrics_out, engine.metrics)
        history_path = resolve(args.history, "HISTORY", None)
        if history_path:
            # Run-history ledger (obs/history.py): one JSONL line per
            # run — cfg/model/host fingerprints, verdict, counts,
            # rates, report summary.  scripts/bench_history.py renders
            # the trajectory.
            from .obs import history as history_mod
            from .obs.flight import host_fingerprint
            with open(args.cfg) as f:
                cfg_text = f.read()
            history_mod.append_entry(
                history_path,
                history_mod.entry_from_result(
                    "check", res, cfg_text=cfg_text, dims=setup.dims,
                    host_fingerprint=host_fingerprint(),
                    label=os.path.basename(args.cfg)))
            print(f"history: entry appended to {history_path}",
                  file=sys.stderr)
        if res.violation is not None:
            if args.no_trace:
                print("\nviolating state (trace recording disabled):")
                print(format_state(res.violation.state, setup.dims))
            else:
                # TLC-style rendered error trace (engine/explain.py) —
                # the one trace rendering, --render-trace or not.  The
                # engine's run-end hook already replayed the chain (one
                # expand dispatch per step) and rendered this exact
                # text into counterexample.txt whenever a workdir was
                # resolvable (--render-trace guarantees one via the "."
                # fallback above), so print THAT file; only a run with
                # no workdir (or a failed render) replays here.
                print()
                if res.counterexample:
                    with open(res.counterexample["txt"],
                              encoding="utf-8") as f:
                        print(f.read(), end="")
                    print(f"\ncounterexample written: "
                          f"{res.counterexample['txt']} (+ .json)")
                else:
                    from .engine import explain as explain_mod
                    steps = engine.replay(res.violation.fingerprint)
                    print(explain_mod.render_text(
                        steps, setup.dims, violation=res.violation),
                        end="")
            return 1
        if res.deadlock is not None:
            print("\ndeadlock state:")
            print(format_state(res.deadlock, setup.dims))
            return 1
        return 0

    # simulate
    from .engine.check import resolve_constraint, resolve_invariants
    use_mesh = args.engine == "mesh"
    if args.engine == "auto":
        import jax
        devs = jax.devices()
        # Multi-process: the global-mesh fleet IS the multi-host mode —
        # anything else would run N duplicate local simulations.
        use_mesh = (jax.process_count() > 1
                    or (len(devs) > 1 and devs[0].platform != "cpu"))
    if use_mesh:
        from .parallel.simulate import MeshSimulator as Simulator
    else:
        from .engine.simulate import Simulator
    sim = Simulator(setup.dims, invariants=resolve_invariants(setup),
                    constraint=resolve_constraint(setup),
                    batch=batch, depth=args.depth,
                    # "v3" is a chunk-tail story; the simulator runs its
                    # v2 (delta) semantics for it (same resolution rule).
                    pipeline=resolve(args.pipeline, "PIPELINE", "auto"))
    # Span tracing (obs/tracing.py): attaching the tracer to the sim's
    # registry mirrors every sim_chunk/sim_fetch phase into the Chrome
    # trace; one top-level span brackets the whole simulation.
    from .obs import SpanTracer
    tracer = SpanTracer(resolve(args.trace_out, "TRACE_OUT", None))
    sim.metrics.tracer = tracer
    max_seconds = (args.max_seconds if args.max_seconds is not None
                   else setup.max_seconds)   # StopAfter duration budget
    with tracer.span("simulate_run", num_steps=args.num_steps,
                     batch=batch, depth=args.depth):
        res = sim.run(initial_states(setup, seed=args.seed),
                      num_steps=args.num_steps, seed=args.seed,
                      max_seconds=max_seconds)
    tracer.write()
    if args.metrics_out:
        _write_metrics(args.metrics_out, sim.metrics)
    print(f"steps visited      {res.steps}")
    print(f"traces             {res.traces}")
    print(f"wall seconds       {res.wall_seconds:.2f}")
    print(f"states/sec         {res.states_per_second:.0f}")
    if res.violation_invariant is not None:
        print(f"VIOLATION          {res.violation_invariant}")
        if res.violation_trace:
            for g, st in res.violation_trace:
                label = ("Initial state" if g < 0
                         else setup.dims.describe_instance(g))
                print(f"-- {label}")
                print(format_state(st, setup.dims))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
