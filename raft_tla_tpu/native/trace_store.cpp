// Native predecessor-trace store — TLC's trace file rebuilt as an in-memory
// open-addressing hash map (SURVEY §2.4 R5).
//
// TLC reconstructs counterexamples from a disk-backed trace of (fingerprint
// -> predecessor fingerprint) records [TLC semantics — external].  Here the
// engine streams one compacted (fp, parent fp, action id) triple per newly
// discovered state off the device each batch; this store ingests those
// batches at memcpy-like rates so the host-side bookkeeping never throttles
// the device pipeline.  Python binds via ctypes (native/__init__.py loads
// the .so; engine/trace.py wraps it) — no pybind11 dependency.
//
// Layout: open addressing, linear probing, power-of-two capacity, grow at
// 70% load.  First insert wins (BFS reaches a state first along a shortest
// path; later duplicates arrive only from in-flight batches of the same
// level and must not overwrite the shortest-path parent).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

struct Entry {
    uint64_t fp;
    uint64_t parent;
    int32_t action;
    uint8_t used;
};

struct Store {
    Entry* slots;
    uint64_t capacity;   // power of two
    uint64_t size;
};

// splitmix64: decorrelates slot index from the engine's own fingerprint
// mixing so pathological fp batches cannot cluster probes.
inline uint64_t mix(uint64_t x) {
    x += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
}

void grow(Store* s);

inline void insert_one(Store* s, uint64_t fp, uint64_t parent,
                       int32_t action) {
    uint64_t mask = s->capacity - 1;
    uint64_t i = mix(fp) & mask;
    while (s->slots[i].used) {
        if (s->slots[i].fp == fp) return;  // first insert wins
        i = (i + 1) & mask;
    }
    s->slots[i] = Entry{fp, parent, action, 1};
    s->size++;
    if (s->size * 10 >= s->capacity * 7) grow(s);
}

void grow(Store* s) {
    Entry* old = s->slots;
    uint64_t old_cap = s->capacity;
    s->capacity <<= 1;
    s->slots = static_cast<Entry*>(calloc(s->capacity, sizeof(Entry)));
    s->size = 0;
    for (uint64_t i = 0; i < old_cap; i++)
        if (old[i].used)
            insert_one(s, old[i].fp, old[i].parent, old[i].action);
    free(old);
}

}  // namespace

extern "C" {

void* ts_create(uint64_t initial_capacity) {
    uint64_t cap = 1024;
    while (cap < initial_capacity) cap <<= 1;
    Store* s = static_cast<Store*>(malloc(sizeof(Store)));
    s->slots = static_cast<Entry*>(calloc(cap, sizeof(Entry)));
    s->capacity = cap;
    s->size = 0;
    return s;
}

void ts_destroy(void* h) {
    Store* s = static_cast<Store*>(h);
    free(s->slots);
    free(s);
}

uint64_t ts_size(void* h) { return static_cast<Store*>(h)->size; }

void ts_add_batch(void* h, const uint64_t* fps, const uint64_t* parents,
                  const int32_t* actions, uint64_t n) {
    Store* s = static_cast<Store*>(h);
    for (uint64_t k = 0; k < n; k++)
        insert_one(s, fps[k], parents[k], actions[k]);
}

int ts_get(void* h, uint64_t fp, uint64_t* parent, int32_t* action) {
    Store* s = static_cast<Store*>(h);
    uint64_t mask = s->capacity - 1;
    uint64_t i = mix(fp) & mask;
    while (s->slots[i].used) {
        if (s->slots[i].fp == fp) {
            *parent = s->slots[i].parent;
            *action = s->slots[i].action;
            return 1;
        }
        i = (i + 1) & mask;
    }
    return 0;
}

// Bulk export for checkpointing: writes up to `cap` triples; returns the
// number written (== size when cap is sufficient).
uint64_t ts_export(void* h, uint64_t* fps, uint64_t* parents,
                   int32_t* actions, uint64_t cap) {
    Store* s = static_cast<Store*>(h);
    uint64_t k = 0;
    for (uint64_t i = 0; i < s->capacity && k < cap; i++) {
        if (s->slots[i].used) {
            fps[k] = s->slots[i].fp;
            parents[k] = s->slots[i].parent;
            actions[k] = s->slots[i].action;
            k++;
        }
    }
    return k;
}

}  // extern "C"
