"""Native (C++) runtime components, bound via ctypes.

The checker's device pipeline is JAX/XLA; the host-side runtime pieces that
TLC implements natively (trace store; checkpoint IO helpers) are C++ here
too, built on first use with the ambient ``g++`` into a shared library next
to the sources.  Everything degrades gracefully: if no compiler is available
the pure-Python fallbacks in ``engine/trace.py`` are used instead.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libraftnative.so")
_SRC = [os.path.join(_HERE, "trace_store.cpp")]
_LOCK = threading.Lock()
_LIB = None
_TRIED = False


def _build() -> bool:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           "-o", _SO] + _SRC
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if needed; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        newest_src = max(os.path.getmtime(p) for p in _SRC)
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < newest_src:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ts_create.restype = ctypes.c_void_p
        lib.ts_create.argtypes = [ctypes.c_uint64]
        lib.ts_destroy.argtypes = [ctypes.c_void_p]
        lib.ts_size.restype = ctypes.c_uint64
        lib.ts_size.argtypes = [ctypes.c_void_p]
        lib.ts_add_batch.argtypes = [ctypes.c_void_p, u64p, u64p, i32p,
                                     ctypes.c_uint64]
        lib.ts_get.restype = ctypes.c_int
        lib.ts_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64, u64p, i32p]
        lib.ts_export.restype = ctypes.c_uint64
        lib.ts_export.argtypes = [ctypes.c_void_p, u64p, u64p, i32p,
                                  ctypes.c_uint64]
        _LIB = lib
        return _LIB
