"""TLC-parity run report — the semantic run-end statistics block.

TLC closes every run with a signature statistics block: the fingerprint
collision probability estimate, "N states generated, M distinct states
found", the depth of the state graph, and (with ``-coverage``) the
per-action table.  The engines have collected every ingredient of that
block for PRs (counters, per-level events, action coverage, seen-set
gauges) without ever assembling it; this module is the assembler.

``build_report`` folds one finished :class:`~..engine.bfs.EngineResult`
(plus the run's coverage accumulator and the per-level stats the engines
record at each level boundary) into one JSON-able dict:

- ``collision``: the 64-bit fingerprint collision probability, TLC's
  "calculated (optimistic)" formula ``distinct * (generated - distinct)
  / 2**64`` (tlc2.tool.ModelChecker reportSuccess — each distinct
  fingerprint tested against each duplicate hit), plus the count of
  dual-key collisions the run actually OBSERVED (replay/extraction
  mismatches detected host-side; 0 on healthy runs — the engine cannot
  see a collision the fingerprint cannot, so observed means *detected*);
- ``diameter`` / ``distinct`` / ``generated`` / ``verdict``;
- ``levels``: the per-level table (frontier width, cumulative distinct/
  generated, queue rows, seen-set size/load at each level boundary) —
  the level-width curve ScalaBFS/PULSE-style frontier analyses read;
- ``out_degree``: mean enabled successors per expanded parent, total and
  per action family (from the same packed stats as coverage);
- ``seen_set``: final load factor, capacity, growths — the load curve.

Everything is host-side arithmetic over already-fetched counters: the
report can never perturb engine results (bit-identity on/off is tested).

Surfaces: a ``statespace`` run event (payload ``report``), the TLC-style
stderr block at run end (progress-enabled runs), ``EngineResult.report``,
bench JSON, the server ``check`` response, and ``statespace/*`` registry
gauges (the ``stats`` op).  Zero-dep and jax-free, like all of ``obs/``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: 2^64 as a float — the fingerprint space TLC's probability formula
#: divides by (the engines' dual 32+32-bit key is 64 bits too).
_FP_SPACE = float(1 << 64)


def collision_probability(distinct: int, generated: int) -> float:
    """TLC's "calculated (optimistic)" fingerprint-collision estimate:
    every one of the ``generated - distinct`` duplicate hits was decided
    by fingerprint equality alone, each with a ``distinct / 2**64``
    chance of being a masked genuinely-new state."""
    dupes = max(0, generated - distinct)
    return (distinct / _FP_SPACE) * dupes


def build_report(result, coverage=None, level_stats=None,
                 seen_capacity: Optional[int] = None,
                 seen_size: Optional[int] = None,
                 observed_collisions: int = 0) -> dict:
    """Assemble the TLC-parity report dict from a finished run.

    ``result`` duck-types :class:`~..engine.bfs.EngineResult` (distinct /
    generated / diameter / levels / stop_reason / violation / deadlock);
    ``coverage`` is the run's :class:`.coverage.ActionCoverage` (None on
    trace-only callers); ``level_stats`` the engines' per-level snapshot
    list (each ``{"level", "frontier", "distinct", "generated",
    "seen_size", "seen_capacity"}``) — levels missing from it (resumed
    prefixes) still appear in the table with width only."""
    levels: List[int] = list(getattr(result, "levels", []) or [])
    by_level: Dict[int, dict] = {int(d.get("level", -1)): d
                                 for d in (level_stats or [])}
    table = []
    for lvl, width in enumerate(levels):
        row = {"level": lvl, "frontier": int(width)}
        extra = by_level.get(lvl)
        if extra is not None:
            row["distinct"] = int(extra.get("distinct", 0))
            row["generated"] = int(extra.get("generated", 0))
            cap = int(extra.get("seen_capacity", 0) or 0)
            size = int(extra.get("seen_size", 0) or 0)
            if cap:
                row["seen_size"] = size
                row["seen_load"] = round(size / cap, 4)
        table.append(row)
    peak = max(range(len(levels)), key=lambda i: levels[i],
               default=None) if levels else None

    distinct = int(getattr(result, "distinct", 0))
    generated = int(getattr(result, "generated", 0))
    verdict = ("violation" if getattr(result, "violation", None) is not None
               else "deadlock" if getattr(result, "deadlock", None)
               is not None else "ok")

    out_degree: dict = {}
    if coverage is not None and coverage.expanded:
        exp = coverage.expanded
        out_degree = {
            "expanded_parents": exp,
            "mean": round(coverage.total_generated / exp, 4),
            "per_family": {n: round(coverage.generated[n] / exp, 4)
                           for n in coverage.names},
        }

    seen: dict = {}
    if seen_capacity:
        seen["capacity"] = int(seen_capacity)
        # Final load from the run's live seen-set gauges (the table
        # itself holds MORE keys than enqueued states: constraint-
        # violating states are inserted but never expanded).
        seen["final_load"] = round(
            (seen_size if seen_size is not None else distinct)
            / seen_capacity, 4)
    growths = list(getattr(result, "growth_stalls", ()) or ())
    if growths:
        seen["growths"] = [[int(c), float(s)] for c, s in growths]
    # The load CURVE rides the level table (seen_load per boundary);
    # summarize its endpoint here for the one-line rendering.
    loads = [r["seen_load"] for r in table if "seen_load" in r]
    if loads:
        seen["load_curve_final"] = loads[-1]

    # BLEST family-group attribution (models/actions.py): which action
    # families ride each stacked expansion kernel, so a per-family win
    # (or regression) is attributable to its group.
    fam_groups = [dict(g) for g in
                  (getattr(result, "family_groups", None) or [])]

    return {
        "distinct": distinct,
        "generated": generated,
        "diameter": int(getattr(result, "diameter", 0)),
        "stop_reason": getattr(result, "stop_reason", None),
        "verdict": verdict,
        "family_groups": fam_groups,
        "collision": {
            "calculated": collision_probability(distinct, generated),
            "formula": "distinct * (generated - distinct) / 2^64",
            "observed_dual_key": int(observed_collisions),
        },
        "levels": table,
        "frontier_peak": ({"level": peak, "frontier": levels[peak]}
                          if peak is not None else None),
        "out_degree": out_degree,
        "seen_set": seen,
    }


def feed_metrics(report: dict, metrics) -> None:
    """Mirror the report's scalar spine into ``statespace/*`` gauges so
    the server ``stats`` op / ``--metrics-out`` snapshots carry it
    (gauges — idempotent across re-reports, like coverage)."""
    metrics.gauge("statespace/collision_probability",
                  report["collision"]["calculated"])
    metrics.gauge("statespace/collisions_observed",
                  report["collision"]["observed_dual_key"])
    metrics.gauge("statespace/diameter", report["diameter"])
    peak = report.get("frontier_peak") or {}
    if peak:
        metrics.gauge("statespace/frontier_peak", peak["frontier"])
    od = report.get("out_degree") or {}
    if od:
        metrics.gauge("statespace/mean_out_degree", od["mean"])
    seen = report.get("seen_set") or {}
    if "final_load" in seen:
        metrics.gauge("statespace/seen_load", seen["final_load"])


def _fmt_prob(p: float) -> str:
    return f"{p:.2e}" if p else "0"


def render_report(report: dict) -> str:
    """The TLC-style stderr block (the ``MCraft.cfg`` run-end shape):
    headline counts + collision estimate, then the per-level table and
    the out-degree/seen-set summaries."""
    col = report["collision"]
    lines = [
        f"state space: {report['generated']:,} states generated, "
        f"{report['distinct']:,} distinct states found, diameter "
        f"{report['diameter']} ({report['verdict']}, "
        f"stop: {report['stop_reason']})",
        f"  fingerprint collision probability: calculated (optimistic) "
        f"{_fmt_prob(col['calculated'])}"
        f"; observed dual-key collisions: {col['observed_dual_key']}",
    ]
    table = report.get("levels") or []
    if table:
        lines.append("  level  frontier     distinct    generated  "
                     "fpset-load")
        for row in table:
            d = (f"{row['distinct']:12,d}" if "distinct" in row
                 else f"{'--':>12s}")
            g = (f"{row['generated']:12,d}" if "generated" in row
                 else f"{'--':>12s}")
            load = (f"{row['seen_load']:10.3f}" if "seen_load" in row
                    else f"{'--':>10s}")
            lines.append(f"  {row['level']:5d} {row['frontier']:9,d} "
                         f"{d} {g}  {load}")
        peak = report.get("frontier_peak")
        if peak:
            lines.append(f"  widest level: {peak['level']} "
                         f"({peak['frontier']:,} states)")
    od = report.get("out_degree") or {}
    if od:
        widest = max(od["per_family"], key=od["per_family"].get)
        lines.append(
            f"  out-degree: mean {od['mean']:.2f} over "
            f"{od['expanded_parents']:,} expanded parents; widest family "
            f"{widest} ({od['per_family'][widest]:.2f})")
    seen = report.get("seen_set") or {}
    if seen.get("capacity"):
        g = (f", {len(seen['growths'])} growth(s)"
             if seen.get("growths") else "")
        lines.append(f"  seen-set: final load {seen['final_load']:.3f} "
                     f"of {seen['capacity']:,} keys{g}")
    groups = report.get("family_groups") or []
    if groups:
        total_k = sum(g["kernels"] for g in groups)
        parts = ", ".join(f"{g['group']}={g['kernels']}k/{g['lanes']}l"
                          for g in groups)
        lines.append(f"  expansion groups: {len(groups)} stacked groups, "
                     f"{total_k} member kernels ({parts})")
    return "\n".join(lines)


def summarize(report: Optional[dict]) -> dict:
    """The compact projection the run-history ledger stores per run
    (obs/history.py): enough to read a trajectory without replaying the
    whole report."""
    if not report:
        return {}
    peak = report.get("frontier_peak") or {}
    od = report.get("out_degree") or {}
    out = {
        "collision_calculated": report["collision"]["calculated"],
        "diameter": report["diameter"],
        "verdict": report["verdict"],
        "levels": len(report.get("levels") or []),
        "frontier_peak": peak.get("frontier"),
        "mean_out_degree": od.get("mean"),
    }
    groups = report.get("family_groups") or []
    if groups:
        # Compact per-group projection: kernel count per stacked group,
        # so the ledger shows HOW batched the expansion was per run.
        out["family_groups"] = {g["group"]: g["kernels"] for g in groups}
    return out
