"""Cross-run history ledger — the durable trajectory of measurements.

Every prior surface (bench JSON, run events, reports) is per-run; the
trajectory across runs lived in hand-curated ``BENCH_rNN.json`` files
and round notes — which is exactly how the PR 7 trap happened (an
absolute rate silently compared across a ~4x slower container, because
nothing recorded which host produced which number).  This module is the
append-only JSONL ledger closing that gap: one line per run, recording

- identity: ``cfg_fingerprint`` (sha256 of the cfg text) +
  ``model_fingerprint`` (sha256 of ``repr(dims)``) + the full
  ``host_fingerprint`` (obs/flight.py) and its short ``host_key``;
- outcome: verdict / stop_reason, distinct / generated / diameter /
  wall seconds, headline rates;
- how it ran: pipeline + resolved fused-stage plan;
- the ``statespace`` report summary (obs/report.py ``summarize``);
- for bench runs, the full bench JSON (``bench``) — which is what lets
  ``scripts/bench_diff.py --history`` resolve its baseline from the
  ledger (newest same-host-key bench entry) instead of a hand-picked
  file.

Writers: ``check --history PATH`` / the ``HISTORY`` cfg directive
(cli.py) and ``BENCH_HISTORY`` (bench.py).  Readers:
``scripts/bench_history.py`` (trajectory table, ``--import-legacy``
seeding from the committed BENCH_r*/MULTICHIP_r* files) and
``scripts/bench_diff.py`` (baseline auto-resolution).  Zero-dep and
jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import List, Optional

ENTRY_VERSION = 1

#: host_fingerprint keys that decide comparability — hostname alone is
#: NOT identity (same container class, new pod).  THE single
#: definition: scripts/bench_diff.py imports this for its cross-host
#: WARNING, so the ledger's host_key and the diff's warning can never
#: disagree about what "same host" means.
HOST_KEYS = ("cpu_model", "device_kind", "device_count", "platform",
             "jax", "jaxlib")


def host_key(fp: Optional[dict]) -> Optional[str]:
    """Short stable digest of the comparability-deciding fingerprint
    fields; None for a missing/empty fingerprint (legacy imports) — an
    unknown host must render as unknown, never as a real key."""
    if not fp or not any(fp.get(k) for k in HOST_KEYS):
        return None
    blob = json.dumps([fp.get(k) for k in HOST_KEYS])
    return hashlib.sha256(blob.encode()).hexdigest()[:10]


def fingerprint_text(text) -> str:
    if isinstance(text, str):
        text = text.encode()
    return hashlib.sha256(text).hexdigest()


def make_entry(kind: str, *, label: Optional[str] = None,
               cfg_text: Optional[str] = None,
               dims=None, host_fingerprint: Optional[dict] = None,
               verdict: Optional[str] = None,
               stop_reason: Optional[str] = None,
               distinct: Optional[int] = None,
               generated: Optional[int] = None,
               diameter: Optional[int] = None,
               wall_seconds: Optional[float] = None,
               distinct_per_sec: Optional[float] = None,
               generated_per_sec: Optional[float] = None,
               pipeline: Optional[str] = None,
               fused_stages: Optional[dict] = None,
               report_summary: Optional[dict] = None,
               bench: Optional[dict] = None,
               ts: Optional[float] = None,
               extra: Optional[dict] = None) -> dict:
    """One ledger line.  ``kind`` is ``check`` / ``bench`` / ``server``
    (the checker service's executed-job entries, which carry ``job_id``
    and ``tenant`` via ``extra``) / whatever a legacy import labels;
    unknown fields stay None rather than absent so every line has the
    same shape.  ``extra`` keys are merged last (they may not shadow
    the schema: a colliding key raises)."""
    out = {
        "v": ENTRY_VERSION,
        "ts": round(time.time() if ts is None else ts, 3),
        "kind": kind,
        "label": label,
        "cfg_fingerprint": (fingerprint_text(cfg_text)
                            if cfg_text is not None else None),
        "model_fingerprint": (fingerprint_text(repr(dims))
                              if dims is not None else None),
        "host_fingerprint": dict(host_fingerprint or {}) or None,
        "host_key": host_key(host_fingerprint),
        "verdict": verdict,
        "stop_reason": stop_reason,
        "distinct": distinct,
        "generated": generated,
        "diameter": diameter,
        "wall_seconds": wall_seconds,
        "distinct_per_sec": distinct_per_sec,
        "generated_per_sec": generated_per_sec,
        "pipeline": pipeline,
        "fused_stages": dict(fused_stages or {}) or None,
        "report": dict(report_summary or {}) or None,
        "bench": bench,
    }
    for k, v in (extra or {}).items():
        if k in out:
            raise ValueError(f"extra key {k!r} shadows a ledger field")
        out[k] = v
    return out


def entry_from_result(kind: str, res, *, cfg_text=None, dims=None,
                      host_fingerprint=None, label=None,
                      extra=None) -> dict:
    """Ledger entry from a finished ``EngineResult`` (the ``check
    --history`` writer).  Lazy import of report.summarize keeps this
    module's import graph flat."""
    from .report import summarize
    wall = float(getattr(res, "wall_seconds", 0.0) or 0.0)
    verdict = ("violation" if getattr(res, "violation", None) is not None
               else "deadlock" if getattr(res, "deadlock", None)
               is not None else "ok")
    return make_entry(
        kind, label=label, cfg_text=cfg_text, dims=dims,
        host_fingerprint=host_fingerprint,
        verdict=verdict, stop_reason=res.stop_reason,
        distinct=res.distinct, generated=res.generated,
        diameter=res.diameter, wall_seconds=round(wall, 3),
        distinct_per_sec=round(res.distinct / wall, 1) if wall else None,
        generated_per_sec=round(res.generated / wall, 1) if wall else None,
        pipeline=res.pipeline or None,
        fused_stages=res.fused_stages,
        report_summary=summarize(getattr(res, "report", None)),
        extra=extra)


def entry_from_bench(doc: dict, *, label=None, kind="bench",
                     ts=None) -> dict:
    """Ledger entry from one bench.py JSON object (raw form)."""
    from .report import summarize
    return make_entry(
        kind, label=label, ts=ts,
        host_fingerprint=doc.get("host_fingerprint"),
        verdict="ok" if doc.get("stop_reason") != "violation" else
        "violation",
        stop_reason=doc.get("stop_reason"),
        distinct=doc.get("distinct_states"),
        generated=doc.get("generated_states"),
        diameter=doc.get("diameter"),
        wall_seconds=doc.get("wall_s"),
        distinct_per_sec=doc.get("value"),
        generated_per_sec=doc.get("generated_per_sec"),
        pipeline=doc.get("pipeline"),
        fused_stages=doc.get("fused_stages"),
        report_summary=summarize(doc.get("report")),
        bench=doc)


def append_entry(path: str, entry: dict, default=None) -> None:
    """Append one JSONL line (O_APPEND single write — concurrent
    appenders on a local filesystem interleave at line granularity).
    ONE definition of the append idiom: the serving job journal
    (serving/jobs.py) writes through here too (with ``default=str``
    for its richer records), so a future durability change — fsync,
    line-length guard — lands in every append-only log at once."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(entry, sort_keys=True, default=default)
                + "\n")


def read_history(path: str) -> List[dict]:
    """Parse the ledger; raises FileNotFoundError/ValueError on a
    missing or corrupt file (the bench_diff gate convention: a gate
    that cannot read its evidence fails loudly)."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"run-history ledger missing: {path}")
    out = []
    with open(path, encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{ln}: malformed ledger line "
                                 f"({e})")
            if not isinstance(rec, dict) or "kind" not in rec:
                raise ValueError(f"{path}:{ln}: not a ledger entry: "
                                 f"{line[:120]}")
            out.append(rec)
    return out


def resolve_baseline(path: str, host_fp: Optional[dict],
                     kind: str = "bench",
                     exclude_bench: Optional[dict] = None
                     ) -> Optional[dict]:
    """The newest ledger entry of ``kind`` whose host_key matches
    ``host_fp``'s AND which carries an embedded bench object — the
    auto-resolved bench_diff baseline.  None when no same-host entry
    exists (cross-host baselines must be picked deliberately, never
    silently — the whole point of the ledger).

    ``exclude_bench``: the CANDIDATE's bench object.  The documented
    workflow records a run with BENCH_HISTORY and then gates its own
    stdout JSON with ``bench_diff --history`` — without this exclusion
    the newest same-host entry would be the candidate's own ledger
    line, and the gate would vacuously self-compare (0% change hides a
    real regression).  Identity: matching ``run_id`` (bench.py stamps
    one into both the printed JSON and the ledger copy — robust to the
    captured file being annotated or reformatted later), falling back
    to whole-document equality for run_id-less docs."""
    key = host_key(host_fp)
    if key is None:
        return None

    def is_candidate(bench: dict) -> bool:
        if exclude_bench is None:
            return False
        rid, crid = bench.get("run_id"), exclude_bench.get("run_id")
        if rid is not None and crid is not None:
            return rid == crid
        return bench == exclude_bench

    for rec in reversed(read_history(path)):
        if rec.get("kind") == kind and rec.get("host_key") == key \
                and rec.get("bench") \
                and not is_candidate(rec["bench"]):
            return rec
    return None


def perf_columns(entry: dict):
    """(launches/chunk, advisor-top, peak bandwidth fraction) from an
    entry's embedded bench perf block (obs/perf.py) — or the
    xplane_summary dialect, which embeds the same block shape.  The
    bandwidth fraction is the MAX across the profiled roofline stages
    (the most saturated stage — what a v3/v4 fusion round is trying to
    raise).  (None, None, None) for entries predating the metric, so
    the trajectory renders '--' instead of guessing."""
    bench = entry.get("bench") or {}
    perf = bench.get("perf") or {}
    lpc = (perf.get("launch") or {}).get("launches_per_chunk")
    top = (perf.get("advisor") or {}).get("top")
    stages = ((perf.get("roofline") or {}).get("stages") or {})
    fracs = [r.get("bandwidth_fraction") for r in stages.values()
             if isinstance(r, dict)
             and r.get("bandwidth_fraction") is not None]
    return lpc, top, (max(fracs) if fracs else None)


def hunt_columns(entry: dict):
    """(saturation, novel rate, time-to-violation seconds) from a swarm
    entry's hunt summary (obs/hunt.py summarize) — carried either as
    the entry's own ``hunt`` extra (``check --mode swarm --history``,
    the server's swarm leg) or inside the embedded bench doc
    (BENCH_MODE=swarm).  (None, None, None) for exhaustive rows and
    hunt-less swarm rows, so the trajectory renders '--'."""
    hunt = entry.get("hunt")
    if not isinstance(hunt, dict):
        hunt = (entry.get("bench") or {}).get("hunt")
    if not isinstance(hunt, dict):
        return None, None, None
    return (hunt.get("saturation"), hunt.get("novel_rate"),
            hunt.get("time_to_violation_seconds"))


def render_table(entries: List[dict], perf: bool = False,
                 hunt: bool = False) -> str:
    """The trajectory table (scripts/bench_history.py): one row per
    entry, host-key column + explicit flags where adjacent entries are
    NOT rate-comparable (different or unknown host) — the r05 trap,
    rendered impossible to miss.  ``perf=True`` adds the performance-
    observatory columns (pipeline + launches/chunk + peak bandwidth
    fraction + advisor pick) so the trajectory shows whether fusion
    work (v3's fused tail, v4's megakernel) is actually RETIRING
    launches and raising saturation across rounds, not just moving
    wall-clock.  ``hunt=True`` adds the hunt-observatory columns
    (coverage saturation + novelty rate + time-to-violation from
    obs/hunt.py summaries) so a swarm trajectory answers "is each
    round's hunt saturating sooner / latching faster" at a glance."""
    pcols = (f" {'pipe':>4s} {'launch/chunk':>12s} {'bw-frac':>8s} "
             f"{'advisor':14s}") if perf else ""
    hcols = (f" {'satur':>7s} {'novel':>7s} {'t-viol':>7s}") if hunt \
        else ""
    lines = [f"{'#':>3s} {'label':20s} {'kind':9s} {'host':10s} "
             f"{'distinct/s':>12s} {'distinct':>12s} {'diam':>5s} "
             f"{'verdict':10s}{pcols}{hcols} flags"]
    first = object()
    prev_key = first              # sentinel: first row never flags
    warnings = []
    for i, e in enumerate(entries):
        key = e.get("host_key")
        flags = []
        if key is None:
            flags.append("host?")
        if prev_key is not first and key != prev_key:
            flags.append("HOST-CHANGE")
            warnings.append(
                f"entry {i} ({e.get('label') or e.get('ts')}): host "
                f"changed ({prev_key or 'unknown'} -> "
                f"{key or 'unknown'}) — rates before/after are not "
                f"comparable")
        rate = e.get("distinct_per_sec")
        # Swarm-dialect rows (kind=swarm, from check --mode swarm or
        # BENCH_MODE=swarm): the rate column carries the tier's steps/s
        # headline, flagged as such — a walker's rate sitting in an
        # exhaustive distinct/s trajectory must read as a different
        # dialect, not as a host anomaly or a throughput jump.  These
        # rows carry a real host_fingerprint, so the host?/HOST-CHANGE
        # flags stay what they mean.
        sw = e.get("swarm")
        if sw is None and isinstance(e.get("bench"), dict) \
                and e["bench"].get("mode") == "swarm":
            sw = e["bench"]
        if isinstance(sw, dict):
            rate = sw.get("steps_per_sec", rate)
            flags.append("steps/s")
        d, dia = e.get("distinct"), e.get("diameter")
        row = (f"{i:3d} {str(e.get('label') or '-'):20s} "
               f"{str(e.get('kind') or '-'):9s} {str(key or '?'):10s} "
               + (f"{rate:12,.1f}" if isinstance(rate, (int, float))
                  else f"{'--':>12s}")
               + (f" {d:12,d}" if isinstance(d, int)
                  else f" {'--':>12s}")
               + (f" {dia:5d}" if isinstance(dia, int)
                  else f" {'--':>5s}")
               + f" {str(e.get('verdict') or '?'):10s}")
        if perf:
            lpc, top, bw = perf_columns(e)
            row += (f" {str(e.get('pipeline') or '--'):>4s}"
                    + (f" {lpc:12,.0f}" if isinstance(lpc, (int, float))
                       else f" {'--':>12s}")
                    + (f" {bw:8.1%}" if isinstance(bw, (int, float))
                       else f" {'--':>8s}")
                    + f" {str(top or '--'):14s}")
        if hunt:
            sat, novel, ttv = hunt_columns(e)
            row += ((f" {sat:7.1%}" if isinstance(sat, (int, float))
                     else f" {'--':>7s}")
                    + (f" {novel:7.1%}"
                       if isinstance(novel, (int, float))
                       else f" {'--':>7s}")
                    + (f" {ttv:6.1f}s"
                       if isinstance(ttv, (int, float))
                       else f" {'--':>7s}"))
        row += " " + (",".join(flags) if flags else "-")
        lines.append(row)
        prev_key = key
    for w in warnings:
        lines.append(f"WARNING: {w}")
    return "\n".join(lines)
