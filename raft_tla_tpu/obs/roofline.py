"""Static roofline model — per-stage HBM-traffic floors from traced
jaxprs, priced against device bandwidth, with a fusion advisor.

NORTHSTAR §c argues from a *bandwidth floor*: the chunk's per-batch data
is small (tens of MB), so at HBM bandwidth the movement floor is
~0.1-0.3 ms/batch while the measured chunk is 89.45 ms — the gap is
kernel granularity, not physics.  Until now that floor was a hand
calculation in a markdown file.  This module derives it mechanically,
per stage, from the SAME stage programs the ChunkProfiler times
(obs/profile.py build_stage_programs / _v3), so the model rows and the
measured rows share keys and can be joined into achieved-bandwidth
fractions.

The byte model is a **traffic floor**: every stage INPUT is read once
(or, when it is only ever accessed through gather / dynamic_slice
windows, only the windows are read), every stage OUTPUT is written once
(scatter / dynamic_update_slice outputs count only their update
windows), and intermediates are free — the perfectly-fused ideal.  Loop
bodies (the FPSet probe chain) are counted once: the floor of a
data-dependent walk.  The walk rides :func:`analysis.interp.eval_jaxpr`
with a provenance domain (which stage input does this value alias?) —
the same shared evaluator the effects/bounds passes use, no new tracer.

``achieved fraction = (floor bytes / measured stage seconds) / peak``;
``headroom = measured - floor_time`` is the stage's time above the
bandwidth floor — what fusion can reclaim.  The **fusion advisor**
(:func:`advise`) ranks stages by ``launch_count x per-launch overhead +
headroom`` and names the top candidate: the measurement-driven answer
to "what do we fuse next" that ROADMAP item 1 asks for, replacing
hand-reading NORTHSTAR §c.

Peak bandwidth comes from a device-kind table (TPU generations; a
deliberately conservative DDR figure off-accelerator) overridable with
``RAFT_PEAK_GBPS`` — the ``source`` field always says which was used,
so a fraction computed against an assumed CPU figure can never be
mistaken for a hardware measurement.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

#: Peak HBM bandwidth by device-kind substring (bytes/s).  Datasheet
#: numbers; matched case-insensitively against ``jax.devices()[0]
#: .device_kind``.  Override with RAFT_PEAK_GBPS (GB/s) for hardware
#: not listed here.
PEAK_BW_TABLE = (
    ("v5p", 2765e9),
    ("v5 lite", 819e9), ("v5e", 819e9),
    ("v6 lite", 1638e9), ("v6e", 1638e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)

#: Off-accelerator fallback: dual-channel DDR4-3200 class (~51 GB/s).
#: The point of a CPU row is shape, not absolutes — the source field
#: marks it "assumed".
CPU_ASSUMED_BW = 51.2e9

_VIEW_PRIMS = frozenset(("reshape", "squeeze", "expand_dims",
                         "broadcast_in_dim"))
_ALIAS_PRIMS = frozenset(("reshape", "squeeze", "expand_dims"))
_WINDOW_READ = frozenset(("gather", "dynamic_slice"))
#: operand-position-0 read-modify-write primitives: traffic is the
#: update window, and the output aliases the operand.
_WINDOW_RMW = frozenset(("scatter", "scatter-add", "scatter_add",
                         "dynamic_update_slice"))


def peak_bandwidth() -> Dict[str, object]:
    """{"bytes_per_sec", "source"} for the first visible device.
    RAFT_PEAK_GBPS (GB/s) overrides; unknown accelerators fall back to
    the assumed-CPU figure with a source that says so."""
    env = os.environ.get("RAFT_PEAK_GBPS")
    if env:
        # Malformed override falls through to detection: this runs
        # inside the engines' fail-soft perf build AND its fallback
        # handler, so raising here would fail the engine build.
        try:
            return {"bytes_per_sec": float(env) * 1e9,
                    "source": "RAFT_PEAK_GBPS override"}
        except ValueError:
            import sys
            print(f"perf: ignoring malformed RAFT_PEAK_GBPS={env!r} "
                  f"(want GB/s as a number)", file=sys.stderr)
    kind, platform = "", "cpu"
    try:
        import jax
        dev = jax.devices()[0]
        kind = (getattr(dev, "device_kind", "") or "").lower()
        platform = dev.platform
    except Exception:
        pass
    if platform not in ("cpu",):
        for sub, bw in PEAK_BW_TABLE:
            if sub in kind:
                return {"bytes_per_sec": bw,
                        "source": f"datasheet ({kind or platform})"}
        return {"bytes_per_sec": CPU_ASSUMED_BW,
                "source": f"assumed (unknown accelerator {kind!r})"}
    return {"bytes_per_sec": CPU_ASSUMED_BW,
            "source": "assumed (cpu ddr-class)"}


# ---------------------------------------------------------------------------
# Provenance traffic walk (analysis/interp.py eval_jaxpr domain)


class _Src:
    """Provenance of one value: the stage-input index it aliases (via
    shape-preserving view prims and loop carries), or None."""

    __slots__ = ("root",)

    def __init__(self, root=None):
        self.root = root


def _aval_bytes(aval) -> int:
    import numpy as np
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * np.dtype(aval.dtype).itemsize


class TrafficDomain:
    """Domain for :func:`analysis.interp.eval_jaxpr` accumulating the
    traffic-floor facts: which stage inputs are read fully vs only
    through windows, window bytes written back into passed-through
    inputs, and the device-op tally (obs/perf.py shares the counting
    rules through :data:`_VIEW_PRIMS`)."""

    def __init__(self):
        self.full_read = set()            # roots read at full extent
        self.win_read: Dict[int, int] = {}    # root -> window bytes
        self.win_written: Dict[int, int] = {}  # root -> window bytes
        self.launches = 0                 # device ops (view prims free)
        self.while_launches = 0           # ...of which inside loop bodies
        self.collectives = 0
        self.collectives_in_loop = 0
        self._in_while = 0
        self.notes = set()
        # Deferred import: perf and roofline lazily import each other
        # (perf consumes the walk, the walk tags perf's collectives).
        from .perf import COLLECTIVE_PRIMS
        self._collective_prims = COLLECTIVE_PRIMS

    # -- domain protocol ----------------------------------------------
    def lift(self, x):
        return x if isinstance(x, _Src) else _Src(None)

    def unknown(self, aval, invals, why):
        for v in invals:
            self._read_full(v)
        self.notes.add(f"opaque call: {why}")
        return _Src(None)

    # -- accumulators --------------------------------------------------
    def _read_full(self, v):
        if isinstance(v, _Src) and v.root is not None:
            self.full_read.add(v.root)

    def _read_win(self, v, nbytes):
        if isinstance(v, _Src) and v.root is not None:
            self.win_read[v.root] = self.win_read.get(v.root, 0) + nbytes

    def _write_win(self, v, nbytes):
        if isinstance(v, _Src) and v.root is not None:
            self.win_written[v.root] = (self.win_written.get(v.root, 0)
                                        + nbytes)

    def _launch(self, name=None):
        self.launches += 1
        if self._in_while:
            self.while_launches += 1
        if name in self._collective_prims:
            self.collectives += 1
            if self._in_while:
                self.collectives_in_loop += 1

    # -- primitive rules -----------------------------------------------
    def apply(self, name, eqn, invals):
        nouts = len(eqn.outvars)
        if name == "while":
            return self._p_while(eqn, invals)
        if name == "cond":
            return self._p_cond(eqn, invals)
        if name == "scan":
            return self._p_scan(eqn, invals)
        if name == "shard_map":
            return self._p_shard_map(eqn, invals)
        if name == "pallas_call":
            # One kernel by construction; block windows are invisible
            # from the jaxpr, so operands count at full extent — an
            # over-estimate that only ever UNDERSTATES an already-fused
            # stage's headroom (it can't promote a fused stage to the
            # advisor's top slot).
            self._launch()
            for v in invals:
                self._read_full(v)
            self.notes.add("pallas_call traffic at operand granularity")
            return [_Src(None) for _ in range(nouts)]
        if name in _WINDOW_READ:
            self._read_win(invals[0], _aval_bytes(eqn.outvars[0].aval))
            for v in invals[1:]:
                self._read_full(v)
            self._launch(name)
            return [_Src(None) for _ in range(nouts)]
        if name in _WINDOW_RMW:
            upd = (eqn.invars[1].aval if name == "dynamic_update_slice"
                   else eqn.invars[2].aval)
            nb = _aval_bytes(upd)
            self._read_win(invals[0], nb)
            self._write_win(invals[0], nb)
            for v in invals[1:]:
                self._read_full(v)
            self._launch(name)
            out = (_Src(invals[0].root)
                   if isinstance(invals[0], _Src) else _Src(None))
            return [out] + [_Src(None)] * (nouts - 1)
        if name in _ALIAS_PRIMS:
            return [_Src(invals[0].root
                         if isinstance(invals[0], _Src) else None)]
        if name in _VIEW_PRIMS:        # broadcast: splat, fused for free
            for v in invals:
                self._read_full(v)
            return [_Src(None) for _ in range(nouts)]
        for v in invals:
            self._read_full(v)
        self._launch(name)
        return [_Src(None) for _ in range(nouts)]

    # -- control flow ---------------------------------------------------
    def _p_while(self, eqn, invals):
        from ..analysis.interp import eval_jaxpr
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cond_c = invals[:cn]
        body_c = invals[cn:cn + bn]
        carry = invals[cn + bn:]
        self._in_while += 1
        eval_jaxpr(p["cond_jaxpr"], cond_c + carry, self)
        outs = eval_jaxpr(p["body_jaxpr"], body_c + carry, self)
        self._in_while -= 1
        self.notes.add("loop bodies counted once (traffic/launch floor)")
        joined = []
        for init, out in zip(carry, outs):
            r0 = init.root if isinstance(init, _Src) else None
            r1 = out.root if isinstance(out, _Src) else None
            joined.append(_Src(r0 if r0 == r1 else None))
        return joined

    def _p_cond(self, eqn, invals):
        from ..analysis.interp import eval_jaxpr
        pred, ops = invals[0], invals[1:]
        self._read_full(pred)
        base = (self.launches, self.while_launches, self.collectives,
                self.collectives_in_loop)
        best = base
        outs_all = []
        for br in eqn.params["branches"]:
            (self.launches, self.while_launches, self.collectives,
             self.collectives_in_loop) = base
            outs_all.append(eval_jaxpr(br, list(ops), self))
            now = (self.launches, self.while_launches, self.collectives,
                   self.collectives_in_loop)
            # One branch executes: price each counter at its own branch
            # max (element-wise — tuple max would be lexicographic and
            # drop a cheaper-launch branch's larger collective count).
            best = tuple(max(b, n) for b, n in zip(best, now))
        (self.launches, self.while_launches, self.collectives,
         self.collectives_in_loop) = best
        joined = []
        for i in range(len(eqn.outvars)):
            roots = {o[i].root if isinstance(o[i], _Src) else None
                     for o in outs_all}
            joined.append(_Src(roots.pop() if len(roots) == 1 else None))
        return joined

    def _p_scan(self, eqn, invals):
        from ..analysis.interp import eval_jaxpr
        p = eqn.params
        nc, ncar = p["num_consts"], p["num_carry"]
        consts, carry = invals[:nc], invals[nc:nc + ncar]
        xs = invals[nc + ncar:]
        for v in xs:                    # all iterations read everything
            self._read_full(v)
        self._in_while += 1
        eval_jaxpr(p["jaxpr"], consts + carry + [_Src(None)] * len(xs),
                   self)
        self._in_while -= 1
        self.notes.add("scan body counted once (floor)")
        return [_Src(None) for _ in eqn.outvars]

    def _p_shard_map(self, eqn, invals):
        from ..analysis.interp import eval_jaxpr
        inner = eqn.params.get("jaxpr")
        if inner is not None and not hasattr(inner, "consts"):
            # shard_map carries an OPEN jaxpr; close it for the shared
            # evaluator (per-shard avals: traffic is per-chip).
            try:
                from jax.extend.core import ClosedJaxpr
            except ImportError:
                from jax.core import ClosedJaxpr
            inner = ClosedJaxpr(inner, ())
        if inner is None or len(inner.jaxpr.invars) != len(invals):
            return [self.unknown(v.aval, invals, "shard_map")
                    for v in eqn.outvars]
        outs = eval_jaxpr(inner, list(invals), self)
        self.notes.add("shard_map traffic/launches are per-chip")
        return [o if isinstance(o, _Src) else _Src(None) for o in outs]


def jaxpr_traffic(closed, arg_avals) -> dict:
    """Traffic floor of one traced program: {"bytes_read",
    "bytes_written", "launches", "while_launches", "collectives",
    "collectives_in_loop", "notes"}.  ``arg_avals`` are the FLAT input
    avals in invar order (what the caller traced with)."""
    from ..analysis.interp import eval_jaxpr
    dom = TrafficDomain()
    outs = eval_jaxpr(closed, [_Src(i) for i in range(len(arg_avals))],
                      dom)
    bytes_read = 0
    for i, aval in enumerate(arg_avals):
        full = _aval_bytes(aval)
        if i in dom.full_read:
            bytes_read += full
        elif i in dom.win_read:
            bytes_read += min(full, dom.win_read[i])
    bytes_written = 0
    for o, var in zip(outs, closed.jaxpr.outvars):
        r = o.root if isinstance(o, _Src) else None
        if r is not None:
            if r in dom.win_written:    # carry-through, window-updated
                bytes_written += min(_aval_bytes(var.aval),
                                     dom.win_written[r])
            # unchanged passthrough of an input: nothing written
        else:
            bytes_written += _aval_bytes(var.aval)
    return {"bytes_read": bytes_read, "bytes_written": bytes_written,
            "launches": dom.launches,
            "while_launches": dom.while_launches,
            "collectives": dom.collectives,
            "collectives_in_loop": dom.collectives_in_loop,
            "notes": sorted(dom.notes)}


# ---------------------------------------------------------------------------
# Per-stage traffic over the shared profiler stage programs


def stage_traffic(dims, B: int, K: int, *, pipeline: str = "v1",
                  compact_method: str = "scatter", v3_force=None,
                  seen_capacity: int = 1 << 14, ring: int = 16,
                  swarm_pipeline: str = "v1") -> Dict[str, dict]:
    """{stage: traffic dict} for the ChunkProfiler's stage programs —
    v1 granularity (expand/fingerprint/dedup_insert/enqueue), the v3
    fused-stage granularity, the v4 megakernel granularity
    (front/insert_enqueue), or the swarm walk-kernel granularity
    (expand/choose/latch/ring_probe; ``ring``/``swarm_pipeline``
    mirror the swarm engine's dedup capacity and resolved expand
    pipeline) — matching ``chunk_stages`` keys so measured means and
    modeled floors join by name.  Trace-only (eval_shape chains the
    stage signatures); nothing executes or compiles.

    ``seen_capacity`` shapes the probe table aval; it never enters the
    byte model (the insert touches probe WINDOWS, counted per round) —
    any small power of two gives identical results."""
    import jax
    import jax.tree_util as jtu

    from . import profile as profile_mod
    from ..ops import fpset

    if pipeline == "swarm":
        progs = profile_mod.build_stage_programs_swarm(
            dims, B, ring, pipeline=swarm_pipeline)
    elif pipeline == "v3":
        progs = profile_mod.build_stage_programs_v3(
            dims, B, K, compact_method, force=v3_force)
    elif pipeline == "v4":
        progs = profile_mod.build_stage_programs_v4(
            dims, B, K, compact_method, force=v3_force)
    else:
        progs = profile_mod.build_stage_programs(dims, B, K,
                                                 compact_method)

    def traced(fn, *args):
        closed = jax.make_jaxpr(fn)(*args)
        flat, _ = jtu.tree_flatten(args)
        return jaxpr_traffic(closed, flat)

    import jax.numpy as jnp
    from ..models.schema import state_width
    sw = state_width(dims)
    rows = jax.ShapeDtypeStruct((B, sw), jnp.uint8)
    valid = jax.ShapeDtypeStruct((B,), jnp.bool_)
    out: Dict[str, dict] = {}
    if pipeline == "swarm":
        k = jax.ShapeDtypeStruct((), jnp.int32)
        rh = jax.ShapeDtypeStruct((B, ring), jnp.uint32)
        rp = jax.ShapeDtypeStruct((B,), jnp.int32)
        packed, en, ovf = jax.eval_shape(progs["expand"], rows, valid)
        out["expand"] = traced(progs["expand"], rows, valid)
        choice = jax.eval_shape(progs["choose"], en, k)
        out["choose"] = traced(progs["choose"], en, k)
        _nrows, fp_hi, fp_lo = jax.eval_shape(progs["latch"], packed,
                                              choice)
        out["latch"] = traced(progs["latch"], packed, choice)
        out["ring_probe"] = traced(progs["ring_probe"], rh, rh, rp,
                                   fp_hi, fp_lo, en, ovf)
        for t in out.values():
            t["bytes_total"] = t["bytes_read"] + t["bytes_written"]
        return out
    seen = jax.eval_shape(lambda: fpset.empty(seen_capacity))
    qnext = jax.ShapeDtypeStruct((progs["queue_rows"], sw), jnp.uint8)
    if pipeline == "v4":
        lane_id, kvalid, kh, kl, krows = jax.eval_shape(
            progs["front"], rows, valid)
        out["front"] = traced(progs["front"], rows, valid)
        out["insert_enqueue"] = traced(progs["insert_enqueue"], seen, kh,
                                       kl, kvalid, krows, qnext)
    elif pipeline == "v3":
        states, en = jax.eval_shape(progs["masks"], rows, valid)
        out["masks"] = traced(progs["masks"], rows, valid)
        lane_id, kvalid = jax.eval_shape(progs["compact"], en)
        out["compact"] = traced(progs["compact"], en)
        kh, kl, krows = jax.eval_shape(progs["fingerprint"], states,
                                       lane_id)
        out["fingerprint"] = traced(progs["fingerprint"], states, lane_id)
        out["insert_enqueue"] = traced(progs["insert_enqueue"], seen, kh,
                                       kl, kvalid, krows, qnext)
    else:
        cflat, lane_id, kvalid = jax.eval_shape(progs["expand"], rows,
                                                valid)
        out["expand"] = traced(progs["expand"], rows, valid)
        kstates, kh, kl = jax.eval_shape(progs["fingerprint"], cflat,
                                         lane_id)
        out["fingerprint"] = traced(progs["fingerprint"], cflat, lane_id)
        out["dedup_insert"] = traced(progs["dedup_insert"], seen, kh, kl,
                                     kvalid)
        out["enqueue"] = traced(progs["enqueue"], qnext, kstates, kvalid)
    for t in out.values():
        t["bytes_total"] = t["bytes_read"] + t["bytes_written"]
    return out


# ---------------------------------------------------------------------------
# Roofline rows + fusion advisor


def build_roofline(traffic: Dict[str, dict],
                   stage_means: Optional[Dict[str, float]],
                   peak: Dict[str, object]) -> Dict[str, dict]:
    """Join the modeled floors with the ChunkProfiler's measured stage
    means into roofline rows.  Rows without a measurement (profiler off,
    mesh) keep floors + launches with null achieved fields — the model
    half still renders, it just cannot claim a fraction."""
    bw = float(peak["bytes_per_sec"])
    means = stage_means or {}
    rows: Dict[str, dict] = {}
    for stage, t in traffic.items():
        floor_s = t["bytes_total"] / bw if bw else None
        mean_s = means.get(stage)
        row = {
            "bytes_read": t["bytes_read"],
            "bytes_written": t["bytes_written"],
            "bytes_total": t["bytes_total"],
            "launches": t["launches"],
            "floor_seconds": round(floor_s, 9) if floor_s else floor_s,
            "mean_seconds": (round(mean_s, 6) if mean_s is not None
                             else None),
            "achieved_gbps": None,
            "bandwidth_fraction": None,
            "headroom_seconds": None,
            "notes": t.get("notes", []),
        }
        if mean_s:
            achieved = t["bytes_total"] / mean_s
            row["achieved_gbps"] = round(achieved / 1e9, 3)
            row["bandwidth_fraction"] = round(achieved / bw, 6) if bw \
                else None
            row["headroom_seconds"] = round(
                max(0.0, mean_s - (floor_s or 0.0)), 6)
        rows[stage] = row
    return rows


def advise(rows: Dict[str, dict], overhead_seconds: float) -> dict:
    """Rank the stages by reclaimable time — ``launches x per-launch
    overhead + bandwidth headroom`` — and name the top fusion candidate.
    Stages without a measured mean score on the launch tax alone (their
    headroom is unknowable statically), so the advisor still answers on
    a profiler-less run, just with less evidence; ``basis`` says which
    case each row is."""
    ranking = []
    for stage, row in rows.items():
        tax = row["launches"] * overhead_seconds
        headroom = row["headroom_seconds"]
        score = tax + (headroom or 0.0)
        ranking.append({
            "stage": stage,
            "score_seconds": round(score, 6),
            "launch_tax_seconds": round(tax, 6),
            "headroom_seconds": headroom,
            "launches": row["launches"],
            "bandwidth_fraction": row["bandwidth_fraction"],
            "basis": ("measured+model" if headroom is not None
                      else "launch-model-only"),
        })
    ranking.sort(key=lambda r: (-r["score_seconds"], r["stage"]))
    if not ranking:
        return {"ranking": [], "top": None, "verdict": "no stages"}
    top = ranking[0]
    frac = top["bandwidth_fraction"]
    verdict = (
        f"fuse '{top['stage']}' next: {top['launches']} device ops/batch "
        f"(~{top['launch_tax_seconds'] * 1e3:.2f} ms launch tax)"
        + (f", {top['headroom_seconds'] * 1e3:.2f} ms above the "
           f"bandwidth floor"
           f" ({frac:.1%} of peak achieved)" if top["headroom_seconds"]
           is not None and frac is not None else ", unmeasured headroom"))
    return {"ranking": ranking, "top": top["stage"], "verdict": verdict}
