"""Hunt observatory — saturation estimation + walk-level analytics for
the swarm tier.

The exhaustive engines always know where they stand: the frontier
either empties (closure) or the budget runs out, and obs/report.py
renders the exact census.  A swarm hunt has no such ground truth — the
user's only real question is *"is this hunt saturated, or still finding
new states?"* — and TLC's ``-simulate`` never answers it.  This module
does, with the classic species-richness machinery:

- **observation stream**: every ring-accepted state visit is one
  observation of one species (a 64-bit fingerprint).  The engine
  classifies each observation on-device against two persistent Bloom
  filters (ops/walk_kernels.py ``bloom_*``): *fresh* (first observation
  of its species) or *promote* (exactly the second), so the host only
  ever fetches a handful of scalars per chunk;
- **Good-Turing missing mass**: with ``N`` observations of which
  ``n1 = fresh - promote`` species were seen exactly once, the Turing
  estimate of the probability that the NEXT accepted state is a
  never-seen species is ``n1 / N`` (``hunt/unseen_mass``), and sample
  coverage is its complement (``hunt/saturation``).  Totals are
  partition-invariant (the per-step series is not: slicing reorders
  which duplicate observation counts as "first", but never how many
  species or repeats exist).  Bloom collisions bias *fresh* down — the
  report carries the filter load so the bias is auditable;
- **walk analytics**: the per-step novelty series (bounded,
  pair-folded), the final-depth histogram of every restarted trace,
  the restart-reason census (dead end / pack overflow / constraint /
  ring revisit / depth bound), and the per-family efficacy table —
  which Holzmann diversification subsets *find* states vs spin.

Everything here is host-side arithmetic over already-fetched counters:
the observatory can never perturb the hunt (tests/test_swarm.py pins
verdict + fingerprint-multiset bit-identity with hunt on vs off).

Surfaces: the ``hunt`` run event (payload ``hunt``) and the enriched
``swarm_progress``/``run_end`` swarm blocks, ``SwarmResult.report
["hunt"]``, bench JSON, the server ``check`` response, ``hunt/*``
registry gauges (Prometheus: ``raft_hunt_*``), flight-recorder ``hunt``
snapshots, and the history ledger.  Zero-dep and jax-free like all of
``obs/``; keep it OFF the eager ``obs/__init__`` import path (same
heap-layout precaution as obs/perf.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

#: Restart-reason keys, in the engine's decision order (the first rule
#: that fires owns the restart).
RESTART_REASONS = ("deadend", "overflow", "constraint", "revisit",
                   "depth_bound")


def good_turing(fresh: int, promote: int, accepts: int) -> dict:
    """The Good-Turing block from the three device tallies.

    ``fresh`` species were observed at least once, of which ``promote``
    reached a second observation — so ``n1 = fresh - promote`` are
    singletons.  Turing's estimator: ``unseen_mass = n1 / N`` is the
    probability the next observation is a new species;
    ``saturation = 1 - unseen_mass`` is the sample coverage.  An empty
    sample is reported as fully unsaturated (the honest prior for a
    hunt that has seen nothing)."""
    n1 = max(0, int(fresh) - int(promote))
    n = int(accepts)
    unseen = (n1 / n) if n else 1.0
    return {
        "observations": n,
        "distinct_observed": int(fresh),
        "singletons": n1,
        "doubletons_plus": int(promote),
        "unseen_mass": round(unseen, 6),
        "saturation": round(1.0 - unseen, 6),
    }


class NoveltySeries:
    """Bounded per-step novelty curve: ``(step_end, novel, accepts)``
    buckets, pair-folded whenever the point budget is exceeded — a
    million-step hunt still renders as <= ``max_points`` buckets with
    exact totals (folding adds adjacent buckets, it never drops one)."""

    def __init__(self, max_points: int = 2048):
        if max_points < 2:
            raise ValueError(f"max_points must be >= 2, got {max_points}")
        self.max_points = max_points
        self._steps: List[int] = []     # bucket-end global step (exclusive)
        self._novel: List[int] = []
        self._accepts: List[int] = []

    def extend(self, k_end: int, novel: Sequence[int],
               accepts: Sequence[int]) -> None:
        """Append per-step counts for global steps ``[k_end - len,
        k_end)`` (one entry per lockstep step, summed over walks)."""
        n = len(novel)
        for i in range(n):
            self._steps.append(int(k_end) - n + i + 1)
            self._novel.append(int(novel[i]))
            self._accepts.append(int(accepts[i]))
        while len(self._steps) > self.max_points:
            self._fold()

    def _fold(self) -> None:
        self._steps = self._steps[1::2]
        self._novel = [a + b for a, b in
                       zip(self._novel[::2], self._novel[1::2])]
        self._accepts = [a + b for a, b in
                         zip(self._accepts[::2], self._accepts[1::2])]

    def points(self) -> List[List[int]]:
        """``[[step_end, novel, accepts], ...]`` — the raw buckets."""
        return [[s, f, a] for s, f, a in
                zip(self._steps, self._novel, self._accepts)]

    def rates(self, buckets: int = 0) -> List[List[float]]:
        """``[[step_end, novel_rate], ...]`` with ``novel_rate`` the
        fresh fraction of accepted visits per bucket; optionally
        re-folded down to <= ``buckets`` points (drift gating wants a
        fixed-width curve regardless of run length)."""
        steps, novel, acc = (list(self._steps), list(self._novel),
                             list(self._accepts))
        if buckets:
            while len(steps) > buckets:
                steps = steps[1::2]
                novel = [a + b for a, b in zip(novel[::2], novel[1::2])]
                acc = [a + b for a, b in zip(acc[::2], acc[1::2])]
        return [[s, round(f / a, 6) if a else 0.0]
                for s, f, a in zip(steps, novel, acc)]


class HuntAccumulator:
    """Host-side fold of the per-chunk device tallies — one instance
    per swarm run, fed once per (chunk, slice) dispatch.  Pure
    arithmetic; owns no device state."""

    def __init__(self, family_names: Sequence[str], max_depth: int,
                 bloom_cells: int = 0, max_points: int = 2048):
        self.family_names = list(family_names)
        self.max_depth = int(max_depth)
        self.bloom_cells = int(bloom_cells)
        self.series = NoveltySeries(max_points)
        self.accepts = 0
        self.fresh = 0
        self.promote = 0
        self.steps = 0                  # lockstep walk-steps observed
        self.reasons = {k: 0 for k in RESTART_REASONS}
        self.depth_hist = [0] * (self.max_depth + 1)
        f = len(self.family_names)
        self.fam_chosen = [0] * f
        self.fam_accept = [0] * f
        self.fam_fresh = [0] * f
        #: Final Bloom-filter load (occupied cell fraction), set once at
        #: run end from the fetched filter — the estimator-health knob.
        self.bloom_load: Optional[float] = None

    def add_slice(self, fresh: int, promote: int, reasons: Sequence[int],
                  depth_hist: Sequence[int], fam_chosen: Sequence[int],
                  fam_accept: Sequence[int],
                  fam_fresh: Sequence[int]) -> None:
        """Fold one dispatch's scalar/vector tallies (``reasons`` in
        :data:`RESTART_REASONS` order)."""
        self.fresh += int(fresh)
        self.promote += int(promote)
        for k, v in zip(RESTART_REASONS, reasons):
            self.reasons[k] += int(v)
        for i, v in enumerate(depth_hist):
            if i < len(self.depth_hist):
                self.depth_hist[i] += int(v)
        for i, v in enumerate(fam_chosen):
            self.fam_chosen[i] += int(v)
        for i, v in enumerate(fam_accept):
            self.fam_accept[i] += int(v)
        for i, v in enumerate(fam_fresh):
            self.fam_fresh[i] += int(v)

    def add_steps(self, k_end: int, walk_steps: int,
                  novel_per_step: Sequence[int],
                  accept_per_step: Sequence[int]) -> None:
        """Fold one chunk round's per-step series (summed over slices):
        ``walk_steps`` is walks x steps this round; the series arrays
        cover global steps ``[k_end - len, k_end)``."""
        self.steps += int(walk_steps)
        self.accepts += sum(int(a) for a in accept_per_step)
        self.series.extend(k_end, novel_per_step, accept_per_step)

    # -- projections ---------------------------------------------------
    def estimate(self) -> dict:
        return good_turing(self.fresh, self.promote, self.accepts)

    def snapshot(self) -> dict:
        """The compact live block riding ``swarm_progress`` payloads,
        flight-recorder ``hunt`` records, and the ``hunt/*`` gauges."""
        est = self.estimate()
        recent = self.series.rates(buckets=8)
        return {
            "saturation": est["saturation"],
            "unseen_mass": est["unseen_mass"],
            "distinct_observed": est["distinct_observed"],
            "singletons": est["singletons"],
            "observations": est["observations"],
            "novel_rate_recent": recent[-1][1] if recent else 0.0,
            "revisit_rate": (round(self.reasons["revisit"] / self.steps, 6)
                             if self.steps else 0.0),
        }


def build_report(acc: HuntAccumulator,
                 violation_at_seconds: Optional[float] = None,
                 wall_seconds: float = 0.0) -> dict:
    """Assemble the hunt report dict — the swarm sibling of
    obs/report.py's statespace report, from one finished run's
    accumulator."""
    est = acc.estimate()
    total_restarts = sum(acc.reasons.values())
    # Depth distribution of completed traces, with summary quantiles.
    hist = list(acc.depth_hist)
    n_traces = sum(hist)
    mean_depth = (sum(i * c for i, c in enumerate(hist)) / n_traces
                  if n_traces else 0.0)
    p50 = p90 = 0
    if n_traces:
        cum = 0
        for i, c in enumerate(hist):
            cum += c
            if not p50 and cum * 2 >= n_traces:
                p50 = i
            if cum * 10 >= n_traces * 9:
                p90 = i
                break
    families = []
    for i, name in enumerate(acc.family_names):
        chosen = acc.fam_chosen[i] if i < len(acc.fam_chosen) else 0
        accepted = acc.fam_accept[i] if i < len(acc.fam_accept) else 0
        fresh = acc.fam_fresh[i] if i < len(acc.fam_fresh) else 0
        families.append({
            "family": name,
            "chosen": int(chosen),
            "accepted": int(accepted),
            "fresh": int(fresh),
            "fresh_rate": round(fresh / chosen, 6) if chosen else 0.0,
        })
    bloom: dict = {}
    if acc.bloom_cells:
        bloom["cells"] = acc.bloom_cells
        if acc.bloom_load is not None:
            bloom["load"] = round(acc.bloom_load, 6)
            # Two-probe filter: collision (false-positive) probability
            # ~= load^2 — the fraction of genuinely-fresh observations
            # the estimator may have misfiled as repeats.
            bloom["collision_probability"] = round(acc.bloom_load ** 2, 8)
    return {
        "saturation": est["saturation"],
        "unseen_mass": est["unseen_mass"],
        "distinct_observed": est["distinct_observed"],
        "singletons": est["singletons"],
        "doubletons_plus": est["doubletons_plus"],
        "observations": est["observations"],
        "steps": acc.steps,
        "novel_rate": (round(est["distinct_observed"] / est["observations"],
                             6) if est["observations"] else 0.0),
        "revisit_rate": (round(acc.reasons["revisit"] / acc.steps, 6)
                         if acc.steps else 0.0),
        "novelty_curve": acc.series.rates(),
        "depth": {"histogram": hist, "traces": n_traces,
                  "mean": round(mean_depth, 4), "p50": p50, "p90": p90},
        "restarts": {"total": total_restarts, **dict(acc.reasons)},
        "families": families,
        "bloom": bloom,
        "time_to_violation_seconds": violation_at_seconds,
        "wall_seconds": round(float(wall_seconds), 6),
    }


def feed_metrics(report: dict, metrics) -> None:
    """Mirror the report's scalar spine into ``hunt/*`` gauges (the
    Prometheus names: ``raft_hunt_saturation`` etc. via obs/expose.py's
    prefix rule) — gauges, idempotent across re-reports."""
    metrics.gauge("hunt/saturation", report["saturation"])
    metrics.gauge("hunt/unseen_mass", report["unseen_mass"])
    metrics.gauge("hunt/distinct_observed", report["distinct_observed"])
    metrics.gauge("hunt/singletons", report["singletons"])
    metrics.gauge("hunt/novel_rate", report["novel_rate"])
    metrics.gauge("hunt/revisit_rate", report["revisit_rate"])
    if report.get("time_to_violation_seconds") is not None:
        metrics.gauge("hunt/time_to_violation_seconds",
                      report["time_to_violation_seconds"])


def render_report(report: dict) -> str:
    """The human block printed at swarm run end (CLI summary / bench
    stderr) — headline saturation, then the depth/restart/family
    tables."""
    lines = [
        f"hunt: {report['distinct_observed']:,} distinct states observed "
        f"in {report['observations']:,} accepted visits "
        f"({report['steps']:,} walk-steps); saturation "
        f"{report['saturation']:.4f} (unseen mass "
        f"{report['unseen_mass']:.4f}, {report['singletons']:,} "
        f"singletons)",
    ]
    if report.get("time_to_violation_seconds") is not None:
        lines.append(f"  first counterexample at "
                     f"{report['time_to_violation_seconds']:.3f}s")
    curve = report.get("novelty_curve") or []
    if curve:
        tail = curve[-1]
        lines.append(f"  novelty rate: {report['novel_rate']:.4f} overall"
                     f", {tail[1]:.4f} in the last bucket "
                     f"(step {tail[0]:,})")
    d = report.get("depth") or {}
    if d.get("traces"):
        lines.append(f"  trace depth: mean {d['mean']:.2f}, p50 "
                     f"{d['p50']}, p90 {d['p90']} over {d['traces']:,} "
                     f"completed traces")
    r = report.get("restarts") or {}
    if r.get("total"):
        parts = ", ".join(f"{k}={r[k]:,}" for k in RESTART_REASONS
                          if r.get(k))
        lines.append(f"  restarts: {r['total']:,} ({parts})")
    fams = report.get("families") or []
    live = [f for f in fams if f["chosen"]]
    if live:
        best = max(live, key=lambda f: f["fresh"])
        lines.append("  family        chosen    accepted       fresh  "
                     "fresh-rate")
        for f in live:
            lines.append(f"  {f['family']:<12s} {f['chosen']:9,d} "
                         f"{f['accepted']:11,d} {f['fresh']:11,d}  "
                         f"{f['fresh_rate']:10.4f}")
        lines.append(f"  most productive family: {best['family']} "
                     f"({best['fresh']:,} fresh states)")
    bloom = report.get("bloom") or {}
    if bloom.get("load") is not None:
        lines.append(f"  estimator filter: {bloom['cells']:,} cells at "
                     f"load {bloom['load']:.4f} (collision p "
                     f"{bloom['collision_probability']:.2e})")
    return "\n".join(lines)


def summarize(report: Optional[dict]) -> dict:
    """The compact projection the run-history ledger stores per swarm
    run (obs/history.py ``kind=swarm`` entries) — enough for the
    trajectory table and bench_diff's hunt columns."""
    if not report:
        return {}
    fams = report.get("families") or []
    live = [f for f in fams if f.get("fresh")]
    best = max(live, key=lambda f: f["fresh"]) if live else None
    return {
        "saturation": report["saturation"],
        "unseen_mass": report["unseen_mass"],
        "distinct_observed": report["distinct_observed"],
        "novel_rate": report["novel_rate"],
        "revisit_rate": report["revisit_rate"],
        "novelty_curve": _refold(report.get("novelty_curve") or [], 8),
        "depth_p50": (report.get("depth") or {}).get("p50"),
        "time_to_violation_seconds":
            report.get("time_to_violation_seconds"),
        "best_family": best["family"] if best else None,
    }


def _refold(curve: List[List[float]], buckets: int) -> List[List[float]]:
    """Fold a rendered rate curve down to <= ``buckets`` points for the
    ledger (rates averaged pairwise — close enough for drift gating; the
    exact counts live only in the full report)."""
    pts = [list(p) for p in curve]
    while len(pts) > buckets:
        pts = [[b[0], round((a[1] + b[1]) / 2.0, 6)]
               for a, b in zip(pts[::2], pts[1::2])]
    return pts
