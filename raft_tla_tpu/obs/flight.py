"""Flight recorder — always-on black-box telemetry + postmortem dumps.

Every other observability leg (JSONL events, Chrome traces, the chunk
profiler, coverage) is post-hoc and file-based: a run that dies over a
wedged TPU tunnel, a SIGTERM'd supervised child, or a fault-injected
``os._exit`` leaves nothing but whatever already hit disk.  This module
is the black box: a bounded in-memory ring of recent telemetry records
— run events (mirrored automatically from every :class:`RunEventLog`,
file-backed or not), rate-limited per-chunk progress snapshots,
chunk-stage profiler samples, and run-context/registry deltas — always
on at near-zero overhead (a deque append under a lock per record, a few
records per second at most), plus a **postmortem dump**: when the
recorder is armed for a run and the process dies abnormally, the ring
(and a final metrics-registry snapshot) is written to
``<workdir>/postmortem.json`` so the last N seconds of telemetry
survive the crash.

Dump triggers, covering every way a run has actually died in this repo:

- an exception escaping ``engine.run()`` (the engines' shared
  ``_telemetry_run`` dumps in its error path and stamps
  ``postmortem_path`` into the ``run_end`` event);
- ``SIGTERM`` (handler installed while armed; dumps, then re-delivers
  the signal with the previous disposition restored);
- a fault-injected hard kill (``resilience/faults.py`` ``_die`` dumps
  best-effort before ``os._exit`` — atexit hooks never run there);
- any other interpreter exit while armed (``atexit`` backstop).

A clean run end (exhausted / violation / deadlock / budget stop)
disarms without dumping — a postmortem file always means a run that did
NOT complete.

The ring is also the live half of **run attach**: the server's ``watch``
op and the standalone ``--metrics-port`` HTTP listener
(:mod:`.expose`) read their snapshots from here, never from the event
file — so a plain ``check``/bench run is watchable with no event log
configured at all.

Zero-dependency and jax-free at import, like the rest of ``obs/``
(:func:`host_fingerprint` imports jax lazily and degrades to nulls).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Dict, Optional

#: Records kept per kind.  Per-kind rings (not one shared ring) so a
#: high-rate kind (progress) can never evict the rare, precious ones
#: (run events, run context) out of the black box.
DEFAULT_CAPACITY = 256

#: Minimum seconds between per-chunk progress records — the engines'
#: chunk loops call :meth:`FlightRecorder.progress` every stats fetch,
#: and this floor keeps the always-on cost at a few records/second no
#: matter how fast the host loop spins.  The first record of a run
#: always lands (the limiter is per-recorder, reset on ``arm``).
PROGRESS_EVERY_S = 0.5


def host_fingerprint() -> dict:
    """Identity of the host + accelerator stack a measurement ran on:
    CPU model, jax/jaxlib versions, device kind and count, platform.
    Embedded in bench JSON (``scripts/bench_diff.py`` warns when two
    diffed benches disagree — absolute numbers off a different host are
    not comparable, the PR 7 BENCH_r05 trap) and in every postmortem
    dump.  Best-effort: a jax-less or /proc-less environment yields
    nulls, never a raise."""
    out = {"cpu_model": None, "jax": None, "jaxlib": None,
           "device_kind": None, "device_count": None, "platform": None,
           "hostname": None}
    try:
        import platform as _platform
        out["hostname"] = _platform.node() or None
        out["cpu_model"] = _platform.processor() or None
    except Exception:
        pass
    try:                       # Linux: the processor() string is often ""
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    out["cpu_model"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    try:
        import jax
        out["jax"] = jax.__version__
        try:
            import jaxlib
            out["jaxlib"] = getattr(jaxlib, "__version__", None)
        except Exception:
            pass
        devs = jax.devices()
        out["device_count"] = len(devs)
        out["platform"] = devs[0].platform
        out["device_kind"] = getattr(devs[0], "device_kind", None)
    except Exception:
        pass
    return out


class FlightRecorder:
    """Bounded per-kind ring of recent telemetry records.

    Thread-safe: the engine's host loop, the server's handler threads,
    and the HTTP listener all touch one process-global instance
    (:data:`RECORDER`).  Each record is a small dict stamped with a
    process-monotone ``seq`` (so consumers can order across kinds and
    detect new data) and ``ts``.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        # RLock, not Lock: the SIGTERM/atexit dump path runs snapshot()
        # in the MAIN thread, and the signal handler can interrupt the
        # main thread INSIDE a record() that already holds the lock — a
        # plain Lock would deadlock the dying process right where it is
        # supposed to write its black box.  (CPython guarantees the
        # interrupted critical section resumes after the handler; a
        # same-thread re-entrant read sees a consistent-enough ring —
        # at worst the in-flight record is absent.)
        self._lock = threading.RLock()
        self._rings: Dict[str, deque] = {}
        self._seq = 0
        # -- postmortem arming (one run at a time, like the device) ----
        self._live = False            # a run is in flight (watch liveness)
        self._armed_path: Optional[str] = None   # where a dump would land
        self._armed_context: Optional[dict] = None
        self._metrics = None           # registry to snapshot into dumps
        self._live_evlog = None        # run's RunEventLog for watch_attach
        self._hooks_installed = False
        self._prev_sigterm = None
        self._last_progress = float("-inf")

    # -- recording -----------------------------------------------------
    def record(self, kind: str, **fields) -> int:
        """Append one record; returns its ``seq``."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            ring = self._rings.get(kind)
            if ring is None:
                ring = self._rings[kind] = deque(maxlen=self.capacity)
            rec = {"seq": seq, "ts": round(time.time(), 6)}
            rec.update(fields)
            ring.append(rec)
        return seq

    def progress(self, **fields) -> Optional[int]:
        """Rate-limited progress record (the engines' per-chunk call):
        at most one per :data:`PROGRESS_EVERY_S`; the first call after
        ``arm()`` always records.  Returns the seq when recorded."""
        now = time.monotonic()
        if now - self._last_progress < PROGRESS_EVERY_S:
            return None
        self._last_progress = now
        return self.record("progress", **fields)

    # -- reading -------------------------------------------------------
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def snapshot(self, kinds=None, last: Optional[int] = None) -> dict:
        """{kind: [records oldest->newest]}; ``last`` trims each kind to
        its newest N records."""
        with self._lock:
            out = {}
            for kind, ring in self._rings.items():
                if kinds is not None and kind not in kinds:
                    continue
                recs = list(ring)
                if last is not None:
                    recs = recs[-last:]
                out[kind] = recs
            return out

    def last_record(self, kind: str) -> Optional[dict]:
        with self._lock:
            ring = self._rings.get(kind)
            return ring[-1] if ring else None

    def last_event(self, event_type: str) -> Optional[dict]:
        """Newest mirrored run event of one type (the ``event`` ring
        holds every RunEventLog emit) — how the watch op finds the last
        ``level_complete`` / ``coverage`` / ``run_end``."""
        with self._lock:
            ring = self._rings.get("event")
            if not ring:
                return None
            for rec in reversed(ring):
                if rec.get("event") == event_type:
                    return rec
        return None

    def clear(self) -> None:
        """Testing hook: drop every ring (the seq counter keeps
        advancing — consumers rely on it being process-monotone)."""
        with self._lock:
            self._rings.clear()

    # -- run attach ----------------------------------------------------
    def set_live_evlog(self, evlog) -> None:
        """Register the current run's event log (engines'
        ``_telemetry_run``) so a watcher attaching mid-run can leave a
        ``watch_attach`` event in the run's durable record."""
        self._live_evlog = evlog

    def note_attach(self, **client) -> int:
        """A watcher attached (server ``watch`` op / HTTP ``/flight``
        consumer): record it in the ring and, when a run is live, in its
        JSONL event log (payload object ``client`` — see
        ``obs/events.py`` KNOWN_EVENTS)."""
        seq = self.record("watch_attach", client=dict(client))
        evlog = self._live_evlog
        if evlog is not None:
            try:
                evlog.emit("watch_attach", client=dict(client))
            except Exception:
                pass               # attach bookkeeping must never kill a run
        return seq

    # -- postmortem ----------------------------------------------------
    def arm(self, path: Optional[str], metrics=None,
            context: Optional[dict] = None) -> None:
        """Arm for one run: liveness on (watchers see a run in flight)
        and the postmortem dump targeted at ``path``.  ``path`` None
        arms the bookkeeping (context/metrics still feed watch
        snapshots, ``armed`` still reports the live run) but disables
        the dump — there is nowhere to write it."""
        self._live = True
        self._armed_path = path
        self._armed_context = dict(context or {})
        self._metrics = metrics
        self._last_progress = float("-inf")   # first progress always lands
        if context:
            self.record("run_context", **dict(context))
        self._install_hooks()

    def disarm(self) -> None:
        """The run completed (any stop_reason) — no dump on exit."""
        self._live = False
        self._armed_path = None
        self._armed_context = None
        self._metrics = None

    def context(self) -> dict:
        """The armed run's context snapshot (engine/pipeline plus any
        ``run_context_extra`` tags — job id / tenant under the serving
        layer); {} when no run is live.  The server's per-job watch
        reads this to attribute the ring's progress records to the job
        that owns the device right now."""
        with self._lock:
            return dict(self._armed_context or {})

    @property
    def armed(self) -> bool:
        """A run is in flight.  Liveness, NOT dump-path-configured: a
        run without a checkpoint/postmortem dir is still live for the
        watch consumers (its dump is simply disabled — ``dump()``
        no-ops on the missing path)."""
        return self._live

    def dump(self, reason: str, path: Optional[str] = None
             ) -> Optional[str]:
        """Write the postmortem JSON (atomic tmp + rename) and return
        its path, or None when there is nowhere to write (not armed and
        no explicit path).  Never raises — this runs from signal
        handlers, ``atexit``, and the fault-injection death path, where
        a secondary failure must not mask the primary one."""
        path = path or self._armed_path
        if path is None:
            return None
        try:
            doc = {
                "postmortem": True,
                "reason": reason,
                "written_ts": round(time.time(), 6),
                "pid": os.getpid(),
                "context": dict(self._armed_context or {}),
                "host": host_fingerprint(),
                "records": self.snapshot(),
            }
            mt = self._metrics
            if mt is not None:
                try:
                    doc["metrics"] = mt.snapshot()
                except Exception:
                    pass
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, default=str)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:
            return None

    # -- process hooks -------------------------------------------------
    def _install_hooks(self) -> None:
        """atexit backstop + SIGTERM handler, installed once per
        process.  The SIGTERM handler dumps, restores the previous
        disposition, and re-delivers — so supervisors/timeouts that
        expect SIGTERM to kill still see it kill."""
        if self._hooks_installed:
            return
        self._hooks_installed = True
        atexit.register(self._atexit_dump)
        try:
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._on_sigterm)
        except (ValueError, OSError):
            # Not the main thread (server-embedded engines) or an
            # environment without signals: the atexit/error paths still
            # cover everything except a hard external kill.
            self._prev_sigterm = None

    def _atexit_dump(self) -> None:
        if self.armed:
            self.dump("atexit_while_armed")

    def _on_sigterm(self, signum, frame) -> None:
        self.dump("sigterm")
        # Restore the EXACT previous disposition (SIG_IGN / SIG_DFL /
        # handler — signal.signal accepts all three) and re-deliver:
        # the host's choice is respected, including a deliberate
        # SIG_IGN, which the recorder must not convert into a death.
        prev = self._prev_sigterm
        try:
            signal.signal(signum, prev if prev is not None
                          else signal.SIG_DFL)
        except (ValueError, OSError, TypeError):
            pass
        try:
            os.kill(os.getpid(), signum)    # re-deliver
        except OSError:
            os._exit(143)


#: The process-global black box every layer feeds (engines, event logs,
#: profiler, server) and every consumer reads (watch op, HTTP listener,
#: postmortem dumps).  One per process, like the server's _METRICS.
RECORDER = FlightRecorder()
