"""Metrics registry — counters, gauges, histograms, and phase timing.

TLC's only live observability is a ~per-minute progress line; the engines
here replace their scattered prints and packed-stats side channels with
one registry every layer (engine, mesh, server, CLI, bench) writes into.
Zero-dependency and thread-safe: the checker service handles requests on
multiple threads against one process-global registry, and the engines'
host loops update theirs thousands of times per second — so every
operation is a few dict ops under one lock, and nothing here ever
imports jax (the registry must be importable in tooling that never
touches a device).

Metric name convention: ``<layer>/<what>`` with ``/`` separators, e.g.
``engine/generated``, ``server/requests/check``, ``phase/stats_fetch``.
Phase timers observe into histograms named ``phase/<name>`` whose
``total`` is the accumulated seconds — ``phase_seconds()`` projects just
that view, which is what run events and bench reports embed.
"""

from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

# Histogram bucket upper bounds: geometric decades with a 1-2-5 ladder,
# 1 us .. 100 s — wide enough for both kernel dispatches and whole
# checkpoint writes.  Values are generic (a histogram may observe bytes
# or rows too); the ladder just has to be monotone.
_DEFAULT_BOUNDS = tuple(
    m * 10.0 ** e for e in range(-6, 3) for m in (1.0, 2.0, 5.0))

PHASE_PREFIX = "phase/"


class Histogram:
    """Lock-free value container; the registry serializes access."""

    __slots__ = ("count", "total", "min", "max", "bounds", "buckets")

    def __init__(self, bounds=_DEFAULT_BOUNDS):
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.bounds = bounds
        self.buckets = [0] * (len(bounds) + 1)   # +1 overflow bucket

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        lo, hi = 0, len(self.bounds)
        while lo < hi:                    # first bound >= value
            mid = (lo + hi) // 2
            if self.bounds[mid] < value:
                lo = mid + 1
            else:
                hi = mid
        self.buckets[lo] += 1

    def summary(self) -> dict:
        out = {"count": self.count, "total": self.total}
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
            out["mean"] = self.total / self.count
            # Only the occupied buckets, keyed by upper bound ("+inf" for
            # the overflow bucket) — compact in JSON snapshots.
            out["buckets"] = {
                ("+inf" if i == len(self.bounds)
                 else f"{self.bounds[i]:g}"): c
                for i, c in enumerate(self.buckets) if c}
        return out


class MetricsRegistry:
    """Named counters (monotone), gauges (last value wins), histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}
        # Optional span tracer (obs/tracing.py SpanTracer, duck-typed —
        # this module stays import-free): when attached and enabled,
        # every phase_timer block is mirrored as a Chrome-trace span, so
        # one attachment instruments every existing phase site.
        self.tracer = None

    # -- writers -------------------------------------------------------
    def counter(self, name: str, inc: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            h.observe(value)

    @contextmanager
    def phase_timer(self, name: str):
        """Accumulate wall seconds into the ``phase/<name>`` histogram.
        Phases are the host-side stages of an engine loop (chunk dispatch,
        stats fetch, spill drain, checkpoint, ...): non-overlapping by
        construction at the call sites, so their totals partition the
        loop's wall time."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(PHASE_PREFIX + name, time.perf_counter() - t0)
            tracer = self.tracer
            if tracer is not None and tracer.enabled:
                tracer.complete(name, t0)

    # -- readers -------------------------------------------------------
    def counter_value(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge_value(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def phase_seconds(self) -> Dict[str, float]:
        """{phase name: accumulated seconds} — the per-phase breakdown
        run events and bench JSON embed."""
        with self._lock:
            return {name[len(PHASE_PREFIX):]: h.total
                    for name, h in self._histograms.items()
                    if name.startswith(PHASE_PREFIX)}

    def snapshot(self) -> dict:
        """One JSON-ready dict of everything — the supported interface
        for ``--metrics-out`` files and the server's ``stats`` op."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {n: h.summary()
                               for n, h in self._histograms.items()},
            }


def phase_delta(now: Dict[str, float],
                base: Optional[Dict[str, float]]) -> Dict[str, float]:
    """Per-phase seconds accumulated since ``base`` (an earlier
    ``phase_seconds()`` snapshot) — used to scope phase breakdowns to one
    run or one BFS level on a registry that outlives both."""
    if not base:
        return dict(now)
    return {k: v - base.get(k, 0.0) for k, v in now.items()
            if v - base.get(k, 0.0) > 0.0}
