"""Per-stage chunk profiler — the instrument behind ``--profile-chunks``.

NORTHSTAR.md's decision rule needs per-stage timings of the chunk
pipeline (expand / fingerprint / dedup-insert / enqueue) on whatever
hardware a run actually lands on, and until now the only way to get them
was the ad-hoc ``scripts/profile_step.py`` path on a synthetic frontier.
This module puts that decomposition behind one API and INSIDE the
engine: every Nth chunk call, the profiler re-runs the sampled batch
through separately-jitted stage programs with ``block_until_ready``
fencing between stages, accumulates per-stage histograms into the
MetricsRegistry (``chunk_stage/<stage>``), and emits one
``chunk_profile`` run event plus a stderr stage-budget table keyed to
NORTHSTAR's measured per-stage budget at run end.

The profiler is **observational**: the engine's real fused chunk program
still does all the work, and the sampled batch is re-expanded on the
side purely for measurement — so engine results are bit-identical with
profiling on or off (the acceptance contract), at the cost of roughly
``1/N`` extra compute.  The staged decomposition measures the v1
(classical) pipeline regardless of which pipeline the engine runs: the
stages are the NORTHSTAR budget's row headings, and cross-pipeline
comparability of the headings matters more than mirroring v2's fused
deltas.  The separately-timed ``total`` program (all four stages in one
jit, non-donating) is the fusion reference: ``sum(stages)`` vs
``total`` prices the inter-stage materialization XLA elides.

Stage -> pipeline mapping (engine/chunk.py):

    expand        unflatten + vmap(expand) over B*G lanes + compaction
    fingerprint   gather K candidate structs + two-lane hash
    dedup_insert  ops/fpset.py batched insert (in-batch dedup + probe)
    enqueue       materialize K uint8 rows + position scatter

jax is imported lazily (constructor), keeping ``obs`` importable in
device-less tooling like the rest of the package.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, Optional

STAGES = ("expand", "fingerprint", "dedup_insert", "enqueue")

STAGE_PREFIX = "chunk_stage/"

#: NORTHSTAR.md §c measured v1 budget (ms/batch, B=2048, TPU v5e chip),
#: folded onto this profiler's stage granularity: expand includes the
#: compact stage (36.6 + 21.4), enqueue includes row materialization
#: (24.6 + 14.5).  Reference column of the run-end table — compare
#: shapes, not absolutes, off that hardware/batch.
NORTHSTAR_BUDGET_MS = {
    "expand": 58.0,
    "fingerprint": 6.7,
    "dedup_insert": 5.3,
    "enqueue": 39.1,
}


def build_stage_programs(dims, B: int, K: int,
                         compact_method: str = "scatter") -> dict:
    """The jitted stage programs, shared by :class:`ChunkProfiler` and
    ``scripts/profile_step.py`` (which used to hand-roll the same
    decomposition).  Returns ``{stage_name: fn, "total": fn,
    "queue_rows": int, "empty_seen": fn}``; see module docstring for the
    stage -> pipeline mapping."""
    import jax
    import jax.numpy as jnp

    from ..models.actions import build_expand
    from ..models.schema import flatten_state, unflatten_state
    from ..ops import fpset
    from ..ops.compact import build_compactor
    from ..ops.fingerprint import build_fingerprint

    _I32 = jnp.int32
    G = dims.n_instances
    BG = B * G
    expand = build_expand(dims)
    fingerprint = build_fingerprint(dims)
    compactor = build_compactor(B, G, K, method=compact_method)
    # Profiler-local next-queue: K live rows + K per-lane trash slots
    # (the engine's trash-spread rule, ops/fpset.py design note 3).  The
    # scatter's cost scales with the rows written (K), not the target
    # size, so the small target keeps profiler memory bounded.
    QP = K

    def s_expand(rows, valid):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        cands, en, _ovf = jax.vmap(expand)(states)
        en = en & valid[:, None]
        _P, _total, lane_id, kvalid = compactor(en)
        cflat = jax.tree.map(
            lambda a: a.reshape((BG,) + a.shape[2:]), cands)
        return cflat, lane_id, kvalid

    def s_fingerprint(cflat, lane_id):
        kstates = jax.tree.map(lambda a: a[lane_id], cflat)
        kh, kl = jax.vmap(fingerprint)(kstates)
        return kstates, kh, kl

    def s_insert(seen, kh, kl, kvalid):
        return fpset.insert(seen, kh, kl, kvalid)

    def s_enqueue(qnext, kstates, enq):
        krows = jax.vmap(flatten_state, (0, None))(kstates, dims)
        pos = jnp.cumsum(enq.astype(_I32)) - 1
        pos = jnp.where(enq, pos, QP + jnp.arange(K, dtype=_I32))
        return qnext.at[pos].set(krows, mode="drop")

    def s_total(rows, valid, seen, qnext):
        cflat, lane_id, kvalid = s_expand(rows, valid)
        kstates, kh, kl = s_fingerprint(cflat, lane_id)
        seen, new, _fail = s_insert(seen, kh, kl, kvalid)
        qnext = s_enqueue(qnext, kstates, new)
        return seen, qnext, jnp.sum(new, dtype=_I32)

    return {
        "expand": jax.jit(s_expand),
        "fingerprint": jax.jit(s_fingerprint),
        "dedup_insert": jax.jit(s_insert),
        "enqueue": jax.jit(s_enqueue),
        "total": jax.jit(s_total),
        "queue_rows": 2 * QP,
        "empty_seen": lambda cap: fpset.empty(cap),
    }


class ChunkProfiler:
    """Samples every ``every``-th chunk call of one engine run.

    Owns two persistent FPSet tables (staged and fused paths receive
    every sample's keys, so both see the same load trajectory) and a
    small scatter target; everything else is rebuilt per sample from the
    engine's own frontier rows."""

    def __init__(self, dims, *, batch: int, lanes: int,
                 seen_capacity: int, compact_method: str = "scatter",
                 every: int = 1, metrics=None):
        self.dims = dims
        self.B, self.K = int(batch), int(lanes)
        self.seen_capacity = int(seen_capacity)
        self.compact_method = compact_method
        self.every = max(1, int(every))
        self.metrics = metrics
        self.samples = 0
        self._calls = 0
        self._built = None
        self._stage_totals: Dict[str, float] = {s: 0.0 for s in STAGES}
        self._total_total = 0.0

    def reset(self) -> None:
        """Zero the accumulators for a new run (warm/reused engines);
        compiled stage programs and the persistent tables are kept."""
        self.samples = 0
        self._calls = 0
        self._stage_totals = {s: 0.0 for s in STAGES}
        self._total_total = 0.0

    # -- sampling ------------------------------------------------------
    def want(self) -> bool:
        """Advance the chunk-call counter; True when this call should be
        sampled (first call always is, so short runs still profile)."""
        self._calls += 1
        return (self._calls - 1) % self.every == 0

    def _build(self, rows, valid):
        import jax
        import jax.numpy as jnp
        progs = build_stage_programs(self.dims, self.B, self.K,
                                     self.compact_method)
        from ..models.schema import state_width
        sw = state_width(self.dims)
        self._qnext = jnp.zeros((progs["queue_rows"], sw), jnp.uint8)
        self._seen_staged = progs["empty_seen"](self.seen_capacity)
        self._seen_total = progs["empty_seen"](self.seen_capacity)
        # One untimed pass compiles every program, so compile time never
        # lands in the first sample's histogram bucket.
        cflat, lane_id, kvalid = progs["expand"](rows, valid)
        kstates, kh, kl = progs["fingerprint"](cflat, lane_id)
        self._seen_staged, new, _f = progs["dedup_insert"](
            self._seen_staged, kh, kl, kvalid)
        self._qnext = progs["enqueue"](self._qnext, kstates, new)
        self._seen_total, self._qnext, n = progs["total"](
            rows, valid, self._seen_total, self._qnext)
        jax.block_until_ready((self._seen_staged, self._qnext, n))
        self._built = progs
        return progs

    def sample(self, rows, valid) -> None:
        """Profile one batch: ``rows`` [B, sw] device/host rows, ``valid``
        [B] bool parent-validity mask.  Fenced with block_until_ready
        before and between stages so each interval is one stage's device
        time (plus one dispatch — the fused ``total`` row prices that
        overhead)."""
        import jax
        import jax.numpy as jnp
        rows = jnp.asarray(rows)
        valid = jnp.asarray(valid)
        progs = self._built or self._build(rows, valid)
        mt = self.metrics
        timings = {}

        def fence(stage, out):
            jax.block_until_ready(out)
            t = time.perf_counter()
            dt = t - fence.t0
            fence.t0 = t
            timings[stage] = dt
            return out

        fence.t0 = time.perf_counter()
        cflat, lane_id, kvalid = fence(
            "expand", progs["expand"](rows, valid))
        kstates, kh, kl = fence(
            "fingerprint", progs["fingerprint"](cflat, lane_id))
        self._seen_staged, new, fail = fence("dedup_insert", progs[
            "dedup_insert"](self._seen_staged, kh, kl, kvalid))
        if mt is not None and bool(fail):
            # The profiler's private table saturated: dedup_insert
            # timings from here on measure a pathologically full probe,
            # not the engine's.  Surfaced as a counter, never fatal.
            mt.counter("chunk_stage/insert_fail")
        self._qnext = fence(
            "enqueue", progs["enqueue"](self._qnext, kstates, new))
        self._seen_total, self._qnext, _n = fence("total", progs[
            "total"](rows, valid, self._seen_total, self._qnext))

        self.samples += 1
        for s in STAGES:
            self._stage_totals[s] += timings[s]
            if mt is not None:
                mt.observe(STAGE_PREFIX + s, timings[s])
        self._total_total += timings["total"]
        if mt is not None:
            mt.observe(STAGE_PREFIX + "total", timings["total"])

    # -- reporting -----------------------------------------------------
    def stage_means(self) -> Dict[str, float]:
        """{stage: mean seconds/sampled batch} (+ ``total`` for the fused
        reference) — what bench JSON embeds as ``chunk_stages``."""
        if not self.samples:
            return {}
        out = {s: self._stage_totals[s] / self.samples for s in STAGES}
        out["total"] = self._total_total / self.samples
        return out

    def summary(self) -> dict:
        means = self.stage_means()
        staged_sum = sum(means.get(s, 0.0) for s in STAGES)
        return {
            "samples": self.samples,
            "every": self.every,
            "batch": self.B,
            "lanes": self.K,
            "stages": {s: {"mean_seconds": round(means[s], 6),
                           "total_seconds":
                               round(self._stage_totals[s], 6),
                           "budget_ms_b2048": NORTHSTAR_BUDGET_MS[s]}
                       for s in STAGES} if self.samples else {},
            "fused_total_mean_seconds": round(means.get("total", 0.0), 6),
            "staged_sum_mean_seconds": round(staged_sum, 6),
        }

    def render_table(self) -> str:
        """Run-end stage-budget table: measured mean ms per stage next to
        NORTHSTAR §c's measured v1 budget (B=2048, v5e) — the shape
        comparison that names which stage to fuse next."""
        means = self.stage_means()
        if not means:
            return "chunk profile: no samples"
        lines = [f"chunk profile ({self.samples} sampled batches, "
                 f"B={self.B}, K={self.K}, every {self.every}th call):",
                 f"  {'stage':14s} {'mean ms':>10s} {'share':>7s} "
                 f"{'NORTHSTAR ms@B=2048':>20s}"]
        staged_sum = sum(means[s] for s in STAGES)
        for s in STAGES:
            ms = means[s] * 1e3
            share = means[s] / staged_sum if staged_sum else 0.0
            lines.append(f"  {s:14s} {ms:10.2f} {share:6.1%} "
                         f"{NORTHSTAR_BUDGET_MS[s]:20.1f}")
        lines.append(f"  {'sum(stages)':14s} {staged_sum * 1e3:10.2f}")
        lines.append(f"  {'fused total':14s} {means['total'] * 1e3:10.2f}"
                     f"  (inter-stage materialization the fused program "
                     f"elides)")
        return "\n".join(lines)

    def finish(self, evlog, stream=None) -> None:
        """Run-end hook: emit the ``chunk_profile`` event and print the
        stage-budget table.  No-op when nothing was sampled."""
        if not self.samples:
            return
        evlog.emit("chunk_profile", **self.summary())
        print(self.render_table(), file=stream or sys.stderr)


def profile_stages(dims, rows, valid=None, *, lanes: Optional[int] = None,
                   seen_capacity: int = 1 << 20, n: int = 3,
                   compact_method: str = "scatter") -> Dict[str, float]:
    """One-shot stage profile of a frontier batch — the
    ``scripts/profile_step.py`` entry point, now on the shared programs.
    Returns {stage: mean seconds} over ``n`` fenced repetitions (first
    repetition untimed: compile)."""
    import numpy as np

    from ..ops.compact import choose_k
    B = int(rows.shape[0])
    if valid is None:
        valid = np.ones((B,), bool)
    prof = ChunkProfiler(
        dims, batch=B,
        lanes=lanes or choose_k(B, dims.n_instances, None),
        seen_capacity=seen_capacity, compact_method=compact_method)
    for _ in range(n):
        prof.sample(rows, valid)
    return prof.stage_means()
