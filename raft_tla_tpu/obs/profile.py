"""Per-stage chunk profiler — the instrument behind ``--profile-chunks``.

NORTHSTAR.md's decision rule needs per-stage timings of the chunk
pipeline (expand / fingerprint / dedup-insert / enqueue) on whatever
hardware a run actually lands on, and until now the only way to get them
was the ad-hoc ``scripts/profile_step.py`` path on a synthetic frontier.
This module puts that decomposition behind one API and INSIDE the
engine: every Nth chunk call, the profiler re-runs the sampled batch
through separately-jitted stage programs with ``block_until_ready``
fencing between stages, accumulates per-stage histograms into the
MetricsRegistry (``chunk_stage/<stage>``), and emits one
``chunk_profile`` run event plus a stderr stage-budget table keyed to
NORTHSTAR's measured per-stage budget at run end.

The profiler is **observational**: the engine's real fused chunk program
still does all the work, and the sampled batch is re-expanded on the
side purely for measurement — so engine results are bit-identical with
profiling on or off (the acceptance contract), at the cost of roughly
``1/N`` extra compute.  The staged decomposition measures the v1
(classical) pipeline regardless of which pipeline the engine runs: the
stages are the NORTHSTAR budget's row headings, and cross-pipeline
comparability of the headings matters more than mirroring v2's fused
deltas.  The separately-timed ``total`` program (all four stages in one
jit, non-donating) is the fusion reference: ``sum(stages)`` vs
``total`` prices the inter-stage materialization XLA elides.

Stage -> pipeline mapping (engine/chunk.py):

    expand        unflatten + vmap(expand) over B*G lanes + compaction
    fingerprint   gather K candidate structs + two-lane hash
    dedup_insert  ops/fpset.py batched insert (in-batch dedup + probe)
    enqueue       materialize K uint8 rows + position scatter

``pipeline="v3"`` switches to the FUSED-stage granularity of the v3
chunk (ops/pipeline_v3.py) — the decomposition that actually runs
there, so its table prices the fused kernels instead of a pipeline the
engine is not executing:

    masks           guards-only enabled/overflow masks (actions2)
    compact         lane compaction (Pallas scan on TPU, XLA off it)
    fingerprint     delta fingerprints + K-lane sparse rows
    insert_enqueue  the fused probe/insert -> DMA-append tail

``pipeline="v4"`` narrows further to the v4 megakernel granularity
(ops/pipeline_v4.py) — two fused launches per chunk:

    front           masks + POR + compact + fingerprint megakernel
    insert_enqueue  the fused probe/insert -> DMA-append tail

``pipeline="swarm"`` profiles the walk-kernel decomposition of the
swarm tier's lockstep scan body (engine/swarm.py) instead of a
frontier chunk — same fencing discipline, swarm stage headings:

    expand        unflatten + enabled/overflow masks (v1 full expand
                  or v2 guards-only, matching the engine's pipeline)
    choose        counter-PRNG draws + family-diversified choice
    latch         chosen-successor materialization + fingerprint
    ring_probe    per-walk ring dedup probe -> push -> restart reset

``scripts/bench_diff.py`` folds the granularities onto common coarse
stages when diffing across pipelines.

jax is imported lazily (constructor), keeping ``obs`` importable in
device-less tooling like the rest of the package.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, Optional

STAGES = ("expand", "fingerprint", "dedup_insert", "enqueue")
STAGES_V3 = ("masks", "compact", "fingerprint", "insert_enqueue")
STAGES_V4 = ("front", "insert_enqueue")
STAGES_SWARM = ("expand", "choose", "latch", "ring_probe")

STAGE_PREFIX = "chunk_stage/"

#: NORTHSTAR.md §c measured v1 budget (ms/batch, B=2048, TPU v5e chip),
#: folded onto this profiler's stage granularity: expand includes the
#: compact stage (36.6 + 21.4), enqueue includes row materialization
#: (24.6 + 14.5).  Reference column of the run-end table — compare
#: shapes, not absolutes, off that hardware/batch.
NORTHSTAR_BUDGET_MS = {
    "expand": 58.0,
    "fingerprint": 6.7,
    "dedup_insert": 5.3,
    "enqueue": 39.1,
}


def build_stage_programs(dims, B: int, K: int,
                         compact_method: str = "scatter") -> dict:
    """The jitted stage programs, shared by :class:`ChunkProfiler` and
    ``scripts/profile_step.py`` (which used to hand-roll the same
    decomposition).  Returns ``{stage_name: fn, "total": fn,
    "queue_rows": int, "empty_seen": fn}``; see module docstring for the
    stage -> pipeline mapping."""
    import jax
    import jax.numpy as jnp

    from ..models.actions import build_expand
    from ..models.schema import flatten_state, unflatten_state
    from ..ops import fpset
    from ..ops.compact import build_compactor
    from ..ops.fingerprint import build_fingerprint

    _I32 = jnp.int32
    G = dims.n_instances
    BG = B * G
    expand = build_expand(dims)
    fingerprint = build_fingerprint(dims)
    compactor = build_compactor(B, G, K, method=compact_method)
    # Profiler-local next-queue: K live rows + K per-lane trash slots
    # (the engine's trash-spread rule, ops/fpset.py design note 3).  The
    # scatter's cost scales with the rows written (K), not the target
    # size, so the small target keeps profiler memory bounded.
    QP = K

    def s_expand(rows, valid):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        cands, en, _ovf = jax.vmap(expand)(states)
        en = en & valid[:, None]
        _P, _total, lane_id, kvalid = compactor(en)
        cflat = jax.tree.map(
            lambda a: a.reshape((BG,) + a.shape[2:]), cands)
        return cflat, lane_id, kvalid

    def s_fingerprint(cflat, lane_id):
        kstates = jax.tree.map(lambda a: a[lane_id], cflat)
        kh, kl = jax.vmap(fingerprint)(kstates)
        return kstates, kh, kl

    def s_insert(seen, kh, kl, kvalid):
        return fpset.insert(seen, kh, kl, kvalid)

    def s_enqueue(qnext, kstates, enq):
        krows = jax.vmap(flatten_state, (0, None))(kstates, dims)
        pos = jnp.cumsum(enq.astype(_I32)) - 1
        pos = jnp.where(enq, pos, QP + jnp.arange(K, dtype=_I32))
        return qnext.at[pos].set(krows, mode="drop")

    def s_total(rows, valid, seen, qnext):
        cflat, lane_id, kvalid = s_expand(rows, valid)
        kstates, kh, kl = s_fingerprint(cflat, lane_id)
        seen, new, _fail = s_insert(seen, kh, kl, kvalid)
        qnext = s_enqueue(qnext, kstates, new)
        return seen, qnext, jnp.sum(new, dtype=_I32)

    return {
        "expand": jax.jit(s_expand),
        "fingerprint": jax.jit(s_fingerprint),
        "dedup_insert": jax.jit(s_insert),
        "enqueue": jax.jit(s_enqueue),
        "total": jax.jit(s_total),
        "queue_rows": 2 * QP,
        "empty_seen": lambda cap: fpset.empty(cap),
    }


def build_stage_programs_v3(dims, B: int, K: int,
                            compact_method: str = "scatter",
                            force: Optional[dict] = None) -> dict:
    """Stage programs at the v3 fused-chunk granularity (STAGES_V3).

    The decomposition mirrors engine/chunk.py's v3 path exactly: v2
    guards-only masks, the plan-resolved compactor (Pallas where it
    lowers), delta fingerprints + sparse K-lane rows, then the fused
    probe/insert->enqueue tail.  ``force`` must be the ENGINE'S
    ``EngineConfig.v3_force_stages`` so the per-stage plan resolution
    matches the engine's.  Caveat: when the fused tail itself fell back,
    this profiler's split-tail stand-in is the DEFAULT XLA pair
    (fpset.insert + scatter) regardless of insert_method/enqueue_method
    overrides — the fallback engine's exotic-override combinations are
    not mirrored here.  Same return shape as
    ``build_stage_programs``."""
    import jax
    import jax.numpy as jnp

    from ..models.actions2 import build_v2
    from ..models.schema import flatten_state, state_width, unflatten_state
    from ..ops import fpset
    from ..ops import pipeline_v3
    from ..ops.compact import build_compactor

    _I32 = jnp.int32
    G = dims.n_instances
    v2 = build_v2(dims)
    QP = K
    # Re-resolved here (not reused from the engine) because the fused
    # tail binds the queue capacity statically and the profiler runs
    # against its own QP-row scratch queue — but the INPUTS that decide
    # each stage's lowering (force, compact_method, platform) are the
    # engine's, so the resolved lowerings match the engine's plan.
    plan = pipeline_v3.resolve_plan(B, G, K, Q=QP, sw=state_width(dims),
                                    force=force)
    compactor = plan.compactor or build_compactor(B, G, K,
                                                  method=compact_method)

    def s_masks(rows, valid):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        en, _ovf = jax.vmap(v2.masks)(states)
        return states, en & valid[:, None]

    def s_compact(en):
        _P, _total, lane_id, kvalid = compactor(en)
        return lane_id, kvalid

    def s_fingerprint(states, lane_id):
        ph = jax.vmap(v2.parent_hash)(states)
        pidx = lane_id // G
        kparents = jax.tree.map(lambda a: a[pidx], states)
        kph = jax.tree.map(lambda a: a[pidx], ph)
        kh, kl, kstates = jax.vmap(v2.lane_out)(kparents, kph, lane_id % G)
        krows = jax.vmap(flatten_state, (0, None))(kstates, dims)
        return kh, kl, krows

    def s_tail(seen, kh, kl, kvalid, krows, qnext):
        cons = jnp.ones((K,), bool)
        if plan.tail is not None:
            seen, new, fail, qnext = plan.tail(
                seen, kh, kl, kvalid, krows, cons, jnp.int32(0), qnext)
        else:
            seen, new, fail = fpset.insert(seen, kh, kl, kvalid)
            pos = jnp.cumsum(new.astype(_I32)) - 1
            pos = jnp.where(new, pos, QP + jnp.arange(K, dtype=_I32))
            qnext = qnext.at[pos].set(krows, mode="drop")
        # fail rides out so the profiler's insert_fail saturation
        # counter guards v3 sampling exactly as it guards v1's.
        return seen, qnext, new, fail

    def s_total(rows, valid, seen, qnext):
        states, en = s_masks(rows, valid)
        lane_id, kvalid = s_compact(en)
        kh, kl, krows = s_fingerprint(states, lane_id)
        seen, qnext, new, _fail = s_tail(seen, kh, kl, kvalid, krows,
                                         qnext)
        return seen, qnext, jnp.sum(new, dtype=_I32)

    return {
        "masks": jax.jit(s_masks),
        "compact": jax.jit(s_compact),
        "fingerprint": jax.jit(s_fingerprint),
        "insert_enqueue": jax.jit(s_tail),
        "total": jax.jit(s_total),
        "queue_rows": 2 * QP,
        "empty_seen": lambda cap: fpset.empty(cap),
        "plan": plan,
    }


def build_stage_programs_v4(dims, B: int, K: int,
                            compact_method: str = "scatter",
                            force: Optional[dict] = None) -> dict:
    """Stage programs at the v4 megakernel granularity (STAGES_V4).

    ``front`` is the whole-chunk VMEM megakernel (masks + compact +
    delta fingerprint in one Pallas launch); ``insert_enqueue`` is the
    same fused tail v3 runs.  When the front group degraded (forced or
    the kernel failed to build), the profiled ``front`` stand-in is the
    v3-style split chain so its timing still covers the same work.
    ``force`` must be the engine's ``EngineConfig.v4_force_stages``.
    Constraint/invariant hooks are not mirrored (profiler scratch runs
    have none), matching the v3 profiler's all-true ``cons``.  Same
    return shape as ``build_stage_programs``."""
    import jax
    import jax.numpy as jnp

    from ..models.actions2 import build_v2
    from ..models.schema import flatten_state, state_width, unflatten_state
    from ..ops import fpset
    from ..ops import pipeline_v4
    from ..ops.compact import build_compactor

    _I32 = jnp.int32
    G = dims.n_instances
    v2 = build_v2(dims)
    QP = K
    plan = pipeline_v4.resolve_plan(
        B, G, K, Q=QP, sw=state_width(dims), force=force,
        front_ctx={"dims": dims, "v2": v2, "constraint": None,
                   "inv_fns": None, "por_mask": None,
                   "por_priority": None})
    compactor = plan.compactor or build_compactor(B, G, K,
                                                  method=compact_method)

    if plan.front is not None:
        def s_front(rows, valid):
            (_en, _ovf, _pruned, _P, _total, lane_id, kvalid, kh, kl,
             krows, _cons, _inv, _phi, _plo) = plan.front(rows, valid)
            return lane_id, kvalid, kh, kl, krows
    else:
        def s_front(rows, valid):
            states = jax.vmap(unflatten_state, (0, None))(rows, dims)
            en, _ovf = jax.vmap(v2.masks)(states)
            en = en & valid[:, None]
            _P, _total, lane_id, kvalid = compactor(en)
            ph = jax.vmap(v2.parent_hash)(states)
            pidx = lane_id // G
            kparents = jax.tree.map(lambda a: a[pidx], states)
            kph = jax.tree.map(lambda a: a[pidx], ph)
            kh, kl, kstates = jax.vmap(v2.lane_out)(kparents, kph,
                                                    lane_id % G)
            krows = jax.vmap(flatten_state, (0, None))(kstates, dims)
            return lane_id, kvalid, kh, kl, krows

    def s_tail(seen, kh, kl, kvalid, krows, qnext):
        cons = jnp.ones((K,), bool)
        if plan.tail is not None:
            seen, new, fail, qnext = plan.tail(
                seen, kh, kl, kvalid, krows, cons, jnp.int32(0), qnext)
        else:
            seen, new, fail = fpset.insert(seen, kh, kl, kvalid)
            pos = jnp.cumsum(new.astype(_I32)) - 1
            pos = jnp.where(new, pos, QP + jnp.arange(K, dtype=_I32))
            qnext = qnext.at[pos].set(krows, mode="drop")
        return seen, qnext, new, fail

    def s_total(rows, valid, seen, qnext):
        _lane_id, kvalid, kh, kl, krows = s_front(rows, valid)
        seen, qnext, new, _fail = s_tail(seen, kh, kl, kvalid, krows,
                                         qnext)
        return seen, qnext, jnp.sum(new, dtype=_I32)

    return {
        "front": jax.jit(s_front),
        "insert_enqueue": jax.jit(s_tail),
        "total": jax.jit(s_total),
        "queue_rows": 2 * QP,
        "empty_seen": lambda cap: fpset.empty(cap),
        "plan": plan,
    }


def build_stage_programs_swarm(dims, B: int, R: int,
                               pipeline: str = "v1") -> dict:
    """Stage programs at the swarm walk-kernel granularity
    (STAGES_SWARM), mirroring one lockstep step of
    ``engine/swarm.py``'s scan body for lane count ``B`` and ring
    capacity ``R``.  ``pipeline`` is the ENGINE'S resolved expand
    pipeline name ("v1" full expand or "v2" guards-only), so the
    profiled expand stage prices the masks the engine actually runs.

    The profiled step is the decision core only: invariant evaluation
    and the violation latch are not mirrored (same rule as the v3/v4
    profilers' all-true ``cons``), and the PRNG is keyed on a
    synthetic ``(seed=0, walk=lane, step=sample)`` tuple — timings
    need representative control flow, not the engine's draws.  The
    per-sample rings persist in the :class:`ChunkProfiler`, so probe
    cost sees a realistically loaded ring, not a cold sentinel one.
    Returns ``{stage: fn, "total": fn, "ring_capacity": R}``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.actions import build_expand
    from ..models.schema import (build_pack_guard, flatten_state,
                                 unflatten_state)
    from ..ops.fingerprint import build_fingerprint
    from ..ops.walk_kernels import (CHOICE_STREAM, FAMILY_STREAM,
                                    family_subset, preferred_choice,
                                    ring_probe, ring_push, ring_reset,
                                    walk_bits)

    _I32 = jnp.int32
    fingerprint = build_fingerprint(dims)
    fam = jnp.asarray(np.repeat(
        np.arange(len(dims.family_sizes), dtype=np.int32),
        dims.family_sizes))
    walk_ids = jnp.arange(B, dtype=jnp.int32)
    epoch = jnp.zeros((B,), jnp.int32)
    seed = jnp.uint32(0)
    lanes = jnp.arange(B)
    v2 = None
    if pipeline == "v2":
        from ..models.actions2 import build_v2
        v2 = build_v2(dims)
    expand = None if v2 is not None else build_expand(dims)
    pack_ok = None if v2 is not None else build_pack_guard(dims)

    def s_expand(rows, valid):
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        if v2 is None:
            cands, en, ovf = jax.vmap(expand)(states)
            ovf = ovf | (en & ~jax.vmap(jax.vmap(pack_ok))(cands))
            packed = cands
        else:
            en, ovf = jax.vmap(v2.masks)(states)
            packed = states
        return packed, en & valid[:, None], ovf

    def s_choose(en, k):
        bits = walk_bits(seed, walk_ids, k, CHOICE_STREAM)
        mbits = walk_bits(seed, walk_ids, epoch, FAMILY_STREAM)
        return preferred_choice(bits, en, family_subset(mbits, fam))

    def s_latch(packed, choice):
        if v2 is None:
            nxt = jax.tree.map(lambda a: a[lanes, choice], packed)
        else:
            ph = jax.vmap(v2.parent_hash)(packed)
            _h, _l, nxt = jax.vmap(v2.lane_out)(packed, ph,
                                                choice.astype(_I32))
        nrows = jax.vmap(flatten_state, (0, None))(nxt, dims)
        fp_hi, fp_lo = jax.vmap(fingerprint)(nxt)
        return nrows, fp_hi, fp_lo

    def s_ring(rh, rl, rp, fp_hi, fp_lo, en, ovf):
        seen = ring_probe(rh, rl, fp_hi, fp_lo)
        accept = (jnp.any(en, axis=1) & ~jnp.any(ovf, axis=1) & ~seen)
        rh, rl, rp = ring_push(rh, rl, rp, fp_hi, fp_lo, accept)
        rh, rl, rp = ring_reset(rh, rl, rp, ~accept)
        return rh, rl, rp, jnp.sum(accept, dtype=_I32)

    def s_total(rows, valid, rh, rl, rp, k):
        packed, en, ovf = s_expand(rows, valid)
        choice = s_choose(en, k)
        _nrows, fp_hi, fp_lo = s_latch(packed, choice)
        return s_ring(rh, rl, rp, fp_hi, fp_lo, en, ovf)

    return {
        "expand": jax.jit(s_expand),
        "choose": jax.jit(s_choose),
        "latch": jax.jit(s_latch),
        "ring_probe": jax.jit(s_ring),
        "total": jax.jit(s_total),
        "ring_capacity": R,
    }


class ChunkProfiler:
    """Samples every ``every``-th chunk call of one engine run.

    Owns two persistent FPSet tables (staged and fused paths receive
    every sample's keys, so both see the same load trajectory) and a
    small scatter target; everything else is rebuilt per sample from the
    engine's own frontier rows."""

    def __init__(self, dims, *, batch: int, lanes: int,
                 seen_capacity: int, compact_method: str = "scatter",
                 pipeline: str = "v1", v3_force=None, every: int = 1,
                 metrics=None, swarm_pipeline: str = "v1",
                 ring: int = 16):
        self.dims = dims
        self.B, self.K = int(batch), int(lanes)
        self.seen_capacity = int(seen_capacity)
        self.compact_method = compact_method
        # The engine's EngineConfig.v3_force_stages (or v4_force_stages
        # when pipeline="v4"), so the profiled stage lowerings are
        # exactly the ones the engine runs.
        self.v3_force = v3_force
        # "v1" = the classical NORTHSTAR-budget decomposition (default,
        # cross-pipeline comparable); "v3"/"v4" = the fused-stage
        # decomposition that chunk actually executes; "swarm" = the
        # walk-kernel step of the swarm tier (swarm_pipeline names the
        # engine's resolved expand pipeline, ring its dedup capacity).
        if pipeline not in ("v1", "v3", "v4", "swarm"):
            raise ValueError(f"profiler pipeline must be "
                             f"v1/v3/v4/swarm, got {pipeline!r}")
        self.pipeline = pipeline
        self.swarm_pipeline = swarm_pipeline
        self.ring_capacity = int(ring)
        self._swarm_k = 0
        self.stages = {"v3": STAGES_V3, "v4": STAGES_V4,
                       "swarm": STAGES_SWARM}.get(pipeline, STAGES)
        self.every = max(1, int(every))
        self.metrics = metrics
        self.samples = 0
        self._calls = 0
        self._built = None
        self._stage_totals: Dict[str, float] = {s: 0.0
                                                for s in self.stages}
        self._total_total = 0.0

    def reset(self) -> None:
        """Zero the accumulators for a new run (warm/reused engines);
        compiled stage programs and the persistent tables are kept."""
        self.samples = 0
        self._calls = 0
        self._stage_totals = {s: 0.0 for s in self.stages}
        self._total_total = 0.0

    # -- sampling ------------------------------------------------------
    def want(self) -> bool:
        """Advance the chunk-call counter; True when this call should be
        sampled (first call always is, so short runs still profile)."""
        self._calls += 1
        return (self._calls - 1) % self.every == 0

    def _build(self, rows, valid):
        import jax
        import jax.numpy as jnp
        if self.pipeline == "swarm":
            from ..ops.walk_kernels import ring_init
            progs = build_stage_programs_swarm(
                self.dims, self.B, self.ring_capacity,
                pipeline=self.swarm_pipeline)
            # Two persistent ring sets, the swarm analogue of the
            # staged/fused FPSet pair below: both paths see the same
            # probe-load trajectory across samples.
            self._ring_s = ring_init(self.B, self.ring_capacity)
            self._ring_t = ring_init(self.B, self.ring_capacity)
            self._staged_chain(progs, rows, valid)
            rh, rl, rp, n = progs["total"](rows, valid, *self._ring_t,
                                           jnp.int32(0))
            self._ring_t = (rh, rl, rp)
            jax.block_until_ready((self._ring_s[0], rh, n))
            self._built = progs
            return progs
        if self.pipeline == "v3":
            progs = build_stage_programs_v3(self.dims, self.B, self.K,
                                            self.compact_method,
                                            force=self.v3_force)
        elif self.pipeline == "v4":
            progs = build_stage_programs_v4(self.dims, self.B, self.K,
                                            self.compact_method,
                                            force=self.v3_force)
        else:
            progs = build_stage_programs(self.dims, self.B, self.K,
                                         self.compact_method)
        from ..models.schema import state_width
        sw = state_width(self.dims)
        self._qnext = jnp.zeros((progs["queue_rows"], sw), jnp.uint8)
        self._seen_staged = progs["empty_seen"](self.seen_capacity)
        self._seen_total = progs["empty_seen"](self.seen_capacity)
        # One untimed pass compiles every program, so compile time never
        # lands in the first sample's histogram bucket.
        self._staged_chain(progs, rows, valid)
        self._seen_total, self._qnext, n = progs["total"](
            rows, valid, self._seen_total, self._qnext)
        jax.block_until_ready((self._seen_staged, self._qnext, n))
        self._built = progs
        return progs

    def _staged_chain(self, progs, rows, valid, fence=None):
        """Run the per-stage programs in pipeline order, fencing each
        when ``fence`` is given (the shared driver for warm-up and
        sampling; one sequence per stage granularity)."""
        fence = fence or (lambda stage, out: out)
        if self.pipeline == "swarm":
            import jax.numpy as jnp
            k = jnp.int32(self._swarm_k)
            packed, en, ovf = fence(
                "expand", progs["expand"](rows, valid))
            choice = fence("choose", progs["choose"](en, k))
            _nrows, fp_hi, fp_lo = fence(
                "latch", progs["latch"](packed, choice))
            rh, rl, rp, _n = fence(
                "ring_probe", progs["ring_probe"](
                    *self._ring_s, fp_hi, fp_lo, en, ovf))
            self._ring_s = (rh, rl, rp)
            return None
        if self.pipeline == "v4":
            lane_id, kvalid, kh, kl, krows = fence(
                "front", progs["front"](rows, valid))
            self._seen_staged, self._qnext, new, fail = fence(
                "insert_enqueue", progs["insert_enqueue"](
                    self._seen_staged, kh, kl, kvalid, krows,
                    self._qnext))
            return fail
        if self.pipeline == "v3":
            states, en = fence("masks", progs["masks"](rows, valid))
            lane_id, kvalid = fence("compact", progs["compact"](en))
            kh, kl, krows = fence(
                "fingerprint", progs["fingerprint"](states, lane_id))
            self._seen_staged, self._qnext, new, fail = fence(
                "insert_enqueue", progs["insert_enqueue"](
                    self._seen_staged, kh, kl, kvalid, krows,
                    self._qnext))
            return fail
        cflat, lane_id, kvalid = fence(
            "expand", progs["expand"](rows, valid))
        kstates, kh, kl = fence(
            "fingerprint", progs["fingerprint"](cflat, lane_id))
        self._seen_staged, new, fail = fence("dedup_insert", progs[
            "dedup_insert"](self._seen_staged, kh, kl, kvalid))
        self._qnext = fence(
            "enqueue", progs["enqueue"](self._qnext, kstates, new))
        return fail

    def sample(self, rows, valid) -> None:
        """Profile one batch: ``rows`` [B, sw] device/host rows, ``valid``
        [B] bool parent-validity mask.  Fenced with block_until_ready
        before and between stages so each interval is one stage's device
        time (plus one dispatch — the fused ``total`` row prices that
        overhead)."""
        import jax
        import jax.numpy as jnp
        rows = jnp.asarray(rows)
        valid = jnp.asarray(valid)
        progs = self._built or self._build(rows, valid)
        mt = self.metrics
        timings = {}

        def fence(stage, out):
            jax.block_until_ready(out)
            t = time.perf_counter()
            dt = t - fence.t0
            fence.t0 = t
            timings[stage] = dt
            return out

        fence.t0 = time.perf_counter()
        fail = self._staged_chain(progs, rows, valid, fence=fence)
        if mt is not None and fail is not None and bool(fail):
            # The profiler's private table saturated: dedup_insert
            # timings from here on measure a pathologically full probe,
            # not the engine's.  Surfaced as a counter, never fatal.
            mt.counter("chunk_stage/insert_fail")
        if self.pipeline == "swarm":
            rh, rl, rp, _n = fence("total", progs["total"](
                rows, valid, *self._ring_t,
                jnp.int32(self._swarm_k)))
            self._ring_t = (rh, rl, rp)
            self._swarm_k += 1
        else:
            self._seen_total, self._qnext, _n = fence("total", progs[
                "total"](rows, valid, self._seen_total, self._qnext))

        self.samples += 1
        for s in self.stages:
            self._stage_totals[s] += timings[s]
            if mt is not None:
                mt.observe(STAGE_PREFIX + s, timings[s])
        self._total_total += timings["total"]
        if mt is not None:
            mt.observe(STAGE_PREFIX + "total", timings["total"])
        # Black-box mirror (obs/flight.py): recent per-stage samples ride
        # in the flight ring, so a postmortem dump carries the last
        # chunk-stage timings even when the run never reached its
        # chunk_profile run-end event.
        try:
            from .flight import RECORDER
            RECORDER.record(
                "chunk_stage", sample=self.samples,
                pipeline=self.pipeline, batch=self.B,
                stages={s: round(timings[s], 6) for s in self.stages},
                total=round(timings["total"], 6))
        except Exception:
            pass

    # -- reporting -----------------------------------------------------
    def stage_means(self) -> Dict[str, float]:
        """{stage: mean seconds/sampled batch} (+ ``total`` for the fused
        reference) — what bench JSON embeds as ``chunk_stages``.  Keys
        follow the profiled granularity (STAGES or STAGES_V3);
        bench_diff folds mismatched granularities when diffing."""
        if not self.samples:
            return {}
        out = {s: self._stage_totals[s] / self.samples
               for s in self.stages}
        out["total"] = self._total_total / self.samples
        return out

    def summary(self) -> dict:
        means = self.stage_means()
        staged_sum = sum(means.get(s, 0.0) for s in self.stages)
        return {
            "samples": self.samples,
            "every": self.every,
            "batch": self.B,
            "lanes": self.K,
            "pipeline": self.pipeline,
            "stages": {s: {"mean_seconds": round(means[s], 6),
                           "total_seconds":
                               round(self._stage_totals[s], 6),
                           # v3 stage names have no NORTHSTAR v1 budget
                           # row; null, never a KeyError.
                           "budget_ms_b2048": NORTHSTAR_BUDGET_MS.get(s)}
                       for s in self.stages} if self.samples else {},
            "fused_total_mean_seconds": round(means.get("total", 0.0), 6),
            "staged_sum_mean_seconds": round(staged_sum, 6),
        }

    def render_table(self) -> str:
        """Run-end stage-budget table: measured mean ms per stage next to
        NORTHSTAR §c's measured v1 budget (B=2048, v5e) — the shape
        comparison that names which stage to fuse next.  v3 runs render
        their fused-stage rows ("-" in the budget column: the v1 budget
        has no such row) — coherent per-granularity output instead of a
        KeyError on the new stage names."""
        means = self.stage_means()
        if not means:
            return "chunk profile: no samples"
        lines = [f"chunk profile ({self.samples} sampled batches, "
                 f"B={self.B}, K={self.K}, every {self.every}th call, "
                 f"{self.pipeline} stages):",
                 f"  {'stage':14s} {'mean ms':>10s} {'share':>7s} "
                 f"{'NORTHSTAR ms@B=2048':>20s}"]
        staged_sum = sum(means[s] for s in self.stages)
        for s in self.stages:
            ms = means[s] * 1e3
            share = means[s] / staged_sum if staged_sum else 0.0
            budget = NORTHSTAR_BUDGET_MS.get(s)
            btxt = f"{budget:20.1f}" if budget is not None else f"{'-':>20s}"
            lines.append(f"  {s:14s} {ms:10.2f} {share:6.1%} {btxt}")
        lines.append(f"  {'sum(stages)':14s} {staged_sum * 1e3:10.2f}")
        lines.append(f"  {'fused total':14s} {means['total'] * 1e3:10.2f}"
                     f"  (inter-stage materialization the fused program "
                     f"elides)")
        return "\n".join(lines)

    def finish(self, evlog, stream=None) -> None:
        """Run-end hook: emit the ``chunk_profile`` event and print the
        stage-budget table.  No-op when nothing was sampled."""
        if not self.samples:
            return
        evlog.emit("chunk_profile", **self.summary())
        print(self.render_table(), file=stream or sys.stderr)


class XlaProfileCapture:
    """Opt-in ``jax.profiler`` trace window over N sampled chunk calls —
    the hardware-truth layer (``--xla-profile[=N]`` / ``XLA_PROFILE``
    directive).

    The host-side chunk profiler above times WHOLE stage programs with
    fences; it cannot see inside a program — which XLA/Mosaic kernels
    run, their launch count, or HBM traffic.  That is exactly the
    evidence NORTHSTAR §d's XLA-vs-Pallas decision needs, and
    ``jax.profiler.start_trace`` captures it (XPlane protos + a
    Perfetto-openable trace under ``<logdir>/plugins/profile/...``).

    Correlation contract: each captured chunk dispatch is bracketed in
    a ``jax.profiler.StepTraceAnnotation("chunk", step_num=i)`` — the
    SAME span name the SpanTracer's ``phase_timer("chunk")`` records in
    the ``--trace-out`` Chrome trace — so the device-profiler timeline
    and the host span timeline line up by name + step index.

    Observational and fail-soft: the capture never changes what the
    engine computes, and a profiler that cannot start (unsupported
    backend, missing permissions over a tunnel) records its failure in
    the ``xla_profile`` event instead of killing the run.
    """

    def __init__(self, logdir: str, chunks: int):
        self.logdir = logdir
        self.chunks = max(1, int(chunks))
        self.steps = 0
        self.active = False
        self.done = False
        self.status: Optional[str] = None

    def _start(self) -> None:
        import jax
        try:
            import os
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self.active = True
            self.status = "ok"
        except Exception as e:
            self.done = True
            self.status = f"start failed: {type(e).__name__}: {e}"

    def step(self):
        """Context manager bracketing ONE chunk dispatch.  Starts the
        trace lazily on the first call (so warm-up compilation never
        pollutes the capture), annotates the step, and stops after
        ``chunks`` calls.  A no-op once done."""
        from contextlib import contextmanager

        @contextmanager
        def _cm():
            if self.done:
                yield
                return
            if not self.active:
                self._start()
                if self.done:           # start failed
                    yield
                    return
            import jax
            self.steps += 1
            try:
                with jax.profiler.StepTraceAnnotation(
                        "chunk", step_num=self.steps):
                    yield
            finally:
                if self.steps >= self.chunks:
                    self.stop()
        return _cm()

    def stop(self) -> None:
        if not self.active:
            return
        self.active = False
        self.done = True
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            self.status = f"stop failed: {type(e).__name__}: {e}"

    def summary(self) -> dict:
        """The ``xla_profile`` event's ``capture`` payload object."""
        return {"logdir": self.logdir, "chunks": self.chunks,
                "steps": self.steps,
                "status": self.status or "never started",
                "span_name": "chunk"}

    def finish(self, evlog) -> None:
        """Run-end hook: close an open window (early-exit runs) and emit
        the ``xla_profile`` event + flight record."""
        self.stop()
        evlog.emit("xla_profile", capture=self.summary())
        try:
            from .flight import RECORDER
            RECORDER.record("xla_profile", capture=self.summary())
        except Exception:
            pass


def profile_stages(dims, rows, valid=None, *, lanes: Optional[int] = None,
                   seen_capacity: int = 1 << 20, n: int = 3,
                   compact_method: str = "scatter",
                   pipeline: str = "v1") -> Dict[str, float]:
    """One-shot stage profile of a frontier batch — the
    ``scripts/profile_step.py`` entry point, now on the shared programs.
    Returns {stage: mean seconds} over ``n`` fenced repetitions (first
    repetition untimed: compile)."""
    import numpy as np

    from ..ops.compact import choose_k
    B = int(rows.shape[0])
    if valid is None:
        valid = np.ones((B,), bool)
    prof = ChunkProfiler(
        dims, batch=B,
        lanes=lanes or choose_k(B, dims.n_instances, None),
        seen_capacity=seen_capacity, compact_method=compact_method,
        pipeline=pipeline)
    for _ in range(n):
        prof.sample(rows, valid)
    return prof.stage_means()
