"""Metrics exposition — Prometheus text format + a standalone listener.

The MetricsRegistry has always been snapshot-able as JSON
(``--metrics-out``, the server's ``stats`` op); this module renders the
same snapshot in the **Prometheus text exposition format**
(version 0.0.4 — the ``text/plain`` format every Prometheus/VictoriaMetrics/
Grafana-agent scraper speaks), so a long check run or the checker
service can sit behind a stock scrape config with zero glue:

- :func:`render_prometheus` — snapshot dict -> exposition text.
  Name mapping: ``engine/distinct`` -> ``raft_engine_distinct_total``
  (counters get the conventional ``_total`` suffix), gauges keep their
  sanitized name, histograms emit cumulative ``_bucket{le="..."}`` rows
  plus ``_sum``/``_count``.  Optional labels (e.g. ``host="3"`` for one
  controller of a multi-host group) are rendered on every sample.
- :func:`parse_prometheus` — a strict self-contained parser/validator
  for the same format (zero-dep, so tests and CI can gate "the
  exposition is valid" without installing a Prometheus client).
- :func:`serve_metrics` / :func:`start_metrics_server` — a tiny
  threaded HTTP listener (``--metrics-port`` on the CLI,
  ``BENCH_METRICS_PORT`` on the bench, ``--metrics-port`` on the
  checker service) with three endpoints: ``/metrics`` (the exposition —
  point a scraper here), ``/flight`` (the flight recorder's ring as
  JSON — what ``python -m raft_tla_tpu watch http://host:port`` polls
  for a live console on a plain check run that has no checker service
  in front of it), and ``/jobs`` (the serving layer's job registry as
  JSON, when the host process wired a ``jobs_provider`` — the checker
  service does, so one GET shows the queue a scraper's gauges
  summarize).

Zero-dependency and jax-free, like the rest of ``obs/`` (the registry
must stay exposable from tooling that never touches a device).
"""

from __future__ import annotations

import json
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

#: Exposition content type (the 0.0.4 text format).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Every metric name is prefixed so a shared Prometheus can tell this
#: process's series from everything else it scrapes.
NAME_PREFIX = "raft_"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def metric_name(raw: str) -> str:
    """Registry name -> Prometheus metric name: prefix + every
    non-``[a-zA-Z0-9_]`` run collapsed to one ``_``.  ``engine/distinct``
    -> ``raft_engine_distinct``; idempotent for already-clean names."""
    clean = re.sub(r"[^a-zA-Z0-9_]+", "_", raw).strip("_")
    name = NAME_PREFIX + clean
    if not _NAME_OK.match(name):
        name = NAME_PREFIX + "invalid"
    return name


def _esc_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    # Integral floats render without the trailing .0 — cosmetic, but it
    # keeps counter lines looking like counters.
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def default_labels() -> Dict[str, str]:
    """Per-host labels under a multi-controller process group (the same
    piece identity checkpoint/event files carry): ``{host: "<i>"}`` when
    ``jax.process_count() > 1``, else no labels.  jax is imported
    lazily; a jax-less process is single-host by definition."""
    try:
        import jax
        if jax.process_count() > 1:
            return {"host": str(jax.process_index())}
    except Exception:
        pass
    return {}


def render_prometheus(snapshot: dict,
                      labels: Optional[Dict[str, str]] = None) -> str:
    """Registry ``snapshot()`` dict -> Prometheus text exposition.

    Histogram buckets are re-cumulated from the summary's sparse
    occupied-bucket dict (upper-bound string -> count) into the
    monotone ``le``-labelled series Prometheus requires, closing with
    the mandatory ``le="+Inf"`` row equal to ``_count``."""
    out = []
    for raw, val in sorted((snapshot.get("counters") or {}).items()):
        name = metric_name(raw)
        if not name.endswith("_total"):
            name += "_total"
        out.append(f"# HELP {name} registry counter {raw!r}")
        out.append(f"# TYPE {name} counter")
        out.append(f"{name}{_fmt_labels(labels)} {_fmt_value(val)}")
    for raw, val in sorted((snapshot.get("gauges") or {}).items()):
        name = metric_name(raw)
        out.append(f"# HELP {name} registry gauge {raw!r}")
        out.append(f"# TYPE {name} gauge")
        out.append(f"{name}{_fmt_labels(labels)} {_fmt_value(val)}")
    for raw, summ in sorted((snapshot.get("histograms") or {}).items()):
        name = metric_name(raw)
        count = int(summ.get("count", 0))
        total = float(summ.get("total", 0.0))
        out.append(f"# HELP {name} registry histogram {raw!r}")
        out.append(f"# TYPE {name} histogram")
        occupied = summ.get("buckets") or {}
        # Sparse occupied buckets -> cumulative le series.  Keys are the
        # upper-bound strings the registry's summary() emits ("+inf"
        # for the overflow bucket).
        finite = sorted(
            ((float(k), c) for k, c in occupied.items()
             if k.lower() not in ("+inf", "inf")),
            key=lambda kv: kv[0])
        cum = 0
        for bound, c in finite:
            cum += int(c)
            lbl = dict(labels or {})
            lbl["le"] = _fmt_value(float(bound))
            out.append(f"{name}_bucket{_fmt_labels(lbl)} {cum}")
        lbl = dict(labels or {})
        lbl["le"] = "+Inf"
        out.append(f"{name}_bucket{_fmt_labels(lbl)} {count}")
        out.append(f"{name}_sum{_fmt_labels(labels)} {_fmt_value(total)}")
        out.append(f"{name}_count{_fmt_labels(labels)} {count}")
    return "\n".join(out) + "\n" if out else "\n"


def parse_prometheus(text: str) -> Dict[str, list]:
    """Parse/validate text exposition; returns ``{metric name: [(labels
    dict, value float), ...]}``.  Raises ``ValueError`` on anything a
    strict scraper would reject: malformed sample lines, samples whose
    ``# TYPE`` family was declared twice, non-monotone histogram
    ``_bucket`` series, or a ``_count`` disagreeing with the ``+Inf``
    bucket.  This is the CI gate for the ``metrics`` op / ``/metrics``
    endpoint (the acceptance-criteria "parses as valid exposition")."""
    samples: Dict[str, list] = {}
    types: Dict[str, str] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                fam, kind = parts[2], (parts[3] if len(parts) > 3 else "")
                if fam in types:
                    raise ValueError(
                        f"line {ln}: duplicate # TYPE for {fam}")
                if kind.split()[0] not in ("counter", "gauge",
                                           "histogram", "summary",
                                           "untyped"):
                    raise ValueError(
                        f"line {ln}: unknown TYPE {kind!r} for {fam}")
                types[fam] = kind.split()[0]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {ln}: malformed sample: {line!r}")
        labels = {}
        if m.group("labels"):
            consumed = 0
            for lm in _LABEL_RE.finditer(m.group("labels")):
                labels[lm.group(1)] = lm.group(2)
                consumed += 1
            if consumed == 0 and m.group("labels").strip():
                raise ValueError(
                    f"line {ln}: malformed labels: {line!r}")
        raw = m.group("value")
        try:
            value = float(raw.replace("+Inf", "inf")
                          .replace("-Inf", "-inf"))
        except ValueError:
            raise ValueError(f"line {ln}: bad value {raw!r}: {line!r}")
        samples.setdefault(m.group("name"), []).append((labels, value))
    # Histogram coherence: per family, bucket series monotone in le and
    # the +Inf bucket equals _count.
    for fam, kind in types.items():
        if kind != "histogram":
            continue
        buckets = samples.get(f"{fam}_bucket", [])
        if not buckets:
            raise ValueError(f"histogram {fam} has no _bucket samples")
        def le_key(lv):
            le = lv[0].get("le", "")
            return math.inf if le == "+Inf" else float(le)
        ordered = sorted(buckets, key=le_key)
        counts = [v for _l, v in ordered]
        if any(b > a for a, b in zip(counts[1:], counts)):
            raise ValueError(
                f"histogram {fam}: non-monotone bucket counts {counts}")
        inf_rows = [v for l, v in buckets if l.get("le") == "+Inf"]
        count_rows = [v for _l, v in samples.get(f"{fam}_count", [])]
        if not inf_rows:
            raise ValueError(f"histogram {fam}: missing le=\"+Inf\"")
        if count_rows and inf_rows[0] != count_rows[0]:
            raise ValueError(
                f"histogram {fam}: +Inf bucket {inf_rows[0]} != _count "
                f"{count_rows[0]}")
    return samples


def counter_sample(samples: Dict[str, list], raw_name: str
                   ) -> Optional[float]:
    """Value of the counter exported for registry name ``raw_name``
    (first sample), or None — the stats-vs-metrics agreement check in
    tests/CI reads through this so the name mapping lives in ONE
    place."""
    name = metric_name(raw_name)
    if not name.endswith("_total"):
        name += "_total"
    rows = samples.get(name)
    return rows[0][1] if rows else None


# -- standalone HTTP listener ---------------------------------------------

class _MetricsHandler(BaseHTTPRequestHandler):
    """GET-only: ``/metrics`` (exposition), ``/flight`` (ring JSON),
    ``/`` (tiny index).  Anything else 404s.  Errors answer 500 rather
    than killing the handler thread."""

    server_version = "raft-metrics/1"

    def do_GET(self):                               # noqa: N802 (stdlib API)
        try:
            if self.path.split("?")[0] == "/metrics":
                body = render_prometheus(
                    self.server.registry.snapshot(),
                    labels=self.server.labels).encode()
                ctype = CONTENT_TYPE
            elif self.path.split("?")[0] == "/flight":
                flight = self.server.flight
                if flight is not None:
                    # Attach bookkeeping at most once per minute per
                    # peer, not per poll: a 2 s-interval watcher would
                    # otherwise flood the run's event log and evict
                    # real events from the bounded black-box ring it is
                    # trying to observe — while a later, separate
                    # attach episode from the same host still records.
                    import time as _time
                    peer = str(self.client_address[0])
                    now = _time.monotonic()
                    seen = self.server.seen_watchers
                    if now - seen.get(peer, float("-inf")) > 60.0:
                        seen[peer] = now
                        flight.note_attach(transport="http", peer=peer)
                    # ?last=N trims each kind to its newest N records —
                    # the watch console polls with last=8; the bare
                    # endpoint serves the full ring (the black-box dump
                    # view).
                    last = None
                    q = self.path.partition("?")[2]
                    for kv in q.split("&"):
                        if kv.startswith("last="):
                            try:
                                last = max(1, int(kv[5:]))
                            except ValueError:
                                pass
                    doc = {"ok": True, "seq": flight.seq(),
                           "armed": flight.armed,
                           "records": flight.snapshot(last=last)}
                else:
                    doc = {"ok": False, "error": "no flight recorder"}
                body = (json.dumps(doc, default=str) + "\n").encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/jobs":
                provider = self.server.jobs_provider
                if provider is not None:
                    doc = {"ok": True}
                    doc.update(provider())
                else:
                    doc = {"ok": False, "error": "no job manager"}
                body = (json.dumps(doc, default=str) + "\n").encode()
                ctype = "application/json"
            elif self.path.split("?")[0] == "/":
                body = b"raft_tla_tpu metrics: /metrics /flight /jobs\n"
                ctype = "text/plain"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except BrokenPipeError:
            pass
        except Exception as e:                      # pragma: no cover
            try:
                self.send_error(500, str(e)[:200])
            except Exception:
                pass

    def log_message(self, fmt, *args):
        pass      # scrapes every few seconds must not spam stderr


class MetricsHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    registry = None
    flight = None
    #: Zero-arg callable returning the /jobs document (the serving
    #: layer's ``JobManager.jobs_doc``); None = endpoint answers
    #: ``{"ok": false}`` (a plain check run has no job registry).
    jobs_provider = None
    labels: Optional[Dict[str, str]] = None

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # peer -> monotonic ts of its last recorded watch_attach (the
        # per-peer attach rate limit in the /flight handler).
        self.seen_watchers = {}


def serve_metrics(port: int, registry, flight=None,
                  host: str = "127.0.0.1",
                  labels: Optional[Dict[str, str]] = None,
                  jobs_provider=None) -> MetricsHTTPServer:
    """Create (not start) the listener; port 0 picks an ephemeral port
    (``server_address[1]``).  Same trust model as the checker service:
    unauthenticated, loopback by default."""
    srv = MetricsHTTPServer((host, port), _MetricsHandler)
    srv.registry = registry
    srv.flight = flight
    srv.jobs_provider = jobs_provider
    srv.labels = labels if labels is not None else default_labels()
    return srv


def start_metrics_server(port: int, registry, flight=None,
                         host: str = "127.0.0.1",
                         labels: Optional[Dict[str, str]] = None,
                         jobs_provider=None
                         ) -> Tuple[MetricsHTTPServer, threading.Thread]:
    """serve_metrics + a daemon thread running it; returns (server,
    thread).  Callers ``server.shutdown()`` when the run ends (or just
    exit — daemon threads don't pin the process)."""
    srv = serve_metrics(port, registry, flight=flight, host=host,
                        labels=labels, jobs_provider=jobs_provider)
    t = threading.Thread(target=srv.serve_forever,
                         name="metrics-http", daemon=True)
    t.start()
    return srv, t
