"""Telemetry subsystem shared by every engine and entry point.

One spine, several legs:

- :mod:`.metrics` — a zero-dep, thread-safe :class:`MetricsRegistry`
  (counters / gauges / histograms) with a :meth:`~MetricsRegistry.phase_timer`
  context manager wrapping the host-side phases of the BFS chunk loop
  and the simulate/mesh paths;
- :mod:`.events` — the structured JSONL :class:`RunEventLog`
  (run_start, level_complete, fpset_resize, spill, checkpoint,
  violation, deadlock, chunk_profile, coverage, run_end) written next
  to the checkpoint dir and per-host under ``parallel/mesh.py``;
- :mod:`.tracing` — :class:`SpanTracer`, nested spans serialized as
  Chrome trace-event JSON (``--trace-out``; opens in Perfetto).
  Attached to a registry it mirrors every phase_timer block;
- :mod:`.profile` — :class:`ChunkProfiler`, the per-stage chunk
  decomposition behind ``--profile-chunks`` (expand / fingerprint /
  dedup-insert / enqueue histograms + the run-end stage-budget table);
- :mod:`.coverage` — :class:`ActionCoverage`, TLC-style per-action
  generated/distinct/disabled counters and the run-end coverage table;
- :mod:`.flight` — the always-on :class:`FlightRecorder` black box
  (bounded ring of recent events/progress/stage samples) with the
  crash/SIGTERM/fault-kill **postmortem dump** and the process-global
  :data:`~.flight.RECORDER` the live-introspection consumers read;
- :mod:`.expose` — Prometheus text exposition of the registry
  (``render_prometheus``/``parse_prometheus``) and the standalone
  ``--metrics-port`` HTTP listener (``/metrics`` + ``/flight``) behind
  the ``watch`` run-attach console;
- :mod:`.report` — the TLC-parity **statespace run report** (collision
  probability, per-level frontier table, out-degree, seen-set load)
  assembled host-side at run end: the ``statespace`` event,
  ``EngineResult.report``, and the TLC-style stderr block;
- :mod:`.perf` / :mod:`.roofline` — the **performance observatory**
  (``--perf``): static launch accounting over the engines' real traced
  chunk programs, per-stage HBM-traffic floors joined with the
  ChunkProfiler's measured means into achieved-bandwidth fractions,
  and the fusion advisor naming the next fusion target (the ``perf``
  run event, ``EngineResult.perf``, ``perf/*`` gauges);
- :mod:`.history` — the append-only JSONL **run-history ledger**
  (``check --history`` / ``HISTORY`` directive / ``BENCH_HISTORY``):
  per-run cfg/model/host fingerprints, verdict, rates, and report
  summary; ``scripts/bench_history.py`` renders the trajectory and
  ``scripts/bench_diff.py --history`` resolves baselines from it.

The CLI exposes them via ``--metrics-out`` / ``--events-out`` /
``--trace-out`` / ``--profile-chunks`` / ``--metrics-port`` /
``--xla-profile``, the checker service via the ``stats`` / ``metrics``
/ ``watch`` requests, and ``bench.py`` embeds the phase breakdown,
chunk stage means, and coverage in its JSON (``scripts/bench_diff.py``
gates on all three).  See README.md "Observability" for the schemas.
"""

from .metrics import (Histogram, MetricsRegistry, PHASE_PREFIX,  # noqa: F401
                      phase_delta)
from .events import (KNOWN_EVENTS, REQUIRED_EVENTS, RunEventLog,  # noqa: F401
                     all_device_memory_stats, device_memory_stats,
                     events_path, peak_host_rss_bytes,
                     validate_and_cleanup, validate_run_events)
from .tracing import SpanTracer, validate_chrome_trace           # noqa: F401
from .coverage import ActionCoverage                             # noqa: F401
from .flight import (FlightRecorder, RECORDER,                   # noqa: F401
                     host_fingerprint)
from .expose import (parse_prometheus, render_prometheus,        # noqa: F401
                     serve_metrics, start_metrics_server)
from .report import (build_report, collision_probability,        # noqa: F401
                     render_report)
from . import history                                            # noqa: F401
# NOTE deliberately NOT imported here: obs.perf / obs.roofline (the
# performance observatory).  Importing them at package init would put
# two new modules into the import-time heap history of EVERY test and
# tool that touches obs — and jaxlib's CPU client is heap-layout
# fragile under the big mesh tests (the tests/conftest.py reorder
# rationale), so new modules stay off the default import path as a
# precaution.  Consumers import them lazily:
# ``from raft_tla_tpu.obs import perf`` /
# ``from raft_tla_tpu.obs import roofline`` at use sites.
# .profile imports jax lazily but pulls model/ops modules at call time;
# import the classes here for the one-stop namespace (still jax-free at
# import).
from .profile import ChunkProfiler, XlaProfileCapture            # noqa: F401
