"""Telemetry subsystem shared by every engine and entry point.

Two halves, one spine:

- :mod:`.metrics` — a zero-dep, thread-safe :class:`MetricsRegistry`
  (counters / gauges / histograms) with a :meth:`~MetricsRegistry.phase_timer`
  context manager wrapping the host-side phases of the BFS chunk loop
  and the simulate/mesh paths;
- :mod:`.events` — the structured JSONL :class:`RunEventLog`
  (run_start, level_complete, fpset_resize, spill, checkpoint,
  violation, deadlock, run_end) written next to the checkpoint dir and
  per-host under ``parallel/mesh.py``.

The CLI exposes them via ``--metrics-out`` / ``--events-out``, the
checker service via the ``stats`` request, and ``bench.py`` embeds the
final phase breakdown in its JSON.  See README.md "Observability" for
the event schema and metric-name inventory.
"""

from .metrics import (Histogram, MetricsRegistry, PHASE_PREFIX,  # noqa: F401
                      phase_delta)
from .events import (REQUIRED_EVENTS, RunEventLog,               # noqa: F401
                     device_memory_stats, events_path,
                     validate_and_cleanup, validate_run_events)
