"""Launch accounting + the per-run performance block (``--perf``).

The engine's known bottleneck is kernel granularity — hundreds of small
launches per chunk against a ~0.1-0.3 ms bandwidth floor (NORTHSTAR §c)
— yet no telemetry leg could attribute time to launches.  This module
closes that gap with a **static launch model** plus a cheap dynamic
feed:

- *static*: walk the engine's REAL traced chunk program (the exact
  jaxpr ``engine/bfs.py`` / ``parallel/mesh.py`` compile, v1/v2/v3,
  POR mask and fused tail included) counting device ops — every
  equation except pure layout prims, loop bodies once, ``pallas_call``
  = one.  The count is a deterministic PRE-FUSION upper bound on kernel
  launches (XLA fuses some neighbors; a Pallas stage is exactly one),
  which makes fused-vs-unfused deltas first-class and CI-pinnable: a
  stage silently un-fusing moves the pin.  The measured truth comes
  from the device profiler (``scripts/xplane_summary.py`` over the
  stage-5b XPlane artifacts) — the static model is the gate, the
  XPlane number is the evidence.
- *dynamic*: the host loop feeds (batches, seconds) per chunk call —
  two ints it already has — giving ``launches_per_chunk`` and the
  **launch tax**: ``launches x per-launch overhead`` priced against the
  measured chunk seconds (``launch_overhead_share``).

At run end the accounting joins the static roofline
(:mod:`obs.roofline`) with the ChunkProfiler's measured stage means
into achieved-bandwidth fractions, asks the fusion advisor for the top
candidate, and lands everything as the ``perf`` run event,
``EngineResult.perf``, ``perf/*`` gauges, and a stderr table.  Strictly
observational: the walk happens at build time on the traced jaxpr, the
dynamic feed is host arithmetic — engine results are bit-identical
with ``--perf`` on or off (tested).

Per-launch overhead defaults to 5 us (typical accelerator dispatch
floor); override with ``RAFT_LAUNCH_OVERHEAD_US``.  Because the launch
count is an upper bound, the share is too — it brackets, not measures,
the tax.  jax is imported lazily, keeping ``obs`` importable in
device-less tooling.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional

#: Collective primitives (mesh chunk): counted separately so the
#: modeled collective share of the sharded path is explainable.
COLLECTIVE_PRIMS = frozenset((
    "psum", "pmin", "pmax", "all_to_all", "all_gather", "ppermute",
    "reduce_scatter", "psum_scatter", "axis_index"))

DEFAULT_LAUNCH_OVERHEAD_US = 5.0


def launch_overhead_seconds() -> float:
    """Per-launch overhead assumption (seconds); RAFT_LAUNCH_OVERHEAD_US
    overrides the 5 us default.  Malformed values warn and fall back:
    this runs inside the engines' fail-soft perf build and its fallback
    handler, so raising would fail the engine build."""
    env = os.environ.get("RAFT_LAUNCH_OVERHEAD_US")
    if env is not None:
        try:
            return float(env) * 1e-6
        except ValueError:
            print(f"perf: ignoring malformed RAFT_LAUNCH_OVERHEAD_US="
                  f"{env!r} (want microseconds as a number)",
                  file=sys.stderr)
    return DEFAULT_LAUNCH_OVERHEAD_US * 1e-6


def analyze_chunk_program(fn, *arg_avals) -> dict:
    """Trace ``fn`` (an engine's chunk program — jitted is fine, the
    walk recurses through pjit/shard_map) at the given avals and return
    the static launch model:

    - ``launches_per_batch``: device ops inside loop bodies — the batch
      while_loop is the chunk program's only top-level loop, so this is
      the per-batch cost (nested probe loops counted once, a floor);
    - ``launches_fixed``: ops outside any loop (stats packing, once per
      chunk call);
    - ``collectives_per_batch``: collective ops per batch (mesh).
    """
    import jax
    import jax.tree_util as jtu

    from .roofline import jaxpr_traffic
    closed = jax.make_jaxpr(fn)(*arg_avals)
    flat, _ = jtu.tree_flatten(arg_avals)
    t = jaxpr_traffic(closed, flat)
    return {
        "launches_per_batch": t["while_launches"],
        "launches_fixed": t["launches"] - t["while_launches"],
        "collectives_per_batch": t["collectives_in_loop"],
        "collectives_fixed": t["collectives"]
        - t["collectives_in_loop"],
        "model": "jaxpr device ops (pre-fusion upper bound; "
                 "loop bodies once)",
        "notes": t["notes"],
    }


class PerfAccounting:
    """One engine run's performance attribution: static models built at
    engine construction, dynamic (batches, seconds) fed per chunk call,
    the perf block assembled at run end.

    Everything here is host-side bookkeeping; the only non-trivial cost
    is the one-time jaxpr walk at build (sub-second, amortized across
    runs on a warm engine)."""

    def __init__(self, *, pipeline: str, launch_model: Optional[dict],
                 stage_traffic: Optional[Dict[str, dict]],
                 peak: Optional[dict] = None,
                 plan_launches: Optional[Dict[str, object]] = None,
                 metrics=None):
        from . import roofline as roofline_mod
        self.pipeline = pipeline
        self.launch_model = launch_model
        self.traffic = stage_traffic
        self.peak = peak or roofline_mod.peak_bandwidth()
        #: v3 only — resolve_plan's expected launches per stage (a
        #: Pallas/fused stage is exactly 1 kernel); the fused-vs-
        #: unfused delta in its most legible form.
        self.plan_launches = plan_launches
        self.metrics = metrics
        self.overhead_s = launch_overhead_seconds()
        self.reset()

    def reset(self) -> None:
        """Per-run accumulators (warm engines reuse the static halves)."""
        self.chunk_calls = 0
        self.batches = 0
        self.chunk_seconds = 0.0
        self._level_batches = 0
        self.level_launches: List[dict] = []
        self.collective_probe_seconds: Optional[float] = None

    # -- dynamic feed ---------------------------------------------------
    def add_chunk(self, batches: int, seconds: float) -> None:
        """One chunk call's measured (device batches, wall seconds) —
        fed from the packed-stats fetch the loop already does."""
        self.chunk_calls += 1
        self.batches += int(batches)
        self._level_batches += int(batches)
        self.chunk_seconds += float(seconds)

    def end_level(self, level: int) -> None:
        """Level boundary: snapshot the level's launch total so OOM /
        skew events can be correlated with launch pressure per level."""
        lm = self.launch_model
        if lm is not None:
            self.level_launches.append({
                "level": int(level), "batches": self._level_batches,
                "launches": self._level_batches
                * lm["launches_per_batch"]})
        self._level_batches = 0

    def note_collective_probe(self, seconds: float) -> None:
        """Mesh path: one timed psum round (sampled per level) — the
        latency term of the modeled collective share."""
        self.collective_probe_seconds = float(seconds)

    # -- assembly -------------------------------------------------------
    def launches_per_chunk(self) -> Optional[float]:
        lm = self.launch_model
        if lm is None or not self.chunk_calls:
            return None
        per_batch = lm["launches_per_batch"]
        return (per_batch * self.batches / self.chunk_calls
                + lm["launches_fixed"])

    def summary(self, chunk_stages: Optional[Dict[str, float]] = None
                ) -> dict:
        """The ``perf`` block: launch accounting + roofline rows +
        advisor verdict (+ the modeled collective share on the mesh)."""
        from . import roofline as roofline_mod
        lm = self.launch_model
        lpc = self.launches_per_chunk()
        launch: Dict[str, object] = {
            "model": (lm or {}).get("model"),
            "launches_per_batch": (lm or {}).get("launches_per_batch"),
            "launches_fixed_per_chunk": (lm or {}).get("launches_fixed"),
            "chunk_calls": self.chunk_calls,
            "batches": self.batches,
            "chunk_seconds": round(self.chunk_seconds, 6),
            "launches_per_chunk": (round(lpc, 1) if lpc is not None
                                   else None),
            "launch_overhead_us": round(self.overhead_s * 1e6, 3),
            "launch_overhead_share": None,
            "per_level": self.level_launches,
        }
        if lm is not None and self.chunk_seconds and self.batches:
            tax = (lm["launches_per_batch"] * self.batches
                   + lm["launches_fixed"] * self.chunk_calls) \
                * self.overhead_s
            launch["launch_tax_seconds"] = round(tax, 6)
            launch["launch_overhead_share"] = round(
                min(1.0, tax / self.chunk_seconds), 6)
        means = dict(chunk_stages or {})
        means.pop("total", None)
        rows = (roofline_mod.build_roofline(self.traffic, means, self.peak)
                if self.traffic else {})
        advisor = roofline_mod.advise(rows, self.overhead_s) if rows \
            else {"ranking": [], "top": None,
                  "verdict": ("launch accounting only (no per-stage "
                              "roofline on this engine)"
                              if self.traffic is None else
                              "no stage model (launch trace failed)")}
        out = {
            "pipeline": self.pipeline,
            "launch": launch,
            "roofline": {"peak_bytes_per_sec":
                         float(self.peak["bytes_per_sec"]),
                         "peak_source": self.peak["source"],
                         "stages": rows},
            "advisor": advisor,
        }
        if self.plan_launches is not None:
            out["plan_launches"] = dict(self.plan_launches)
        if self.collective_probe_seconds is not None and lm is not None:
            probe = self.collective_probe_seconds
            coll = {"probe_seconds": round(probe, 6),
                    "collectives_per_batch": lm["collectives_per_batch"],
                    "share": None}
            if self.chunk_seconds and self.batches:
                coll["share"] = round(min(1.0, (
                    probe * lm["collectives_per_batch"] * self.batches)
                    / self.chunk_seconds), 6)
            out["collectives"] = coll
        return out

    def feed_metrics(self, mt, perf: dict) -> None:
        """Gauges from the assembled block — ONE tax formula lives in
        summary(), so the event payload and the gauges cannot drift."""
        launch = perf["launch"]
        if launch["launches_per_chunk"] is not None:
            mt.gauge("perf/launches_per_chunk",
                     launch["launches_per_chunk"])
        if launch["launch_overhead_share"] is not None:
            mt.gauge("perf/launch_overhead_share",
                     launch["launch_overhead_share"])

    def render_table(self, perf: dict) -> str:
        """Run-end stderr table: the launch tax priced against measured
        chunk time, roofline rows, and the advisor's one-line verdict —
        the replacement for hand-reading NORTHSTAR §c."""
        launch = perf["launch"]
        lines = [f"perf observatory ({self.pipeline} pipeline, "
                 f"{launch['chunk_calls']} chunk calls, "
                 f"{launch['batches']} batches):"]
        if launch["launches_per_batch"] is not None:
            share = launch["launch_overhead_share"]
            lines.append(
                f"  launches: {launch['launches_per_batch']} device ops/"
                f"batch (pre-fusion bound), "
                f"{launch['launches_per_chunk'] or 0:,.0f}/chunk; tax @ "
                f"{launch['launch_overhead_us']:g} us = "
                + (f"{share:.1%} of measured chunk time"
                   if share is not None else "n/a (no chunk time)"))
        rows = perf["roofline"]["stages"]
        if rows:
            lines.append(
                f"  roofline vs {perf['roofline']['peak_bytes_per_sec'] / 1e9:,.0f}"
                f" GB/s ({perf['roofline']['peak_source']}):")
            lines.append(f"    {'stage':14s} {'KB/batch':>10s} "
                         f"{'floor ms':>9s} {'meas ms':>9s} "
                         f"{'of peak':>8s} {'ops':>6s}")
            for stage, r in rows.items():
                meas = (f"{r['mean_seconds'] * 1e3:9.3f}"
                        if r["mean_seconds"] is not None else f"{'-':>9s}")
                frac = (f"{r['bandwidth_fraction']:8.1%}"
                        if r["bandwidth_fraction"] is not None
                        else f"{'-':>8s}")
                lines.append(
                    f"    {stage:14s} {r['bytes_total'] / 1024:10.1f} "
                    f"{(r['floor_seconds'] or 0) * 1e3:9.4f} {meas} "
                    f"{frac} {r['launches']:6d}")
        if perf.get("collectives"):
            c = perf["collectives"]
            share = c["share"]
            lines.append(
                f"  collectives: {c['collectives_per_batch']}/batch, "
                f"probe {c['probe_seconds'] * 1e3:.3f} ms"
                + (f", modeled share {share:.1%}" if share is not None
                   else ""))
        lines.append(f"  advisor: {perf['advisor']['verdict']}")
        return "\n".join(lines)

    def finish(self, evlog, chunk_stages=None, stream=None) -> dict:
        """Run-end hook (both engines): assemble the block, emit the
        ``perf`` event, push gauges, print the table.  Returns the block
        (what ``EngineResult.perf`` carries)."""
        perf = self.summary(chunk_stages)
        evlog.emit("perf", perf=perf)
        if self.metrics is not None:
            self.feed_metrics(self.metrics, perf)
        print(self.render_table(perf), file=stream or sys.stderr)
        return perf


def build_accounting(*, pipeline: str, chunk_fn, chunk_avals,
                     dims=None, B: Optional[int] = None,
                     K: Optional[int] = None,
                     compact_method: str = "scatter", v3_force=None,
                     plan=None, with_stages: bool = True,
                     metrics=None, engine: str = "engine",
                     ring: int = 16, swarm_pipeline: str = "v1"
                     ) -> PerfAccounting:
    """Build one engine's PerfAccounting at construction time: trace the
    real chunk program for the launch model and (single-chip) the shared
    stage programs for the roofline traffic.  Fail-soft by construction:
    a model that cannot be built warns on stderr (named by ``engine``)
    and degrades to a perf block with nulls — same resolved ``pipeline``
    label either way — never a failed engine build.

    ``pipeline="swarm"`` prices the swarm tier instead: the traced
    chunk is the whole lockstep scan (launches_per_batch then counts
    device ops per scan STEP — the swarm's per-step pin next to the
    BFS per-batch ones), and the roofline rows come from the
    walk-kernel stage programs (``ring``/``swarm_pipeline`` mirror the
    engine's ring capacity and resolved expand pipeline)."""
    from . import roofline as roofline_mod
    launch_model = None
    traffic = None
    try:
        launch_model = analyze_chunk_program(chunk_fn, *chunk_avals)
        if with_stages and dims is not None:
            traffic = roofline_mod.stage_traffic(
                dims, B, K,
                pipeline=(pipeline if pipeline in ("v3", "v4", "swarm")
                          else "v1"),
                compact_method=compact_method, v3_force=v3_force,
                ring=ring, swarm_pipeline=swarm_pipeline)
    except Exception as e:
        print(f"perf: {engine} launch/roofline model unavailable "
              f"({type(e).__name__}: {e}); continuing without",
              file=sys.stderr)
    plan_launches = None
    if plan is not None:
        plan_launches = dict(getattr(plan, "launches", None) or {})
    return PerfAccounting(pipeline=pipeline, launch_model=launch_model,
                          stage_traffic=traffic,
                          plan_launches=plan_launches, metrics=metrics)


def timed_collective_probe(fn, *args, warm: bool = True) -> float:
    """Fence-timed single collective round (mesh skew telemetry): a
    warm-up call (compile) unless the caller already warmed ``fn``,
    then one timed call.  ``fn`` must block until the result is
    host-visible (multihost's agreement primitives do — they return
    host ints).  Callers probing every level should warm once at
    construction and pass ``warm=False`` so each level pays exactly
    one collective round."""
    if warm:
        fn(*args)                   # warm-up: compile off the sample
    t0 = time.perf_counter()
    fn(*args)
    return time.perf_counter() - t0
