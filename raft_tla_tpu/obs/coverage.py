"""TLC-style action coverage — per-action generated/distinct/disabled.

TLC's ``-coverage`` report is the thing users actually read when a check
stalls: per action, how many successor states it generated and how many
of those were distinct, over time.  The engines already compute per-lane
enablement and novelty masks on device; this module gives those masks
the TLC rendering: per-family **generated** (enabled successor
evaluations — TLC's "states found"), **distinct** (novel states whose
first FPSet insertion came through this action's lane), and **disabled**
(guard evaluations that came up false — ``expanded_parents x
family_size - generated``, computed host-side from the same packed
stats, zero extra device work).

Counter provenance: ``generated`` per family is the exact series the
engines have always accumulated into ``EngineResult.action_counts``
(``generated_by_action`` in bench JSON), read from the SAME packed stats
vector — the run-end table therefore matches bench JSON bit-exactly by
construction.  ``distinct`` is a second per-family reduction of the
insert's novelty mask (engine/chunk.py), summing to the run's
expansion-phase distinct count (roots are not action coverage).

Zero-dep host-side accumulator (no jax), like the rest of ``obs/``.
Consumers: a ``coverage`` run event each progress interval and at run
end, ``coverage/<family>/generated|distinct`` registry counters (the
server's ``stats`` op), the run-end stderr table, and the ``coverage``
object in bench JSON that ``scripts/bench_diff.py`` gates on.
"""

from __future__ import annotations

from typing import Dict, List, Sequence


class ActionCoverage:
    """Per-action-family coverage accumulator (one per engine run)."""

    def __init__(self, family_names: Sequence[str],
                 family_sizes: Sequence[int]):
        self.names: List[str] = list(family_names)
        self.sizes: List[int] = [int(s) for s in family_sizes]
        self.generated: Dict[str, int] = {n: 0 for n in self.names}
        self.distinct: Dict[str, int] = {n: 0 for n in self.names}
        #: Enabled lanes the partial-order reduction masked out before
        #: fingerprinting (analysis/por.py; zero with POR off) — the
        #: reduced-vs-full accounting: a pruned guard evaluation was
        #: TRUE, so it belongs to neither ``generated`` nor
        #: ``disabled``.
        self.pruned: Dict[str, int] = {n: 0 for n in self.names}
        #: Parents actually expanded (each evaluates every instance's
        #: guard once) — the base for the disabled counts.
        self.expanded = 0

    def add_chunk(self, expanded: int, gen_counts, new_counts,
                  pruned_counts=None) -> None:
        """Fold one chunk call's packed per-family stats in.
        ``gen_counts``/``new_counts``/``pruned_counts`` are the
        per-family vectors from the chunk stats (any int sequence),
        ``expanded`` the parents the call advanced past."""
        self.expanded += int(expanded)
        for name, g, d in zip(self.names, gen_counts, new_counts):
            g, d = int(g), int(d)
            if g:
                self.generated[name] += g
            if d:
                self.distinct[name] += d
        if pruned_counts is not None:
            for name, p in zip(self.names, pruned_counts):
                p = int(p)
                if p:
                    self.pruned[name] += p

    def seed_generated(self, action_counts: Dict[str, int]) -> None:
        """Resume support: continue the generated series from a
        checkpoint's ``action_counts`` so the run-end table still
        matches ``generated_by_action`` exactly.  Distinct/expanded are
        not checkpointed and restart from zero — a resumed run's
        distinct column covers the post-resume portion only."""
        for name, c in action_counts.items():
            if name in self.generated:
                self.generated[name] += int(c)

    def disabled(self, name: str) -> int:
        size = self.sizes[self.names.index(name)]
        # Clamped: a resumed run's expanded counter restarts at zero
        # while generated resumes from the checkpoint, which would
        # otherwise push this negative.  Pruned lanes had a TRUE guard,
        # so they are subtracted from the disabled base too.
        return max(0, self.expanded * size - self.generated[name]
                   - self.pruned[name])

    @property
    def total_generated(self) -> int:
        return sum(self.generated.values())

    @property
    def total_distinct(self) -> int:
        return sum(self.distinct.values())

    @property
    def total_pruned(self) -> int:
        return sum(self.pruned.values())

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """JSON-ready ``{family: {generated, distinct, disabled,
        pruned}}`` — the payload of ``coverage`` events and bench JSON's
        ``coverage``."""
        return {n: {"generated": self.generated[n],
                    "distinct": self.distinct[n],
                    "disabled": self.disabled(n),
                    "pruned": self.pruned[n]}
                for n in self.names}

    def feed_metrics(self, metrics) -> None:
        """Mirror the totals into registry gauges (idempotent — gauges,
        not counters, so a progress-interval refresh never double-counts)
        for the server's ``stats`` op and ``--metrics-out`` snapshots."""
        for n in self.names:
            metrics.gauge(f"coverage/{n}/generated", self.generated[n])
            metrics.gauge(f"coverage/{n}/distinct", self.distinct[n])
            metrics.gauge(f"coverage/{n}/disabled", self.disabled(n))
            metrics.gauge(f"coverage/{n}/pruned", self.pruned[n])
        metrics.gauge("coverage/expanded_states", self.expanded)

    def render_table(self) -> str:
        """The TLC-parity run-end report (stderr): one row per action
        family, sorted by generated, with the distinct ratio that tells
        a user which actions are churning duplicates.  A ``pruned``
        column appears only when the run's POR mask dropped anything, so
        full-expansion renders are byte-identical to the pre-POR
        format."""
        rows = sorted(self.names, key=lambda n: -self.generated[n])
        width = max([len(n) for n in self.names] + [6])
        por = self.total_pruned > 0
        prun_hdr = f" {'pruned':>12s}" if por else ""
        lines = [f"coverage (actions: {len(self.names)}, parents "
                 f"expanded: {self.expanded:,}"
                 + (f", POR pruned: {self.total_pruned:,}" if por else "")
                 + "):",
                 f"  {'action':{width}s} {'generated':>12s} "
                 f"{'distinct':>12s} {'disabled':>14s}{prun_hdr} "
                 f"{'new%':>6s}"]
        for n in rows:
            g, d = self.generated[n], self.distinct[n]
            pct = f"{100.0 * d / g:5.1f}%" if g else "    --"
            prun = f" {self.pruned[n]:12,d}" if por else ""
            lines.append(f"  {n:{width}s} {g:12,d} {d:12,d} "
                         f"{self.disabled(n):14,d}{prun} {pct:>6s}")
        lines.append(f"  {'total':{width}s} {self.total_generated:12,d} "
                     f"{self.total_distinct:12,d}")
        return "\n".join(lines)
