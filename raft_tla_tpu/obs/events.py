"""Structured run events — one JSONL line per engine lifecycle event.

The event log is the durable half of the telemetry subsystem (the
registry is the live half): engines append one JSON object per line for
``run_start``, ``level_complete``, ``fpset_resize``, ``spill``,
``checkpoint``, ``violation``, ``deadlock``, and ``run_end``.  Every
event carries ``ts`` (epoch seconds) and ``elapsed_seconds`` (since the
log was opened); level and end events add live counters, the per-phase
wall-time breakdown, and the device memory probe.  The JSONL file is the
supported interface for dashboards and regression tooling — the bench
harness fails loudly when a run leaves it missing or malformed
(``validate_run_events``).

Placement: ``EngineConfig.events_out`` names the file; when unset it
defaults to ``events.jsonl`` next to the checkpoint dir (TLC's states/
analog), and stays disabled when neither is set.  Multi-host runs write
one file per controller (``events_path`` suffixes the piece id), same
model as checkpoint/trace pieces.

A ``RunEventLog(None)`` is a no-op sink, so engines emit unconditionally.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

#: Event types a complete, healthy run always contains.
REQUIRED_EVENTS = ("run_start", "run_end")

#: Every event type the engines/tooling emit (documentation + the
#: validator's schema table).  Unknown types still validate — forward
#: compatibility — but known STRUCTURED types must carry their payload
#: field, so a half-written profiler/coverage emitter fails the bench
#: gate instead of shipping empty records.
KNOWN_EVENTS = (
    "run_start", "level_complete", "fpset_resize", "spill", "checkpoint",
    "violation", "deadlock", "run_end", "restart", "supervised_done",
    "supervise_giveup", "degraded", "analysis",
    # Deep-profiling layer (obs/profile.py, obs/coverage.py):
    "chunk_profile",    # per-stage chunk timings; payload: "stages"
    "coverage",         # TLC-style per-action counters; payload: "actions"
    # Flight-recorder / live-introspection layer (obs/flight.py,
    # obs/expose.py):
    "postmortem",       # a black-box dump was written; payload: "dump"
    "watch_attach",     # a live watcher attached; payload: "client"
    "xla_profile",      # device-profiler capture window; payload: "capture"
    # Semantic-observability layer (obs/report.py): the TLC-parity
    # statespace report, one per completed run.  ``run_end`` also gains
    # ``counterexample_path`` when a traced violation was rendered
    # (engine/explain.py).
    "statespace",       # TLC-parity run report; payload: "report"
    # Performance observatory (obs/perf.py, obs/roofline.py): launch
    # accounting + static roofline + fusion-advisor verdict, one per
    # completed --perf run; and the mesh's per-shard balance warning
    # (parallel/mesh.py skew telemetry).
    "perf",             # launch/roofline/advisor block; payload: "perf"
    "skew",             # shard imbalance warning; payload: "balance"
    # Swarm tier (engine/swarm.py): periodic walker progress.  Swarm
    # runs also attach the same ``swarm`` payload object to their
    # ``run_end`` (exhaustive run_ends carry none, so only the
    # progress event gets schema-table enforcement).
    "swarm_progress",   # walker-fleet progress; payload: "swarm"
    # Hunt observatory (obs/hunt.py): the run-end saturation /
    # walk-analytics report for swarm runs — the probabilistic sibling
    # of ``statespace``.
    "hunt",             # swarm coverage report; payload: "hunt"
)

#: Structured payload field each new event type must carry.
_EVENT_PAYLOAD_FIELDS = {"chunk_profile": "stages", "coverage": "actions",
                         "postmortem": "dump", "watch_attach": "client",
                         "xla_profile": "capture", "statespace": "report",
                         "perf": "perf", "skew": "balance",
                         "swarm_progress": "swarm", "hunt": "hunt"}


#: memory_stats() keys kept in event payloads (one extraction for the
#: single-device and per-device probes, so they can never desynchronize).
_MEMORY_KEEP = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")


def _probe_device(device) -> dict:
    try:
        stats = device.memory_stats() or {}
    except Exception:
        return {}
    return {k: int(stats[k]) for k in _MEMORY_KEEP if k in stats}


def device_memory_stats() -> dict:
    """Compact view of the first device's ``memory_stats()`` probe (the
    same probe ``engine/bfs._auto_capacities`` sizes from); {} when the
    backend reports nothing (virtual CPU devices) or jax is unavailable."""
    try:
        import jax
        return _probe_device(jax.devices()[0])
    except Exception:
        return {}


def all_device_memory_stats() -> list:
    """Per-device memory probes for the run_end event, one dict per
    visible device IN ORDER.  Guarded the same way as the single-device
    probe: a platform whose devices report nothing (CPU, virtual
    devices) contributes ``{}`` per device — the field is always
    present, never silently absent — and a jax-less process returns
    ``[]``."""
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return []
    return [_probe_device(d) for d in devices]


def peak_host_rss_bytes():
    """Peak resident set size of this process in bytes (ru_maxrss is KB
    on Linux, bytes on macOS — normalize to bytes), or None where the
    resource module is unavailable (non-POSIX)."""
    try:
        import resource
        import sys
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak) if sys.platform == "darwin" else int(peak) * 1024
    except Exception:
        return None


def events_path(events_out: Optional[str], checkpoint_dir: Optional[str],
                process_index: int = 0,
                process_count: int = 1) -> Optional[str]:
    """Resolve the event-log path for one controller.  ``events_out``
    wins; otherwise the file lands next to the checkpoints; None/None
    disables.  Under a process group each controller writes its own
    piece file (suffix before the extension), mirroring checkpoint
    pieces — merge for dashboards by concatenation, order by ``ts``."""
    path = events_out
    if path is None and checkpoint_dir is not None:
        path = os.path.join(checkpoint_dir, "events.jsonl")
    if path is None or process_count <= 1:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.p{process_index}of{process_count}{ext or '.jsonl'}"


class RunEventLog:
    """Append-only JSONL event writer; ``RunEventLog(None)`` discards
    the FILE half only — every emit is also mirrored into the
    process-global flight recorder ring (obs/flight.py), which is how
    a run with no event log configured still shows up in the ``watch``
    console and the postmortem dump.  Thread-safe: the run's engine
    thread and a watch attach (server handler thread) may emit into
    one log concurrently, and interleaved partial lines would corrupt
    the JSONL contract."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._f = None
        self._t0 = time.time()
        self._lock = threading.Lock()
        if path is not None:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._f = open(path, "a", encoding="utf-8")

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def elapsed(self) -> float:
        """Seconds since the log was opened (the run's true wall clock —
        unlike the engines' budget clock ``t0`` it never shifts for
        off-clock stalls, so phase sums can be audited against it)."""
        return time.time() - self._t0

    def emit(self, event: str, **fields) -> None:
        now = time.time()
        rec = {"event": event, "ts": round(now, 6),
               "elapsed_seconds": round(now - self._t0, 6)}
        rec.update(fields)
        # Flight-recorder mirror FIRST (before the file check): the ring
        # is the always-on black box, fed even by file-less RunEventLog
        # instances — a crash during a run with no --events-out still
        # postmortems its recent events.  Lazy import avoids an import
        # cycle at package init (flight is a sibling leg).
        try:
            from .flight import RECORDER
            RECORDER.record("event", **rec)
        except Exception:
            pass
        if self._f is None:
            return
        # One line per event, flushed immediately: a crashed run's log
        # stays readable up to the crash (append-only, no buffering).
        # Under the lock: concurrent emitters (engine thread + a watch
        # attach) must never interleave partial lines.
        with self._lock:
            f = self._f
            if f is None:
                return
            f.write(json.dumps(rec, default=str) + "\n")
            f.flush()

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def validate_and_cleanup(events_file: str, scratch_dir: Optional[str],
                         required=REQUIRED_EVENTS) -> int:
    """Bench-harness gate: validate a run's event log, removing
    ``scratch_dir`` whether validation succeeds or raises (a failing CI
    run must not orphan its scratch directory either).  Returns the
    event count; raises like :func:`validate_run_events`.  One shared
    copy for ``bench.py`` and ``scripts/true_bench.py``."""
    import shutil
    try:
        return len(validate_run_events(events_file, required=required))
    finally:
        if scratch_dir is not None:
            shutil.rmtree(scratch_dir, ignore_errors=True)


def validate_run_events(path: str,
                        required=REQUIRED_EVENTS) -> list:
    """Parse a run event log and verify it is healthy: the file exists,
    every line is a JSON object with ``event`` and ``ts``, and every
    ``required`` event type appears.  Returns the parsed events; raises
    ``FileNotFoundError``/``ValueError`` otherwise.  This is the bench
    harness's telemetry-regression gate (nonzero rc on failure)."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"run event log missing: {path}")
    events = []
    with open(path, encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{ln}: malformed event line ({e})")
            if not isinstance(rec, dict) or "event" not in rec \
                    or "ts" not in rec:
                raise ValueError(
                    f"{path}:{ln}: event record missing 'event'/'ts': "
                    f"{line[:120]}")
            payload = _EVENT_PAYLOAD_FIELDS.get(rec["event"])
            if payload is not None and not isinstance(
                    rec.get(payload), dict):
                raise ValueError(
                    f"{path}:{ln}: {rec['event']!r} event missing its "
                    f"{payload!r} payload object: {line[:120]}")
            events.append(rec)
    have = {e["event"] for e in events}
    missing = [r for r in required if r not in have]
    if missing:
        raise ValueError(
            f"{path}: incomplete run event log — missing {missing} "
            f"(saw {sorted(have)})")
    return events
