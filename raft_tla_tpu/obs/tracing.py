"""Span tracing — Chrome trace-event JSON for Perfetto / chrome://tracing.

The third leg of the telemetry spine (metrics = live numbers, events =
durable lifecycle JSONL, tracing = *time-structured* spans).  A
:class:`SpanTracer` records nested spans — context-managed ``with
tracer.span("chunk"): ...`` blocks, or explicit ``complete()`` stamps for
loop-shaped scopes — and serializes them to the Chrome trace-event array
format, so a ``check --trace-out run.json`` opens directly in Perfetto
(drag-and-drop) or ``chrome://tracing`` with per-thread nesting intact.

Zero-dependency and thread-safe, like the rest of ``obs/``: spans append
under one lock, thread ids come from the recording thread, and nothing
here imports jax.  A ``SpanTracer(None)`` is a no-op sink (the
``RunEventLog(None)`` pattern), so call sites never branch.

Wiring: the engines attach their tracer to the
:class:`~raft_tla_tpu.obs.metrics.MetricsRegistry` (``registry.tracer``),
which mirrors every ``phase_timer`` block into a span — one attachment
instruments every existing phase site (chunk dispatch, stats fetch,
spill, checkpoint, sim_chunk, server request latencies, ...).  The
engines add the scopes phases can't express: a ``run`` span, one
``level`` span per BFS level, and the supervisor adds one ``attempt``
span per child run plus ``restart`` instants.

Format notes (the subset Perfetto accepts without complaint): a JSON
*array* of event objects; ``ph: "X"`` complete events carry ``ts`` and
``dur`` in microseconds; ``ph: "i"`` instants carry ``s: "t"`` (thread
scope); ``ph: "M"`` metadata names processes/threads.  ``ts`` is
relative to tracer creation — merge multi-process traces by the
``trace_start_unix`` metadata arg each file carries.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional


class SpanTracer:
    """Thread-safe span recorder; ``SpanTracer(None)`` discards.

    ``path`` is where :meth:`write` serializes to by default (the
    ``--trace-out`` file); recording is in-memory, flushed by the
    engines at every level boundary and at run end (atomic rewrite), so
    a crash loses at most the current level's spans and the hot loop
    never blocks on disk.
    """

    def __init__(self, path: Optional[str] = None,
                 process_name: str = "raft_tla_tpu"):
        self.path = path
        self._process_name = process_name
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self.reset()

    def reset(self) -> None:
        """Drop everything recorded and restart the clock — one trace
        file describes ONE run, so warm/reused engines call this at
        every run start (``_telemetry_run``) instead of appending a
        second ``run`` span to the first run's events.  The supervisor's
        own tracer is deliberately never reset: its attempt/restart
        timeline spans the whole supervision episode."""
        with self._lock:
            self._events = []
            self._named_tids = set()
        self._t0 = time.perf_counter()
        if self.path is not None:
            # Process metadata + the epoch anchor for cross-process merge.
            self._append({"name": "process_name", "ph": "M",
                          "pid": self._pid, "tid": 0,
                          "args": {"name": self._process_name}})
            self._append({"name": "trace_start_unix", "ph": "M",
                          "pid": self._pid, "tid": 0,
                          "args": {"unix_seconds": round(time.time(), 6)}})

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # -- recording -----------------------------------------------------
    def _append(self, rec: dict) -> None:
        with self._lock:
            self._events.append(rec)

    def _tid(self) -> int:
        tid = threading.get_ident()
        if tid not in self._named_tids:
            self._named_tids.add(tid)
            self._append({"name": "thread_name", "ph": "M",
                          "pid": self._pid, "tid": tid,
                          "args": {"name": threading.current_thread().name}})
        return tid

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextmanager
    def span(self, name: str, **args):
        """Record the block as one complete (``ph: "X"``) event.  Nesting
        is implicit: Chrome/Perfetto stack same-thread spans by ts/dur."""
        if self.path is None:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            self.complete(name, start, **args)

    def complete(self, name: str, start_perf_counter: float, **args) -> None:
        """Record a span from an earlier ``time.perf_counter()`` stamp to
        now — the loop-shaped-scope form (level boundaries, supervisor
        attempts), where a ``with`` block can't bracket the region."""
        if self.path is None:
            return
        end = time.perf_counter()
        rec = {"name": name, "ph": "X", "pid": self._pid,
               "tid": self._tid(),
               "ts": round((start_perf_counter - self._t0) * 1e6, 3),
               "dur": round((end - start_perf_counter) * 1e6, 3)}
        if args:
            rec["args"] = args
        self._append(rec)

    def instant(self, name: str, **args) -> None:
        """Point-in-time marker (``ph: "i"``, thread scope)."""
        if self.path is None:
            return
        rec = {"name": name, "ph": "i", "s": "t", "pid": self._pid,
               "tid": self._tid(), "ts": round(self._now_us(), 3)}
        if args:
            rec["args"] = args
        self._append(rec)

    # -- serialization -------------------------------------------------
    def write(self, path: Optional[str] = None) -> Optional[str]:
        """Serialize everything recorded so far as one Chrome trace JSON
        array (atomic tmp + rename; repeat calls rewrite — the engines
        call this at every run end, so the newest run always lands even
        if a later one crashes mid-write).  Returns the path written, or
        None when the tracer is disabled."""
        path = path or self.path
        if path is None:
            return None
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with self._lock:
            events = list(self._events)
        tmp = f"{path}.tmp{self._pid}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(events, f, default=str)
            f.write("\n")
        os.replace(tmp, path)
        return path


def validate_chrome_trace(path: str) -> list:
    """Validate a ``--trace-out`` file: it must parse as a JSON *array*
    of event objects each carrying ``name``/``ph`` (and ``ts`` for
    non-metadata phases) — the shape Perfetto accepts.  Returns the
    events; raises ``FileNotFoundError``/``ValueError`` otherwise.  The
    bench/CI tooling calls this next to ``validate_run_events`` so a
    trace regression fails as loudly as an event-log one."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"trace file missing: {path}")
    with open(path, encoding="utf-8") as f:
        try:
            events = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON ({e})")
    if not isinstance(events, list):
        raise ValueError(
            f"{path}: Chrome trace must be a JSON array of events, got "
            f"{type(events).__name__} (the object-with-traceEvents form "
            f"is not what this tracer emits)")
    for i, rec in enumerate(events):
        if not isinstance(rec, dict) or "name" not in rec \
                or "ph" not in rec:
            raise ValueError(
                f"{path}: event {i} is not an object with 'name'/'ph': "
                f"{str(rec)[:120]}")
        if rec["ph"] != "M" and "ts" not in rec:
            raise ValueError(
                f"{path}: event {i} ({rec['name']!r}, ph={rec['ph']!r}) "
                f"missing 'ts'")
    return events
