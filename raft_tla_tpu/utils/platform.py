"""Platform selection helpers — the axon (TPU-tunnel) workaround, once.

This machine's ambient environment force-registers the ``axon`` PJRT plugin
via sitecustomize and overrides ``jax_platforms`` by config, so requesting
CPU through environment variables alone is not enough: once registered, any
backend initialization blocks on the TPU relay.  These helpers put jax back
on CPU reliably.  They depend on one private jax API
(``xla_bridge._backend_factories``) — kept in this single module so a jax
upgrade has exactly one place to fix.
"""

from __future__ import annotations

import os


def force_cpu() -> None:
    """Pin jax to the CPU backend, deregistering the axon plugin if the
    sitecustomize hook installed it.  Must run before backend init."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge

        xla_bridge._backend_factories.pop("axon", None)
    except Exception:
        pass


def neutralize_axon_if_cpu_requested() -> None:
    """Apply :func:`force_cpu` only when the environment asks for CPU —
    leaves real-TPU runs (JAX_PLATFORMS=axon) untouched."""
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        force_cpu()


def _host_fingerprint() -> str:
    """A short digest of this host's CPU identity (model + ISA feature
    flags).  XLA's persistent cache keys entries by program, not by the
    host CPU's feature set, so a cache populated on one machine can hand
    a different machine code using unsupported instructions — the
    BENCH_r04 stderr carried XLA's own warning that this "could lead to
    execution errors such as SIGILL".  Keying the cache *directory* by
    host identity makes cross-host reuse structurally impossible."""
    import hashlib
    import platform as _platform

    parts = [_platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags", "Features")):
                    parts.append(line.strip())
                    if len(parts) >= 3:
                        break
    except OSError:
        parts.append(_platform.processor())
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


def enable_persistent_cache(tag: str = "") -> None:
    """Point jax at the repo-local persistent compilation cache.  The BFS
    chunk program takes ~1 min (TPU) to minutes (CPU) to compile; with the
    cache, every CLI/bench/driver invocation after the first is instant.
    Safe to call multiple times, before or after backend init.

    The cache lives under a per-host subdirectory (see
    :func:`_host_fingerprint`) so a cache written by a different machine
    — e.g. a CI host with a wider AVX feature set than the TPU-tunnel
    host — can never be loaded here and SIGILL a bench mid-window.

    KNOWN-BENIGN residual warning: XLA's CPU AOT loader may still print
    a "Machine type used for XLA:CPU compilation doesn't match" error
    naming ``+prefer-no-gather``/``+prefer-no-scatter`` — those are XLA
    *tuning pseudo-features* it records at compile time but that host
    feature detection never reports, so the message fires even when the
    cache entry was written by THIS host in THIS session (verified
    2026-07-31: fresh per-host dir, same process lineage).  It is a
    false positive for the SIGILL hazard; a real cross-host entry can no
    longer be loaded at all under the fingerprinted directory.

    ``tag`` further namespaces the directory by *execution context* on
    the same host.  The unit suite runs on 8 virtual CPU devices
    (conftest's ``--xla_force_host_platform_device_count=8``) while
    every CLI/bench/server invocation runs on 1; letting both contexts
    interleave entries in one directory changes the suite's
    compile-vs-load history run to run, and jaxlib's CPU client is
    heap-layout fragile enough under the big mesh tests that a
    foreign-context cache state reproduces both a lowering-time abort
    and a wrong-resume ``seen-set probe failure`` (observed 2026-08-06:
    ``test_mesh`` green with a suite-pure cache, aborted with a
    bench-populated one).  A tagged caller gets its own subdirectory,
    so cross-context interleaving is structurally impossible."""
    import jax

    cache = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache",
        _host_fingerprint() + ("-" + tag if tag else ""))
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def compat_shard_map(mesh):
    """``shard_map(fn, mesh, in_specs, out_specs)`` across jax versions.

    Newer jax exposes ``jax.shard_map`` (replication checking spelled
    ``check_vma``); 0.4.x ships it as
    ``jax.experimental.shard_map.shard_map`` with the ``check_rep``
    spelling.  Returns ``shard(fn, in_specs=..., out_specs=...)`` bound to
    ``mesh`` with replication checking off on either API — like the
    backend-factory workaround above, version-compat jax surface lives in
    this one module."""
    import functools

    import jax
    if hasattr(jax, "shard_map"):
        return functools.partial(jax.shard_map, mesh=mesh, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    def shard(fn, *, in_specs, out_specs):
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)

    return shard
