"""TLC ``.cfg`` configuration parsing and model resolution.

The reference harness configs (/root/reference/MCraft.cfg,
/root/reference/Smokeraft.cfg) remain the source of truth (SURVEY §5.6/H1-H2):
this module parses the TLC cfg grammar subset they use —

    CONSTANT/CONSTANTS blocks with ``name = modelvalue``,
    ``name = {set literal}``, ``name = number``, and ``name <- definition``
    substitutions; SPECIFICATION; INVARIANT(S); CONSTRAINT(S);
    CHECK_DEADLOCK; ``\\*`` comments

— and resolves them against the spec's known definition names.  Instead of a
full TLA+ parser, the companion ``.tla`` harness module (MCraft.tla /
Smokeraft.tla, looked up next to the cfg) is scanned for the three shapes the
harnesses actually use:

- model-value set definitions ``name == {v1, v2}`` (MCraft.tla:15-21),
- the smoke subset size ``k == 2`` (Smokeraft.tla:17-19),
- StopAfter budgets ``TLCGet("duration") > 1`` / ``TLCGet("diameter") > 100``
  (Smokeraft.tla:88-92).

Bounded exhaustive configs (the BASELINE.json runs) use ordinary cfg constants
``MaxTerm/MaxLogLen/MaxMsgCount`` consumed by the built-in ``BoundedSpace``
constraint — standard TLC practice, no grammar extension required.

**TPU backend keys** (the ``TPUraft.cfg`` mechanism from the BASELINE.json
north star): engine parameters ride in the cfg as ``\\* TPU: KEY = VALUE``
comment directives, e.g. ``\\* TPU: BATCH = 8192``.  Because they are TLC
comments, a backend-annotated cfg still parses and runs under stock TLC
unchanged — the cfg stays the single source of truth for both engines.
Recognized keys: BATCH, QUEUE_CAPACITY, SEEN_CAPACITY, N_MSG_SLOTS,
MAX_LOG, PLATFORM, CHECKPOINT_DIR, CHECKPOINT_EVERY, CHECKPOINT_INTERVAL,
SPILL_DIR, TRACE_DIR, PROGRESS_SECONDS, EVENTS_OUT, KEEP_CHECKPOINTS,
TRACE_OUT (Chrome-trace span file), PROFILE_CHUNKS (per-stage chunk
profiling cadence), POR (statically-certified partial-order reduction),
POR_TABLE (pre-certified reduction-table artifact path), PIPELINE
(successor pipeline: auto / v1 / v2 / v3 / v4 — v3 is the fused Pallas
chunk, v4 the whole-chunk VMEM megakernel; engine/bfs.py
EngineConfig.pipeline), XLA_PROFILE (device-profiler
capture: trace the first N chunk calls through jax.profiler,
obs/profile.py XlaProfileCapture), METRICS_PORT (serve /metrics
Prometheus exposition + /flight live snapshots over HTTP for the run,
obs/expose.py), REPORT (the TLC-parity statespace run report,
obs/report.py; TRUE by default — FALSE drops every report surface),
COUNTEREXAMPLE_DIR (where a traced violation's rendered counterexample
lands, engine/explain.py; defaults next to CHECKPOINT_DIR), HISTORY
(append one run-history ledger entry per run to this JSONL file,
obs/history.py), PERF (the performance observatory: launch accounting,
static roofline + fusion advisor, obs/perf.py — observational, implies
sparse chunk profiling), MODE (checking engine tier: ``exhaustive``
(default) or ``swarm`` — the vmap'd randomized-walk engine,
engine/swarm.py), WALKS (swarm mode: concurrent walks per device).
Precedence everywhere: CLI flag > cfg backend key > built-in default.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple

from ..models.dims import RaftDims
from ..models.invariants import Bounds

_KEYWORDS = {
    "CONSTANT", "CONSTANTS", "SPECIFICATION", "INVARIANT", "INVARIANTS",
    "CONSTRAINT", "CONSTRAINTS", "ACTION_CONSTRAINT", "INIT", "NEXT",
    "SYMMETRY", "VIEW", "CHECK_DEADLOCK", "PROPERTY", "PROPERTIES",
}


@dataclasses.dataclass
class ParsedCfg:
    assignments: Dict[str, object] = dataclasses.field(default_factory=dict)
    substitutions: Dict[str, str] = dataclasses.field(default_factory=dict)
    specification: Optional[str] = None
    init: Optional[str] = None
    next: Optional[str] = None
    invariants: List[str] = dataclasses.field(default_factory=list)
    constraints: List[str] = dataclasses.field(default_factory=list)
    action_constraints: List[str] = dataclasses.field(default_factory=list)
    properties: List[str] = dataclasses.field(default_factory=list)
    symmetry: Optional[str] = None
    view: Optional[str] = None
    check_deadlock: bool = True        # TLC default
    backend: Dict[str, object] = dataclasses.field(default_factory=dict)


def _tokenize(text: str) -> List[str]:
    text = re.sub(r"\\\*[^\n]*", " ", text)          # \* line comments
    text = re.sub(r"\(\*.*?\*\)", " ", text, flags=re.S)  # (* block *)
    # Split keeping braces/commas/operators as tokens.
    return re.findall(r"<-|=|\{|\}|,|[^\s{},=]+", text)


_BACKEND_KEYS = {
    "BATCH", "QUEUE_CAPACITY", "SEEN_CAPACITY", "N_MSG_SLOTS", "MAX_LOG",
    "PLATFORM", "CHECKPOINT_DIR", "CHECKPOINT_EVERY", "CHECKPOINT_INTERVAL",
    "SPILL_DIR", "TRACE_DIR", "PROGRESS_SECONDS", "EVENTS_OUT",
    "KEEP_CHECKPOINTS", "TRACE_OUT", "PROFILE_CHUNKS", "POR", "POR_TABLE",
    "PIPELINE", "XLA_PROFILE", "METRICS_PORT", "REPORT",
    "COUNTEREXAMPLE_DIR", "HISTORY", "PERF", "MODE", "WALKS",
}


def parse_backend_directives(text: str) -> Dict[str, object]:
    """``\\* TPU: KEY = VALUE`` comment directives (see module docstring)."""
    out: Dict[str, object] = {}
    for m in re.finditer(r"^\s*\\\*\s*TPU:\s*(\w+)\s*=\s*(\S+)",
                         text, flags=re.M | re.I):
        key, raw = m.group(1).upper(), m.group(2)
        if key not in _BACKEND_KEYS:
            raise ValueError(f"unknown TPU backend key {key!r}; "
                             f"recognized: {sorted(_BACKEND_KEYS)}")
        if re.fullmatch(r"-?\d+", raw):
            out[key] = int(raw)
        elif re.fullmatch(r"-?\d+\.\d*", raw):
            out[key] = float(raw)
        elif raw.upper() in ("TRUE", "FALSE"):
            # Case-insensitive like the keys: boolean directives (POR)
            # must not silently truthy-string their way to enabled when
            # written ``= false``.
            out[key] = raw.upper() == "TRUE"
        else:
            out[key] = raw
    return out


def parse_cfg(text: str) -> ParsedCfg:
    toks = _tokenize(text)
    cfg = ParsedCfg()
    cfg.backend = parse_backend_directives(text)
    i, n = 0, len(toks)

    def parse_value(j: int) -> Tuple[object, int]:
        if toks[j] == "{":
            vals, j = [], j + 1
            while toks[j] != "}":
                if toks[j] != ",":
                    vals.append(toks[j])
                j += 1
            return tuple(vals), j + 1
        v = toks[j]
        if re.fullmatch(r"-?\d+", v):
            return int(v), j + 1
        if v in ("TRUE", "FALSE"):
            return v == "TRUE", j + 1
        return v, j + 1

    mode = None
    while i < n:
        t = toks[i]
        if t in _KEYWORDS:
            mode = t
            i += 1
            if t == "CHECK_DEADLOCK":
                cfg.check_deadlock = toks[i] == "TRUE"
                i += 1
                mode = None
            continue
        if mode in ("CONSTANT", "CONSTANTS", "INIT", "NEXT"):
            # INIT/NEXT in cfg name an operator; `Init <- SmokeInit` appears
            # inside a CONSTANT block in Smokeraft.cfg:43-44 — both accepted.
            name = t
            if i + 1 < n and toks[i + 1] == "=":
                val, i2 = parse_value(i + 2)
                cfg.assignments[name] = val
                i = i2
            elif i + 1 < n and toks[i + 1] == "<-":
                cfg.substitutions[name] = toks[i + 2]
                i += 3
            elif mode in ("INIT", "NEXT"):
                setattr(cfg, mode.lower(), name)
                i += 1
                mode = None
            else:
                i += 1
        elif mode == "SPECIFICATION":
            cfg.specification = t
            i += 1
            mode = None
        elif mode in ("INVARIANT", "INVARIANTS"):
            cfg.invariants.append(t)
            i += 1
        elif mode in ("CONSTRAINT", "CONSTRAINTS"):
            cfg.constraints.append(t)
            i += 1
        elif mode == "ACTION_CONSTRAINT":
            # TLC action constraints range over transitions (primed and
            # unprimed state) — different semantics from state constraints;
            # rejected explicitly rather than silently misinterpreted.
            cfg.action_constraints.append(t)
            i += 1
        elif mode in ("PROPERTY", "PROPERTIES"):
            cfg.properties.append(t)
            i += 1
        elif mode in ("SYMMETRY", "VIEW"):
            # Captured so load_config can reject them loudly (below); the
            # reference cfgs use neither (MCraft.cfg:1-39 has "No SYMMETRY,
            # no VIEW" per SURVEY §1 L5), so rejection — not implementation
            # — is the required behavior: silently dropping either would
            # report non-TLC state counts with no warning.
            setattr(cfg, mode.lower(), t)
            i += 1
            mode = None
        else:
            i += 1
    return cfg


# ---------------------------------------------------------------------------
# Companion-module scanning (the three shapes the reference harnesses use).

def scan_module_definitions(text: str) -> Dict[str, object]:
    """Extract ``name == <set literal | int>`` definitions from a harness
    module (handles the newline between name and body, MCraft.tla:15-21)."""
    out: Dict[str, object] = {}
    for m in re.finditer(
            r"^\s*(\w+)\s*==\s*\n?\s*(\{[^}]*\}|-?\d+)\s*$",
            re.sub(r"\\\*[^\n]*", "", text), flags=re.M):
        name, body = m.group(1), m.group(2).strip()
        if body.startswith("{"):
            out[name] = tuple(x.strip() for x in body[1:-1].split(",")
                              if x.strip())
        else:
            out[name] = int(body)
    return out


# Engine counters a TLCGet-consulting constraint may read — the live values
# TLC exposes through its control channel (SURVEY §5.5).  duration/diameter
# map onto the engines' native budget machinery; the rest are checked
# against live result counters after every chunk of work.
EXIT_COUNTERS = ("duration", "diameter", "distinct", "generated", "queue")

_TLCSET_EXIT = r'TLCSet\(\s*"exit"\s*,\s*TLCGet\("(\w+)"\)\s*>\s*(\d+)\s*\)'


@dataclasses.dataclass(frozen=True)
class ExitOp:
    """One operator of the StopAfter shape found in a companion module."""
    conds: Tuple[Tuple[str, float], ...]
    # True iff the body is NOTHING but TLCSet exit conjuncts — only then may
    # the operator be consumed as a pure budget; a mixed budget+predicate
    # CONSTRAINT is rejected at load (dropping the predicate half would
    # silently change state counts).
    pure: bool


def scan_exit_operators(text: str) -> Dict[str, ExitOp]:
    """Find operators of the Smokeraft StopAfter shape (Smokeraft.tla:88-92)

        Name ==
            /\\ TLCSet("exit", TLCGet("<counter>") > <n>)
            ...

    and return {operator name: ExitOp}.  This is the general TLCGet/TLCSet
    metrics-control coupling: any such PURE operator named as CONSTRAINT in
    a cfg becomes a budget consulting live engine counters — no code changes
    needed for e.g. ``TLCGet("distinct") > 1000000``.  Validation (unknown
    counters, impure bodies) happens in load_config, and only for operators
    a cfg actually names — an unused helper must not poison the module."""
    out: Dict[str, ExitOp] = {}
    clean = re.sub(r"\(\*.*?\*\)", "", text, flags=re.S)   # (* block *)
    clean = re.sub(r"\\\*[^\n]*", "", clean)               # \* line
    defs = list(re.finditer(r"^\s*(\w+)\s*(\([^)]*\))?\s*==", clean,
                            flags=re.M))
    for k, m in enumerate(defs):
        end = defs[k + 1].start() if k + 1 < len(defs) else len(clean)
        body = clean[m.end():end]
        conds = re.findall(_TLCSET_EXIT, body)
        if not conds:
            continue
        # Residue after removing the exit conjuncts: only /\ , \/ glue and
        # the module terminator's ='s may remain for the body to be pure.
        residue = re.sub(_TLCSET_EXIT, "", body)
        pure = re.fullmatch(r"[\s/\\=-]*", residue) is not None
        out[m.group(1)] = ExitOp(
            conds=tuple((c, float(n)) for c, n in conds), pure=pure)
    return out


# ---------------------------------------------------------------------------
# Resolution into a runnable setup.

@dataclasses.dataclass
class CheckSetup:
    """Everything the engine needs, resolved from one cfg."""

    dims: RaftDims
    bounds: Bounds
    invariants: List[str]
    constraints: List[str]
    check_deadlock: bool
    smoke: bool = False                 # Init <- SmokeInit override
    smoke_k: int = 2
    max_seconds: Optional[float] = None
    max_diameter: Optional[int] = None
    # Further TLCGet-consulting budgets (counter, threshold) beyond the two
    # with native engine machinery: distinct / generated / queue.
    exit_conditions: Tuple[Tuple[str, float], ...] = ()
    server_names: Tuple[str, ...] = ()
    value_names: Tuple[str, ...] = ()
    cfg: Optional[ParsedCfg] = None
    backend: Dict[str, object] = dataclasses.field(default_factory=dict)


def load_config(cfg_path: str, max_log: Optional[int] = None,
                n_msg_slots: Optional[int] = None) -> CheckSetup:
    """Parse cfg + companion module, intern model values, derive dims.
    ``max_log``/``n_msg_slots`` arguments (CLI flags) override the cfg's
    ``\\* TPU:`` backend directives, which override built-in defaults."""
    with open(cfg_path) as f:
        cfg = parse_cfg(f.read())
    if max_log is None:
        max_log = cfg.backend.get("MAX_LOG")
    if n_msg_slots is None:
        n_msg_slots = cfg.backend.get("N_MSG_SLOTS", 32)
    moddefs: Dict[str, object] = {}
    exit_ops: Dict[str, ExitOp] = {}
    # Scan the companion module and its EXTENDS chain (Smokeraft EXTENDS
    # MCraft — Smokeraft.tla:2 — whose const_* definitions the cfg names).
    mod_dir = os.path.dirname(os.path.abspath(cfg_path))
    pending = [os.path.splitext(os.path.basename(cfg_path))[0]]
    seen_mods = set()
    while pending:
        mod = pending.pop()
        if mod in seen_mods:
            continue
        seen_mods.add(mod)
        cand = os.path.join(mod_dir, mod + ".tla")
        if not os.path.exists(cand):
            continue
        with open(cand) as f:
            text = f.read()
        moddefs.update(scan_module_definitions(text))
        for name, conds in scan_exit_operators(text).items():
            exit_ops.setdefault(name, conds)
        ext = re.search(r"^\s*EXTENDS\s+([^\n]+)", text, flags=re.M)
        if ext:
            pending.extend(x.strip() for x in ext.group(1).split(","))

    def resolve_set(name: str) -> Tuple[str, ...]:
        if name in cfg.assignments and isinstance(cfg.assignments[name],
                                                  tuple):
            return cfg.assignments[name]
        if name in cfg.substitutions:
            target = cfg.substitutions[name]
            if target in moddefs and isinstance(moddefs[target], tuple):
                return moddefs[target]
            raise ValueError(
                f"cannot resolve {name} <- {target}: definition not found "
                f"in companion module of {cfg_path}")
        raise ValueError(f"no binding for constant {name} in {cfg_path}")

    servers = resolve_set("Server")
    values = resolve_set("Value")

    def int_const(name: str) -> Optional[int]:
        v = cfg.assignments.get(name)
        return v if isinstance(v, int) else None

    bounds = Bounds(max_term=int_const("MaxTerm"),
                    max_log_len=int_const("MaxLogLen"),
                    max_msg_count=int_const("MaxMsgCount"),
                    max_in_flight=int_const("MaxInFlight"))

    if cfg.action_constraints:
        raise NotImplementedError(
            f"ACTION_CONSTRAINT {cfg.action_constraints} not supported: "
            "action constraints range over transitions, not states")

    if cfg.symmetry is not None:
        raise NotImplementedError(
            f"SYMMETRY {cfg.symmetry} not supported: symmetry reduction "
            "quotients the state space and changes distinct-state counts; "
            "running without it would silently disagree with TLC")

    if cfg.view is not None:
        raise NotImplementedError(
            f"VIEW {cfg.view} not supported: a view changes which states "
            "are considered distinct; fingerprints here cover the full "
            "canonical state only")

    if cfg.properties:
        # Temporal properties (PROPERTY/PROPERTIES) need liveness checking
        # (fairness, SCC search over the behavior graph) — a different
        # algorithm from safety BFS.  Rejected loudly: dropping them would
        # let a cfg 'pass' a property that was never checked.
        raise NotImplementedError(
            f"PROPERTY {cfg.properties} not supported: temporal/liveness "
            "checking is not implemented; this engine checks INVARIANT "
            "(safety) properties only")

    smoke = cfg.substitutions.get("Init") == "SmokeInit" \
        or cfg.init == "SmokeInit"
    smoke_k = moddefs.get("k", 2) if smoke else 2

    if max_log is None:
        if bounds.max_log_len is not None:
            # Expanded states have len <= MaxLogLen; their successors can
            # exceed the bound by one appended entry (counted, not expanded).
            max_log = bounds.max_log_len + 1
        elif smoke:
            max_log = 12    # init logs <= 3 (Smokeraft.tla:70) + headroom
        else:
            max_log = 8

    # Any CONSTRAINT whose companion-module definition is a TLCSet("exit",
    # TLCGet(...) > n) conjunction is a budget, not a state predicate —
    # Smokeraft's StopAfter is simply the reference instance of the shape.
    max_seconds = max_diameter = None
    exit_conditions: List[Tuple[str, float]] = []
    budget_names = [c for c in cfg.constraints if c in exit_ops]
    for name in budget_names:
        op = exit_ops[name]
        if not op.pure:
            raise NotImplementedError(
                f"CONSTRAINT {name} mixes TLCSet exit budgets with other "
                "conjuncts; dropping the non-budget half would silently "
                "change state counts — split the operator into a pure "
                "budget and a pure state predicate")
        for counter, threshold in op.conds:
            if counter not in EXIT_COUNTERS:
                raise NotImplementedError(
                    f'TLCGet("{counter}") in CONSTRAINT {name} not '
                    f"supported; available engine counters: {EXIT_COUNTERS}")
            # TLC exits when ANY TLCSet("exit", ...) trips, so when the
            # same counter is bounded twice the SMALLEST threshold wins.
            if counter == "duration":
                max_seconds = threshold if max_seconds is None \
                    else min(max_seconds, threshold)
            elif counter == "diameter":
                max_diameter = int(threshold) if max_diameter is None \
                    else min(max_diameter, int(threshold))
            else:
                exit_conditions.append((counter, threshold))

    # TargetConfigs (a set of membership bitmasks over the interned server
    # order) selects the joint-consensus reconfiguration variant
    # (models/reconfig.py) — the BASELINE.json configs[4] state space.
    if "TargetConfigs" in cfg.assignments:
        from ..models.reconfig import ReconfigDims
        raw = cfg.assignments["TargetConfigs"]
        if not isinstance(raw, tuple):
            raw = (raw,)
        targets = tuple(sorted(int(x) for x in raw))
        dims = ReconfigDims(n_servers=len(servers), n_values=len(values),
                            max_log=max_log, n_msg_slots=n_msg_slots,
                            targets=targets)
    else:
        dims = RaftDims(n_servers=len(servers), n_values=len(values),
                        max_log=max_log, n_msg_slots=n_msg_slots)

    return CheckSetup(
        dims=dims,
        bounds=bounds,
        invariants=list(cfg.invariants),
        constraints=[c for c in cfg.constraints if c not in budget_names],
        check_deadlock=cfg.check_deadlock,
        smoke=smoke, smoke_k=smoke_k,
        max_seconds=max_seconds, max_diameter=max_diameter,
        exit_conditions=tuple(exit_conditions),
        server_names=servers, value_names=values, cfg=cfg,
        backend=dict(cfg.backend))
