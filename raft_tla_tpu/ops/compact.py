"""Candidate-lane compaction — the stage between expand and the FPSet.

The expand kernel emits a [B, G] enabled mask whose true fraction is
typically well under 10% (measured fan-out ~6% of G on MCraft_bounded), so
everything downstream of expand — fingerprint insert, row materialization,
invariant/constraint evaluation, enqueue — runs on K << B*G compacted
lanes.  This module is the single implementation both engines (engine/
bfs.py, parallel/mesh.py) and the profiling instrument (scripts/
profile_step.py) share; its invariants are load-bearing:

- ``K`` is a power of two and ``K >= G``, so one parent's worst-case
  fan-out always fits and a batch always makes progress (``P >= 1``);
- **progress limiting**: only the longest prefix of parents whose total
  fan-out fits K is taken; the caller advances its queue offset by ``P``,
  so a fan-out burst costs extra steps, never dropped states;
- every scatter/gather lane has its own cold address: masked-off lanes
  write to per-lane trash slots in [K, 2K) and unfilled live slots keep a
  spread init, because a shared hot address serializes the op on TPU
  (ops/fpset.py design notes 1+3).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import fpset

_I32 = jnp.int32


def choose_k(B: int, G: int, requested=None) -> int:
    """Compacted-lane count: the requested value (engine config) or the
    16-lanes-per-parent default, rounded to a power of two.

    Floored at ``max(G, B)``: G so one parent's worst-case fan-out fits
    (progress guarantee), and B because the engines' ingest path enqueues
    up to B rows per call against a spill watermark of K — a smaller K
    would let one ingest call run live rows into the scatter-trash region.
    Capped at ``_pow2(B*G)``; more lanes than candidates is pure waste."""
    k = requested
    if k is None:
        k = min(16 * B, B * G)
    return min(fpset._pow2(max(k, G, B)), fpset._pow2(B * G))


def inv_positions(mask, out_len: int):
    """Invert a boolean mask's compaction map: result[k] = index of the
    (k+1)-th True lane, for k < sum(mask); clipped in-range otherwise
    (callers gate dead slots themselves).  The searchsorted(side="left")
    over the running count with +1 queries is the subtle core shared by
    the searchsorted compactor and the window enqueue/trace lowerings —
    keep it in ONE place."""
    cum = jnp.cumsum(mask.astype(_I32))
    q = jnp.arange(1, out_len + 1, dtype=_I32)
    return jnp.clip(jnp.searchsorted(cum, q, side="left"),
                    0, mask.shape[0] - 1).astype(_I32)


def kspread(B: int, G: int, K: int):
    """Hash-spread addresses for dead compacted slots — the ONE
    definition shared by every compact lowering (both methods here and
    ops/compact_pallas.py), because lane_id bit-identity across
    lowerings depends on all of them initializing dead slots from the
    identical vector."""
    return jnp.asarray((np.arange(K) * 2654435761) % (B * G), _I32)


def build_compactor(B: int, G: int, K: int, reduce_p=None,
                    method: str = "scatter"):
    """Returns ``compact(en) -> (P, total, lane_id, kvalid)`` for a
    [B, G] enabled mask:

    - ``P``       parents taken this step (advance the offset by this);
    - ``total``   number of live compacted lanes (== sum of en over the
                  first P parents);
    - ``lane_id`` [K] flat candidate-lane index per compacted slot
                  (spread addresses in dead slots).  Disabled lanes write
                  to the K-slot trash region ``K + (lane & (K-1))``; when
                  B*G > K that aliases ~B*G/K lanes per trash slot (~G/16
                  ≈ 8 at the default K = 16·B) — bounded write conflicts,
                  accepted: spreading fully would need a K+B*G-wide
                  scratch target, and an 8-way conflict is noise next to
                  the all-lanes-one-address serialization this avoids;
    - ``kvalid``  [K] liveness mask (arange < total).

    ``reduce_p`` (optional) reduces the locally-computed P before it is
    applied — the mesh engine passes ``lax.pmin`` over the device axis so
    every chip advances its offset identically (the chunk body contains
    collectives, so trip counts must agree).

    ``method`` selects the lowering, with IDENTICAL outputs (unit-tested):

    - "scatter": the original formulation — a B*G-lane scatter of lane
      indices into the K live + K trash slots;
    - "searchsorted": invert the mapping instead — ``lane_id[k]`` is the
      first flat lane whose running enabled-count reaches ``k+1``, i.e. a
      binary search of ``arange(K)+1`` in the [B*G] cumsum.  ~log2(B*G)
      gather rounds over K lanes replaces the B*G-lane scatter (the TPU
      profile's 21 ms compact stage is that scatter); dead slots get the
      same spread addresses as "scatter"."""
    BG = B * G
    lane_f = jnp.arange(BG, dtype=_I32)
    kspr = kspread(B, G, K)

    def _prefix(en):
        per_parent = jnp.sum(en, axis=1, dtype=_I32)        # [B]
        cum = jnp.cumsum(per_parent)                        # [B]
        P = jnp.sum(cum <= K, dtype=_I32)
        if reduce_p is not None:
            P = reduce_p(P)
        total = jnp.where(P > 0, cum[jnp.clip(P - 1, 0, B - 1)], 0)
        enf = (en & (jnp.arange(B, dtype=_I32) < P)[:, None]).reshape(-1)
        kvalid = jnp.arange(K, dtype=_I32) < total
        return P, total, enf, kvalid

    def compact_scatter(en):
        P, total, enf, kvalid = _prefix(en)
        posk = jnp.cumsum(enf.astype(_I32)) - 1
        pos = jnp.where(enf, posk, K + (lane_f & (K - 1)))
        lane_id = jnp.concatenate([kspr, kspr]) \
            .at[pos].set(lane_f)[:K]
        return P, total, lane_id, kvalid

    def compact_searchsorted(en):
        P, total, enf, kvalid = _prefix(en)
        lane_id = jnp.where(kvalid, inv_positions(enf, K), kspr)
        return P, total, lane_id, kvalid

    if method == "scatter":
        return compact_scatter
    if method == "searchsorted":
        return compact_searchsorted
    raise ValueError(f"unknown compactor method {method!r}")
