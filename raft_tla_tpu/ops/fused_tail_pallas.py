"""Fused Pallas probe/insert -> enqueue — the v3 pipeline's tail stage.

NORTHSTAR.md §d names the insert+enqueue residue (19.8 ms measured) as
the dominant term once the v2 delta pipeline removes expand/materialize
cost, and the decision rule stages a single fused kernel for it.  This
module is that kernel: the sequential probe/insert chain of
ops/fpset_pallas.py (shared inner loop — the probe order is literally
the same code) extended so that the novelty bit never round-trips to
HBM between the two stages.  The moment a query resolves as new, the
same grid program issues the row's HBM-to-HBM DMA append at the running
enqueue cursor — XLA's separate insert kernel, novelty-mask
materialization, position cumsum, and K-row scatter collapse into one
launch.

Layout contract (bit-identical to the "scatter" enqueue lowering,
engine/chunk.py): live rows land at ``next_count + rank-among-enqueued``
in lane order (sequential grid order IS lane order, so the running
cursor reproduces the cumsum positions exactly), and every non-enqueued
lane writes its row to the per-lane trash slot ``trash_base + lane`` —
the same addresses the scatter path uses, so even the trash region
matches byte-for-byte.  The unconditional DMA (destination select, not
a predicated copy) sidesteps predicated-DMA lowering exactly as the
insert kernel's branch-free write-back does.

``is_new``/``fail``/stored-key-set semantics are ops/fpset_pallas.py's
(same contract as ops/fpset.py insert).  Bit-identity is proven on CPU
via interpret mode (tests/test_fused.py); ``interpret`` defaults to
automatic (real lowering on TPU, interpreter elsewhere).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .fingerprint import SENTINEL
from .fpset import FPSet, PROBE_ROUNDS, _pad_pow2
from .fpset_pallas import _BLOCK, probe_insert_query
from .pallas_compat import tpu_compiler_params

_U32 = jnp.uint32
_I32 = jnp.int32


def _kernel(qhi_ref, qlo_ref, valid_ref, enq_ref,   # [BLK] VMEM in blocks
            nc_ref,                                 # [1] SMEM: next_count
            hi_in, lo_in,                           # [C] ANY (aliased)
            krows_ref,                              # [KP,SW] ANY in
            q_in,                                   # [QA,SW] ANY (aliased)
            hi_ref, lo_ref,                         # [C] ANY out
            q_ref,                                  # [QA,SW] ANY out
            new_ref,                                # [BLK] VMEM out block
            fail_ref, cnt_ref,                      # [1] outs, revisited
            scr, sem, rsem,                         # scratch + DMA sems
            *, c_mask: int, rounds: int, blk: int, trash_base: int):
    del hi_in, lo_in, q_in

    @pl.when(pl.program_id(0) == 0)
    def _():
        fail_ref[0] = _I32(0)
        cnt_ref[0] = nc_ref[0]

    # Bound OUTSIDE the query loop: jax 0.4.x interpret mode cannot
    # evaluate the program_id primitive once it is staged into an inner
    # while jaxpr.
    gbase = pl.program_id(0) * blk

    def one_query(i, local_fail):
        qh = qhi_ref[i]
        ql = qlo_ref[i]
        pending0 = valid_ref[i] != 0
        newf, pending = probe_insert_query(hi_ref, lo_ref, scr, sem,
                                           qh, ql, pending0, c_mask, rounds)
        new_ref[i] = newf.astype(_I32)
        # Enqueue leg: the row goes out NOW, while the novelty bit is
        # still in a register — at the running cursor when enqueued, to
        # its per-lane trash slot otherwise (the scatter lowering's
        # addresses; destination select keeps the DMA unconditional).
        gidx = gbase + i
        do_enq = newf & (enq_ref[i] != 0)
        dst = jnp.where(do_enq, cnt_ref[0], trash_base + gidx)
        cp = pltpu.make_async_copy(
            krows_ref.at[pl.ds(gidx, 1), :],
            q_ref.at[pl.ds(dst, 1), :], rsem)
        cp.start()
        cp.wait()
        cnt_ref[0] = cnt_ref[0] + do_enq.astype(_I32)
        return local_fail | pending.astype(_I32)

    local_fail = jax.lax.fori_loop(0, qhi_ref.shape[0], one_query, _I32(0))
    fail_ref[0] = fail_ref[0] | local_fail


# No donate_argnums — same rationale as ops/fpset_pallas.py: the inner
# jit inlines inside the engines' chunk, and input_output_aliases already
# provides the in-place table/queue update.
@functools.partial(jax.jit, static_argnames=("trash_base", "interpret"))
def _tail_padded(s: FPSet, qhi, qlo, valid, enq_ok, krows, qnext,
                 next_count, trash_base: int, interpret: bool):
    c = s.hi.shape[0]
    kp = qhi.shape[0]
    blk = min(_BLOCK, kp)
    grid = kp // blk
    kern = functools.partial(_kernel, c_mask=c - 1, rounds=PROBE_ROUNDS,
                             blk=blk, trash_base=trash_base)
    hi, lo, q_out, is_new, fail, _cnt = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((1,), lambda i: (0,)),
            pl.BlockSpec((1,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c,), jnp.uint32),
            jax.ShapeDtypeStruct((c,), jnp.uint32),
            jax.ShapeDtypeStruct(qnext.shape, qnext.dtype),
            jax.ShapeDtypeStruct((kp,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 1), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={5: 0, 6: 1, 8: 2},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            has_side_effects=True),
        interpret=interpret,
    )(qhi, qlo, valid.astype(_I32), enq_ok.astype(_I32),
      next_count[None].astype(_I32), s.hi, s.lo, krows, qnext)
    is_new = is_new.astype(bool)
    return (FPSet(hi=hi, lo=lo,
                  size=s.size + jnp.sum(is_new, dtype=_I32)),
            is_new, fail[0] > 0, q_out)


def insert_enqueue(s: FPSet, qhi, qlo, valid, krows, enq_ok, qnext,
                   next_count, trash_base: int,
                   interpret: bool | None = None
                   ) -> Tuple[FPSet, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused tail: ``(table', is_new, fail, qnext')``.

    ``is_new`` follows the insert contract (exactly one query per
    distinct new key); rows with ``is_new & enq_ok`` land contiguously
    at ``qnext[next_count + rank]`` in lane order, every other lane's
    row at ``qnext[trash_base + lane]`` — both identical to the XLA
    scatter enqueue.  The caller advances its count by
    ``sum(is_new & enq_ok)`` and must guarantee
    ``qnext.shape[0] >= trash_base + len(qhi)`` (the engines' PAD >= K
    allocation rule)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    k = qhi.shape[0]
    (qhi, qlo, valid, enq_ok), _ = _pad_pow2(
        (qhi, qlo, jnp.asarray(valid, bool), jnp.asarray(enq_ok, bool)),
        (SENTINEL, SENTINEL, False, False))
    kp = qhi.shape[0]
    if qnext.shape[0] < trash_base + kp:
        raise ValueError(
            f"qnext has {qnext.shape[0]} rows; the per-lane trash region "
            f"needs trash_base + {kp} = {trash_base + kp}")
    if kp != k:
        pad = jnp.zeros((kp - k,) + krows.shape[1:], krows.dtype)
        krows = jnp.concatenate([krows, pad])
    s, is_new, fail, q_out = _tail_padded(
        s, qhi, qlo, valid, enq_ok, krows, qnext,
        jnp.asarray(next_count, _I32), trash_base, interpret)
    return s, is_new[:k], fail, q_out
