"""Pallas whole-chunk FRONT megakernel — the v4 pipeline's fused
masks -> POR -> compact -> delta-fingerprint stage group.

The v3 pipeline (ops/pipeline_v3.py) retired the chunk's tail into one
Pallas kernel but left the front as three separate XLA stages, each
round-tripping the [B, G] mask and the parent-struct window through HBM
(NORTHSTAR.md §c: the masks + compact + fingerprint stages are the bulk
of the remaining per-batch device ops).  This kernel moves the whole
front inward: the B-row parent window is loaded into VMEM ONCE and the
guards-only enabled/overflow masks, the optional partial-order
reduction, the sequential compaction scan (the ops/compact_pallas.py
formulation, inlined), the delta fingerprints + sparse successor rows,
the state constraint, the invariant dispatch, and the parent
fingerprints all run in a single launch.  Together with the fused tail
(ops/fused_tail_pallas.py) the chunk body becomes two Pallas launches
per batch — the "one kernel launch per chunk" step ROADMAP item 1
records as PR 7's successor.

Mechanically, the kernel body cannot CLOSE OVER the model's baked-in
arrays (fingerprint salts, zeta tables — Pallas rejects captured
constants), so the two pure-math halves of the front — masks+POR before
the scan, fingerprints/constraint/invariants after it — are
``jax.closure_convert``-ed at build time and their hoisted constants
ride in as ordinary VMEM operands.  The sequential lane-assembly scan
between them stays a ref-mutation ``fori_loop`` (the compact_pallas
formulation, already proven to lower on TPU Mosaic).

Bit-identity: the converted bodies ARE the jaxprs of the same jnp model
functions the XLA path runs (models/actions2.py masks/lane_out,
models/schema.py flatten/unflatten, models/invariants.py dispatch) on
the same values.  In interpret mode (CPU) executing them is executing
those ops, so v4-vs-v2 engine differentials hold exactly; on TPU a
Mosaic lowering that rejects the gather-heavy body degrades the whole
front group back to the v3-style split stages at plan time
(ops/pipeline_v4.py build-and-probe — fallback is the contract).

Outputs mirror engine/chunk.py's front section exactly: the
post-progress-limit enabled/overflow masks, the pre-progress-limit POR
pruned mask, (P, total, lane_id, kvalid) from compaction, the K-lane
fingerprints/rows/constraint/invariant results, and the per-lane parent
fingerprints the trace recorder consumes.  The parent fingerprints are
computed unconditionally (trace-off runs pay a few extra VMEM ops
rather than a second kernel variant).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.invariants import build_inv_id
from ..models.schema import flatten_state, state_width, unflatten_state
from .compact import kspread

_I32 = jnp.int32
_U32 = jnp.uint32
_U8 = jnp.uint8

_N_OUT = 14


def _pack_consts(consts):
    """Constants hoisted by closure_convert, massaged into VMEM-legal
    operands: 0-d arrays become (1,), bools become int32.  Returns
    (operands, restore) with ``restore`` mapping the in-kernel ref
    loads back to the original shapes/dtypes."""
    ops, meta = [], []
    for c in consts:
        c = jnp.asarray(c)
        scalar = c.ndim == 0
        isbool = c.dtype == jnp.bool_
        out = c.reshape((1,)) if scalar else c
        if isbool:
            out = out.astype(_I32)
        ops.append(out)
        meta.append((scalar, isbool))

    def restore(vals):
        res = []
        for v, (scalar, isbool) in zip(vals, meta):
            if isbool:
                v = v != 0
            res.append(v[0] if scalar else v)
        return res

    return ops, restore


def _front_kernel(*refs, math1, math2, rest1, rest2, n1, por,
                  B, G, K):
    """One grid-less program computing the whole chunk front in VMEM.

    ``refs`` = rows, valid, kspread, (por_mask, por_priority)?, the n1
    hoisted constants of the masks half, the hoisted constants of the
    fingerprint half, then the 14 output refs."""
    base = 5 if por else 3
    rows = refs[0][...]                                 # [B, sw] u8
    valid = refs[1][...] != 0                           # [B]
    kspread_v = refs[2][...]
    por_args = ()
    if por:
        por_args = (refs[3][...] != 0, refs[4][...])
    split = len(refs) - _N_OUT
    c1 = rest1([r[...] for r in refs[base:base + n1]])
    c2 = rest2([r[...] for r in refs[base + n1:split]])
    (en_ref, ovf_ref, pruned_ref, p_ref, total_ref, lane_ref,
     kvalid_ref, kh_ref, kl_ref, krows_ref, cons_ref, inv_ref,
     phi_ref, plo_ref) = refs[split:]

    # -- masks + POR (closure-converted pure half #1) ------------------
    en, ovf, pruned = math1(rows, valid, *por_args, *c1)

    # -- compaction (ops/compact_pallas.py scan, inlined) --------------
    per_parent = jnp.sum(en.astype(_I32), axis=1)       # [B]
    cum = jnp.cumsum(per_parent)
    P = jnp.sum((cum <= K).astype(_I32))
    total = jnp.where(P > 0, cum[jnp.clip(P - 1, 0, B - 1)], _I32(0))
    p_ref[0] = P
    total_ref[0] = total
    kvalid_ref[...] = (jnp.arange(K, dtype=_I32) < total).astype(_I32)
    lane_ref[...] = kspread_v           # dead slots: shared hash spread
    ptaken = jnp.arange(B, dtype=_I32) < P
    enf = (en & ptaken[:, None]).reshape(-1)

    def body(f, slot):
        take = enf[f]

        @pl.when(take)
        def _():
            lane_ref[pl.ds(slot, 1)] = jnp.full((1,), f, _I32)

        return slot + take.astype(_I32)

    jax.lax.fori_loop(0, B * G, body, _I32(0))

    # Progress-limited masks out; pruned stays pre-limit (the chunk body
    # applies "& ptaken" when accounting fam_pruned, like the XLA path).
    en_ref[...] = (en & ptaken[:, None]).astype(_I32)
    ovf_ref[...] = (ovf & ptaken[:, None]).astype(_I32)
    pruned_ref[...] = pruned.astype(_I32)

    # -- fingerprints + constraint/invariants (pure half #2) -----------
    lane_id = lane_ref[...]             # read-back: the scan is done
    kh, kl, krows, cons, inv, phi, plo = math2(rows, lane_id, *c2)
    kh_ref[...] = kh
    kl_ref[...] = kl
    krows_ref[...] = krows
    cons_ref[...] = cons.astype(_I32)
    inv_ref[...] = inv
    phi_ref[...] = phi
    plo_ref[...] = plo


def build_front(*, dims, v2, constraint, inv_fns, B: int, G: int,
                K: int, por_mask=None, por_priority=None,
                interpret: bool | None = None):
    """Build the fused front: ``front(rows, valid) -> (en, ovf, pruned,
    P, total, lane_id, kvalid, kh, kl, krows, cons_ok, inv, parent_hi,
    parent_lo)`` with the same dtypes/semantics as engine/chunk.py's
    split front.  ``v2`` is models/actions2.build_v2's pipeline (v4
    shares v2's delta kernels); ``inv_fns`` the run's invariant
    predicate list (may be empty/None)."""
    sw = state_width(dims)
    inv_id = build_inv_id(list(inv_fns)) if inv_fns else None
    por = por_mask is not None
    kspr = kspread(B, G, K)
    pm = jnp.asarray(por_mask) if por else None
    pp = jnp.asarray(por_priority) if por else None

    def _math1(rows, valid, *por_args):
        """Masks + POR: the exact engine/chunk.py v2 front."""
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        en, ovf = jax.vmap(v2.masks)(states)
        en = en & valid[:, None]
        ovf = ovf & valid[:, None]
        if por:
            pmask, ppri = por_args
            amp = en & pmask[None, :]
            any_amp = jnp.any(amp, axis=1)
            pri = jnp.where(amp, ppri[None, :], jnp.int32(2147483647))
            sel = jnp.argmin(pri, axis=1)
            keep = jnp.where(
                any_amp[:, None],
                jnp.arange(G, dtype=_I32)[None, :] == sel[:, None],
                jnp.ones((B, G), bool))
            pruned = en & ~keep
            en = en & keep
            ovf = ovf & keep
        else:
            pruned = jnp.zeros((B, G), bool)
        return en, ovf, pruned

    def _math2(rows, lane_id):
        """Delta fingerprints + sparse successors + constraint/
        invariant dispatch + per-lane parent fps, on the K lanes."""
        states = jax.vmap(unflatten_state, (0, None))(rows, dims)
        ph = jax.vmap(v2.parent_hash)(states)
        pidx = lane_id // G
        kparents = jax.tree.map(lambda a: a[pidx], states)
        kph = jax.tree.map(lambda a: a[pidx], ph)
        kh, kl, kstates = jax.vmap(v2.lane_out)(
            kparents, kph, lane_id % G)
        krows = jax.vmap(flatten_state, (0, None))(kstates, dims)
        if constraint is not None:
            cons = jax.vmap(constraint)(kstates)
        else:
            cons = jnp.ones((K,), bool)
        if inv_id is not None:
            inv = jax.vmap(inv_id)(kstates)
        else:
            inv = jnp.full((K,), -1, _I32)
        php, plp = jax.vmap(v2.parent_fp)(ph)
        return kh, kl, krows, cons, inv, php[pidx], plp[pidx]

    # The kernel body may not close over arrays (Pallas rejects captured
    # constants), so hoist each half's baked-in model arrays (salt/zeta
    # tables, family grids) into explicit operands.  jax.closure_convert
    # would only hoist AD-perturbable tracers, so do it directly: trace
    # each half to a jaxpr and re-play it in-kernel with the jaxpr
    # consts passed as VMEM refs.
    rows_av = jax.ShapeDtypeStruct((B, sw), _U8)
    valid_av = jax.ShapeDtypeStruct((B,), jnp.bool_)
    lane_av = jax.ShapeDtypeStruct((K,), _I32)
    por_avs = ((jax.ShapeDtypeStruct(pm.shape, jnp.bool_),
                jax.ShapeDtypeStruct(pp.shape, pp.dtype)) if por else ())
    closed1 = jax.make_jaxpr(_math1)(rows_av, valid_av, *por_avs)
    closed2 = jax.make_jaxpr(_math2)(rows_av, lane_av)

    def _replay(closed):
        def run(*args_then_consts):
            n = len(closed.jaxpr.invars)
            args = args_then_consts[:n]
            consts = args_then_consts[n:]
            return jax.core.eval_jaxpr(closed.jaxpr, consts, *args)
        return run

    math1, math2 = _replay(closed1), _replay(closed2)
    ops1, rest1 = _pack_consts(closed1.consts)
    ops2, rest2 = _pack_consts(closed2.consts)

    kern = functools.partial(
        _front_kernel, math1=math1, math2=math2, rest1=rest1,
        rest2=rest2, n1=len(ops1), por=por, B=B, G=G, K=K)
    n_in = (5 if por else 3) + len(ops1) + len(ops2)
    out_shape = [
        jax.ShapeDtypeStruct((B, G), _I32),     # en (post progress limit)
        jax.ShapeDtypeStruct((B, G), _I32),     # ovf
        jax.ShapeDtypeStruct((B, G), _I32),     # pruned (pre limit)
        jax.ShapeDtypeStruct((1,), _I32),       # P
        jax.ShapeDtypeStruct((1,), _I32),       # total
        jax.ShapeDtypeStruct((K,), _I32),       # lane_id
        jax.ShapeDtypeStruct((K,), _I32),       # kvalid
        jax.ShapeDtypeStruct((K,), _U32),       # kh
        jax.ShapeDtypeStruct((K,), _U32),       # kl
        jax.ShapeDtypeStruct((K, sw), _U8),     # krows
        jax.ShapeDtypeStruct((K,), _I32),       # cons_ok
        jax.ShapeDtypeStruct((K,), _I32),       # inv
        jax.ShapeDtypeStruct((K,), _U32),       # parent_hi
        jax.ShapeDtypeStruct((K,), _U32),       # parent_lo
    ]
    call = pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n_in,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * _N_OUT,
        out_shape=out_shape,
        interpret=(jax.devices()[0].platform != "tpu"
                   if interpret is None else interpret),
    )

    def front(rows, valid):
        args = [rows, valid.astype(_I32), kspr]
        if por:
            args += [pm.astype(_I32), pp]
        args += list(ops1) + list(ops2)
        (en, ovf, pruned, p, total, lane_id, kvalid, kh, kl, krows,
         cons, inv, phi, plo) = call(*args)
        return (en != 0, ovf != 0, pruned != 0, p[0], total[0],
                lane_id, kvalid != 0, kh, kl, krows, cons != 0, inv,
                phi, plo)

    return front
