"""jax-version compatibility for the Pallas TPU kernels.

The kernels target the current Pallas API (``pltpu.CompilerParams`` with
``has_side_effects``); jax 0.4.x spells the class ``TPUCompilerParams``
and moves side-effect declaration elsewhere.  Same situation as
``utils.platform.compat_shard_map`` (which revived the whole parallel/
layer on 0.4.x): one shim, so every kernel module builds its compiler
params the same way on either API instead of each growing its own
try/except.

Unsupported fields are DROPPED, not errored: they are lowering hints
(DCE protection, grid semantics) that only matter under a real Mosaic
lowering — 0.4.x TPU deployments lose nothing the in-place
``input_output_aliases`` contract doesn't already pin, and interpret
mode (every CPU test) ignores compiler params entirely.
"""

from __future__ import annotations

import inspect

from jax.experimental.pallas import tpu as pltpu


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` on current jax; on 0.4.x,
    ``TPUCompilerParams`` with the unsupported fields dropped."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    fields = set(inspect.signature(cls).parameters)
    return cls(**{k: v for k, v in kwargs.items() if k in fields})
