"""v3 fused-chunk stage plan — glue between EngineConfig.pipeline="v3"
and the Pallas stage kernels.

The v3 pipeline is the v2 delta pipeline (models/actions2.py semantics,
bit-identical by construction) with the chunk's stages progressively
moved into Pallas kernels so the K-lane survivor window stops
round-tripping to HBM between stages (NORTHSTAR.md §c/§d):

    masks        guards-only enabled/overflow masks      [always XLA]
    compact      ops/compact_pallas.py sequential scan   [Pallas]
    fingerprint  v2 delta fingerprints + sparse rows     [always XLA]
    insert       ops/fused_tail_pallas.py                [Pallas, fused
    enqueue        probe/insert -> DMA append             with insert]

Two stages are XLA by design, not by fallback: the masks stage is the
whole model's guard alphabet (a jaxpr program XLA already fuses into
one kernel — a Pallas port would re-implement the spec), and the delta
fingerprint is sparse gather arithmetic over the parent struct that
only wins in Pallas once the struct itself is VMEM-resident (the
staged next step).  The other stages resolve per platform/engine with
AUTOMATIC fallback to the XLA lowering wherever a kernel cannot be
built or probed — a v3 run never fails because one stage will not
lower, it degrades that stage and records why (``V3Plan.stages`` /
``reasons``, surfaced on ``EngineResult.fused_stages``).

Platform policy (overridable per stage with ``force`` for tests):

- TPU single chip: compact=pallas, insert+enqueue=fused.
- CPU single chip: compact=xla (the sequential B*G scan is priced for
  VMEM residency; interpret-mode emulation would dominate the chunk),
  insert+enqueue=fused in interpret mode — the correctness-bearing
  fused tail runs everywhere.
- mesh: compact=xla (P is pmin-replicated across chips — a collective
  cannot live inside a Pallas stage), insert=xla (owner-routed
  all_to_all dedup is a collective), enqueue=pallas
  (ops/enqueue_pallas.py rides inside shard_map).
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Optional

STAGES = ("masks", "compact", "fingerprint", "insert", "enqueue")


class V3Plan(NamedTuple):
    stages: Dict[str, str]       # stage -> "xla" | "pallas" | "fused"
    reasons: Dict[str, str]      # stage -> why it is not Pallas/fused
    compactor: Optional[Callable]   # Pallas compactor, or None = XLA
    tail: Optional[Callable]     # fused insert+enqueue, or None = split
    enqueue_method: str          # chunk-body enqueue when tail is None
    # Expected kernel launches per stage, per batch: a Pallas/fused
    # stage is exactly ONE kernel (the fused insert+enqueue pair share
    # it); an XLA stage is None here — its pre-fusion device-op count
    # comes from the launch model's jaxpr walk (obs/perf.py), which
    # this plan cannot know without the model's kernels.  Makes the
    # fused-vs-unfused launch delta first-class on EngineResult.perf.
    # Default None, not {}: a NamedTuple field default is CLASS-level,
    # so a dict here would be shared (and mutable) across instances.
    launches: Optional[Dict[str, Optional[int]]] = None


def describe(plan: V3Plan) -> str:
    """One-line stage map for logs/results: "masks=xla compact=pallas ..."."""
    return " ".join(f"{s}={plan.stages[s]}" for s in STAGES)


def resolve_plan(B: int, G: int, K: int, *, Q: int, sw: int = 8,
                 mesh: bool = False, enqueue_method: str = "scatter",
                 force: Optional[Dict[str, str]] = None,
                 interpret: Optional[bool] = None) -> V3Plan:
    """Resolve the per-stage lowering for one engine build.

    ``Q`` is the live next-queue capacity (the fused tail's trash base);
    ``sw`` the packed state-row width (the tail probe's row shape).
    ``force`` overrides the platform policy per stage ({"compact":
    "pallas", ...}); "insert"/"enqueue" accept "fused" jointly — except
    on the mesh, whose collective-coupled stages are not forceable.
    Every Pallas choice is build-and-probe verified here at the REAL
    per-program shapes (the full [B, G] mask; the tail's real K-query
    grid and sw-byte rows, over small HBM extents), so a kernel that
    cannot construct or lower its blocks falls back NOW with a recorded
    reason instead of failing the first chunk.  Residual risk: a
    lowering failure keyed to the total HBM extent (table/queue length)
    would still surface at the first chunk compile — extents are the
    one thing the probe shrinks."""
    import jax
    force = dict(force or {})
    # Validate up front: a typo'd stage name or value must not silently
    # degrade to the platform policy (a "forced full-Pallas" test would
    # then compare XLA against XLA and pass vacuously).
    _VALID = {"masks": ("xla",), "compact": ("pallas", "xla"),
              "fingerprint": ("xla",), "insert": ("fused", "xla"),
              "enqueue": ("fused", "pallas", "xla")}
    for stage, impl in force.items():
        if stage not in _VALID or impl not in _VALID[stage]:
            raise ValueError(
                f"v3_force_stages: unknown {stage!r}={impl!r}; valid: "
                + ", ".join(f"{s}∈{v}" for s, v in _VALID.items()))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    stages = {s: "xla" for s in STAGES}
    reasons = {
        "masks": "model guard alphabet; XLA fuses the guards-only pass",
        "fingerprint": "delta arithmetic over the parent struct; Pallas "
                       "win needs the VMEM-resident struct window "
                       "(staged next)",
    }
    compactor = None
    tail = None

    # -- compact stage -------------------------------------------------
    if mesh:
        # Not overridable by force: the mesh compactor's P reduction is
        # a pmin collective (and the engine would ignore a forced
        # Pallas compactor anyway) — honoring the force here would make
        # fused_stages claim a lowering that never runs.
        want_compact = "xla"
        reasons["compact"] = ("P is pmin-replicated across chips; a "
                              "collective cannot live inside a "
                              "Pallas stage")
    else:
        want_compact = force.get("compact")
    if want_compact is None:
        if interpret:
            want_compact = "xla"
            reasons["compact"] = ("sequential B*G scan is priced for TPU "
                                  "VMEM residency; interpret-mode "
                                  "emulation would dominate the CPU chunk")
        else:
            want_compact = "pallas"
    if want_compact == "pallas":
        try:
            from . import compact_pallas
            cand = compact_pallas.build_compactor(B, G, K,
                                                  interpret=interpret)
            import jax.numpy as jnp
            jax.block_until_ready(cand(jnp.zeros((B, G), bool)))
            compactor = cand
            stages["compact"] = "pallas"
            reasons.pop("compact", None)
        except Exception as e:  # noqa: BLE001 — fallback is the contract
            reasons["compact"] = (f"pallas compact failed to build/probe: "
                                  f"{type(e).__name__}: {str(e)[:160]}")
    elif "compact" not in reasons:
        reasons["compact"] = "forced to xla"

    # -- insert + enqueue (fused tail) ---------------------------------
    if mesh:
        # Not overridable by force: the mesh insert IS the owner-routed
        # all_to_all dedup — a per-chip fused tail would dedup locally
        # and silently double-count cross-chip duplicates.
        want_tail = "xla"
        reasons["insert"] = ("owner-routed all_to_all dedup is a "
                             "collective; cannot fuse on the mesh")
    else:
        want_tail = force.get("insert", force.get("enqueue"))
        if want_tail is None:
            want_tail = "fused"
    if want_tail == "fused":
        try:
            from . import fused_tail_pallas

            def cand_tail(seen, kh, kl, kvalid, krows, cons_ok,
                          next_count, qnext):
                return fused_tail_pallas.insert_enqueue(
                    seen, kh, kl, kvalid, krows, cons_ok, qnext,
                    next_count, Q, interpret=interpret)

            _probe_tail(K, sw, interpret)
            tail = cand_tail
            stages["insert"] = stages["enqueue"] = "fused"
        except Exception as e:  # noqa: BLE001 — fallback is the contract
            reasons["insert"] = (f"fused tail failed to build/probe: "
                                 f"{type(e).__name__}: {str(e)[:160]}")
    if tail is None and "insert" not in reasons:
        reasons["insert"] = "forced to xla"

    # -- split enqueue when the tail is not fused ----------------------
    enq = enqueue_method
    if tail is None:
        want_enq = force.get("enqueue")
        if want_enq in ("pallas", "xla"):
            enq = "scatter" if want_enq == "xla" else "pallas"
        elif mesh:
            enq = "pallas"   # enqueue_pallas inside shard_map
        if enq == "pallas":
            try:
                _probe_enqueue(K, sw, interpret)
                stages["enqueue"] = "pallas"
            except Exception as e:  # noqa: BLE001 — fallback contract
                reasons["enqueue"] = (f"pallas enqueue failed to "
                                      f"build/probe: {type(e).__name__}: "
                                      f"{str(e)[:160]}")
                enq = enqueue_method
    # Expected launches per stage (obs/perf.py consumes this): each
    # resolved Pallas kernel is exactly one launch; the fused tail is
    # ONE kernel covering insert+enqueue (so enqueue's own count is 0
    # when fused — summing the dict never double-prices the pair); XLA
    # stages are None (their pre-fusion op count is the launch model's
    # to derive from the traced jaxpr).
    launches: Dict[str, Optional[int]] = {s: None for s in STAGES}
    if stages["compact"] == "pallas":
        launches["compact"] = 1
    if stages["insert"] == "fused":
        launches["insert"], launches["enqueue"] = 1, 0
    elif stages["enqueue"] == "pallas":
        launches["enqueue"] = 1
    return V3Plan(stages=stages, reasons=reasons, compactor=compactor,
                  tail=tail, enqueue_method=enq, launches=launches)


def _probe_enqueue(K: int, sw: int, interpret: bool) -> None:
    """Compile-and-run the run-coalesced Pallas enqueue once at the real
    per-copy shapes (K rows of sw bytes, empty mask) so lowering errors
    degrade the stage at plan time.  The probe runs outside shard_map —
    the kernel contains no collectives, so a per-chip lowering that
    compiles solo compiles identically inside the mesh program."""
    import jax
    import jax.numpy as jnp

    from . import enqueue_pallas
    out = enqueue_pallas.enqueue(
        jnp.zeros((2 * K, sw), jnp.uint8), jnp.int32(0),
        jnp.zeros((K, sw), jnp.uint8), jnp.zeros((K,), bool),
        interpret=interpret)
    jax.block_until_ready(out)


def _probe_tail(K: int, sw: int, interpret: bool) -> None:
    """Compile-and-run the fused tail once at the REAL per-program
    shapes — K queries (the real block size and grid), sw-byte rows —
    over small HBM extents (a 256-slot table, a K-row queue with
    trash_base=0), so per-block Mosaic lowering errors surface at plan
    time, not at the first chunk.  Only the total table/queue extents
    (and the trash-base constant) differ from the engine's call."""
    import jax
    import jax.numpy as jnp

    from . import fpset, fused_tail_pallas
    seen = fpset.empty(256)
    out = fused_tail_pallas.insert_enqueue(
        seen,
        jnp.arange(K, dtype=jnp.uint32),
        jnp.arange(K, dtype=jnp.uint32),
        jnp.zeros((K,), bool),          # all-invalid: no probe walking,
        jnp.zeros((K, sw), jnp.uint8),  # the run is trash-copies only
        jnp.zeros((K,), bool),
        jnp.zeros((K, sw), jnp.uint8),
        jnp.int32(0),
        0, interpret=interpret)
    jax.block_until_ready(out)
