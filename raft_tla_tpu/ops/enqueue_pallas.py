"""Pallas enqueue — run-coalesced DMA writer for the compacted rows.

The measured TPU chunk's single biggest post-v2 residue is the enqueue
scatter: 14.5 ms to place K 473-byte rows (`artifacts/
profile_step_tpu.txt`; NORTHSTAR.md §c).  The XLA lowerings move every
row through gather/scatter machinery ("scatter": per-row scatter with
K trash writes for masked lanes; "window": K-row searchsorted gather +
one dynamic_update_slice).  But the *destination is contiguous*: the
enq lanes land at [next_count, next_count + new_n) in queue order — an
append, not a scatter.  This kernel exploits that directly:

- OUTSIDE the kernel (vectorized [K] int ops, microseconds): decompose
  the enq mask into maximal runs of consecutive live lanes, quantized
  into fixed-``S``-row copy segments (DMA slice sizes must be static);
  emit per-copy (src_lane, dst_row) arrays with `inv_positions`.
- INSIDE the kernel: one sequential loop issuing an HBM→HBM DMA of S
  rows per segment — no VMEM staging, no per-row scatter, no trash
  writes.  ~new_n/S + runs copies of S·SW ≈ 4 KB each instead of K
  row-scatters.

Overhang rule (what makes quantization safe): a run's last segment may
copy up to S-1 rows past the run's true end — junk rows from disabled
lanes.  Segments are issued in ascending destination order, and the
NEXT run's first segment starts exactly where the previous run's real
rows ended, overwriting the junk; only the final segment's overhang
survives, and it lies in [next_count + new_n, next_count + new_n + S)
— beyond the live region (never read: all readers slice [:count]) and
in-bounds (the batch watermark keeps next_count <= Q - K and the queue
carries PAD >= K extra rows).

Live rows [0, final next_count) are bit-identical to both XLA lowerings
(the "window" method set the precedent that only live rows are compared
— its trash region also differs from "scatter"'s).  Switchable as
``EngineConfig.enqueue_method = "pallas"``; interpret mode off-TPU, and
staged in the profile matrix so the next tunnel window prices it
against both XLA lowerings (the second half of the NORTHSTAR §d
fused-chunk decision, next to ops/fpset_pallas.py's insert).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .compact import inv_positions
from .pallas_compat import tpu_compiler_params

_I32 = jnp.int32

# Rows per DMA segment.  Power of two; 8 rows x ~500 B ~= 4 KB per copy.
SEG = 8


def build_copy_plan(enq, next_count, K: int):
    """Vectorized segment plan: ``(src, dst, n_copies)`` where copy c
    moves ``SEG`` rows ``krows[src[c] : src[c]+SEG]`` to
    ``queue[dst[c] : dst[c]+SEG]``, for c < n_copies, in ascending
    destination order."""
    idx = jnp.arange(K, dtype=_I32)
    enq = jnp.asarray(enq, bool)
    prev = jnp.concatenate([jnp.zeros((1,), bool), enq[:-1]])
    run_start = jax.lax.cummax(jnp.where(enq & ~prev, idx, -1))
    pos_in_run = idx - run_start          # valid on enq lanes only
    copy_flag = enq & (pos_in_run % SEG == 0)
    excl = jnp.cumsum(enq.astype(_I32)) - enq.astype(_I32)
    lane = inv_positions(copy_flag, K)    # c-th copy's source lane
    src = lane
    dst = (next_count + excl)[lane]
    return src.astype(_I32), dst.astype(_I32), jnp.sum(copy_flag,
                                                       dtype=_I32)


def _kernel(src_ref, dst_ref, n_ref, krows_ref, q_in, q_ref, sem):
    del q_in   # aliased with q_ref — all access through the output ref
    # Copy count read ONCE, before the loop: a while_loop whose
    # condition reads a ref cannot be state-discharged by jax 0.4.x
    # interpret mode (the body's DMA effects discharge fine).
    n = n_ref[0]

    def body(c, carry):
        cp = pltpu.make_async_copy(
            krows_ref.at[pl.ds(src_ref[c], SEG), :],
            q_ref.at[pl.ds(dst_ref[c], SEG), :],
            sem)
        cp.start()
        cp.wait()
        return carry

    jax.lax.fori_loop(0, n, body, _I32(0))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _enqueue_jit(qnext, next_count, krows, enq, interpret: bool):
    K, SW = krows.shape
    src, dst, n_copies = build_copy_plan(enq, next_count, K)
    krows_pad = jnp.concatenate(
        [krows, jnp.zeros((SEG, SW), krows.dtype)])
    (q_out,) = [pl.pallas_call(
        _kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(qnext.shape, qnext.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
        input_output_aliases={4: 0},
        compiler_params=tpu_compiler_params(has_side_effects=True),
        interpret=interpret,
    )(src, dst, n_copies[None], krows_pad, qnext)]
    return q_out


def enqueue(qnext, next_count, krows, enq, interpret: bool | None = None):
    """Write ``krows[enq]`` contiguously at ``qnext[next_count:]`` —
    same live rows as the XLA enqueue lowerings (engine/chunk.py)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _enqueue_jit(qnext, next_count, krows, enq, interpret)
