"""Pallas lane compaction — the v3 fused pipeline's compact stage.

The XLA compact lowerings (ops/compact.py) move the [B, G] enabled mask
through either a B*G-lane scatter or ~log2(B*G) searchsorted gather
rounds — each a separate kernel launch with an HBM round trip for the
mask and the index vectors (the TPU profile's 21 ms compact stage;
NORTHSTAR.md §c).  This kernel keeps the whole mask VMEM-resident and
compacts it with ONE sequential in-register scan: per flat candidate
lane, append its index to the next free survivor slot.  No scatter, no
sort, no intermediate HBM traffic — the formulation the fused-chunk
decision rule (NORTHSTAR §d) wants priced next to both XLA lowerings.

Outputs are bit-identical to ``ops.compact.build_compactor`` (both
methods; they agree by construction): ``(P, total, lane_id, kvalid)``
with the same progress-limited parent prefix, the same ascending
survivor order, and the same hash-spread addresses in dead slots.

The sequential scan is priced for TPU VMEM residency; in interpret mode
(CPU) it emulates at Python-traced-loop speed, so the v3 plan
(ops/pipeline_v3.py) only selects it off-TPU when a test forces it —
the automatic per-stage fallback keeps CPU runs on the XLA compactor.

``reduce_p`` (the mesh engine's pmin hook) is deliberately NOT
supported: a cross-chip collective cannot live inside a Pallas stage,
which is exactly why the mesh plan falls back to XLA for this stage.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .compact import kspread

_I32 = jnp.int32


def _kernel(en_ref, kspread_ref,            # [B,G] i32, [K] i32 (VMEM)
            p_ref, total_ref,               # [1] i32 outs
            lane_ref, kvalid_ref,           # [K] i32 outs
            *, B: int, G: int, K: int):
    en = en_ref[...] != 0                               # [B, G]
    per_parent = jnp.sum(en.astype(_I32), axis=1)       # [B]
    cum = jnp.cumsum(per_parent)
    # Progress limiting (ops/compact.py invariant): longest parent
    # prefix whose fan-out fits K.
    P = jnp.sum((cum <= K).astype(_I32))
    total = jnp.where(P > 0, cum[jnp.clip(P - 1, 0, B - 1)], _I32(0))
    p_ref[0] = P
    total_ref[0] = total
    kvalid_ref[...] = (jnp.arange(K, dtype=_I32) < total).astype(_I32)
    # Dead slots keep the same hash-spread init as both XLA methods.
    lane_ref[...] = kspread_ref[...]
    enf = (en & (jnp.arange(B, dtype=_I32) < P)[:, None]).reshape(-1)

    def body(f, slot):
        take = enf[f]

        @pl.when(take)
        def _():
            lane_ref[pl.ds(slot, 1)] = jnp.full((1,), f, _I32)

        return slot + take.astype(_I32)

    jax.lax.fori_loop(0, B * G, body, _I32(0))


@functools.partial(jax.jit, static_argnames=("K", "interpret"))
def _compact_jit(en, kspread, K: int, interpret: bool):
    B, G = en.shape
    kern = functools.partial(_kernel, B=B, G=G, K=K)
    p, total, lane_id, kvalid = pl.pallas_call(
        kern,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1,), _I32),
            jax.ShapeDtypeStruct((1,), _I32),
            jax.ShapeDtypeStruct((K,), _I32),
            jax.ShapeDtypeStruct((K,), _I32),
        ],
        interpret=interpret,
    )(en.astype(_I32), kspread)
    return p[0], total[0], lane_id, kvalid.astype(bool)


def build_compactor(B: int, G: int, K: int, interpret: bool | None = None):
    """Drop-in replacement for ``ops.compact.build_compactor`` (same
    ``compact(en) -> (P, total, lane_id, kvalid)`` contract, identical
    outputs).  No ``reduce_p`` hook — see module docstring."""
    # Shared with ops/compact.py: dead-slot bit-identity across every
    # lowering hangs on all of them using the one kspread definition.
    kspr = kspread(B, G, K)

    def compact(en):
        ipt = interpret
        if ipt is None:
            ipt = jax.devices()[0].platform != "tpu"
        return _compact_jit(en, kspr, K, ipt)

    return compact
