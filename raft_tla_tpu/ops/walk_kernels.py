"""Swarm-walk primitives — counter PRNG + per-walk fingerprint rings.

The swarm engine (engine/swarm.py) runs W randomized walks in lockstep
and must stay **partition-invariant**: slicing the W walks into device
batches of 64 or 256 lanes may never change any walk's trajectory.
``jax.random`` key-split chains cannot give that property — a split
sequence threads state through the batch loop, so the stream a walk
sees depends on which slice it landed in.  These kernels instead derive
every per-walk decision from a *counter hash*: pure uint32 avalanche
mixing (the murmur3 fmix32 finalizer already underpinning the state
fingerprints, ops/fingerprint.py) over the tuple ``(seed, walk, step,
stream)``.  Two consequences the engine's contract rests on:

- **replayability** — the i-th decision of walk w under seed s is a
  pure function of (s, w, i); re-running any subset of walks replays
  them bit-identically;
- **partition invariance** — no cross-walk state exists, so the
  visited-fingerprint multiset of a (seed, walks, depth) run is
  independent of the device batch size (tests/test_swarm.py pins it).

The per-walk dedup structure is a fixed-size **fingerprint ring**: the
last R accepted (hi, lo) pairs per walk, probed before every step.
This replaces the exhaustive engines' global sorted FPSet — no host
round-trip, no growth/rehash path, O(R) VPU compares per step — at the
cost of only suppressing short revisit cycles, which is the right
trade for a walker: TLC's ``-simulate`` dedups nothing at all.  The
ring is initialized to the FPSet's reserved all-ones sentinel pair
(ops/fingerprint.py remaps real fingerprints off it), so empty slots
can never alias a real state.

Plain jnp ops throughout (no Pallas): the swarm's profitable platform
today is the CPU CI host and the vmap'd expand kernels it calls into
are already the BLEST-grouped family kernels; see
/opt/skills/guides/ for the accelerator-lowering ladder these would
climb if a fused TPU tail ever pays for itself here.
"""

from __future__ import annotations

import jax.numpy as jnp

from .fingerprint import SENTINEL, fmix32

_U32 = jnp.uint32
_I32 = jnp.int32

#: Decision streams: one odd salt per independent per-step draw, so the
#: successor choice and the restart-root choice of the same (walk, step)
#: never correlate.
CHOICE_STREAM = 0x9E3779B1      # which enabled action instance to take
ROOT_STREAM = 0x85EBCA77        # which root to restart onto
INIT_STREAM = 0x27D4EB2F        # the walk's very first root
FAMILY_STREAM = 0x165667B1      # the trace's family-subset mask


def walk_bits(seed, walk_id, step, stream):
    """Counter-hash random bits for one decision: uint32, a pure
    function of ``(seed, walk_id, step, stream)``.  ``walk_id`` and
    ``step`` may be arrays (one draw per lane — the family-mask stream
    keys ``step`` on each lane's trace epoch); ``seed``/``stream`` are
    scalars.  Three chained fmix32 avalanches — each input fully mixed
    before the next is folded in — give the independence the masked
    draw needs (a modulo over correlated low bits would bias toward
    low action indices)."""
    h = fmix32(jnp.asarray(seed).astype(_U32)
               * _U32(0x85EBCA6B) ^ _U32(stream))
    h = fmix32(h ^ (jnp.asarray(walk_id).astype(_U32) * _U32(0xC2B2AE35)))
    return fmix32(h ^ (jnp.asarray(step).astype(_U32) * _U32(0x9E3779B9)))


def masked_choice(bits, enabled):
    """Uniform index draw over the True lanes of ``enabled`` [..., G]
    from counter ``bits`` [...]: rank = bits mod popcount, then the
    rank-th enabled lane via cumulative count.  Rows with no enabled
    lane return lane 0 — callers must gate on ``any(enabled)`` (the
    same dead-walk contract as the simulator's categorical draw).
    The modulo bias at G ≪ 2^32 is ~G/2^32 — irrelevant next to the
    determinism it buys."""
    cnt = jnp.cumsum(enabled.astype(_I32), axis=-1)
    total = cnt[..., -1]
    rank = (bits % jnp.maximum(total, 1).astype(_U32)).astype(_I32)
    return jnp.argmax(cnt > rank[..., None], axis=-1).astype(_I32)


def family_subset(bits, fam):
    """Per-lane action-family keep-mask, expanded to instance lanes:
    instance ``g`` is *preferred* iff bit ``fam[g] mod 32`` of the
    lane's mask word ``bits`` is set, so each of the model's action
    families (models/actions.py family_groups order) is kept with
    probability 1/2 per draw.  This is Holzmann-style swarm
    diversification: a uniform draw over *instances* drowns a hunt in
    whichever family owns the most lanes (raft's three 32-slot message
    families hold 96 of 132 instances), whereas a per-trace family
    subset gives every trace a different sub-model to explore.  ``fam``
    is the static [G] instance->family index; families past 32 share
    mask bits (still diverse, never unsound — the mask only biases)."""
    shift = (fam % 32).astype(_U32)
    return ((bits[..., None] >> shift) & _U32(1)) != 0


def preferred_choice(bits, enabled, preferred):
    """``masked_choice`` over ``enabled & preferred`` when that set is
    non-empty, else over all of ``enabled``: the family bias can never
    stall a walk that still has successors, so reachability (and the
    dead-walk restart contract) is exactly the unbiased kernel's."""
    pref = enabled & preferred
    use = jnp.where(jnp.any(pref, axis=-1, keepdims=True), pref, enabled)
    return masked_choice(bits, use)


def ring_init(lanes: int, capacity: int):
    """Fresh per-walk rings: ``(ring_hi, ring_lo, pos)`` with every slot
    on the reserved sentinel pair (matches no real fingerprint)."""
    return (jnp.full((lanes, capacity), SENTINEL, _U32),
            jnp.full((lanes, capacity), SENTINEL, _U32),
            jnp.zeros((lanes,), _I32))


def ring_probe(ring_hi, ring_lo, hi, lo):
    """Per-lane membership: is (hi, lo) among the lane's last R accepted
    fingerprints?  Dense compare over the ring axis — R is small and
    static, so this stays one fused VPU reduction per step."""
    return jnp.any((ring_hi == hi[:, None]) & (ring_lo == lo[:, None]),
                   axis=1)


def ring_push(ring_hi, ring_lo, pos, hi, lo, do):
    """Append (hi, lo) at each lane's cursor where ``do``; cursors only
    advance on a real push, so a stalled walk never evicts history."""
    lanes = jnp.arange(ring_hi.shape[0])
    slot = pos % ring_hi.shape[1]
    cur_hi, cur_lo = ring_hi[lanes, slot], ring_lo[lanes, slot]
    ring_hi = ring_hi.at[lanes, slot].set(jnp.where(do, hi, cur_hi))
    ring_lo = ring_lo.at[lanes, slot].set(jnp.where(do, lo, cur_lo))
    return ring_hi, ring_lo, pos + do.astype(_I32)


def ring_reset(ring_hi, ring_lo, pos, mask):
    """Clear the rings of lanes in ``mask`` back to sentinel (a restart
    begins a fresh trace: dedup is per-trace, so a new walk may
    legitimately revisit states an earlier trace saw)."""
    ring_hi = jnp.where(mask[:, None], SENTINEL, ring_hi)
    ring_lo = jnp.where(mask[:, None], SENTINEL, ring_lo)
    return ring_hi, ring_lo, jnp.where(mask, 0, pos)


# -- observational Bloom filters (the hunt observatory) -----------------
# The saturation estimator (obs/hunt.py) needs to classify every
# accepted visit as the first / second / later observation of its
# fingerprint WITHOUT reintroducing the global seen-set the swarm
# exists to avoid.  A pair of fixed-size two-probe Bloom filters
# (seen>=1 / seen>=2) gives that: O(1) gathers per step, scatter-max
# updates (idempotent, so duplicate probes within one dispatch are
# harmless), and — critically — the filters feed NOTHING back into the
# walk decisions, so the hunt's verdict and fingerprint multiset stay
# bit-identical with the observatory off (tests/test_swarm.py pins it).
# Cells are uint8 (jnp scatter-max has no bitwise dtype), so a filter
# is cells bytes of device memory; the default 2^20 keeps the two-probe
# collision probability ~load^2 auditable in the hunt report.

def bloom_init(cells: int):
    """One empty filter: ``cells`` uint8 slots, ``cells`` a power of
    two (the probes mask with ``cells - 1``)."""
    if cells & (cells - 1) or cells < 2:
        raise ValueError(f"bloom cells must be a power of two, "
                         f"got {cells}")
    return jnp.zeros((cells,), jnp.uint8)


def bloom_probes(bloom, hi, lo):
    """The two probe indices for fingerprint (hi, lo): the halves are
    already independent avalanche mixes (ops/fingerprint.py), so their
    low bits are the two hash functions for free."""
    m = _U32(bloom.shape[0] - 1)
    return (hi & m).astype(_I32), (lo & m).astype(_I32)


def bloom_probe(bloom, hi, lo):
    """Per-lane membership: True iff BOTH probe cells are set (the
    standard k=2 conjunction; false positives ~load^2, never false
    negatives)."""
    i1, i2 = bloom_probes(bloom, hi, lo)
    return (bloom[i1] > 0) & (bloom[i2] > 0)


def bloom_push(bloom, hi, lo, do):
    """Insert the lanes where ``do`` (scatter-max: racing duplicate
    indices within one dispatch commute, so partition slicing cannot
    change the resulting filter)."""
    i1, i2 = bloom_probes(bloom, hi, lo)
    m = do.astype(jnp.uint8)
    return bloom.at[i1].max(m).at[i2].max(m)
