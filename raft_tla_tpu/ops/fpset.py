"""The seen-state set — TLC's FPSet rebuilt as an HBM open-addressing table.

TLC keeps seen-state fingerprints in an in-memory/disk hash set probed one
state at a time [TLC semantics — external].  The first TPU port of this kept
a lex-sorted array merged with a full ``lax.sort`` per step — but an 8M-key
bitonic sort per batch is hundreds of full-array passes and dominated the
whole engine.  This version is the SURVEY §2.4 R3 design proper: a
fixed-capacity **open-addressing hash table resident in HBM** (double
hashing rather than cuckoo eviction — eviction chains serialize badly under
vmap, while bounded double-hash probing is a handful of static gather
rounds), with a *batched parallel insert*:

- each query key probes ``slot_k = (h1 + k*h2) mod C`` for a static number
  of rounds, entirely with gathers/scatters — no data-dependent shapes;
- per round, keys matching an occupied slot resolve as already-present;
  keys over an empty slot stake a **claim** (scatter-max of the query index)
  and exactly the claim winner writes, so concurrent inserts of different
  keys never interleave and the table is deterministic;
- losers re-read the slot after the write (catching same-key duplicates in
  the same batch — the winner's key is now visible) and only then advance
  to their next probe slot.

Insert therefore also performs the *in-batch dedup* that previously needed
a candidate-wide sort: exactly one query per distinct new key reports
``is_new``.  Cost per batch is O(rounds × batch), independent of table
capacity; the old design's O(C log^2 C) sort is gone.

``size`` counts stored keys; a query still unresolved after all probe
rounds sets the ``fail`` flag (table effectively full for that
neighborhood) — the engine raises rather than ever silently dropping a
state.  Keep load below ~0.7 · capacity; the engines' capacity checks
enforce a margin.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

from .fingerprint import SENTINEL, fmix32

_U32 = jnp.uint32
_I32 = jnp.int32

# Static probe rounds.  At load factor 0.7 the expected double-hash probe
# count is ~1/(1-0.7) ≈ 3.3; 32 rounds puts the miss probability per query
# around 0.7^32 ≈ 1e-5, and a miss is a *reported error*, never a lost state.
PROBE_ROUNDS = 32

# Claim-table cap (slots).  32 MB of int32 at 2^23; see insert_unique.
CLAIM_CAP = 1 << 23


class FPSet(NamedTuple):
    hi: jnp.ndarray    # [C] uint32 key lane; SENTINEL pair = empty slot
    lo: jnp.ndarray    # [C] uint32
    size: jnp.ndarray  # [] int32 — number of stored keys


def _capacity(requested: int) -> int:
    """Table slots: next power of two >= requested (masked indexing)."""
    c = 1
    while c < requested:
        c <<= 1
    return c


def empty(capacity: int) -> FPSet:
    c = _capacity(capacity)
    return FPSet(hi=jnp.full((c,), SENTINEL, _U32),
                 lo=jnp.full((c,), SENTINEL, _U32),
                 size=jnp.int32(0))


def _probe_base(qhi, qlo, c):
    """(h1, h2) for double hashing; h2 odd => full cycle over power-of-2 C."""
    h1 = fmix32(qhi ^ fmix32(qlo ^ _U32(0x9E3779B9)))
    h2 = fmix32(qlo ^ fmix32(qhi ^ _U32(0x85EBCA6B))) | _U32(1)
    return h1 & _U32(c - 1), h2


# TPU gather/scatter performance is shape-sensitive in three ways this
# module must design around (measured on v5e through the serving tunnel):
# 1. a gather where a large fraction of lanes reads the SAME address (e.g.
#    every invalid query probing the sentinel key's slot) serializes on the
#    hot address — 0.05ms becomes 300ms;
# 2. non-power-of-two query batches hit a slow lowering (270336 lanes is
#    4000x slower than 262144 for the identical gather);
# 3. the same hot-address serialization applies to SCATTERS — including
#    lanes "masked off" by routing them to one shared out-of-range index
#    with mode="drop".  A scatter with half a million lanes on one
#    (dropped!) index costs ~400ms; four of them made one insert cost
#    1.7 s/batch in round 2.  Masked scatters must therefore be
#    *value-neutral*, not address-neutral: every lane writes to its own
#    (hash-random) address, and inactive lanes contribute the operation's
#    identity element (-1 for the claim's max, SENTINEL for the key
#    table's min) so the write is a no-op wherever it lands.
# Hence: every probing entry point pads its query batch to a power of two,
# inactive lanes GATHER from a per-lane spread address instead of a shared
# one, and every scatter is an identity-element combiner (max/min), never
# a .set behind a shared drop index.  All transformations are semantically
# invisible.

def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _pad_pow2(arrs, fill):
    k = arrs[0].shape[0]
    kp = _pow2(k)
    if kp == k:
        return arrs, k
    return tuple(jnp.concatenate(
        [a, jnp.full((kp - k,), f, a.dtype)]) for a, f in zip(arrs, fill)), k


def dedup_batch(khi, klo, valid):
    """In-batch first-occurrence marking via one (cheap) batch-sized sort.
    Returns ((sorted_hi, sorted_lo), order, first_occ).  Duplicate keys are
    *common* in a BFS batch (many parents generate the same successor), and
    a TPU scatter serializes on colliding indices — so the table insert must
    only ever see unique keys; this pre-pass guarantees that."""
    k = khi.shape[0]
    khi = jnp.where(valid, khi, SENTINEL)
    klo = jnp.where(valid, klo, SENTINEL)
    import jax
    sh, sl, order = jax.lax.sort((khi, klo, jnp.arange(k, dtype=_I32)),
                                 num_keys=2)
    is_sent = (sh == SENTINEL) & (sl == SENTINEL)
    prev_ne = jnp.concatenate([
        jnp.array([True]),
        (sh[1:] != sh[:-1]) | (sl[1:] != sl[:-1])])
    return (sh, sl), order, prev_ne & ~is_sent


def insert_unique(s: FPSet, qhi, qlo, valid) -> Tuple["FPSet", jnp.ndarray,
                                                      jnp.ndarray]:
    """Insert a batch of keys.  Returns ``(table', is_new, fail)``:
    ``is_new[k]`` marks exactly one query per distinct key not previously in
    the table; ``fail`` is True if any valid query exhausted its probes.

    PRECONDITION: valid keys are pairwise distinct (use ``dedup_batch``
    first).  The claim round still resolves the rare *hash* collision of
    distinct keys on one slot deterministically, but heavy same-key batches
    would serialize the claim scatter — that case is the pre-pass's job."""
    c = s.hi.shape[0]
    (qhi, qlo, valid), k = _pad_pow2(
        (qhi, qlo, jnp.asarray(valid, bool)),
        (SENTINEL, SENTINEL, False))
    kp = qhi.shape[0]
    hi, lo = s.hi, s.lo
    h1, h2 = _probe_base(qhi, qlo, c)
    arange = jnp.arange(kp, dtype=_I32)
    spread = (arange & (c - 1)).astype(_I32)   # cold per-lane addresses
    pending = valid
    is_new = jnp.zeros((kp,), bool)
    # The claim table may be smaller than the key table (capped: a 2^28
    # table would need a 1 GB int32 claim).  Two lanes attempting
    # *different* slots that alias in the claim table just means one loses
    # and retries its chain next round — correctness is unaffected, and at
    # 2^23 entries the alias probability per round is ~kp/2^23.
    cm = min(c, CLAIM_CAP) - 1
    # Claim values are round-tagged (r*kp + lane) so a round-r attempt
    # always supersedes any stale entry from an earlier round under the
    # max combiner — no reset scatter, and a claim-cap alias can never
    # eclipse a later round's attempt.  Tags must fit int32:
    assert (PROBE_ROUNDS + 1) * kp < 2**31, "claim tag overflow"
    claim = jnp.full((cm + 1,), -1, _I32)
    # Per-lane probe position.  A lane advances its chain ONLY after
    # observing its current slot occupied by a different key; on a claim
    # loss it retries the same slot next round (the winner's write is
    # visible by then).  This preserves the chain invariant every probing
    # reader depends on — the first empty slot of a key's chain terminates
    # the search — even when a claim-cap alias makes a lane lose a claim
    # on a slot that then stays empty.
    #
    # The rounds run as a while_loop with an any(pending) early exit: at
    # the <=0.55 load the engines maintain, nearly every lane resolves in
    # 2-3 rounds, so the loop runs ~3 iterations instead of a static 32 —
    # the full 32 remain the correctness bound the fail flag reports on.
    import jax

    def round_body(carry):
        hi, lo, claim, step, pending, is_new, r = carry
        probe = ((h1 + step * h2) & _U32(c - 1)).astype(_I32)
        idx = jnp.where(pending, probe, spread)
        cur_hi, cur_lo = hi[idx], lo[idx]
        match = pending & (cur_hi == qhi) & (cur_lo == qlo)
        pending = pending & ~match
        occupied = pending & ~((cur_hi == SENTINEL) & (cur_lo == SENTINEL))
        attempt = pending & ~occupied
        # Every scatter below writes to idx (hash-random, no hot address);
        # inactive lanes write the combiner's identity element instead of
        # being routed to a shared drop index (design note 3 above).
        tag = r * _I32(kp) + arange
        claim = claim.at[idx & cm].max(jnp.where(attempt, tag, -1))
        win = attempt & (claim[idx & cm] == tag)
        hi = hi.at[idx].min(jnp.where(win, qhi, SENTINEL))
        lo = lo.at[idx].min(jnp.where(win, qlo, SENTINEL))
        is_new = is_new | win
        pending = pending & ~win
        step = step + occupied.astype(_U32)
        return hi, lo, claim, step, pending, is_new, r + 1

    def round_cond(carry):
        pending, r = carry[4], carry[6]
        return jnp.any(pending) & (r < PROBE_ROUNDS)

    hi, lo, _claim, _step, pending, is_new, _r = jax.lax.while_loop(
        round_cond, round_body,
        (hi, lo, claim, jnp.zeros((kp,), _U32), pending, is_new,
         _I32(0)))
    return (FPSet(hi=hi, lo=lo,
                  size=s.size + jnp.sum(is_new, dtype=_I32)),
            is_new[:k], jnp.any(pending))


def insert(s: FPSet, qhi, qlo, valid) -> Tuple["FPSet", jnp.ndarray,
                                               jnp.ndarray]:
    """Full-batch insert: dedup pre-pass + unique insert.  Returns
    ``(table', is_new, fail)`` with ``is_new`` in the *caller's* (unsorted)
    index domain — exactly one index per distinct new key is marked.
    Pads to a power of two up front so the sort and every probe run on
    fast shapes."""
    (qhi, qlo, valid), k = _pad_pow2(
        (qhi, qlo, jnp.asarray(valid, bool)),
        (SENTINEL, SENTINEL, False))
    kp = qhi.shape[0]
    (sh, sl), order, first = dedup_batch(qhi, qlo, valid)
    s, new_sorted, fail = insert_unique(s, sh, sl, first)
    is_new = jnp.zeros((kp,), bool).at[order].set(new_sorted)
    return s, is_new[:k], fail


def contains(s: FPSet, qhi, qlo):
    """Membership for a batch of keys.  [K] bool.  Sentinel-keyed (invalid)
    lanes report False."""
    c = s.hi.shape[0]
    (qhi, qlo), k = _pad_pow2((qhi, qlo), (SENTINEL, SENTINEL))
    kp = qhi.shape[0]
    h1, h2 = _probe_base(qhi, qlo, c)
    live = ~((qhi == SENTINEL) & (qlo == SENTINEL))
    spread = (jnp.arange(kp, dtype=_I32) & (c - 1)).astype(_I32)
    import jax

    def round_body(carry):
        found, open_, r = carry
        probe = ((h1 + r.astype(_U32) * h2) & _U32(c - 1)).astype(_I32)
        idx = jnp.where(open_, probe, spread)
        cur_hi, cur_lo = s.hi[idx], s.lo[idx]
        found = found | (open_ & (cur_hi == qhi) & (cur_lo == qlo))
        open_ = open_ & ~((cur_hi == SENTINEL) & (cur_lo == SENTINEL)) \
            & ~found
        return found, open_, r + 1

    found, _open, _r = jax.lax.while_loop(
        lambda c: jnp.any(c[1]) & (c[2] < PROBE_ROUNDS), round_body,
        (jnp.zeros(qhi.shape, bool), live, _I32(0)))
    return found[:k]


def to_host_keys(s: FPSet) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize the stored keys host-side, lex-sorted (hi, lo) for a
    deterministic checkpoint layout."""
    hi = np.asarray(s.hi)
    lo = np.asarray(s.lo)
    real = ~((hi == SENTINEL) & (lo == SENTINEL))
    hi, lo = hi[real], lo[real]
    order = np.lexsort((lo, hi))
    return hi[order], lo[order]


def from_host_keys(keys_hi: np.ndarray, keys_lo: np.ndarray,
                   capacity: int, chunk: int = 1 << 15) -> FPSet:
    """Rebuild a table from checkpointed/rehashed keys.

    Every caller feeds keys that are ALREADY pairwise distinct — they
    come out of a hash table (growth rehash) or a checkpointed key dump
    (`to_host_keys` output) — so the per-chunk dedup sort that dominates
    `insert` is pure overhead here: `insert_unique` is used directly.
    That halves the growth-rehash stall the engines record in
    ``EngineResult.growth_stalls`` (VERDICT r4 weak #6: ~11.9 s per
    2M→4M rehash on CPU, most of it the 64 chunk sorts)."""
    import jax

    s = empty(capacity)
    ins = jax.jit(insert_unique, donate_argnums=(0,))
    n = len(keys_hi)
    for base in range(0, n, chunk):
        h = np.asarray(keys_hi[base:base + chunk], np.uint32)
        l = np.asarray(keys_lo[base:base + chunk], np.uint32)
        pad = chunk - len(h)
        valid = np.arange(chunk) < len(h)
        s, _new, fail = ins(
            s, jnp.asarray(np.pad(h, (0, pad))),
            jnp.asarray(np.pad(l, (0, pad))), jnp.asarray(valid))
        if bool(fail):
            raise RuntimeError(
                f"FPSet rebuild overflow: {n} keys into capacity {capacity}")
    return s
