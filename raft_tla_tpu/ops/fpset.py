"""The seen-state set — TLC's FPSet rebuilt as a sorted HBM array.

TLC keeps seen-state fingerprints in an in-memory/disk hash set probed one
state at a time [TLC semantics — external].  A TPU wants the opposite shape:
**batched, sort-based, branch-free**.  This FPSet is a fixed-capacity pair of
uint32 arrays (the two fingerprint lanes) kept lexicographically sorted, with
all free space holding the all-ones sentinel (which sorts to the tail):

- ``contains``: vectorized lower-bound binary search — ``log2(C)`` gather
  rounds over the whole query batch at once (XLA compiles this to a tight
  fori loop; no data-dependent shapes);
- ``merge``: concatenate + two-key ``lax.sort`` + slice.  Sorting is one of
  the things XLA/TPU does extremely well, and a level-synchronous BFS only
  merges once per level, so the amortized cost per state is tiny;
- in-batch dedup of candidate fingerprints rides the same sort (payload =
  original index, ``num_keys=2``).

Capacity is static; the engine host-checks ``size`` and raises before
overflow — a checker must never silently forget states.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .fingerprint import SENTINEL

_U32 = jnp.uint32


class FPSet(NamedTuple):
    hi: jnp.ndarray    # [C] uint32, lex-sorted (hi, lo), sentinel-padded
    lo: jnp.ndarray    # [C] uint32
    size: jnp.ndarray  # [] int32 — number of real keys


def empty(capacity: int) -> FPSet:
    return FPSet(hi=jnp.full((capacity,), SENTINEL, _U32),
                 lo=jnp.full((capacity,), SENTINEL, _U32),
                 size=jnp.int32(0))


def contains(s: FPSet, qhi, qlo):
    """Membership for a batch of fingerprint pairs.  [K] bool."""
    c = s.hi.shape[0]
    lo_b = jnp.zeros(qhi.shape, jnp.int32)
    hi_b = jnp.full(qhi.shape, c, jnp.int32)
    steps = max(1, int(np.ceil(np.log2(c + 1))) + 1)
    for _ in range(steps):                       # static unroll: log2(C)
        mid = (lo_b + hi_b) >> 1
        mh, ml = s.hi[mid], s.lo[mid]
        less = (mh < qhi) | ((mh == qhi) & (ml < qlo))
        lo_b = jnp.where(less, mid + 1, lo_b)
        hi_b = jnp.where(less, hi_b, mid)
    at = jnp.clip(lo_b, 0, c - 1)
    return (s.hi[at] == qhi) & (s.lo[at] == qlo) & (lo_b < c)


def dedup_batch(khi, klo, valid):
    """In-batch first-occurrence marking.  Returns ((sorted_hi, sorted_lo),
    order, first_occ): the lex-sorted keys, the sort permutation (original
    indices), and a mask marking the first occurrence of each distinct
    non-sentinel key in sorted order."""
    k = khi.shape[0]
    khi = jnp.where(valid, khi, SENTINEL)
    klo = jnp.where(valid, klo, SENTINEL)
    sh, sl, order = jax.lax.sort((khi, klo, jnp.arange(k, dtype=jnp.int32)),
                                 num_keys=2)
    is_sent = (sh == SENTINEL) & (sl == SENTINEL)
    prev_ne = jnp.concatenate([
        jnp.array([True]),
        (sh[1:] != sh[:-1]) | (sl[1:] != sl[:-1])])
    return (sh, sl), order, prev_ne & ~is_sent


def merge(s: FPSet, new_hi, new_lo, new_valid) -> FPSet:
    """Insert a batch of (assumed not-already-present) keys; keeps the array
    sorted.  Invalid lanes are sentinels and fall off the concat+sort+slice
    iff size + #valid <= capacity (engine checks ``size`` after)."""
    c = s.hi.shape[0]
    nh = jnp.where(new_valid, new_hi, SENTINEL)
    nl = jnp.where(new_valid, new_lo, SENTINEL)
    ch = jnp.concatenate([s.hi, nh])
    cl = jnp.concatenate([s.lo, nl])
    sh, sl = jax.lax.sort((ch, cl), num_keys=2)
    return FPSet(hi=sh[:c], lo=sl[:c],
                 size=s.size + jnp.sum(new_valid, dtype=jnp.int32))


def to_host_keys(s: FPSet) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize the real keys host-side (checkpointing)."""
    n = int(s.size)
    return np.asarray(s.hi[:n]), np.asarray(s.lo[:n])
