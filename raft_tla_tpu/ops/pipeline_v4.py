"""v4 whole-chunk stage plan — glue between EngineConfig.pipeline="v4"
and the two chunk megakernels.

v4 is the v2 delta pipeline with BOTH halves of the chunk body fused:

    masks        \
    compact       }  ops/chunk_front_pallas.py   [one Pallas launch]
    fingerprint  /
    insert       \\   ops/fused_tail_pallas.py    [one Pallas launch]
    enqueue      /

The front trio is ONE stage group: the megakernel exists precisely so
the [B, G] mask and the parent-struct window never leave VMEM between
masks, compaction, and the delta fingerprints, so its members degrade
together — forcing (or failing to build) ANY of masks/compact/
fingerprint splits the group back to the v3-style arrangement, where
masks+fingerprint are the XLA jaxpr programs and compact resolves per
the v3 platform policy.  The tail pair is the same fused group v3
ships.  As everywhere else in ops/, fallback is the contract: every
kernel is build-and-probe verified at plan time at the real per-program
shapes, a stage that will not lower degrades with a recorded reason
(``V4Plan.stages`` / ``reasons`` -> ``EngineResult.fused_stages``), and
a v4 run never fails because a kernel refused to compile.

Per-stage forcing comes from ``EngineConfig.v4_force_stages`` and the
``RAFT_V4_FORCE`` environment variable ("masks=xla,insert=xla" — env
entries win over config), which is how the fallback-lattice tests pin
each stage to its XLA lowering without plumbing test-only config.

Platform policy:

- TPU single chip: front=fused, tail=fused — two launches per batch.
- CPU single chip: both kernels run in interpret mode.  Unlike v3's
  compact-only scan (pure emulation overhead on CPU), the front
  megakernel's body IS the traced XLA front, so interpreting it costs
  nothing extra while collapsing the chunk jaxpr to ~two launch sites —
  which is exactly what the CI launch pin measures.
- mesh: no front (compact's P is pmin-replicated across chips, and
  owner-routed dedup needs the all_to_all — both collectives), so the
  mesh plan matches v3's: compact/insert=xla, enqueue=pallas.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, NamedTuple, Optional

from .pipeline_v3 import _probe_enqueue, _probe_tail

STAGES = ("masks", "compact", "fingerprint", "insert", "enqueue")
FRONT_STAGES = ("masks", "compact", "fingerprint")

ENV_FORCE = "RAFT_V4_FORCE"


class V4Plan(NamedTuple):
    stages: Dict[str, str]       # stage -> "fused" | "pallas" | "xla"
    reasons: Dict[str, str]      # stage -> why it is not fused
    front: Optional[Callable]    # fused masks+compact+fingerprint, or None
    compactor: Optional[Callable]   # split-front Pallas compactor
    tail: Optional[Callable]     # fused insert+enqueue, or None = split
    enqueue_method: str          # chunk-body enqueue when tail is None
    # Expected kernel launches per stage per batch — same contract as
    # V3Plan.launches: a fused group is ONE kernel billed to its first
    # member (compact/fingerprint are 0 when the front is fused, like
    # enqueue under the fused tail), an XLA stage is None (the launch
    # model derives its op count from the traced jaxpr).  Default None,
    # not {}: NamedTuple defaults are class-level, a dict would be
    # shared across instances.
    launches: Optional[Dict[str, Optional[int]]] = None


def describe(plan: V4Plan) -> str:
    """One-line stage map for logs/results: "masks=fused compact=fused ..."."""
    return " ".join(f"{s}={plan.stages[s]}" for s in STAGES)


def _merged_force(force: Optional[Dict[str, str]]) -> Dict[str, str]:
    """Config force merged with RAFT_V4_FORCE ("a=xla,b=xla"; env wins).
    Malformed entries raise — a typo'd override must not silently run
    the fused kernel the test meant to disable."""
    out = dict(force or {})
    raw = os.environ.get(ENV_FORCE, "").strip()
    if raw:
        for item in raw.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(
                    f"{ENV_FORCE}: expected stage=impl, got {item!r}")
            stage, impl = item.split("=", 1)
            out[stage.strip()] = impl.strip()
    return out


def resolve_plan(B: int, G: int, K: int, *, Q: int, sw: int = 8,
                 mesh: bool = False, enqueue_method: str = "scatter",
                 force: Optional[Dict[str, str]] = None,
                 interpret: Optional[bool] = None,
                 front_ctx: Optional[Dict[str, Any]] = None) -> V4Plan:
    """Resolve the v4 per-stage lowering for one engine build.

    ``front_ctx`` carries what the front megakernel closes over beyond
    shapes: {"dims", "v2", "constraint", "inv_fns", "por_mask",
    "por_priority"} from the engine build (None degrades the front with
    a recorded reason — the profiler's shape-only probes pass one).
    ``Q``/``sw`` as in pipeline_v3.resolve_plan; ``force`` merges with
    the RAFT_V4_FORCE env var (env wins per stage).  Forcing any front
    member away from "fused" degrades the WHOLE front group — the
    megakernel has no partial configuration — after which "compact"
    may still independently resolve to the v3 Pallas scan."""
    import jax
    force = _merged_force(force)
    _VALID = {"masks": ("fused", "xla"),
              "compact": ("fused", "pallas", "xla"),
              "fingerprint": ("fused", "xla"),
              "insert": ("fused", "xla"),
              "enqueue": ("fused", "pallas", "xla")}
    for stage, impl in force.items():
        if stage not in _VALID or impl not in _VALID[stage]:
            raise ValueError(
                f"v4_force_stages: unknown {stage!r}={impl!r}; valid: "
                + ", ".join(f"{s}∈{v}" for s, v in _VALID.items()))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    stages = {s: "xla" for s in STAGES}
    reasons: Dict[str, str] = {}
    front = None
    compactor = None
    tail = None

    # -- front group: masks + compact + fingerprint --------------------
    degraded = None
    if mesh:
        degraded = ("the mesh chunk's compact P is pmin-replicated and "
                    "its dedup is an all_to_all; collectives cannot "
                    "live inside the front kernel")
    else:
        for s in FRONT_STAGES:
            impl = force.get(s)
            if impl is not None and impl != "fused":
                degraded = f"front group degraded: {s} forced to {impl}"
                break
    if degraded is None and front_ctx is None:
        degraded = "no front build context (shape-only plan resolve)"
    if degraded is None:
        try:
            from . import chunk_front_pallas
            import jax.numpy as jnp
            cand = chunk_front_pallas.build_front(
                dims=front_ctx["dims"], v2=front_ctx["v2"],
                constraint=front_ctx.get("constraint"),
                inv_fns=front_ctx.get("inv_fns"),
                B=B, G=G, K=K,
                por_mask=front_ctx.get("por_mask"),
                por_priority=front_ctx.get("por_priority"),
                interpret=interpret)
            jax.block_until_ready(cand(
                jnp.zeros((B, sw), jnp.uint8), jnp.zeros((B,), bool)))
            front = cand
            for s in FRONT_STAGES:
                stages[s] = "fused"
        except Exception as e:  # noqa: BLE001 — fallback is the contract
            degraded = (f"front kernel failed to build/probe: "
                        f"{type(e).__name__}: {str(e)[:160]}")
    if front is None:
        for s in FRONT_STAGES:
            reasons[s] = degraded

    # -- split compact when the front is not fused ---------------------
    if front is None:
        want_compact = force.get("compact")
        if mesh:
            want_compact = "xla"   # pmin collective; not forceable
        if want_compact in (None, "fused"):
            want_compact = "xla" if interpret else "pallas"
            if interpret:
                reasons["compact"] = (
                    reasons.get("compact", "") +
                    "; sequential B*G scan is priced for TPU VMEM "
                    "residency, xla on cpu").lstrip("; ")
        if want_compact == "pallas":
            try:
                from . import compact_pallas
                import jax.numpy as jnp
                cand = compact_pallas.build_compactor(B, G, K,
                                                      interpret=interpret)
                jax.block_until_ready(cand(jnp.zeros((B, G), bool)))
                compactor = cand
                stages["compact"] = "pallas"
            except Exception as e:  # noqa: BLE001 — fallback contract
                reasons["compact"] = (
                    f"pallas compact failed to build/probe: "
                    f"{type(e).__name__}: {str(e)[:160]}")

    # -- insert + enqueue (fused tail) — v3 semantics ------------------
    if mesh:
        want_tail = "xla"
        reasons["insert"] = ("owner-routed all_to_all dedup is a "
                             "collective; cannot fuse on the mesh")
    else:
        want_tail = force.get("insert", force.get("enqueue"))
        if want_tail is None:
            want_tail = "fused"
    if want_tail == "fused":
        try:
            from . import fused_tail_pallas

            def cand_tail(seen, kh, kl, kvalid, krows, cons_ok,
                          next_count, qnext):
                return fused_tail_pallas.insert_enqueue(
                    seen, kh, kl, kvalid, krows, cons_ok, qnext,
                    next_count, Q, interpret=interpret)

            _probe_tail(K, sw, interpret)
            tail = cand_tail
            stages["insert"] = stages["enqueue"] = "fused"
        except Exception as e:  # noqa: BLE001 — fallback is the contract
            reasons["insert"] = (f"fused tail failed to build/probe: "
                                 f"{type(e).__name__}: {str(e)[:160]}")
    if tail is None and "insert" not in reasons:
        reasons["insert"] = "forced to xla"

    # -- split enqueue when the tail is not fused ----------------------
    enq = enqueue_method
    if tail is None:
        want_enq = force.get("enqueue")
        if want_enq in ("pallas", "xla"):
            enq = "scatter" if want_enq == "xla" else "pallas"
        elif mesh:
            enq = "pallas"   # enqueue_pallas inside shard_map
        if enq == "pallas":
            try:
                _probe_enqueue(K, sw, interpret)
                stages["enqueue"] = "pallas"
            except Exception as e:  # noqa: BLE001 — fallback contract
                reasons["enqueue"] = (f"pallas enqueue failed to "
                                      f"build/probe: {type(e).__name__}: "
                                      f"{str(e)[:160]}")
                enq = enqueue_method

    launches: Dict[str, Optional[int]] = {s: None for s in STAGES}
    if front is not None:
        launches["masks"] = 1
        launches["compact"] = launches["fingerprint"] = 0
    elif stages["compact"] == "pallas":
        launches["compact"] = 1
    if stages["insert"] == "fused":
        launches["insert"], launches["enqueue"] = 1, 0
    elif stages["enqueue"] == "pallas":
        launches["enqueue"] = 1
    return V4Plan(stages=stages, reasons=reasons, front=front,
                  compactor=compactor, tail=tail, enqueue_method=enq,
                  launches=launches)
