"""State fingerprinting — TLC's 64-bit fingerprints, TPU-native.

TLC identifies states by a 64-bit fingerprint and dedups on fingerprints
alone, accepting a vanishingly small collision probability [TLC semantics —
external].  We reproduce that contract with **two independent 32-bit lanes**
instead of one emulated u64 (TPUs have no native 64-bit integers; everything
here stays in uint32 on the VPU):

- the *ordered* part of the state (all server-indexed tensors; order is
  semantic, there is no symmetry reduction) is hashed element-wise with
  ``sum(fmix32(x * C_lane + seed)) mod 2^32`` — each position's
  contribution goes through a full avalanche BEFORE the sum, so a
  difference in two positions cannot cancel linearly;
- the *message bag* (raft.tla:31) must hash order-invariantly in slot
  order, so each occupied slot row is double-mixed to a per-message hash
  and the bag contributes ``sum(mix(row) * count)`` — the standard
  commutative multiset hash.  Equal bags give equal sums regardless of
  slot layout, and multiplicities are respected without any sorting pass;
- the bag sum is avalanched again before combining with the ordered part,
  and lane values are finalized with the murmur3 fmix32 avalanche.

Two independent lanes target TLC's ~2^-64 pairwise regime.  The pair
(hi, lo) is also the key layout the sorted fingerprint set (ops/fpset.py)
sorts on with a two-key lexsort.

Hardening history (2026-07-31): the original design summed RAW products
(``sum(x*C)``, multilinear) and combined the bag sum linearly — a family
where structured state differences can cancel linearly, so it was
replaced with the per-element avalanche above as a matter of hygiene.
Measurement note: a 63M-state engine run (MCraft_bounded level 13) found
63,312,389 distinct vs the then-oracle count of 63,312,437 — a 48-state
"deficit" IDENTICAL under both hash designs (artifacts/
mcraft_L13_engine.txt and _v2.txt), which ruled out fingerprint
collisions.  RESOLVED by the dual-key sweep + pair capture
(scripts/row_dedup_sweep.py, ROUND5_NOTES.md): all 48 pairs are
spec-IDENTICAL states that the oracle-side pickle digest split because
``pickle.dumps`` is sensitive to object-identity sharing (memo
backreferences).  **The engine's 63,312,389 is the true count — exact
parity through level 13**; oracle_exhaust.py now hashes memo-free.

The all-ones pair is reserved as the FPSet's empty/pad sentinel; real
fingerprints landing on it are remapped deterministically.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..models.dims import RaftDims
from ..models.schema import StateBatch

_U32 = jnp.uint32
SENTINEL = np.uint32(0xFFFFFFFF)


def fmix32(x):
    """murmur3 finalizer: full-avalanche 32-bit mixer."""
    x = x ^ (x >> 16)
    x = x * _U32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * _U32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _flat_ordered(st: StateBatch):
    """Concatenate every server-indexed field (order is part of state
    identity; nextIndex/matchIndex diagonals included — raft.tla:118-120)."""
    parts = [st.term, st.role, st.voted_for, st.log_term.reshape(-1),
             st.log_val.reshape(-1), st.log_len, st.commit, st.votes_resp,
             st.votes_gran, st.next_idx.reshape(-1),
             st.match_idx.reshape(-1)]
    return jnp.concatenate([p.astype(jnp.int32) for p in parts]).view(_U32)


def build_fingerprint(dims: RaftDims):
    """Returns ``fp(state) -> (hi, lo)`` for a single state; vmap for
    batches.  Constants are fixed (seeded) so fingerprints are stable
    across processes — required for checkpoint/resume compatibility."""
    n, L = dims.n_servers, dims.max_log
    # 7 scalar lanes per server (term, role, votedFor, logLen, commit,
    # votesResponded, votesGranted) + 2 log planes + nextIndex/matchIndex.
    d_ordered = n * (7 + 2 * L) + 2 * n * n
    rng = np.random.RandomState(0x7A57)  # fixed seed: fingerprint stability
    consts = {}
    for lane in (0, 1):
        consts[lane] = (
            jnp.asarray(rng.randint(0, 1 << 32, d_ordered,
                                    dtype=np.uint64).astype(np.uint32) | 1),
            jnp.asarray(rng.randint(0, 1 << 32, dims.msg_width,
                                    dtype=np.uint64).astype(np.uint32) | 1),
            _U32(rng.randint(1, 1 << 32, dtype=np.uint64) | 1),
        )

    def lane_hash(st, flat, lane):
        c_ord, c_msg, seed = consts[lane]
        # Avalanche each position BEFORE summing: a multilinear sum is a
        # family where structured differences CAN cancel linearly across
        # lanes — hardened as hygiene; note the measured L13 deficit was
        # proven NOT to be hash collisions (module docstring).
        base = jnp.sum(fmix32(flat * c_ord + seed), dtype=_U32)
        rows = st.msg.view(_U32) if st.msg.dtype != jnp.uint32 else st.msg
        slot_h = fmix32(fmix32(jnp.sum(rows * c_msg[None, :], axis=1,
                                       dtype=_U32) ^ seed)
                        * _U32(0x85EBCA6B) + seed)                # [M]
        occupied = st.msg_cnt > 0
        msum = jnp.sum(jnp.where(occupied, slot_h
                                 * st.msg_cnt.astype(_U32), _U32(0)),
                       dtype=_U32)
        return fmix32(base + fmix32(msum + seed) * _U32(0x9E3779B9))

    def fingerprint(st: StateBatch):
        flat = _flat_ordered(st)
        hi = lane_hash(st, flat, 0)
        lo = lane_hash(st, flat, 1)
        # Reserve the all-ones pair for the FPSet sentinel.
        is_sent = (hi == SENTINEL) & (lo == SENTINEL)
        return hi, jnp.where(is_sent, _U32(0xFFFFFFFE), lo)

    return fingerprint
