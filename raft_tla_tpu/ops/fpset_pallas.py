"""Pallas probe/insert for the FPSet — the fused-chunk experiment's stage 1.

Motivation (NORTHSTAR.md §c/§d): once the v2 delta pipeline removes the
expand/materialize cost, the measured TPU chunk's dominant residue is the
hash insert (5.3 ms, *including* the dedup sort) and the enqueue scatter
(14.5 ms) — and the whole chunk sits ~100× above the HBM bandwidth floor
because it is hundreds of separate XLA kernels.  The decision rule for
attacking that (NORTHSTAR §d item 3) is a single fused Pallas chunk; this
module is its first, independently-testable stage: the table insert as ONE
Pallas kernel.

Design vs the XLA path (`ops/fpset.py`):

- **Sequential insertion replaces sort + claim.**  The XLA insert needs a
  K-lane `lax.sort` pre-pass (in-batch dedup) and a claim/scatter-max
  protocol (concurrent-writer determinism) because all K lanes insert at
  once.  A Pallas TPU grid executes programs *sequentially* on a core
  ("arbitrary" dimension semantics), so this kernel just inserts queries
  in index order: a later duplicate finds the earlier key present — the
  sort AND the claim machinery disappear.
- **Same probe chains.**  `_probe_base` (double hashing, h2 odd) is
  imported from ops/fpset.py, so a key's candidate slot sequence is
  identical in both lowerings.
- **Same observable contract, different physical layout.**  ``is_new``
  marks exactly the first query index holding each distinct new key
  (the XLA path's stable sort marks the same index); ``fail``/``size``
  match; the stored KEY SET matches.  The raw slot assignment may differ
  when two *distinct* keys contend for one empty slot in the same round
  (the XLA claim hands it to the highest lane, sequential order to the
  lowest) — both layouts satisfy the chain invariant every reader
  depends on (a key occupies the first slot of its probe chain that was
  empty at its insert time), so `contains`, checkpointing
  (`to_host_keys` sorts), and every engine result are unaffected.
  Tests compare is_new/size/fail and the sorted key set, and run whole
  engines under both lowerings (bit-identical results).

Table reads/writes go through single-element async copies (the table
lives in HBM; TPU has no vector gather from HBM — XLA's own gather is a
DMA loop underneath).  The kernel is therefore also the *measurement
instrument* for Mosaic's scalar-DMA round-trip cost, the number that
decides whether the fully-fused chunk kernel (NORTHSTAR §d) is viable:
the staged profile matrix (scripts/tpu_session.sh) times it next to the
XLA insert on the same batch.

Bit-identity is proven on CPU via interpret mode (`tests/test_fpset.py`,
`tests/test_engine.py`); `interpret` defaults to automatic (real lowering
on TPU, interpreter elsewhere).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .fingerprint import SENTINEL
from .fpset import FPSet, PROBE_ROUNDS, _pad_pow2, _probe_base
from .pallas_compat import tpu_compiler_params

_U32 = jnp.uint32
_I32 = jnp.int32

# Queries processed per grid program.  Small enough that the per-program
# VMEM blocks stay tiny; large enough that program-switch overhead
# amortizes.  Must divide the (power-of-two-padded) query count, so keep
# it a power of two.
_BLOCK = 512


def probe_insert_query(hi_ref, lo_ref, scr, sem, qh, ql, pending0,
                       c_mask: int, rounds: int):
    """Sequentially probe/insert ONE key into the table refs — the inner
    chain shared by this module's insert kernel and the fused
    insert+enqueue kernel (ops/fused_tail_pallas.py), so the two
    lowerings can never drift on probe order or claim semantics.
    Returns ``(is_new, still_pending)``; table writes go through the
    refs via single-element async copies."""

    def probe_round(carry):
        r, step, pending, newf, qh, ql, h1, h2 = carry
        idx = ((h1 + step * h2) & _U32(c_mask)).astype(_I32)
        # Fetch the slot (4 B each lane of the key pair).
        rd_hi = pltpu.make_async_copy(
            hi_ref.at[pl.ds(idx, 1)], scr.at[0], sem.at[0])
        rd_lo = pltpu.make_async_copy(
            lo_ref.at[pl.ds(idx, 1)], scr.at[1], sem.at[1])
        rd_hi.start()
        rd_lo.start()
        rd_hi.wait()
        rd_lo.wait()
        cur_hi = scr[0, 0]
        cur_lo = scr[1, 0]
        is_match = (cur_hi == qh) & (cur_lo == ql)
        is_empty = (cur_hi == SENTINEL) & (cur_lo == SENTINEL)
        # Branch-free write-back: claim the slot when empty, else rewrite
        # the value just read (a no-op).  Unconditional DMA sidesteps
        # predicated-DMA lowering; sequential grid order makes it race-free.
        scr[0, 0] = jnp.where(is_empty, qh, cur_hi)
        scr[1, 0] = jnp.where(is_empty, ql, cur_lo)
        wr_hi = pltpu.make_async_copy(
            scr.at[0], hi_ref.at[pl.ds(idx, 1)], sem.at[0])
        wr_lo = pltpu.make_async_copy(
            scr.at[1], lo_ref.at[pl.ds(idx, 1)], sem.at[1])
        wr_hi.start()
        wr_lo.start()
        wr_hi.wait()
        wr_lo.wait()
        newf = newf | is_empty
        pending = pending & ~(is_match | is_empty)
        # Advance the chain only past a slot occupied by a different key.
        step = step + pending.astype(_U32)
        return r + 1, step, pending, newf, qh, ql, h1, h2

    def probe_cond(carry):
        r, _step, pending, *_ = carry
        return pending & (r < rounds)

    h1, h2 = _probe_base(qh, ql, c_mask + 1)
    _r, _s, pending, newf, *_ = jax.lax.while_loop(
        probe_cond, probe_round,
        (_I32(0), _U32(0), pending0, jnp.bool_(False), qh, ql, h1, h2))
    return newf, pending


def _kernel(qhi_ref, qlo_ref, valid_ref,   # [BLK] VMEM in blocks
            hi_in, lo_in,                  # [C] ANY in (aliased to outputs)
            hi_ref, lo_ref,                # [C] ANY out — the same buffers;
                                           # all reads+writes go through these
            new_ref,                       # [BLK] VMEM out block
            fail_ref,                      # [1] out, revisited by all programs
            scr, sem,                      # VMEM (2,1) u32 scratch + 2 DMA sems
            *, c_mask: int, rounds: int):
    del hi_in, lo_in
    @pl.when(pl.program_id(0) == 0)
    def _():
        fail_ref[0] = _I32(0)

    def one_query(i, local_fail):
        qh = qhi_ref[i]
        ql = qlo_ref[i]
        pending0 = valid_ref[i] != 0
        newf, pending = probe_insert_query(hi_ref, lo_ref, scr, sem,
                                           qh, ql, pending0, c_mask, rounds)
        new_ref[i] = newf.astype(_I32)
        return local_fail | pending.astype(_I32)

    local_fail = jax.lax.fori_loop(0, qhi_ref.shape[0], one_query, _I32(0))
    fail_ref[0] = fail_ref[0] | local_fail


# No donate_argnums: when called inside the engines' jitted chunk the
# inner jit inlines (donation is moot), and standalone callers (profile
# matrix, tests) re-time the same table object repeatedly — donation
# would invalidate their buffers.  input_output_aliases inside the
# pallas_call already gives the in-place table update.
@functools.partial(jax.jit, static_argnames=("interpret",))
def _insert_padded(s: FPSet, qhi, qlo, valid, interpret: bool):
    c = s.hi.shape[0]
    kp = qhi.shape[0]
    blk = min(_BLOCK, kp)
    grid = kp // blk
    kern = functools.partial(_kernel, c_mask=c - 1, rounds=PROBE_ROUNDS)
    hi, lo, is_new, fail = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((1,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c,), jnp.uint32),
            jax.ShapeDtypeStruct((c,), jnp.uint32),
            jax.ShapeDtypeStruct((kp,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 1), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases={3: 0, 4: 1},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            has_side_effects=True),
        interpret=interpret,
    )(qhi, qlo, valid.astype(_I32), s.hi, s.lo)
    is_new = is_new.astype(bool)
    return (FPSet(hi=hi, lo=lo,
                  size=s.size + jnp.sum(is_new, dtype=_I32)),
            is_new, fail[0] > 0)


def insert(s: FPSet, qhi, qlo, valid,
           interpret: bool | None = None) -> Tuple[FPSet, jnp.ndarray,
                                                   jnp.ndarray]:
    """Drop-in replacement for :func:`ops.fpset.insert` (same contract:
    ``(table', is_new, fail)``, is_new marking exactly one query per
    distinct new key).  No dedup pre-pass needed — sequential insertion
    dedups in-table."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    (qhi, qlo, valid), k = _pad_pow2(
        (qhi, qlo, jnp.asarray(valid, bool)),
        (SENTINEL, SENTINEL, False))
    s, is_new, fail = _insert_padded(s, qhi, qlo, valid, interpret)
    return s, is_new[:k], fail
