"""Checker service — the TLC-delegation endpoint (SURVEY §2.4 R10).

TLC's distributed mode lets a stock CLI hand work to external processes;
the analogous integration here is a long-lived service wrapping the TPU
engines, reachable from anything that can open a socket — in particular
the TLC module override shipped in ``native/tlc_override/`` (a Java
operator that forwards a ``.cfg`` to this service and returns the result
as a TLA+ record), but also ad-hoc drivers and notebooks.  The service
holds the compiled engines warm between requests, so repeat checks of the
same model skip XLA compilation.

Protocol: newline-delimited JSON over TCP; one request per line, one
response per line.  Requests:

    {"op": "ping"}
        -> {"ok": true, "platform": "tpu"}
    {"op": "check", "cfg": "<path>" | "cfg_text": "<.cfg contents>",
     "batch": 1024, "max_seconds": 60.0, "max_diameter": null,
     "queue_capacity": null, "seen_capacity": null, "trace": false,
     "engine": "single" | "mesh"}
        -> {"ok": true, "distinct": N, "generated": N, "diameter": N,
            "levels": [...], "stop_reason": "...",
            "report": {collision probability, per-level table,
                       out-degree, seen-set load — obs/report.py},
            "violation": null | {"invariant": "...", "fingerprint": "0x..",
                                 "trace": [{"action": "...",
                                            "state": "..."}, ...]},
            "deadlock": null | "<state>", "wall_seconds": S}
    {"op": "simulate", "cfg": ..., "num_steps": N, "depth": D,
     "batch": B, "seed": 0, "max_seconds": S}
        -> {"ok": true, "steps": N, "traces": N, "wall_seconds": S,
            "violation": null | {...}}
    {"op": "stats"}
        -> {"ok": true, "metrics": {counters, gauges, histograms},
            "engine_cache": {"size": n, "capacity": c},
            "sim_cache": {...}}
       Live telemetry (obs/): per-op request counts and latency
       histograms, engine/sim LRU cache hit/miss/eviction counters.
       Served WITHOUT the device lock, so it answers while a check runs.
    {"op": "metrics"}
        -> {"ok": true, "content_type": "text/plain; version=0.0.4...",
            "exposition": "<Prometheus text exposition>"}
       The SAME registry as "stats", rendered in the Prometheus text
       format (obs/expose.py) — point a scraper sidecar here, or mount
       the standalone --metrics-port HTTP listener instead.  Also
       served without the device lock.
    {"op": "watch", "interval": 1.0, "count": 0}
        -> a STREAM of lines (the one multi-line-response op): one
           {"ok": true, "watch": {run, progress, level, coverage,
            chunk_stage, seq, armed}} snapshot per interval, closed by
           {"ok": true, "done": true, ...} when the watched run ends
           (or after "count" snapshots; count 0 = until run end).
       Run attach (obs/flight.py): snapshots come from the in-memory
       flight ring, not the event file — a check with no --events-out
       is still watchable.  Never takes the device lock.

Errors: {"ok": false, "error": "<message>"}.  check/simulate are served
one at a time (a checking run owns the device); concurrent connections
queue.  ping/stats/metrics/watch never queue behind them.

Run:  python -m raft_tla_tpu.server [--port 8610] [--platform cpu]

Trust model: the service is UNAUTHENTICATED and the "cfg" op accepts an
arbitrary filesystem path, whose parse errors can echo file contents —
so the default bind is loopback and the service trusts every client the
bind address admits (same model as TLC's distributed-mode RMI endpoints).
Binding a non-loopback --host hands that power to the network segment;
do it only behind a firewall/ssh tunnel, or pass cfg_text instead of
path-based cfg and run the process with a restricted filesystem view.
"""

from __future__ import annotations

import json
import os
import socketserver
import tempfile
import threading
from typing import Optional

_LOCK = threading.Lock()          # one engine run at a time (one device)
# Process-global telemetry (obs/): request/latency/cache counters for
# every handler thread, exposed verbatim by the "stats" op.  The obs
# package never imports jax, so this is safe before platform selection.
from .obs import MetricsRegistry  # noqa: E402
_METRICS = MetricsRegistry()
# Warm caches, LRU-capped: a long-lived service iterating on cfg_text
# variants must not pin one compiled engine (plus its trace store) per
# variant forever.
_CACHE_CAP = 8
from collections import OrderedDict  # noqa: E402
_ENGINES: "OrderedDict" = OrderedDict()   # (cfg identity, opts) -> engine
_SIMS: "OrderedDict" = OrderedDict()      # ditto for simulators


def _cache_put(cache: "OrderedDict", key, value, name: str):
    cache[key] = value
    cache.move_to_end(key)
    while len(cache) > _CACHE_CAP:
        cache.popitem(last=False)
        _METRICS.counter(f"server/{name}/evictions")


def _cache_get(cache: "OrderedDict", key, name: str):
    v = cache.get(key)
    if v is not None:
        cache.move_to_end(key)
    # Hit/miss counters per LRU cache: a miss on a repeat model means the
    # cap is churning compiled engines — the number that tells an operator
    # to raise _CACHE_CAP before blaming XLA.
    _METRICS.counter(f"server/{name}/" + ("hits" if v is not None
                                          else "misses"))
    return v


def _load_setup(req):
    """Returns (setup, identity).  Identity is a hash of the cfg CONTENT
    (not the path): editing a .cfg between requests must never serve the
    previous model's engine."""
    import hashlib
    from .utils.cfg import load_config
    if req.get("cfg"):
        path = req["cfg"]
        with open(path, "rb") as f:
            ident = hashlib.sha256(f.read()).hexdigest()
        return load_config(path), ident
    if req.get("cfg_text"):
        text = req["cfg_text"]
        ident = hashlib.sha256(text.encode()).hexdigest()
        f = tempfile.NamedTemporaryFile("w", suffix=".cfg", delete=False)
        try:
            f.write(text)
            f.close()
            return load_config(f.name), ident
        finally:
            os.unlink(f.name)
    raise ValueError("need 'cfg' (path) or 'cfg_text'")


def _violation_json(engine, violation, dims):
    from .models.pystate import format_state
    out = {"invariant": violation.invariant,
           "fingerprint": hex(violation.fingerprint)}
    try:
        steps = engine.replay(violation.fingerprint)
        out["trace"] = [
            {"action": ("Init" if g < 0 else dims.describe_instance(g)),
             "state": format_state(st, dims)}
            for g, st in steps]
    except Exception as e:          # trace-off runs: report the state only
        out["trace"] = []
        out["trace_error"] = str(e)
        out["state"] = format_state(violation.state, dims)
    return out


def _do_check(req):
    from .engine.bfs import EngineConfig
    from .engine.check import initial_states, make_engine

    from .models.pystate import format_state

    import dataclasses
    from .engine.check import engine_config_from_backend

    setup, ident = _load_setup(req)
    record_trace = bool(req.get("trace", False))
    # Precedence everywhere (utils/cfg.py): request field > cfg "\* TPU:"
    # backend directive > built-in default — the backend-seeded config is
    # the base, request fields overlay only when present.
    # A JSON null is the protocol's "unset" (the docstring's idiomatic
    # form), so only non-null request values override the directives.
    base = engine_config_from_backend(setup)
    cfg = dataclasses.replace(
        base,
        # Engines share the process-global registry, so engine counters,
        # phase timers, and coverage gauges aggregate across requests
        # and surface in the "stats" op (the obs/ aggregation pattern).
        metrics=_METRICS,
        batch=(int(req["batch"]) if req.get("batch") is not None
               else base.batch),
        queue_capacity=(req["queue_capacity"]
                        if req.get("queue_capacity") is not None
                        else base.queue_capacity),
        seen_capacity=(req["seen_capacity"]
                       if req.get("seen_capacity") is not None
                       else base.seen_capacity),
        max_seconds=req.get("max_seconds"),
        max_diameter=req.get("max_diameter"),
        record_trace=record_trace,
        check_deadlock=req.get("check_deadlock"),
        # Successor pipeline (auto/v1/v2/v3 — v3 is the fused Pallas
        # chunk); same request-over-directive precedence as every key.
        pipeline=(req["pipeline"] if req.get("pipeline") is not None
                  else base.pipeline),
        por=(bool(req["por"]) if req.get("por") is not None
             else base.por),
        por_table=(req["por_table"] if req.get("por_table") is not None
                   else base.por_table))
    # check_deadlock (and the POR mask) are baked into the compiled
    # program, so they key the cache; the StopAfter budgets are
    # host-side and are refreshed on the cached engine's config below.
    # A table artifact keys by CONTENT, not path (the same file-identity
    # rule as ``ident``): regenerating the artifact in place must build
    # a fresh engine, not keep serving the stale mask.
    por_key = None
    if cfg.por_table is not None:
        if isinstance(cfg.por_table, str):
            import hashlib
            with open(cfg.por_table, "rb") as f:
                por_key = hashlib.sha256(f.read()).hexdigest()
        else:
            por_key = cfg.por_table.fingerprint
    # pipeline keys the cache: the chunk program differs per pipeline,
    # so a v3 request must never be served a warm v2 engine (or vice
    # versa).
    key = (ident, req.get("engine", "single"), cfg.batch,
           cfg.queue_capacity, cfg.seen_capacity, record_trace,
           cfg.check_deadlock, cfg.pipeline, cfg.por, por_key)
    engine = _cache_get(_ENGINES, key, "engine_cache")
    if engine is None:
        engine_cls = None
        if req.get("engine") == "mesh":
            from .parallel.mesh import MeshBFSEngine
            engine_cls = MeshBFSEngine
        elif req.get("engine") == "auto":
            engine_cls = "auto"
        # make_engine applies the cfg-file fallbacks (CHECK_DEADLOCK,
        # StopAfter) identically for both engine classes.
        engine = make_engine(setup, cfg, engine_cls=engine_cls)
        _cache_put(_ENGINES, key, engine, "engine_cache")
    # Budgets are per-request: apply the request value (or the cfg-file
    # fallback) to the warm engine's host-side config.
    engine.config.max_seconds = (cfg.max_seconds
                                 if cfg.max_seconds is not None
                                 else setup.max_seconds)
    engine.config.max_diameter = (cfg.max_diameter
                                  if cfg.max_diameter is not None
                                  else setup.max_diameter)
    res = engine.run(initial_states(setup, seed=int(req.get("seed", 0))))
    out = {"ok": True, "distinct": res.distinct,
           "generated": res.generated, "diameter": res.diameter,
           "levels": list(res.levels), "stop_reason": res.stop_reason,
           "wall_seconds": round(res.wall_seconds, 3),
           "batch": engine.config.batch,      # resolved, for observability
           # Which successor pipeline actually ran, and (v3) the
           # resolved per-stage lowering plan — a stage that fell back
           # to XLA is visible to the client, never silent.
           "pipeline": res.pipeline,
           "fused_stages": dict(res.fused_stages),
           "fused_reasons": dict(res.fused_reasons),
           "action_counts": dict(res.action_counts),
           # (capacity-after, off-clock stall seconds) per seen-set
           # doubling — the SEEN_CAPACITY sizing evidence.
           "growth_stalls": list(res.growth_stalls),
           # Host-side per-phase wall-time breakdown for THIS run
           # (obs/ phase timers) — same shape bench.py embeds.
           "phases": {k: round(v, 4) for k, v in res.phases.items()},
           # TLC-style per-action coverage (obs/coverage.py), same
           # object bench JSON carries; also mirrored as coverage/*
           # gauges in the "stats" op.
           "coverage": dict(res.coverage),
           # TLC-parity statespace report (obs/report.py): collision
           # probability, per-level table, out-degree, seen-set load.
           # Also mirrored as statespace/* gauges in "stats", so the
           # two surfaces can never disagree about the scalar spine.
           "report": dict(res.report),
           "violation": None, "deadlock": None}
    if res.violation is not None:
        out["violation"] = _violation_json(engine, res.violation,
                                           setup.dims)
    if res.deadlock is not None:
        out["deadlock"] = format_state(res.deadlock, setup.dims)
    return out


def _do_simulate(req):
    from .engine.check import resolve_constraint, resolve_invariants
    from .engine.simulate import Simulator
    from .engine.check import initial_states

    setup, ident = _load_setup(req)
    batch = (int(req["batch"]) if req.get("batch") is not None
             else int(setup.backend.get("BATCH", 1024)))
    depth = int(req.get("depth", 100))
    key = (ident, batch, depth)
    sim = _cache_get(_SIMS, key, "sim_cache")  # warm path, like _ENGINES
    if sim is None:
        sim = Simulator(setup.dims,
                        invariants=resolve_invariants(setup),
                        constraint=resolve_constraint(setup),
                        batch=batch, depth=depth)
        _cache_put(_SIMS, key, sim, "sim_cache")
    res = sim.run(initial_states(setup, seed=int(req.get("seed", 0))),
                  num_steps=int(req.get("num_steps", 1 << 20)),
                  seed=int(req.get("seed", 0)),
                  max_seconds=req.get("max_seconds"))
    out = {"ok": True, "steps": res.steps, "traces": res.traces,
           "wall_seconds": round(res.wall_seconds, 3), "violation": None}
    if res.violation_invariant is not None:
        from .models.pystate import format_state
        out["violation"] = {
            "invariant": res.violation_invariant,
            "trace": [
                {"action": ("Init" if g < 0
                            else setup.dims.describe_instance(g)),
                 "state": format_state(st, setup.dims)}
                for g, st in (res.violation_trace or [])]}
    return out


def _do_metrics() -> dict:
    """Prometheus text exposition of the same process-global registry
    the ``stats`` op serves as JSON — one snapshot() call feeds both,
    so the two views can never disagree about a counter taken in the
    same instant (the acceptance contract tests exactly this)."""
    from .obs.expose import (CONTENT_TYPE, default_labels,
                             render_prometheus)
    return {"ok": True,
            "content_type": CONTENT_TYPE,
            "exposition": render_prometheus(_METRICS.snapshot(),
                                            labels=default_labels())}


def _do_stats() -> dict:
    """The live-stats endpoint: the process-global registry verbatim
    (request counts, per-op latency histograms, LRU cache hit/miss/
    eviction counters) plus the caches' occupancy.  Read-only and
    lock-free — it answers instantly even while a check owns the device
    lock, which is the whole point of a LIVE stats op."""
    return {"ok": True,
            "metrics": _METRICS.snapshot(),
            "engine_cache": {"size": len(_ENGINES),
                             "capacity": _CACHE_CAP},
            "sim_cache": {"size": len(_SIMS), "capacity": _CACHE_CAP}}


def handle_request(req: dict) -> dict:
    op = req.get("op")
    # Metric names must not echo client-controlled strings: one counter +
    # histogram per distinct bogus op would grow the process-global
    # registry without bound in this long-lived service.
    op_label = op if op in ("ping", "check", "simulate", "stats",
                            "metrics") else "unknown"
    _METRICS.counter(f"server/requests/{op_label}")
    ok = False
    with _METRICS.phase_timer(f"request/{op_label}"):
        try:
            if op == "ping":
                import jax
                resp = {"ok": True,
                        "platform": jax.devices()[0].platform}
            elif op == "stats":
                resp = _do_stats()
            elif op == "metrics":
                resp = _do_metrics()
            elif op in ("check", "simulate"):
                with _LOCK:
                    resp = (_do_check(req) if op == "check"
                            else _do_simulate(req))
            else:
                resp = {"ok": False, "error": f"unknown op {op!r}"}
            ok = bool(resp.get("ok"))
            return resp
        except Exception as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        finally:
            if not ok:
                _METRICS.counter(f"server/errors/{op_label}")


class _Handler(socketserver.StreamRequestHandler):
    """Connection hardening (resilience): the service is long-lived, so a
    single connection must not be able to take it down or pin it —

    - the request LINE is size-bounded (``max_request_bytes``): the
      newline-delimited protocol otherwise buffers an arbitrarily long
      line in RAM before json parsing ever sees it, so one huge line
      could OOM the whole warm-engine process;
    - the socket gets an IDLE timeout (``idle_timeout_seconds``): a dead
      or wedged client would otherwise hold its handler thread (and its
      open fd) forever.  The timeout covers reads between requests and
      response writes — a check/simulate in flight does not tick it,
      because the handler is computing, not blocked on the socket.

    The oversized reject answers ``{"ok": false}`` (the client is
    mid-exchange and waiting for a line) and then closes — an oversized
    line cannot be resynced, its remainder would parse as garbage
    requests.  The idle timeout closes SILENTLY: the client is between
    requests, and an unsolicited error line sitting in the socket
    buffer would be misread as the response to whatever it sends next
    from a stale pooled connection."""

    def handle(self):
        srv = self.server
        try:
            self.connection.settimeout(srv.idle_timeout_seconds)
        except OSError:
            pass
        while True:
            try:
                line = self.rfile.readline(srv.max_request_bytes + 1)
            except (TimeoutError, OSError):
                _METRICS.counter("server/rejected/idle_timeout")
                return       # silent close: see class docstring
            if not line:
                return
            if len(line) > srv.max_request_bytes:
                _METRICS.counter("server/rejected/oversized")
                self._try_respond({
                    "ok": False,
                    "error": f"request line exceeds "
                             f"{srv.max_request_bytes} bytes"})
                return
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError as e:
                resp = {"ok": False, "error": f"bad json: {e}"}
            else:
                if isinstance(req, dict) and req.get("op") == "watch":
                    # The one streaming op: run attach emits one
                    # snapshot line per interval on THIS connection,
                    # then a done line; the connection then continues
                    # serving normal requests.
                    if not self._serve_watch(req):
                        return
                    continue
                resp = handle_request(req)
            if not self._try_respond(resp):
                return

    def _serve_watch(self, req: dict) -> bool:
        """Stream flight-recorder snapshots (obs/flight.py) until the
        watched run ends, ``count`` snapshots have been sent, or the
        client goes away.  Never touches the device lock — attach to a
        server mid-check and the snapshots flow while the check runs.
        Returns False when the client died (ends the handler)."""
        import time as _time

        from .obs.flight import RECORDER
        _METRICS.counter("server/requests/watch")
        try:
            interval = min(max(float(req.get("interval", 1.0)), 0.05),
                           60.0)
            count = int(req.get("count", 0))
        except (TypeError, ValueError) as e:
            return self._try_respond(
                {"ok": False, "error": f"bad watch params: {e}"})
        # 0/negative = until run end — still bounded so an orphaned
        # watcher cannot pin its handler thread forever.
        limit = count if count > 0 else 3600
        attach_seq = RECORDER.note_attach(
            transport="server", peer=str(self.client_address[0]),
            interval=interval, count=count)
        sent = 0
        saw_run = False
        t_attach = _time.monotonic()
        while True:
            run_end = RECORDER.last_event("run_end")
            snapshot = {
                "seq": RECORDER.seq(), "armed": RECORDER.armed,
                "run": RECORDER.last_record("run_context"),
                "progress": RECORDER.last_record("progress"),
                "level": RECORDER.last_event("level_complete"),
                "coverage": RECORDER.last_event("coverage"),
                "chunk_stage": RECORDER.last_record("chunk_stage"),
            }
            if not self._try_respond({"ok": True, "watch": snapshot}):
                return False
            sent += 1
            ended = (run_end is not None
                     and run_end["seq"] > attach_seq)
            saw_run = saw_run or RECORDER.armed or ended
            # Done when: the watched run ended after we attached; an
            # explicit count is exhausted; or (count 0) the run we saw
            # is gone / none ever started within the grace window — a
            # watcher launched alongside its run must ride out engine
            # construction + XLA compilation (tens of seconds on a cold
            # cache), so the no-run-yet grace is time-based.
            idle = (count <= 0 and not RECORDER.armed
                    and (saw_run
                         or _time.monotonic() - t_attach > 120.0))
            if sent >= limit or ended or idle:
                # Re-read: the run can end (emit run_end, then disarm)
                # between the loop-top read and the idle computation —
                # the done line must carry the freshest record, not a
                # stale null.  Pre-attach run_ends stay out: the done
                # line reports THIS watch's run or nothing.
                end = RECORDER.last_event("run_end")
                if end is not None and end["seq"] <= attach_seq:
                    end = None
                return self._try_respond(
                    {"ok": True, "done": True, "snapshots": sent,
                     "run_end": end})
            _time.sleep(interval)

    def _try_respond(self, resp: dict) -> bool:
        """Best-effort one-line reply; False when the client is gone (a
        failed write must end the handler, never crash the thread)."""
        try:
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()
            return True
        except (TimeoutError, OSError):
            _METRICS.counter("server/rejected/dead_client")
            return False


class CheckerServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    # Hardening knobs (see _Handler): overridable per instance/CLI.
    max_request_bytes = 10 << 20       # a sane cfg_text is far smaller
    idle_timeout_seconds = 300.0


def serve(host: str = "127.0.0.1", port: int = 8610,
          max_request_bytes: Optional[int] = None,
          idle_timeout_seconds: Optional[float] = None) -> CheckerServer:
    """Create (and return) a listening server; caller decides threading.
    Port 0 picks an ephemeral port (see ``server_address[1]``)."""
    srv = CheckerServer((host, port), _Handler)
    if max_request_bytes is not None:
        srv.max_request_bytes = max_request_bytes
    if idle_timeout_seconds is not None:
        srv.idle_timeout_seconds = idle_timeout_seconds
    return srv


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(prog="raft_tla_tpu.server")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8610)
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu)")
    p.add_argument("--max-request-bytes", type=int, default=None,
                   help="reject request lines larger than this "
                        f"(default {CheckerServer.max_request_bytes})")
    p.add_argument("--idle-timeout", type=float, default=None,
                   help="drop connections idle longer than this many "
                        "seconds "
                        f"(default {CheckerServer.idle_timeout_seconds})")
    args = p.parse_args(argv)
    if args.platform == "cpu":
        from .utils.platform import force_cpu
        force_cpu()
    srv = serve(args.host, args.port,
                max_request_bytes=args.max_request_bytes,
                idle_timeout_seconds=args.idle_timeout)
    print(f"raft_tla_tpu checker service on "
          f"{srv.server_address[0]}:{srv.server_address[1]}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
