"""Checker service — the TLC-delegation endpoint (SURVEY §2.4 R10).

TLC's distributed mode lets a stock CLI hand work to external processes;
the analogous integration here is a long-lived service wrapping the TPU
engines, reachable from anything that can open a socket — in particular
the TLC module override shipped in ``native/tlc_override/`` (a Java
operator that forwards a ``.cfg`` to this service and returns the result
as a TLA+ record), but also ad-hoc drivers and notebooks.  The service
holds the compiled engines warm between requests, so repeat checks of the
same model skip XLA compilation.

Protocol: newline-delimited JSON over TCP; one request per line, one
response per line.  Requests:

    {"op": "ping"}
        -> {"ok": true, "platform": "tpu"}
    {"op": "check", "cfg": "<path>" | "cfg_text": "<.cfg contents>",
     "batch": 1024, "max_seconds": 60.0, "max_diameter": null,
     "queue_capacity": null, "seen_capacity": null, "trace": false,
     "engine": "single" | "mesh"}
        -> {"ok": true, "distinct": N, "generated": N, "diameter": N,
            "levels": [...], "stop_reason": "...",
            "report": {collision probability, per-level table,
                       out-degree, seen-set load — obs/report.py},
            "violation": null | {"invariant": "...", "fingerprint": "0x..",
                                 "trace": [{"action": "...",
                                            "state": "..."}, ...]},
            "deadlock": null | "<state>", "wall_seconds": S}
    {"op": "check", "cfg": ..., "mode": "swarm", "walks": 1024,
     "max_depth": 64, "num_steps": N, "seed": 0, "max_seconds": S}
        -> {"ok": true, "mode": "swarm", "walks": W, "steps": N,
            "visited": N, "traces": N, "diameter": N,
            "steps_per_second": R, "walks_per_second": R,
            "violation_at_seconds": S | null, "stop_reason": "...",
            "violation": null | {...}, "report": {...}}
       The swarm tier (engine/swarm.py): W deterministic randomized
       walks instead of exhaustive BFS — the cheap high-QPS job class.
       Mode resolves request field > cfg "\\* TPU: MODE" directive >
       exhaustive; an unknown mode answers {"ok": false} and counts
       server/rejected/bad_mode (submit validates it at admission, so
       it can never surface as an executor-thread failure).
    {"op": "simulate", "cfg": ..., "num_steps": N, "depth": D,
     "batch": B, "seed": 0, "max_seconds": S}
        -> {"ok": true, "steps": N, "traces": N, "wall_seconds": S,
            "violation": null | {...}}
    {"op": "stats"}
        -> {"ok": true, "metrics": {counters, gauges, histograms},
            "engine_cache": {"size": n, "capacity": c},
            "sim_cache": {...}}
       Live telemetry (obs/): per-op request counts and latency
       histograms, engine/sim LRU cache hit/miss/eviction counters.
       Served WITHOUT the device lock, so it answers while a check runs.
    {"op": "metrics"}
        -> {"ok": true, "content_type": "text/plain; version=0.0.4...",
            "exposition": "<Prometheus text exposition>"}
       The SAME registry as "stats", rendered in the Prometheus text
       format (obs/expose.py) — point a scraper sidecar here, or mount
       the standalone --metrics-port HTTP listener instead.  Also
       served without the device lock.
    {"op": "watch", "interval": 1.0, "count": 0}
        -> a STREAM of lines (the one multi-line-response op): one
           {"ok": true, "watch": {run, progress, level, coverage,
            chunk_stage, seq, armed}} snapshot per interval, closed by
           {"ok": true, "done": true, ...} when the watched run ends
           (or after "count" snapshots; count 0 = until run end).
       Run attach (obs/flight.py): snapshots come from the in-memory
       flight ring, not the event file — a check with no --events-out
       is still watchable.  Never takes the device lock.
       With "job": "<job-id>" the stream scopes to ONE job (serving/):
       snapshots carry the job summary plus ring progress while that
       job owns the device, and the stream stays open for as long as
       the job is alive — a watcher on a queued or compiling job is
       never reaped as idle.  The done line carries the terminal job.

Async jobs (serving/ — the multi-tenant job layer; see README
"Serving & jobs" for full schemas):

    {"op": "submit", "tenant": "acme", "job": {<check/simulate
     request>}, "cache": false, "slo_seconds": null}
        -> {"ok": true, "job": {id, state: "queued", ...}}
       Bounded admission + per-tenant fair scheduling; the job runs on
       the single executor thread under the same device lock as the
       blocking ops.  Queue-full rejects answer {"ok": false} (and
       count server/rejected/queue_full).  "cache": true completes a
       repeat submit from the fingerprint-keyed result cache (refused
       for max_seconds-budgeted requests — a truncated run is not
       reusable).
    {"op": "status", "job_id": ID}   -> {"ok": true, "job": {...}}
    {"op": "result", "job_id": ID}   -> {"ok": true, "state": ...,
                                         "result": {<check response>}}
    {"op": "cancel", "job_id": ID}   -> {"ok": true, "job": {...}}
       queued/admitted only — a running single-device job is not
       preemptible; a cancelled job never ran and never will.
    {"op": "jobs", "tenant": null, "state": null}
        -> {"ok": true, "jobs": [...], "queue_depth": N, "running": N,
            "by_state": {...}, "queue_capacity": N}

    Every check job gets a scoped JSONL event log + postmortem dir
    under --job-dir/<job-id>/ and job/tenant tags on the flight ring's
    run_context (simulate jobs have neither — the simulator has no run
    event log); every job gets per-tenant counters and queue-wait/SLO
    histograms in the registry (the "stats"/"metrics" ops and the
    --metrics-port HTTP endpoint expose them), and — with --history —
    a kind=server run-history ledger entry.  The journal in --job-dir
    makes the registry survive restarts: queued jobs resume, the job a
    crash caught running is re-run once then failed with a postmortem
    pointer.

Errors: {"ok": false, "error": "<message>"}.  check/simulate are served
one at a time (a checking run owns the device); concurrent connections
queue.  ping/stats/metrics/watch and the job ops never queue behind
them (submit returns as soon as the job is journaled).

Run:  python -m raft_tla_tpu.server [--port 8610] [--platform cpu]
          [--job-dir DIR] [--job-queue N] [--history LEDGER]
          [--metrics-port PORT]

--metrics-port serves GET /metrics (Prometheus text exposition of the
same registry as "stats"), /flight (the flight ring), and /jobs (the
job registry) over HTTP from THIS process — the long-lived server is
the natural scrape target, no engine-side listener required.

Trust model: the service is UNAUTHENTICATED and the "cfg" op accepts an
arbitrary filesystem path, whose parse errors can echo file contents —
so the default bind is loopback and the service trusts every client the
bind address admits (same model as TLC's distributed-mode RMI endpoints).
Binding a non-loopback --host hands that power to the network segment;
do it only behind a firewall/ssh tunnel, or pass cfg_text instead of
path-based cfg and run the process with a restricted filesystem view.
"""

from __future__ import annotations

import json
import os
import socketserver
import tempfile
import threading
from typing import Optional

_LOCK = threading.Lock()          # one engine run at a time (one device)
# Process-global telemetry (obs/): request/latency/cache counters for
# every handler thread, exposed verbatim by the "stats" op.  The obs
# package never imports jax, so this is safe before platform selection.
from .obs import MetricsRegistry  # noqa: E402
_METRICS = MetricsRegistry()
# Warm caches, LRU-capped: a long-lived service iterating on cfg_text
# variants must not pin one compiled engine (plus its trace store) per
# variant forever.
_CACHE_CAP = 8
from collections import OrderedDict  # noqa: E402
_ENGINES: "OrderedDict" = OrderedDict()   # (cfg identity, opts) -> engine
_SIMS: "OrderedDict" = OrderedDict()      # ditto for simulators
_SWARMS: "OrderedDict" = OrderedDict()    # ditto for swarm engines
# NOTE the run-history ledger path (--history) is deliberately NOT a
# module global: several servers can live in one process (tests do),
# and a global would split-brain their ledgers.  It rides per-request
# telemetry (handle_request reads it off the server's JobManager, the
# single source of truth the manager's own restart bookkeeping uses).


def _cache_put(cache: "OrderedDict", key, value, name: str):
    cache[key] = value
    cache.move_to_end(key)
    while len(cache) > _CACHE_CAP:
        cache.popitem(last=False)
        _METRICS.counter(f"server/{name}/evictions")


def _cache_get(cache: "OrderedDict", key, name: str):
    v = cache.get(key)
    if v is not None:
        cache.move_to_end(key)
    # Hit/miss counters per LRU cache: a miss on a repeat model means the
    # cap is churning compiled engines — the number that tells an operator
    # to raise _CACHE_CAP before blaming XLA.
    _METRICS.counter(f"server/{name}/" + ("hits" if v is not None
                                          else "misses"))
    return v


def _load_setup(req):
    """Returns (setup, identity, cfg text).  Identity is a hash of the
    cfg CONTENT (not the path): editing a .cfg between requests must
    never serve the previous model's engine.  The text rides along for
    the history ledger's cfg fingerprint."""
    import hashlib
    from .utils.cfg import load_config
    if req.get("cfg"):
        path = req["cfg"]
        with open(path, "rb") as f:
            raw = f.read()
        ident = hashlib.sha256(raw).hexdigest()
        return load_config(path), ident, raw.decode(errors="replace")
    if req.get("cfg_text"):
        text = req["cfg_text"]
        ident = hashlib.sha256(text.encode()).hexdigest()
        f = tempfile.NamedTemporaryFile("w", suffix=".cfg", delete=False)
        try:
            f.write(text)
            f.close()
            return load_config(f.name), ident, text
        finally:
            os.unlink(f.name)
    raise ValueError("need 'cfg' (path) or 'cfg_text'")


def _cfg_label(req: dict) -> str:
    """Ledger/job label for one request: the cfg basename, or a short
    content fingerprint for path-less cfg_text submissions."""
    if req.get("cfg"):
        return os.path.basename(str(req["cfg"]))
    if req.get("cfg_text"):
        import hashlib
        return ("cfg_text:"
                + hashlib.sha256(req["cfg_text"].encode())
                .hexdigest()[:10])
    return "?"


def _violation_json(engine, violation, dims):
    from .models.pystate import format_state
    out = {"invariant": violation.invariant,
           "fingerprint": hex(violation.fingerprint)}
    try:
        steps = engine.replay(violation.fingerprint)
        out["trace"] = [
            {"action": ("Init" if g < 0 else dims.describe_instance(g)),
             "state": format_state(st, dims)}
            for g, st in steps]
    except Exception as e:          # trace-off runs: report the state only
        out["trace"] = []
        out["trace_error"] = str(e)
        out["state"] = format_state(violation.state, dims)
    return out


def _do_check(req, telemetry=None):
    """Run one check request.  ``telemetry`` (the job executor's
    per-job scoping) carries ``events_out`` / ``postmortem_dir`` /
    ``run_context`` overrides; they are applied to the (possibly warm,
    cached) engine's host-side config on EVERY request — a direct
    check after a job must reset them back to the request's own
    values, never inherit the job's scoped paths."""
    from .engine.bfs import EngineConfig
    from .engine.check import initial_states, make_engine

    from .models.pystate import format_state

    import dataclasses
    from .engine.check import engine_config_from_backend

    setup, ident, cfg_text = _load_setup(req)
    # Engine-tier routing (request "mode" field > cfg "\* TPU: MODE"
    # directive > exhaustive — the standard precedence): swarm-mode
    # checks run the randomized-walk tier (engine/swarm.py) through
    # the same request/telemetry/ledger surface.  Unknown modes reject
    # cleanly here; submit requests are additionally validated at
    # admission (_do_submit) so a bad mode never reaches the executor
    # thread.
    mode = req.get("mode") or setup.backend.get("MODE") or "exhaustive"
    if mode == "swarm":
        return _do_swarm(req, telemetry,
                         _loaded=(setup, ident, cfg_text))
    if mode != "exhaustive":
        _METRICS.counter("server/rejected/bad_mode")
        raise ValueError(f"unknown mode {mode!r} (expected "
                         f"'exhaustive' or 'swarm')")
    record_trace = bool(req.get("trace", False))
    # Precedence everywhere (utils/cfg.py): request field > cfg "\* TPU:"
    # backend directive > built-in default — the backend-seeded config is
    # the base, request fields overlay only when present.
    # A JSON null is the protocol's "unset" (the docstring's idiomatic
    # form), so only non-null request values override the directives.
    base = engine_config_from_backend(setup)
    cfg = dataclasses.replace(
        base,
        # Engines share the process-global registry, so engine counters,
        # phase timers, and coverage gauges aggregate across requests
        # and surface in the "stats" op (the obs/ aggregation pattern).
        metrics=_METRICS,
        batch=(int(req["batch"]) if req.get("batch") is not None
               else base.batch),
        queue_capacity=(req["queue_capacity"]
                        if req.get("queue_capacity") is not None
                        else base.queue_capacity),
        seen_capacity=(req["seen_capacity"]
                       if req.get("seen_capacity") is not None
                       else base.seen_capacity),
        max_seconds=req.get("max_seconds"),
        max_diameter=req.get("max_diameter"),
        record_trace=record_trace,
        check_deadlock=req.get("check_deadlock"),
        # Successor pipeline (auto/v1/v2/v3/v4 — v3 is the fused
        # Pallas chunk, v4 the whole-chunk megakernel); same
        # request-over-directive precedence as every key.
        pipeline=(req["pipeline"] if req.get("pipeline") is not None
                  else base.pipeline),
        por=(bool(req["por"]) if req.get("por") is not None
             else base.por),
        por_table=(req["por_table"] if req.get("por_table") is not None
                   else base.por_table))
    # check_deadlock (and the POR mask) are baked into the compiled
    # program, so they key the cache; the StopAfter budgets are
    # host-side and are refreshed on the cached engine's config below.
    # A table artifact keys by CONTENT, not path (the same file-identity
    # rule as ``ident``): regenerating the artifact in place must build
    # a fresh engine, not keep serving the stale mask.
    por_key = None
    if cfg.por_table is not None:
        if isinstance(cfg.por_table, str):
            import hashlib
            with open(cfg.por_table, "rb") as f:
                por_key = hashlib.sha256(f.read()).hexdigest()
        else:
            por_key = cfg.por_table.fingerprint
    # pipeline keys the cache: the chunk program differs per pipeline,
    # so a v3 request must never be served a warm v2 engine (or vice
    # versa).
    key = (ident, req.get("engine", "single"), cfg.batch,
           cfg.queue_capacity, cfg.seen_capacity, record_trace,
           cfg.check_deadlock, cfg.pipeline, cfg.por, por_key)
    engine = _cache_get(_ENGINES, key, "engine_cache")
    if engine is None:
        engine_cls = None
        if req.get("engine") == "mesh":
            from .parallel.mesh import MeshBFSEngine
            engine_cls = MeshBFSEngine
        elif req.get("engine") == "auto":
            engine_cls = "auto"
        # make_engine applies the cfg-file fallbacks (CHECK_DEADLOCK,
        # StopAfter) identically for both engine classes.
        engine = make_engine(setup, cfg, engine_cls=engine_cls)
        _cache_put(_ENGINES, key, engine, "engine_cache")
    # Budgets are per-request: apply the request value (or the cfg-file
    # fallback) to the warm engine's host-side config.
    engine.config.max_seconds = (cfg.max_seconds
                                 if cfg.max_seconds is not None
                                 else setup.max_seconds)
    engine.config.max_diameter = (cfg.max_diameter
                                  if cfg.max_diameter is not None
                                  else setup.max_diameter)
    # Per-request telemetry scoping (see docstring): ALWAYS assigned,
    # so a cached engine never leaks one job's event log / postmortem
    # dir / run tags into the next request's run.
    tel = telemetry or {}
    engine.config.events_out = tel.get("events_out", cfg.events_out)
    engine.config.postmortem_dir = tel.get("postmortem_dir",
                                           cfg.postmortem_dir)
    engine.config.run_context_extra = tel.get("run_context")
    history_path = tel.get("history")
    res = engine.run(initial_states(setup, seed=int(req.get("seed", 0))))
    if history_path:
        # Served-traffic leg of the run-history ledger: every
        # server-executed check lands a kind=server entry (host_key +
        # job/tenant ids when a job ran it) so bench_history renders
        # served runs alongside CLI/bench ones.  Bookkeeping only —
        # a ledger write failure must not fail the check response.
        try:
            from .obs import history as history_mod
            from .obs.flight import host_fingerprint
            ctx = tel.get("run_context") or {}
            history_mod.append_entry(
                history_path,
                history_mod.entry_from_result(
                    "server", res, cfg_text=cfg_text, dims=setup.dims,
                    host_fingerprint=host_fingerprint(),
                    label=_cfg_label(req),
                    extra={"job_id": ctx.get("job_id"),
                           "tenant": ctx.get("tenant")}))
        except Exception as e:
            import sys as _sys
            print(f"server history append failed: "
                  f"{type(e).__name__}: {e}", file=_sys.stderr)
    out = {"ok": True, "distinct": res.distinct,
           "generated": res.generated, "diameter": res.diameter,
           "levels": list(res.levels), "stop_reason": res.stop_reason,
           "wall_seconds": round(res.wall_seconds, 3),
           "batch": engine.config.batch,      # resolved, for observability
           # Which successor pipeline actually ran, and (v3) the
           # resolved per-stage lowering plan — a stage that fell back
           # to XLA is visible to the client, never silent.
           "pipeline": res.pipeline,
           "fused_stages": dict(res.fused_stages),
           "fused_reasons": dict(res.fused_reasons),
           "action_counts": dict(res.action_counts),
           # (capacity-after, off-clock stall seconds) per seen-set
           # doubling — the SEEN_CAPACITY sizing evidence.
           "growth_stalls": list(res.growth_stalls),
           # Host-side per-phase wall-time breakdown for THIS run
           # (obs/ phase timers) — same shape bench.py embeds.
           "phases": {k: round(v, 4) for k, v in res.phases.items()},
           # TLC-style per-action coverage (obs/coverage.py), same
           # object bench JSON carries; also mirrored as coverage/*
           # gauges in the "stats" op.
           "coverage": dict(res.coverage),
           # TLC-parity statespace report (obs/report.py): collision
           # probability, per-level table, out-degree, seen-set load.
           # Also mirrored as statespace/* gauges in "stats", so the
           # two surfaces can never disagree about the scalar spine.
           "report": dict(res.report),
           "violation": None, "deadlock": None}
    if res.violation is not None:
        out["violation"] = _violation_json(engine, res.violation,
                                           setup.dims)
    if res.deadlock is not None:
        out["deadlock"] = format_state(res.deadlock, setup.dims)
    return out


def _do_swarm(req, telemetry=None, _loaded=None):
    """Run one swarm-mode check request — the cheap high-QPS tier
    (engine/swarm.py), reached via ``_do_check``'s mode routing.  Same
    warm-cache + per-request contract as ``_do_check``: the compiled
    engine is LRU-cached on the program-shaping knobs (walks, depth,
    batch, pipeline key the cache; seed and the step/wall budgets are
    per-request run() arguments), and the job executor's scoped
    ``events_out`` / ``postmortem_dir`` / ``run_context`` are
    (re)assigned on EVERY request so a cached engine never leaks one
    job's paths into the next."""
    from .engine.check import (initial_states, resolve_constraint,
                               resolve_invariants)
    from .engine.swarm import SwarmEngine

    setup, ident, cfg_text = (_loaded if _loaded is not None
                              else _load_setup(req))
    backend = setup.backend
    walks = (int(req["walks"]) if req.get("walks") is not None
             else int(backend.get("WALKS", 1024)))
    max_depth = (int(req["max_depth"])
                 if req.get("max_depth") is not None
                 else int(setup.max_diameter or 128))
    batch = (int(req["batch"]) if req.get("batch") is not None
             else int(backend.get("BATCH", walks)))
    pipeline = (req["pipeline"] if req.get("pipeline") is not None
                else backend.get("PIPELINE", "auto"))
    key = (ident, "swarm", walks, max_depth, min(batch, walks), pipeline)
    eng = _cache_get(_SWARMS, key, "swarm_cache")
    if eng is None:
        eng = SwarmEngine(setup.dims,
                          invariants=resolve_invariants(setup),
                          constraint=resolve_constraint(setup),
                          walks=walks, max_depth=max_depth,
                          batch=min(batch, walks), pipeline=pipeline,
                          metrics=_METRICS)
        _cache_put(_SWARMS, key, eng, "swarm_cache")
    tel = telemetry or {}
    eng.events_out = tel.get("events_out")
    eng.postmortem_dir = tel.get("postmortem_dir")
    eng.run_context_extra = tel.get("run_context")
    # Progress cadence is per-request (a watch-heavy client wants
    # sub-second swarm_progress lines); reassigned every request so a
    # cached engine never inherits the previous job's cadence.
    eng.progress_seconds = (float(req["progress_seconds"])
                            if req.get("progress_seconds") is not None
                            else 5.0)
    seed = int(req.get("seed", 0))
    res = eng.run(initial_states(setup, seed=seed), seed=seed,
                  num_steps=(int(req["num_steps"])
                             if req.get("num_steps") is not None
                             else None),
                  max_seconds=(req.get("max_seconds")
                               if req.get("max_seconds") is not None
                               else setup.max_seconds))
    history_path = tel.get("history")
    if history_path:
        # Two ledger legs per served swarm run: kind=swarm (the tier's
        # own dialect, with the swarm rate block) AND the kind=server
        # serving leg every server-executed check lands — one run,
        # both ledger surfaces.  Bookkeeping only: a ledger write
        # failure must not fail the response.
        try:
            from .obs import history as history_mod
            from .obs.flight import host_fingerprint
            ctx = tel.get("run_context") or {}
            hfp = host_fingerprint()
            hunt_sum = None
            if res.report.get("hunt"):
                from .obs import hunt as hunt_obs
                hunt_sum = hunt_obs.summarize(res.report["hunt"])
            for kind, extra in (
                    ("swarm", {"swarm": res.report.get("swarm"),
                               "hunt": hunt_sum}),
                    ("server", {"job_id": ctx.get("job_id"),
                                "tenant": ctx.get("tenant"),
                                "mode": "swarm"})):
                history_mod.append_entry(
                    history_path,
                    history_mod.entry_from_result(
                        kind, res, cfg_text=cfg_text, dims=setup.dims,
                        host_fingerprint=hfp, label=_cfg_label(req),
                        extra=extra))
        except Exception as e:
            import sys as _sys
            print(f"server history append failed: "
                  f"{type(e).__name__}: {e}", file=_sys.stderr)
    out = {"ok": True, "mode": "swarm", "walks": res.walks,
           "steps": res.steps, "visited": res.visited,
           "traces": res.traces, "distinct": res.distinct,
           "generated": res.generated, "diameter": res.diameter,
           "stop_reason": res.stop_reason,
           "wall_seconds": round(res.wall_seconds, 3),
           "steps_per_second": round(res.steps_per_second, 1),
           "walks_per_second": round(res.walks_per_second, 1),
           "violation_at_seconds": res.violation_at_seconds,
           "pipeline": res.pipeline,
           "phases": {k: round(v, 4) for k, v in res.phases.items()},
           "report": dict(res.report),
           "hunt": res.report.get("hunt"),
           "violation": None}
    if res.violation is not None:
        out["violation"] = _violation_json(eng, res.violation,
                                           setup.dims)
    return out


def _do_simulate(req):
    from .engine.check import resolve_constraint, resolve_invariants
    from .engine.simulate import Simulator
    from .engine.check import initial_states

    setup, ident, _cfg_text = _load_setup(req)
    batch = (int(req["batch"]) if req.get("batch") is not None
             else int(setup.backend.get("BATCH", 1024)))
    depth = int(req.get("depth", 100))
    key = (ident, batch, depth)
    sim = _cache_get(_SIMS, key, "sim_cache")  # warm path, like _ENGINES
    if sim is None:
        sim = Simulator(setup.dims,
                        invariants=resolve_invariants(setup),
                        constraint=resolve_constraint(setup),
                        batch=batch, depth=depth)
        _cache_put(_SIMS, key, sim, "sim_cache")
    res = sim.run(initial_states(setup, seed=int(req.get("seed", 0))),
                  num_steps=int(req.get("num_steps", 1 << 20)),
                  seed=int(req.get("seed", 0)),
                  max_seconds=req.get("max_seconds"))
    out = {"ok": True, "steps": res.steps, "traces": res.traces,
           "wall_seconds": round(res.wall_seconds, 3), "violation": None}
    if res.violation_invariant is not None:
        from .models.pystate import format_state
        out["violation"] = {
            "invariant": res.violation_invariant,
            "trace": [
                {"action": ("Init" if g < 0
                            else setup.dims.describe_instance(g)),
                 "state": format_state(st, setup.dims)}
                for g, st in (res.violation_trace or [])]}
    return out


def _do_metrics() -> dict:
    """Prometheus text exposition of the same process-global registry
    the ``stats`` op serves as JSON — one snapshot() call feeds both,
    so the two views can never disagree about a counter taken in the
    same instant (the acceptance contract tests exactly this)."""
    from .obs.expose import (CONTENT_TYPE, default_labels,
                             render_prometheus)
    return {"ok": True,
            "content_type": CONTENT_TYPE,
            "exposition": render_prometheus(_METRICS.snapshot(),
                                            labels=default_labels())}


def _do_stats() -> dict:
    """The live-stats endpoint: the process-global registry verbatim
    (request counts, per-op latency histograms, LRU cache hit/miss/
    eviction counters) plus the caches' occupancy.  Read-only and
    lock-free — it answers instantly even while a check owns the device
    lock, which is the whole point of a LIVE stats op."""
    return {"ok": True,
            "metrics": _METRICS.snapshot(),
            "engine_cache": {"size": len(_ENGINES),
                             "capacity": _CACHE_CAP},
            "sim_cache": {"size": len(_SIMS), "capacity": _CACHE_CAP},
            "swarm_cache": {"size": len(_SWARMS),
                            "capacity": _CACHE_CAP}}


def _execute_job(request: dict, job: dict,
                 history: Optional[str] = None) -> dict:
    """JobManager executor: the job's request through the SAME device
    lock + handlers as the blocking ops (engine semantics untouched),
    with per-job telemetry scoping — the job's own event log and
    postmortem dir, job/tenant tags on the flight ring's run_context
    record, and the owning server's history ledger."""
    tel = {"events_out": job.get("events_out"),
           "postmortem_dir": job.get("job_dir"),
           "history": history,
           "run_context": {"job_id": job["id"],
                           "tenant": job["tenant"]}}
    with _LOCK:
        if request.get("op") == "simulate":
            return _do_simulate(request)
        return _do_check(request, telemetry=tel)


def _cache_key_for(req: dict, inner: dict) -> Optional[str]:
    """Result-cache key for a submit request (None = uncacheable /
    caching not asked for).  Keyed by cfg CONTENT fingerprint (the
    history ledger's fingerprint idiom — the cfg text determines the
    model) + the canonicalized engine-shaping request fields.
    Wall-clock-budgeted requests are refused: a max_seconds-truncated
    result is not reusable.  Structural invariant: a cacheable job is
    ALWAYS content-pinned (``_do_submit`` converts cfg paths to
    cfg_text before calling here) — fingerprinting a path the job
    would re-read later is the poisoned-cache TOCTOU, so a path-based
    cacheable request is rejected rather than keyed."""
    if not req.get("cache"):
        return None
    if inner.get("max_seconds") is not None:
        raise ValueError("cache: true is not allowed with max_seconds "
                         "(a wall-clock-truncated result is not "
                         "reusable)")
    import hashlib
    from .obs.history import fingerprint_text
    if inner.get("cfg_text"):
        cfg_fp = fingerprint_text(inner["cfg_text"])
    elif inner.get("cfg"):
        raise ValueError("cacheable jobs must be content-pinned "
                         "(cfg_text); _do_submit converts paths")
    else:
        raise ValueError("need 'cfg' (path) or 'cfg_text'")
    shape = {k: v for k, v in sorted(inner.items())
             if k not in ("cfg", "cfg_text")}
    blob = json.dumps([inner.get("op", "check"), cfg_fp, shape],
                      sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def _do_submit(req: dict, manager) -> dict:
    inner = req.get("job")
    if not isinstance(inner, dict) \
            or inner.get("op") not in ("check", "simulate"):
        raise ValueError("submit needs a 'job' object whose op is "
                         "'check' or 'simulate'")
    # Validate the engine-tier selector at ADMISSION, not execution:
    # an unknown mode must answer THIS submit with a clean
    # {"ok": false}, never queue and then surface as an
    # executor-thread exception hours later.
    if inner.get("mode") not in (None, "exhaustive", "swarm"):
        _METRICS.counter("server/rejected/bad_mode")
        raise ValueError(f"unknown mode {inner.get('mode')!r} "
                         f"(expected 'exhaustive' or 'swarm')")
    label = _cfg_label(inner)
    if req.get("cache") and inner.get("cfg"):
        # Pin the cfg CONTENT at submit time: the cache key is
        # fingerprinted now, but the job runs later — a path-based job
        # would re-read the file at execution, and an edit in between
        # would store the NEW model's result under the OLD content's
        # key (a poisoned cache hit).  Content-addressing the job
        # closes the window.
        with open(inner["cfg"], encoding="utf-8") as f:
            inner = dict(inner, cfg_text=f.read())
        inner.pop("cfg")
    job = manager.submit(dict(inner), tenant=req.get("tenant"),
                         label=label,
                         cache_key=_cache_key_for(req, inner),
                         slo_seconds=req.get("slo_seconds"))
    return {"ok": True, "job": job}


def _do_job_op(op: str, req: dict, manager) -> dict:
    if op == "jobs":
        limit = req.get("limit")
        out = {"ok": True}
        out.update(manager.jobs_doc(
            tenant=req.get("tenant"), state=req.get("state"),
            limit=int(limit) if limit is not None else None))
        return out
    job_id = req.get("job_id")
    if not job_id:
        raise ValueError(f"{op} needs 'job_id'")
    if op == "status":
        return {"ok": True, "job": manager.get(job_id)}
    if op == "cancel":
        return {"ok": True, "job": manager.cancel(job_id)}
    # op == "result": state + result read under one manager lock (a
    # retention eviction between two reads must not turn a fetched
    # result into an 'unknown job' error).
    doc = manager.result_doc(job_id)
    return {"ok": True, "state": doc["state"], "result": doc["result"]}


#: Ops that need the job manager (serving/) — split out so the metric
#: label table and the dispatch below can never disagree.
_JOB_OPS = ("submit", "status", "result", "cancel", "jobs")


def handle_request(req: dict, manager=None) -> dict:
    op = req.get("op")
    # Metric names must not echo client-controlled strings: one counter +
    # histogram per distinct bogus op would grow the process-global
    # registry without bound in this long-lived service.
    op_label = op if op in ("ping", "check", "simulate", "stats",
                            "metrics") + _JOB_OPS else "unknown"
    _METRICS.counter(f"server/requests/{op_label}")
    ok = False
    with _METRICS.phase_timer(f"request/{op_label}"):
        try:
            if op == "ping":
                import jax
                resp = {"ok": True,
                        "platform": jax.devices()[0].platform}
            elif op == "stats":
                resp = _do_stats()
            elif op == "metrics":
                resp = _do_metrics()
            elif op in _JOB_OPS:
                # Job ops never take the device lock: submit journals
                # and returns; the executor thread does the running.
                if manager is None:
                    resp = {"ok": False,
                            "error": "no job manager (job ops need a "
                                     "served CheckerServer)"}
                elif op == "submit":
                    resp = _do_submit(req, manager)
                else:
                    resp = _do_job_op(op, req, manager)
            elif op in ("check", "simulate"):
                # Direct (blocking) ops log to the same per-server
                # ledger as jobs — the manager holds the path.
                hist = getattr(manager, "history_path", None)
                with _LOCK:
                    resp = (_do_check(req, telemetry={"history": hist})
                            if op == "check" else _do_simulate(req))
            else:
                resp = {"ok": False, "error": f"unknown op {op!r}"}
            ok = bool(resp.get("ok"))
            return resp
        except Exception as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        finally:
            if not ok:
                _METRICS.counter(f"server/errors/{op_label}")


class _Handler(socketserver.StreamRequestHandler):
    """Connection hardening (resilience): the service is long-lived, so a
    single connection must not be able to take it down or pin it —

    - the request LINE is size-bounded (``max_request_bytes``): the
      newline-delimited protocol otherwise buffers an arbitrarily long
      line in RAM before json parsing ever sees it, so one huge line
      could OOM the whole warm-engine process;
    - the socket gets an IDLE timeout (``idle_timeout_seconds``): a dead
      or wedged client would otherwise hold its handler thread (and its
      open fd) forever.  The timeout covers reads between requests and
      response writes — a check/simulate in flight does not tick it,
      because the handler is computing, not blocked on the socket.

    The oversized reject answers ``{"ok": false}`` (the client is
    mid-exchange and waiting for a line) and then closes — an oversized
    line cannot be resynced, its remainder would parse as garbage
    requests.  The idle timeout closes SILENTLY: the client is between
    requests, and an unsolicited error line sitting in the socket
    buffer would be misread as the response to whatever it sends next
    from a stale pooled connection."""

    def handle(self):
        srv = self.server
        try:
            self.connection.settimeout(srv.idle_timeout_seconds)
        except OSError:
            pass
        while True:
            try:
                line = self.rfile.readline(srv.max_request_bytes + 1)
            except (TimeoutError, OSError):
                _METRICS.counter("server/rejected/idle_timeout")
                return       # silent close: see class docstring
            if not line:
                return
            if len(line) > srv.max_request_bytes:
                _METRICS.counter("server/rejected/oversized")
                self._try_respond({
                    "ok": False,
                    "error": f"request line exceeds "
                             f"{srv.max_request_bytes} bytes"})
                return
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError as e:
                resp = {"ok": False, "error": f"bad json: {e}"}
            else:
                if isinstance(req, dict) and req.get("op") == "watch":
                    # The one streaming op: run attach emits one
                    # snapshot line per interval on THIS connection,
                    # then a done line; the connection then continues
                    # serving normal requests.
                    if not self._serve_watch(req):
                        return
                    continue
                resp = handle_request(req,
                                      getattr(self.server, "jobs", None))
            if not self._try_respond(resp):
                return

    def _serve_watch(self, req: dict) -> bool:
        """Stream flight-recorder snapshots (obs/flight.py) until the
        watched run ends, ``count`` snapshots have been sent, or the
        client goes away.  Never touches the device lock — attach to a
        server mid-check and the snapshots flow while the check runs.
        With ``job`` the stream scopes to one job (``_serve_job_watch``).
        Returns False when the client died (ends the handler)."""
        import time as _time

        from .obs.flight import RECORDER
        _METRICS.counter("server/requests/watch")
        try:
            interval = min(max(float(req.get("interval", 1.0)), 0.05),
                           60.0)
            count = int(req.get("count", 0))
        except (TypeError, ValueError) as e:
            return self._try_respond(
                {"ok": False, "error": f"bad watch params: {e}"})
        # 0/negative = until run end — still bounded so an orphaned
        # watcher cannot pin its handler thread forever.
        limit = count if count > 0 else 3600
        mgr = getattr(self.server, "jobs", None)
        if req.get("job"):
            return self._serve_job_watch(str(req["job"]), mgr,
                                         interval, count, limit)
        attach_seq = RECORDER.note_attach(
            transport="server", peer=str(self.client_address[0]),
            interval=interval, count=count)
        sent = 0
        saw_run = False
        t_attach = _time.monotonic()
        while True:
            run_end = RECORDER.last_event("run_end")
            snapshot = {
                "seq": RECORDER.seq(), "armed": RECORDER.armed,
                "run": RECORDER.last_record("run_context"),
                "progress": RECORDER.last_record("progress"),
                "level": RECORDER.last_event("level_complete"),
                "coverage": RECORDER.last_event("coverage"),
                "chunk_stage": RECORDER.last_record("chunk_stage"),
                "hunt": RECORDER.last_record("hunt"),
            }
            if not self._try_respond({"ok": True, "watch": snapshot}):
                return False
            sent += 1
            ended = (run_end is not None
                     and run_end["seq"] > attach_seq)
            saw_run = saw_run or RECORDER.armed or ended
            # A live job queue counts as a live run for idleness: a
            # watcher attached while jobs are still queued (the engine
            # not yet armed) must ride out the whole queue wait, not
            # get reaped by the no-run grace below — the --idle-timeout
            # interplay regression (ISSUE 13 satellite).
            jobs_alive = mgr is not None and mgr.has_live_jobs()
            # Done when: the watched run ended after we attached; an
            # explicit count is exhausted; or (count 0) the run we saw
            # is gone / none ever started within the grace window — a
            # watcher launched alongside its run must ride out engine
            # construction + XLA compilation (tens of seconds on a cold
            # cache), so the no-run-yet grace is time-based.
            idle = (count <= 0 and not RECORDER.armed and not jobs_alive
                    and (saw_run
                         or _time.monotonic() - t_attach
                         > self.server.watch_grace_seconds))
            if sent >= limit or ended or idle:
                # Re-read: the run can end (emit run_end, then disarm)
                # between the loop-top read and the idle computation —
                # the done line must carry the freshest record, not a
                # stale null.  Pre-attach run_ends stay out: the done
                # line reports THIS watch's run or nothing.
                end = RECORDER.last_event("run_end")
                if end is not None and end["seq"] <= attach_seq:
                    end = None
                return self._try_respond(
                    {"ok": True, "done": True, "snapshots": sent,
                     "run_end": end})
            _time.sleep(interval)

    def _serve_job_watch(self, job_id: str, mgr, interval: float,
                         count: int, limit: int) -> bool:
        """Per-job run attach: one snapshot per interval carrying the
        job's registry summary, plus the flight ring's progress records
        while THIS job owns the device (the manager's running id is
        the authority; the ring's run_context carries the same job_id
        tag).  Liveness is the JOB's, not the engine's: a queued or
        compiling job keeps its watcher — the stream closes on the
        job's terminal state, an explicit ``count``, or a ~24 h safety
        bound; a bound hit on a still-live job closes with
        ``truncated: true`` (re-attach to keep watching), never with a
        false claim that the job ended."""
        import time as _time

        from .obs.flight import RECORDER
        if count <= 0:
            # The generic watch's 3600-snapshot cap would reap a
            # watcher of a deeply queued job in minutes at small
            # intervals; the job stream's orphan bound is a day.
            limit = max(3600, int(86400.0 / interval))
        if mgr is None:
            return self._try_respond(
                {"ok": False, "error": "no job manager"})
        try:
            job = mgr.get(job_id)
        except KeyError as e:
            return self._try_respond({"ok": False, "error": str(e)})
        RECORDER.note_attach(
            transport="server", peer=str(self.client_address[0]),
            interval=interval, count=count, job_id=job_id)
        sent = 0
        while True:
            try:
                job = mgr.get(job_id)
            except KeyError:
                # Terminal-retention eviction raced the watch loop:
                # the job went terminal and was pruned between polls.
                # Close with a done line carrying the last summary we
                # saw — never a dead socket with no terminal record.
                return self._try_respond(
                    {"ok": True, "done": True, "snapshots": sent,
                     "job": job, "evicted": True})
            running = mgr.running_job_id() == job_id
            snapshot = {"seq": RECORDER.seq(), "armed": RECORDER.armed,
                        "job": job, "running": running}
            runrec = RECORDER.last_record("run_context")
            if running and runrec is not None \
                    and runrec.get("job_id") == job_id \
                    and RECORDER.context().get("job_id") == job_id:
                # Ring records are attributed to THIS job only once the
                # armed run_context carries its tag, and only records
                # NEWER than that context (seq-ordered) — a stale
                # progress line from the previous run must never render
                # as this job's.
                snapshot["run"] = runrec
                for key, rec in (
                        ("progress", RECORDER.last_record("progress")),
                        ("level",
                         RECORDER.last_event("level_complete")),
                        ("coverage", RECORDER.last_event("coverage")),
                        ("hunt", RECORDER.last_record("hunt"))):
                    if rec is not None and rec["seq"] > runrec["seq"]:
                        snapshot[key] = rec
            terminal = job["state"] in ("done", "failed", "cancelled")
            if terminal:
                return self._try_respond(
                    {"ok": True, "done": True, "snapshots": sent,
                     "job": job})
            if not self._try_respond({"ok": True, "watch": snapshot}):
                return False
            sent += 1
            if sent >= limit:
                return self._try_respond(
                    {"ok": True, "done": True, "snapshots": sent,
                     "job": job,
                     # Only an explicit count is a clean close; the
                     # safety bound on a live job is a truncation.
                     "truncated": count <= 0})
            _time.sleep(interval)

    def _try_respond(self, resp: dict) -> bool:
        """Best-effort one-line reply; False when the client is gone (a
        failed write must end the handler, never crash the thread)."""
        try:
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()
            return True
        except (TimeoutError, OSError):
            _METRICS.counter("server/rejected/dead_client")
            return False


class CheckerServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    # Hardening knobs (see _Handler): overridable per instance/CLI.
    max_request_bytes = 10 << 20       # a sane cfg_text is far smaller
    idle_timeout_seconds = 300.0
    # How long a count-0 watch with NO live run and NO live jobs waits
    # before concluding there is nothing to watch (see _serve_watch).
    # Class-level so the idle-vs-watch regression tests can shrink it.
    watch_grace_seconds = 120.0
    # Serving layer (serve() wires these): the JobManager behind the
    # submit/status/result/cancel/jobs ops + per-job watch, and the
    # optional HTTP exposition listener (--metrics-port).
    jobs = None
    metrics_http = None

    def server_close(self):
        """Tear down the serving side too: the exposition listener's
        socket and the job executor thread (its queued jobs stay
        journaled for the next server on the same --job-dir).  The
        close WAITS for the in-flight job to finish journaling its
        terminal state — a same-process successor on the same job dir
        would otherwise replay the journal's last word ('running'),
        re-queue the job, and execute it twice while the old executor
        is still finishing it (graceful drain, like the device lock)."""
        if self.metrics_http is not None:
            try:
                self.metrics_http.shutdown()
                self.metrics_http.server_close()
            except Exception:
                pass
            self.metrics_http = None
        if self.jobs is not None:
            if not self.jobs.close(wait=True):
                # The drain gave up (a check can outlast the join
                # budget): the in-flight job is STILL RUNNING and will
                # journal its terminal state when it finishes.  Say so
                # loudly — a successor server started on this job dir
                # before then would replay the 'running' tail and run
                # that job a second time.
                import sys
                print(f"server_close: job executor still running "
                      f"(job {self.jobs.running_job_id()}); do not "
                      f"start another server on "
                      f"{self.jobs.base_dir!r} until it finishes",
                      file=sys.stderr)
        super().server_close()


def serve(host: str = "127.0.0.1", port: int = 8610,
          max_request_bytes: Optional[int] = None,
          idle_timeout_seconds: Optional[float] = None,
          job_dir: Optional[str] = None,
          job_queue_capacity: Optional[int] = None,
          history: Optional[str] = None,
          metrics_port: Optional[int] = None) -> CheckerServer:
    """Create (and return) a listening server; caller decides threading.
    Port 0 picks an ephemeral port (see ``server_address[1]``).

    ``job_dir`` is where the job journal + per-job artifact dirs live;
    None uses a fresh per-process temp dir (jobs work, but the registry
    does not survive a restart — pass a stable dir for that).
    ``history`` appends a kind=server run-history ledger entry per
    server-executed check (scoped to THIS server — several servers in
    one process keep separate ledgers).  ``metrics_port`` serves GET
    /metrics + /flight + /jobs over HTTP from this process (0 =
    ephemeral port, see ``metrics_http.server_address``)."""
    srv = CheckerServer((host, port), _Handler)
    if max_request_bytes is not None:
        srv.max_request_bytes = max_request_bytes
    if idle_timeout_seconds is not None:
        srv.idle_timeout_seconds = idle_timeout_seconds
    from .serving import JobManager
    if job_dir is None:
        job_dir = tempfile.mkdtemp(prefix="raft-jobs-")

    def _executor(request, job):
        return _execute_job(request, job, history=history)

    srv.jobs = JobManager(
        job_dir, executor=_executor, metrics=_METRICS,
        history_path=history,
        **({"queue_capacity": int(job_queue_capacity)}
           if job_queue_capacity is not None else {}))
    if metrics_port is not None:
        from .obs.expose import start_metrics_server
        from .obs.flight import RECORDER
        srv.metrics_http, _ = start_metrics_server(
            int(metrics_port), _METRICS, flight=RECORDER, host=host,
            # Newest 1000 rows per GET: a scraper polling /jobs must
            # not serialize the whole 10k-job retention under the
            # manager lock every few seconds (counts stay global).
            jobs_provider=lambda: srv.jobs.jobs_doc(limit=1000))
    return srv


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(prog="raft_tla_tpu.server")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8610)
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu)")
    p.add_argument("--max-request-bytes", type=int, default=None,
                   help="reject request lines larger than this "
                        f"(default {CheckerServer.max_request_bytes})")
    p.add_argument("--idle-timeout", type=float, default=None,
                   help="drop connections idle longer than this many "
                        "seconds "
                        f"(default {CheckerServer.idle_timeout_seconds})")
    p.add_argument("--job-dir", default=None, metavar="DIR",
                   help="job journal + per-job artifact dirs (serving/"
                        "): pass a stable directory so the job "
                        "registry survives restarts — queued jobs "
                        "resume, the job a crash caught running is "
                        "re-run once then failed with a postmortem "
                        "pointer.  Default: a fresh temp dir (jobs "
                        "work, no cross-restart durability)")
    p.add_argument("--job-queue", type=int, default=None, metavar="N",
                   help="admission queue capacity (queued jobs beyond "
                        "this are rejected with server/rejected/"
                        "queue_full; default 64)")
    p.add_argument("--history", default=None, metavar="FILE",
                   help="append a kind=server run-history ledger entry "
                        "(obs/history.py, with host_key + job/tenant "
                        "ids) per server-executed check, so "
                        "scripts/bench_history.py renders served "
                        "traffic alongside CLI runs")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve GET /metrics (Prometheus text "
                        "exposition), /flight (flight-recorder ring), "
                        "and /jobs (job registry) over HTTP from this "
                        "process — the natural scrape target for the "
                        "long-lived service")
    args = p.parse_args(argv)
    if args.platform == "cpu":
        from .utils.platform import force_cpu
        force_cpu()
    srv = serve(args.host, args.port,
                max_request_bytes=args.max_request_bytes,
                idle_timeout_seconds=args.idle_timeout,
                job_dir=args.job_dir,
                job_queue_capacity=args.job_queue,
                history=args.history,
                metrics_port=args.metrics_port)
    print(f"raft_tla_tpu checker service on "
          f"{srv.server_address[0]}:{srv.server_address[1]}")
    if srv.metrics_http is not None:
        print(f"metrics: http://{srv.metrics_http.server_address[0]}:"
              f"{srv.metrics_http.server_address[1]}/metrics "
              f"(+ /flight /jobs)")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()


if __name__ == "__main__":
    main()
